#!/usr/bin/env python
"""On-chip self-check: diagnose a broken chip path instead of zeroing it.

Round-4 verdict items 1, 3 and 7: the first real-TPU capture collapsed the
headline config's accuracy to chance (BENCH_r04.json hips_bsc_cnn 0.0967)
and published transformer MFU 14.8-18.3x chip peak. Both failures are
platform behaviors the CPU suite cannot see. This module probes each
suspect mechanism directly, in ~2 minutes, and returns a machine-readable
verdict that bench.py stamps into its JSON (``chip_sanity``) before any
throughput phase runs.

Probes:

1. ``transfer_bitexact`` — device_put + np.asarray round-trips of float32
   buffers holding denormal bit-patterns (int32 indices < 2^23 bitcast to
   float32 are denormals) and NaN-payload bit-patterns (indices >=
   0x7F800001 bitcast are signaling NaNs). A transfer path that flushes
   denormals to zero or quiets/canonicalizes NaNs silently corrupts any
   int-bitcast-through-float wire — the DeviceResidentTrainer packing
   (trainer_device.py packed layout) is exactly that.
2. ``bitcast_in_jit`` — the same bit-patterns produced *inside* jit via
   lax.bitcast_convert_type and round-tripped, catching XLA-level
   canonicalization distinct from the transfer path.
3. ``matmul_precision`` — measures the error of a float32 matmul against
   a float64 numpy oracle for default vs "highest" precision. TPUs
   default fp32 matmuls to bf16xbf16 passes on the MXU; the probe
   reports the observed error ratio so accuracy-sensitive paths know
   whether jax.default_matmul_precision("float32") is load-bearing.
4. ``blocking_honest`` — times N chained 2048^3 matmuls with
   block_until_ready, then cross-checks against a *value fetch* of the
   result. If the value fetch costs >2x the "blocked" wall time, timing
   via block_until_ready under-measures and any steps/s derived from it
   is invalid (r04: mfu 14.8 on a 197 TFLOP/s chip).
5. ``bsc_oracle`` — runs the DeviceResidentTrainer fwd_compress/apply
   cycle for N rounds on the live backend against a pure-numpy oracle of
   the same BSC semantics (reference: gradient_compression.cc:191-268
   momentum-corrected accumulate + per-tensor top-k + residual zeroing)
   and reports max |param drift| plus any NaN/Inf in u/v/flat.

Run standalone: python tools/chip_sanity.py  (prints the JSON verdict).
"""

from __future__ import annotations

import json
import time

import numpy as np

__all__ = ["run_chip_sanity"]


def _probe_transfer_bitexact(jax, jnp):
    """Round-trip adversarial float32 bit patterns host->device->host."""
    patterns = np.array([
        0x00000001, 0x00000100, 0x007FFFFF,          # denormals (idx<2^23)
        0x00800000,                                   # smallest normal
        0x7F800001, 0x7FBFFFFF,                       # signaling NaNs
        0x7FC00000, 0x7FFFFFFF,                       # quiet NaNs
        0x80000000, 0xFF800000,                       # -0.0, -inf
        0x3F800000, 0x00012345, 0x00ABCDEF,           # 1.0 + small indices
    ], dtype=np.uint32)
    as_f32 = patterns.view(np.float32)
    back = np.asarray(jax.device_put(as_f32)).view(np.uint32)
    bad = [(f"0x{int(a):08X}", f"0x{int(b):08X}")
           for a, b in zip(patterns, back) if a != b]
    return {"ok": not bad, "corrupted": bad}


def _probe_bitcast_in_jit(jax, jnp):
    """Produce index bit-patterns inside jit (the trainer's exact path)
    and check they reach the host intact, then round-trip back."""
    idx = np.array([0, 1, 255, 70000, (1 << 23) - 1, 1 << 23,
                    (1 << 24) + 12345, (1 << 30) + 7], dtype=np.int32)

    @jax.jit
    def pack(i):
        return jax.lax.bitcast_convert_type(i, jnp.float32)

    @jax.jit
    def unpack(f):
        return jax.lax.bitcast_convert_type(f, jnp.int32)

    down = np.asarray(pack(jnp.asarray(idx)))          # device->host as f32
    host_view = down.view(np.int32)
    up = np.asarray(unpack(jax.device_put(down)))      # host->device->back
    bad_down = [(int(a), int(b)) for a, b in zip(idx, host_view) if a != b]
    bad_up = [(int(a), int(b)) for a, b in zip(idx, up) if a != b]
    return {"ok": not bad_down and not bad_up,
            "corrupt_device_to_host": bad_down,
            "corrupt_round_trip": bad_up}


def _probe_matmul_precision(jax, jnp):
    """fp32 matmul error vs float64 oracle, default vs highest."""
    rng = np.random.default_rng(0)
    a = rng.standard_normal((256, 256)).astype(np.float32)
    b = rng.standard_normal((256, 256)).astype(np.float32)
    oracle = a.astype(np.float64) @ b.astype(np.float64)
    scale = np.abs(oracle).max()

    def err(precision):
        f = jax.jit(lambda x, y: jnp.dot(x, y, precision=precision))
        return float(np.abs(np.asarray(f(a, b)) - oracle).max() / scale)

    e_default = err(None)
    e_highest = err(jax.lax.Precision.HIGHEST)
    # bf16 mantissa is 8 bits vs fp32's 24: a >100x error ratio means the
    # default is a low-precision MXU pass.
    return {"err_default": e_default, "err_highest": e_highest,
            "default_is_lowprec": bool(
                e_default > max(e_highest, 1e-12) * 100)}


def _probe_blocking_honest(jax, jnp):
    """Does block_until_ready actually force execution?

    The r04 axon-tunnel platform "blocks" a 64-matmul chain in 0.02 ms
    (489,000 TFLOP/s implied on a 197 TFLOP/s chip) — which is how mfu
    14.8-18.3 got published. The detector: time a long matmul chain two
    ways, block_until_ready vs fetching a scalar VALUE of the result (a
    value cannot exist before the chain has run; a constant-foldable
    checksum would defeat this, so the chain input is runtime data). If
    the blocked time misses >half the value-derived compute time, or the
    implied FLOP/s beats 1.2x any plausible chip peak, blocking is
    dishonest and only value-fenced timings may be published."""
    n, iters = 2048, 64
    rng = np.random.default_rng(7)
    x = jax.device_put(jnp.asarray(
        rng.standard_normal((n, n)).astype(np.float32) / n,
        dtype=jnp.bfloat16))

    @jax.jit
    def chain(m):
        for _ in range(iters):
            m = jnp.tanh(m @ m * (1.0 / n))
        return jnp.float32(jnp.sum(m.astype(jnp.float32)))

    float(chain(x))                                    # compile + warm
    t0 = time.perf_counter()
    y = chain(x)
    y.block_until_ready()
    t_block = time.perf_counter() - t0
    t0 = time.perf_counter()
    s = float(y)                                       # honest fence
    t_fetch = time.perf_counter() - t0
    t0 = time.perf_counter()
    s2 = float(chain(x))                               # full honest pass
    t_value = time.perf_counter() - t0
    flops = 2.0 * n * n * n * iters
    implied = flops / max(t_block, 1e-9)
    return {"t_block_s": t_block, "t_value_s": t_value,
            "t_residual_fetch_s": t_fetch, "checksum": s2,
            "blocked_tflops_implied": round(implied / 1e12, 1),
            "ok": bool(t_block > 0.5 * t_value and implied < 1.2e15)}


def _probe_bsc_oracle(jax, jnp, rounds=25):
    """DeviceResidentTrainer's device cycle vs a numpy oracle.

    Two-leaf toy model through the real fwd_compress/apply_sgd jitted
    functions via a local single-worker store — no transport, isolating
    the DEVICE packing + top-k + residual + scatter-apply. The
    "gradient" is deliberately matmul-free and deterministic
    (elementwise: g = w_seed * mean(X) with well-separated |w_seed|), so
    the oracle (reference gradient_compression.cc:191-268 semantics in
    numpy) selects the SAME coordinates every round and any drift beyond
    float-noise is corruption — exactly how the r04 denormal-flush bug
    (all indices -> 0) shows up as drift ~ O(weights)."""
    from geomx_tpu.trainer_device import DeviceResidentTrainer
    from geomx_tpu.kvstore import create

    rng = np.random.default_rng(1)
    w1 = rng.standard_normal((20, 16)).astype(np.float32) * 0.1
    w2 = rng.standard_normal((16, 4)).astype(np.float32) * 0.1
    sizes = [w1.size, w2.size]
    total = sum(sizes)
    # distinct, well-separated magnitudes -> no top-k ties anywhere
    seed = (rng.permutation(total).astype(np.float32) + 1.0) / total
    seed *= np.where(rng.random(total) < 0.5, -1.0, 1.0)
    seed_leaves = [seed[:w1.size].reshape(w1.shape),
                   seed[w1.size:].reshape(w2.shape)]
    sj = [jnp.asarray(s) for s in seed_leaves]

    def grad_fn(leaves, Xb, yb):
        scale = jnp.mean(Xb)
        loss = scale * jnp.float32(1.0)
        return loss, [s * scale for s in sj]

    kv = create("local")
    tr = DeviceResidentTrainer([w1, w2], kv, grad_fn, threshold=0.05,
                               learning_rate=0.05)

    # numpy oracle of the same semantics
    flat = np.concatenate([w1.ravel(), w2.ravel()]).astype(np.float32)
    u = np.zeros_like(flat)
    v = np.zeros_like(flat)
    offs = [0, w1.size]
    ks = [max(int(s * 0.05), 1) for s in sizes]

    for r in range(rounds):
        Xb = np.full((4, 4), 1.0 + 0.1 * (r % 7), np.float32)
        tr.step(jnp.asarray(Xb), None)
        g = (seed * np.float32(Xb.mean())).astype(np.float32)
        u = (0.9 * u + g).astype(np.float32)
        v = (v + u).astype(np.float32)
        vals_all, idx_all = [], []
        for off, sz, k in zip(offs, sizes, ks):
            seg = v[off:off + sz]
            ii = np.argsort(-np.abs(seg), kind="stable")[:k]
            vals_all.append(seg[ii].copy())
            idx_all.append(ii + off)
        idx = np.concatenate(idx_all)
        vals = np.concatenate(vals_all)
        v[idx] = 0.0
        u[idx] = 0.0
        np.add.at(flat, idx, -0.05 * vals)

    dev_flat = np.concatenate([l.ravel() for l in tr.leaves])
    drift = float(np.abs(dev_flat - flat).max())
    finite = bool(np.isfinite(dev_flat).all())
    return {"max_param_drift": drift, "device_finite": finite,
            # deterministic selection: honest backends land ~1e-7;
            # index corruption lands ~O(weights) = 0.1
            "ok": finite and drift < 1e-3}


def run_chip_sanity(rounds=25):
    import jax
    import jax.numpy as jnp

    out = {"platform": jax.devices()[0].platform,
           "device": getattr(jax.devices()[0], "device_kind", "?")}
    t0 = time.time()
    for name, fn in [("transfer_bitexact", _probe_transfer_bitexact),
                     ("bitcast_in_jit", _probe_bitcast_in_jit),
                     ("matmul_precision", _probe_matmul_precision),
                     ("blocking_honest", _probe_blocking_honest)]:
        try:
            out[name] = fn(jax, jnp)
        except Exception as e:  # noqa: BLE001 - diagnostic capture
            out[name] = {"ok": False, "error": f"{type(e).__name__}: {e}"}
    try:
        out["bsc_oracle"] = _probe_bsc_oracle(jax, jnp, rounds=rounds)
    except Exception as e:  # noqa: BLE001
        out["bsc_oracle"] = {"ok": False,
                             "error": f"{type(e).__name__}: {e}"}
    out["wall_s"] = round(time.time() - t0, 1)
    # "ok" = CORRECTNESS: the device math/packing path is trustworthy.
    # A dishonest block_until_ready is a TIMING hazard, not a
    # correctness one — it's reported separately so the bench knows it
    # must fence every timing with a value fetch (which it always does
    # post-r04); it must never zero a correctness-passing capture.
    out["ok"] = all(out[k].get("ok", True) for k in
                    ("transfer_bitexact", "bitcast_in_jit", "bsc_oracle"))
    out["timing_fence_required"] = not out.get(
        "blocking_honest", {}).get("ok", False)
    return out


if __name__ == "__main__":
    import sys
    sys.path.insert(0, ".")
    print(json.dumps(run_chip_sanity(), indent=2, default=str))
