"""geomx-modelcheck: small-scope exhaustive exploration of the
membership/epoch/recovery/round-release protocol model.

This is layer 2 of geomx-statecheck. It imports the SAME executable
model the lint pass freezes (``tools.analyze.statemodel.MemberView`` /
``SchedulerView``) and the runtime conformance sanitizer mirrors
(``geomx_tpu/ps/conformance.py``), and drives it through every
interleaving of a small cluster — 2-3 workers, 1-2 servers, one
scheduler — under crash / partition (zombie) / rejoin / retransmit
schedules, checking safety invariants at every state:

    I1  per-receiver epoch monotonicity: no member ever adopts a
        membership broadcast with an epoch lower than its view
    I2  no round aggregates a contribution the dead-set fence should
        have dropped (sender in the server's dead view)
    I3  countdown ledgers drain at quiescence: no open round is left
        waiting on a contribution that can never arrive
    I4  restore never loses an acked update: a recovering server's
        restored version covers everything it acknowledged
    I5  at most one live holder per node id: once the rejoin fence is
        armed, a previous incarnation's traffic is never aggregated
    I6  membership views converge at quiescence: every live member's
        (epoch, dead set) equals the scheduler's

Transport is modeled as per-(src, dst) FIFO links (TCP ordering), with
nondeterministic interleaving ACROSS links, loss to down/partitioned
nodes, and bounded retransmission (``dup``: the head of a link is
re-sent at the tail — a resend racing a newer broadcast, which is how
cross-epoch reordering happens on a reconnect in the real van).

Exploration is iterative DFS over canonicalized states with a visited
set and a simple partial-order reduction: when every enabled action is
a delivery, deliveries to distinct destinations commute (a delivery
mutates only its destination and never enqueues), so only the smallest
destination's deliveries are expanded (``--no-por`` disables this; the
test suite checks both modes reach the same verdict).

Teeth are proved by mutation (``--mutants``): each seeded fence
removal — dropped rejoin fence, static countdown sizing, restore
without version comparison, epoch bump without broadcast, dropped
dead-set fence, stale-broadcast adoption — must trip EXACTLY its
invariant, nothing more, nothing less.

``--replay`` feeds flight-recorder dumps (``flightrec_*.json``) through
the model's monotonicity checks offline — the same conformance the
runtime sanitizer enforces live; ``tools/flight_report.py
--conformance`` delegates here.

Deliberate simplifications (documented, asserted by scope):
- replication is synchronous: a released round is on the replica (and
  acked) immediately; ``tick`` snapshots to disk lazily — exactly the
  window the restore merge must cover
- a server crash/rejoin happens at a round boundary (no in-flight
  pushes to it); mid-push server death is the wire sanitizer's beat
- a rejoined worker restarts its push schedule; rounds the server
  already released are skipped (the restored optimizer resumes past
  them)

Run ``python -m tools.modelcheck`` from the repo root.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from pathlib import Path
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

if __package__ in (None, ""):              # executed as a script
    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from tools.analyze.statemodel import MemberView, SchedulerView  # noqa: E402

SCHED = ("c", 0)   # node id of the scheduler in the model


INVARIANTS = {
    "I1": "per-receiver epoch monotonicity",
    "I2": "no round aggregates a dead-set-fenced contribution",
    "I3": "countdown ledgers drain at quiescence",
    "I4": "restore never loses an acked update",
    "I5": "at most one live holder per node id (rejoin fence holds)",
    "I6": "membership views converge at quiescence",
}

#: mutation flags the model honors; each maps to one real fence
MUTATION_FLAGS = (
    "no_rejoin_fence",      # server ignores _rejoin_epoch when fencing
    "no_dead_fence",        # server ignores the dead set when fencing
    "static_countdown",     # countdown sized from static worker count
    "restore_snap_first",   # restore prefers snapshot, no version cmp
    "no_broadcast",         # declare_dead bumps epoch, skips DEAD_NODE
    "adopt_stale",          # member adopts an older-epoch broadcast
)


@dataclasses.dataclass(frozen=True)
class Scope:
    """Exploration bounds. Budgets are global over a run, not per
    node — small scopes keep the state space exhaustive."""
    workers: int = 2
    servers: int = 1
    rounds: int = 1
    crashes: int = 0        # fail-stop worker crashes
    partitions: int = 0     # asymmetric partitions (zombies keep sending)
    rejoins: int = 0        # worker re-registrations (new incarnation)
    server_crashes: int = 0
    ticks: int = 0          # lazy snapshot-to-disk events
    dups: int = 0           # bounded retransmissions of membership frames
    max_states: int = 400_000


#: the clean suite explored by the bare CLI: every schedule class from
#: the ISSUE (crash, zombie partition, rejoin, server recovery, dup /
#: reorder via retransmit) at the 2-3 worker / 1-2 server scope
SCENARIOS: Dict[str, Scope] = {
    # dup/retransmit coverage lives in the 1-server scopes below: at
    # 3w2s the retransmit schedules push the space past what an
    # exhaustive run should cost, without adding a fence they reach
    "churn-3w2s": Scope(workers=3, servers=2, rounds=1, crashes=1,
                        rejoins=1),
    "zombie-rejoin": Scope(workers=2, servers=1, rounds=1, partitions=1,
                           rejoins=1, dups=1),
    "zombie-no-rejoin": Scope(workers=2, servers=1, rounds=1,
                              partitions=1),
    "crash-before-push": Scope(workers=2, servers=1, rounds=1,
                               crashes=1),
    "crash-only": Scope(workers=2, servers=1, rounds=0, crashes=1),
    "recovery-2r": Scope(workers=2, servers=1, rounds=2,
                         server_crashes=1, ticks=1),
    "double-declare": Scope(workers=3, servers=1, rounds=0, crashes=2,
                            dups=1),
}

#: mutant name -> (mutation flag, scenario, the ONE invariant it trips)
MUTANTS: Dict[str, Tuple[str, str, str]] = {
    "drop_rejoin_fence": ("no_rejoin_fence", "zombie-rejoin", "I5"),
    "zombie_push_aggregated": ("no_dead_fence", "zombie-no-rejoin",
                               "I2"),
    "static_countdown": ("static_countdown", "crash-before-push", "I3"),
    "restore_no_version_check": ("restore_snap_first", "recovery-2r",
                                 "I4"),
    # explored at rounds=0 so the missing broadcast shows up purely as
    # view divergence — with open rounds it would (correctly) wedge
    # countdowns too and trip I3 alongside
    "epoch_bump_without_broadcast": ("no_broadcast", "crash-only",
                                     "I6"),
    "stale_broadcast_adopted": ("adopt_stale", "double-declare", "I1"),
}


class ExplosionError(RuntimeError):
    """The scope exceeded max_states — a scope bug, never truncated
    silently into a 'clean' verdict."""


# ---------------------------------------------------------------------------
# world state
# ---------------------------------------------------------------------------

# link keys: (src, dst) where src/dst are SCHED, ("w", wid, inc) or
# ("s", sid). messages:
#   ("DEAD", epoch, deadset)          scheduler -> member
#   ("TABLE", epoch, revivedset)      scheduler -> member
#   ("PUSH", rnd, wid, inc, epoch)    worker incarnation -> server


class World:
    __slots__ = ("sched", "workers", "zombies", "servers", "links",
                 "used")

    def __init__(self, scope: Scope):
        self.sched = SchedulerView()
        # worker ids 10.., server ids 20..: disjoint, stable
        self.workers: Dict[int, dict] = {
            10 + i: {"inc": 0, "up": True, "zombie": False,
                     "view": MemberView(), "pushed": frozenset()}
            for i in range(scope.workers)}
        # previous incarnations that are still partitioned-but-alive
        self.zombies: Dict[Tuple[int, int], dict] = {}
        self.servers: Dict[int, dict] = {
            20 + j: {"up": True, "view": MemberView(),
                     "ledger": {}, "released": frozenset(),
                     "version": 0, "snap": 0, "replica": 0, "acked": 0}
            for j in range(scope.servers)}
        self.links: Dict[tuple, tuple] = {}
        self.used = {"crashes": 0, "partitions": 0, "rejoins": 0,
                     "server_crashes": 0, "ticks": 0, "dups": 0}

    # -- plumbing --------------------------------------------------------

    def clone(self) -> "World":
        w = World.__new__(World)
        w.sched = self.sched.copy()
        w.workers = {wid: {**rec, "view": rec["view"].copy()}
                     for wid, rec in self.workers.items()}
        w.zombies = {key: {**rec, "view": rec["view"].copy()}
                     for key, rec in self.zombies.items()}
        w.servers = {sid: {**rec, "view": rec["view"].copy(),
                           "ledger": dict(rec["ledger"])}
                     for sid, rec in self.servers.items()}
        w.links = dict(self.links)
        w.used = dict(self.used)
        return w

    def canon(self) -> tuple:
        return (
            self.sched.snapshot(),
            # a crashed (non-zombie) worker's view and push history are
            # unreachable — canonicalize them away so fail-stop branches
            # merge
            tuple((wid, r["inc"], r["up"], r["zombie"],
                   r["view"].snapshot() if r["up"] else (),
                   tuple(sorted(r["pushed"])) if r["up"] else ())
                  for wid, r in self.workers.items()),
            tuple((key, r["view"].snapshot(),
                   tuple(sorted(r["pushed"])))
                  for key, r in sorted(self.zombies.items())),
            tuple((sid, r["up"], r["view"].snapshot(),
                   tuple(sorted((rnd, tuple(sorted(entries)))
                                for rnd, entries in
                                r["ledger"].items())),
                   tuple(sorted(r["released"])), r["version"],
                   r["snap"], r["replica"], r["acked"])
                  for sid, r in self.servers.items()),
            tuple(sorted((k, v) for k, v in self.links.items() if v)),
            tuple(sorted(self.used.items())),
        )

    def enqueue(self, src, dst, msg) -> None:
        key = (src, dst)
        self.links[key] = self.links.get(key, ()) + (msg,)

    def member_dsts(self) -> List[tuple]:
        """Broadcast targets: every up, non-partitioned member the
        scheduler has not declared dead (``_broadcast_membership``
        skips the dead set; a partition IS the link being cut)."""
        out = []
        for wid, rec in sorted(self.workers.items()):
            if rec["up"] and not rec["zombie"] \
                    and wid not in self.sched.dead:
                out.append(("w", wid, rec["inc"]))
        for sid, rec in sorted(self.servers.items()):
            if rec["up"]:
                out.append(("s", sid))
        return out


@dataclasses.dataclass
class Violation:
    invariant: str
    detail: str

    def key(self) -> Tuple[str, str]:
        return (self.invariant, self.detail)


# ---------------------------------------------------------------------------
# transition semantics
# ---------------------------------------------------------------------------


class Model:
    """Action enumeration + application for one (scope, mutations)."""

    def __init__(self, scope: Scope, mutations: FrozenSet[str] = frozenset()):
        unknown = set(mutations) - set(MUTATION_FLAGS)
        if unknown:
            raise ValueError(f"unknown mutation flag(s): {sorted(unknown)}")
        self.scope = scope
        self.mut = mutations

    # -- helpers ---------------------------------------------------------

    def _senders(self, w: World):
        """(wid, inc, view, pushed-getter/setter target dict) for every
        process that can still emit pushes: up workers (zombie or not)
        plus superseded zombie incarnations."""
        for wid, rec in w.workers.items():
            if rec["up"]:
                yield wid, rec["inc"], rec
        for (wid, inc), rec in sorted(w.zombies.items()):
            yield wid, inc, rec

    def _expected(self, w: World, s: dict) -> int:
        """Countdown sizing: the live view (``_expected_local_pushes``)
        or, under the static_countdown mutation, the boot-time count."""
        if "static_countdown" in self.mut:
            return max(self.scope.workers, 1)
        live = [wid for wid in w.workers if wid not in s["view"].dead]
        return max(len(live), 1)

    def _release_check(self, w: World, s: dict,
                       out: List[Violation]) -> None:
        """Re-run every open countdown against the current view —
        ``_on_membership`` + the aggregate-time check in one place."""
        for rnd in sorted(s["ledger"]):
            entries = s["ledger"][rnd]
            distinct = {wid for wid, _inc, _ep in entries}
            if len(distinct) >= self._expected(w, s):
                del s["ledger"][rnd]
                s["released"] = s["released"] | {rnd}
                s["version"] += 1
                # synchronous replication: released == replicated ==
                # acked; ``tick`` models the lazy disk snapshot
                s["replica"] = s["version"]
                s["acked"] = s["version"]

    def _adopt_broadcast(self, view: MemberView, epoch: int, dead,
                         who: str, out: List[Violation]) -> str:
        if "adopt_stale" in self.mut and epoch < view.epoch:
            # seeded removal of the epoch guard in _process_dead_node:
            # the member regresses to the older broadcast
            if epoch < view.epoch:
                out.append(Violation(
                    "I1", f"{who} adopted epoch {epoch} over "
                          f"{view.epoch}"))
            view.epoch = epoch
            view.dead = set(dead)
            return "adopt"
        return view.adopt_broadcast(epoch, dead)

    # -- enumeration -----------------------------------------------------

    def enabled(self, w: World) -> List[tuple]:
        acts: List[tuple] = []
        sc, used = self.scope, w.used
        # deliveries / retransmits
        for key in sorted(k for k, v in w.links.items() if v):
            acts.append(("deliver", key))
            if (used["dups"] < sc.dups
                    and w.links[key][0][0] in ("DEAD", "SYNC")):
                acts.append(("dup", key))
        # pushes: one action per (sender, round), fanning out to every
        # eligible server at once — the real worker sends its key
        # pushes back-to-back, and all cross-node races live in the
        # delivery interleavings anyway (the invariants are per-node)
        for wid, inc, rec in self._senders(w):
            for rnd in range(1, sc.rounds + 1):
                if self._push_targets(w, rec, rnd):
                    acts.append(("push", wid, inc, rnd))
        # faults; crash/partition targets are symmetry-reduced: two
        # "pristine" workers (identical local record, mentioned nowhere
        # else in the state — no in-flight frame, ledger entry, dead
        # set or fence names them) leave the whole state invariant
        # under their swap, so faulting one representative covers both
        may_fault = (used["crashes"] < sc.crashes
                     or used["partitions"] < sc.partitions)
        mentioned = self._mentioned_wids(w) if may_fault else set()
        fault_classes: set = set()
        for wid, rec in sorted(w.workers.items()):
            if rec["up"] and not rec["zombie"]:
                cls = (rec["inc"], rec["view"].snapshot(),
                       tuple(sorted(rec["pushed"])))
                if wid in mentioned:
                    cls = (wid, cls)     # not swappable: unique class
                if cls not in fault_classes:
                    fault_classes.add(cls)
                    if used["crashes"] < sc.crashes:
                        acts.append(("crash", wid))
                    if used["partitions"] < sc.partitions:
                        acts.append(("partition", wid))
            if (not rec["up"] or rec["zombie"]) \
                    and wid not in w.sched.dead:
                acts.append(("detect", wid))
            if (not rec["up"] or rec["zombie"]) \
                    and wid in w.sched.dead \
                    and used["rejoins"] < sc.rejoins:
                acts.append(("rejoin", wid))
        for sid, srv in sorted(w.servers.items()):
            if srv["up"]:
                if used["ticks"] < sc.ticks \
                        and srv["version"] > srv["snap"]:
                    acts.append(("tick", sid))
                if used["server_crashes"] < sc.server_crashes \
                        and not srv["ledger"] \
                        and not any(v and k[1] == ("s", sid)
                                    and v[0][0] == "PUSH"
                                    for k, v in w.links.items()):
                    acts.append(("crash_server", sid))
            else:
                acts.append(("rejoin_server", sid))
        return acts

    # -- application -----------------------------------------------------

    def apply(self, w: World, act: tuple) -> Tuple[World, List[Violation]]:
        w = w.clone()
        out: List[Violation] = []
        kind = act[0]
        getattr(self, "_do_" + kind)(w, act, out)
        self._normalize(w, out)
        return w, out

    def _mentioned_wids(self, w: World) -> set:
        """Worker ids named anywhere outside their own record: dead
        sets, rejoin fences, ledgers, zombie keys, link endpoints and
        frame payloads. A worker NOT in this set is pristine — the
        state is invariant under swapping it with an identical one."""
        out: set = set(w.sched.dead) | set(w.sched.rejoin)
        for rec in w.workers.values():
            out |= rec["view"].dead
            out |= set(rec["view"].rejoin)
        for (zwid, _inc), rec in w.zombies.items():
            out.add(zwid)
            out |= rec["view"].dead
            out |= set(rec["view"].rejoin)
        for srv in w.servers.values():
            out |= srv["view"].dead
            out |= set(srv["view"].rejoin)
            for entries in srv["ledger"].values():
                out |= {e[0] for e in entries}
        for (src, dst), q in w.links.items():
            if src[0] == "w":
                out.add(src[1])
            if dst[0] == "w":
                out.add(dst[1])
            for m in q:
                if m[0] == "PUSH":
                    out.add(m[2])
                elif m[0] == "DEAD":
                    out |= set(m[2])
                else:               # SYNC
                    out |= set(m[2]) | set(m[3])
        return out

    def _done_sending(self, w: World, rec: dict) -> bool:
        """True when this worker can never emit another push: every
        (server, round) is either already pushed or released without
        it (a released round never un-releases)."""
        for sid, srv in w.servers.items():
            for rnd in range(1, self.scope.rounds + 1):
                if (sid, rnd) not in rec["pushed"] \
                        and rnd not in srv["released"]:
                    return False
        return True

    def _normalize(self, w: World, out: List[Violation]) -> None:
        """Two sound state-space reductions applied after every action:

        - drop in-flight messages whose destination can never process
          them (crashed / partitioned / superseded / down) — delivering
          each would be a no-op pop
        - eagerly drain broadcasts to a worker that is done sending:
          from then on its view is write-only (it stamps no more
          pushes), so the adoption commutes with every other action
          and delaying it only multiplies interleavings (I1 still
          checks on the eager adoption; I6 still checks at terminal)
        """
        for key in list(w.links):
            dst = key[1]
            if dst[0] != "w":
                if not w.servers[dst[1]]["up"]:
                    del w.links[key]
                continue
            rec = w.workers.get(dst[1])
            if (rec is None or rec["inc"] != dst[2]
                    or not rec["up"] or rec["zombie"]):
                del w.links[key]
            elif self._done_sending(w, rec):
                for msg in w.links.pop(key):
                    if msg[0] == "DEAD":
                        self._adopt_broadcast(
                            rec["view"], msg[1], msg[2],
                            f"worker {dst[1]}", out)
                    elif msg[0] == "SYNC":
                        rec["view"].adopt_table(msg[1], msg[2])
                        self._adopt_broadcast(
                            rec["view"], msg[1], msg[3],
                            f"worker {dst[1]}", out)

    def _push_targets(self, w: World, rec: dict,
                      rnd: int) -> List[int]:
        """Servers this sender still owes round ``rnd``: up, not yet
        pushed, round not already released without it (elastic release
        / restarted incarnation resumes past it), previous round
        released."""
        out = []
        for sid, srv in sorted(w.servers.items()):
            if not srv["up"] or (sid, rnd) in rec["pushed"]:
                continue
            if rnd in srv["released"]:
                continue
            if rnd > 1 and (rnd - 1) not in srv["released"]:
                continue
            out.append(sid)
        return out

    def _do_push(self, w, act, out):
        _, wid, inc, rnd = act
        rec = (w.workers[wid] if w.workers[wid]["inc"] == inc
               else w.zombies[(wid, inc)])
        targets = self._push_targets(w, rec, rnd)
        rec["pushed"] = frozenset(rec["pushed"]) | {
            (sid, rnd) for sid in targets}
        for sid in targets:
            w.enqueue(("w", wid, inc), ("s", sid),
                      ("PUSH", rnd, wid, inc, rec["view"].epoch))

    def _do_crash(self, w, act, out):
        w.workers[act[1]]["up"] = False
        w.used["crashes"] += 1

    def _do_partition(self, w, act, out):
        w.workers[act[1]]["zombie"] = True
        w.used["partitions"] += 1

    def _do_detect(self, w, act, out):
        wid = act[1]
        res = w.sched.declare_dead([wid])
        if res is None:
            return
        epoch, dead = res
        if "no_broadcast" in self.mut:
            return          # seeded removal of _broadcast_membership
        for dst in w.member_dsts():
            w.enqueue(SCHED, dst, ("DEAD", epoch, dead))

    def _do_rejoin(self, w, act, out):
        wid = act[1]
        rec = w.workers[wid]
        epoch = w.sched.revive(wid)
        if rec["zombie"]:
            # the old incarnation is still out there, still pushing
            w.zombies[(wid, rec["inc"])] = {
                "view": rec["view"], "pushed": rec["pushed"]}
        # new incarnation: registration hands it the current table
        w.workers[wid] = {"inc": rec["inc"] + 1, "up": True,
                          "zombie": False,
                          "view": MemberView(w.sched.epoch,
                                             w.sched.dead,
                                             w.sched.rejoin),
                          "pushed": frozenset()}
        w.used["rejoins"] += 1
        # _scheduler_register: table re-broadcast immediately followed
        # by the DEAD_NODE full-set broadcast on the same FIFO link —
        # modeled as one SYNC frame (a member processes the pair with
        # nothing of its own in between; both are idempotent)
        dead = frozenset(w.sched.dead)
        for dst in w.member_dsts():
            if dst == ("w", wid, rec["inc"] + 1):
                continue    # the newcomer got the table synchronously
            w.enqueue(SCHED, dst,
                      ("SYNC", epoch, frozenset([wid]), dead))

    def _do_dup(self, w, act, out):
        key = act[1]
        q = w.links[key]
        # a retransmit: the head frame is re-sent at the tail, so it
        # arrives AFTER broadcasts that were queued behind it
        w.links[key] = q + (q[0],)
        w.used["dups"] += 1

    def _do_tick(self, w, act, out):
        srv = w.servers[act[1]]
        srv["snap"] = srv["version"]
        w.used["ticks"] += 1

    def _do_crash_server(self, w, act, out):
        w.servers[act[1]]["up"] = False
        w.used["server_crashes"] += 1

    def _do_rejoin_server(self, w, act, out):
        srv = w.servers[act[1]]
        if "restore_snap_first" in self.mut:
            # seeded removal of the version comparison: the snapshot
            # file wins whenever it exists
            restored = srv["snap"] if srv["snap"] > 0 else srv["replica"]
        else:
            restored = max(srv["snap"], srv["replica"])
        if restored < srv["acked"]:
            out.append(Violation(
                "I4", f"server restored v{restored} after acking "
                      f"v{srv['acked']}"))
        srv["up"] = True
        srv["version"] = restored
        srv["ledger"] = {}
        srv["view"] = MemberView(w.sched.epoch, w.sched.dead,
                                 w.sched.rejoin)

    def _do_deliver(self, w, act, out):
        key = act[1]
        src, dst = key
        q = w.links[key]
        msg, w.links[key] = q[0], q[1:]
        if dst[0] == "w":
            _, wid, inc = dst
            rec = w.workers.get(wid)
            if (rec is None or rec["inc"] != inc or not rec["up"]
                    or rec["zombie"]):
                return      # lost: crashed / partitioned / superseded
            if msg[0] == "DEAD":
                self._adopt_broadcast(rec["view"], msg[1], msg[2],
                                      f"worker {wid}", out)
            elif msg[0] == "SYNC":
                rec["view"].adopt_table(msg[1], msg[2])
                self._adopt_broadcast(rec["view"], msg[1], msg[3],
                                      f"worker {wid}", out)
            return
        sid = dst[1]
        srv = w.servers[sid]
        if not srv["up"]:
            return          # lost: the rejoin re-registration resyncs
        if msg[0] == "DEAD":
            if self._adopt_broadcast(srv["view"], msg[1], msg[2],
                                     f"server {sid}", out) == "adopt":
                self._release_check(w, srv, out)
            return
        if msg[0] == "SYNC":
            changed = srv["view"].adopt_table(msg[1], msg[2])
            adopted = self._adopt_broadcast(srv["view"], msg[1],
                                            msg[3], f"server {sid}",
                                            out) == "adopt"
            if changed or adopted:
                self._release_check(w, srv, out)
            return
        # PUSH
        _, rnd, wid, inc, epoch = msg
        stale_dead = wid in srv["view"].dead
        stale_rejoin = epoch < srv["view"].rejoin.get(wid, 0)
        fenced = ((stale_dead and "no_dead_fence" not in self.mut)
                  or (stale_rejoin
                      and "no_rejoin_fence" not in self.mut))
        if fenced:
            rec = w.workers.get(wid)
            if (rec is not None and rec["inc"] == inc and rec["up"]
                    and not rec["zombie"]):
                # dropped WITHOUT ack: a live sender's resender keeps
                # retrying until the server's view catches up with the
                # revival (the fence is only a drop, never a nack) —
                # re-queue at the tail
                w.links[key] = w.links[key] + (msg,)
            # a zombie / superseded incarnation gives up when it
            # learns of its own death: the push is gone for good
            return
        if stale_dead:
            out.append(Violation(
                "I2", f"server {sid} aggregated a push from dead "
                      f"worker {wid}"))
        if stale_rejoin:
            out.append(Violation(
                "I5", f"server {sid} aggregated incarnation {inc} of "
                      f"worker {wid} past its rejoin fence "
                      f"(epoch {epoch} < "
                      f"{srv['view'].rejoin.get(wid, 0)})"))
        if rnd in srv["released"]:
            return          # late push to a completed round: re-acked
        srv["ledger"][rnd] = srv["ledger"].get(rnd, ()) + (
            (wid, inc, epoch),)
        self._release_check(w, srv, out)

    # -- terminal checks -------------------------------------------------

    def at_quiescence(self, w: World) -> List[Violation]:
        out: List[Violation] = []
        for sid, srv in sorted(w.servers.items()):
            if srv["up"] and srv["ledger"]:
                out.append(Violation(
                    "I3", f"server {sid} quiesced with open round(s) "
                          f"{sorted(srv['ledger'])} "
                          f"(view epoch {srv['view'].epoch})"))
        want = (w.sched.epoch, tuple(sorted(w.sched.dead)))
        for wid, rec in sorted(w.workers.items()):
            if rec["up"] and not rec["zombie"]:
                got = (rec["view"].epoch,
                       tuple(sorted(rec["view"].dead)))
                if got != want:
                    out.append(Violation(
                        "I6", f"worker {wid} quiesced at {got}, "
                              f"scheduler at {want}"))
        for sid, srv in sorted(w.servers.items()):
            if srv["up"]:
                got = (srv["view"].epoch,
                       tuple(sorted(srv["view"].dead)))
                if got != want:
                    out.append(Violation(
                        "I6", f"server {sid} quiesced at {got}, "
                              f"scheduler at {want}"))
        return out


# ---------------------------------------------------------------------------
# explorer
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Result:
    scenario: str
    mutations: tuple
    states: int
    transitions: int
    terminals: int
    violations: List[Violation]

    @property
    def invariants_hit(self) -> List[str]:
        return sorted({v.invariant for v in self.violations})

    def to_json(self) -> dict:
        return {"scenario": self.scenario,
                "mutations": list(self.mutations),
                "states": self.states,
                "transitions": self.transitions,
                "terminals": self.terminals,
                "invariants_hit": self.invariants_hit,
                "violations": [{"invariant": v.invariant,
                                "detail": v.detail}
                               for v in self.violations[:20]]}


def explore(scope: Scope, mutations: FrozenSet[str] = frozenset(),
            por: bool = True, scenario: str = "") -> Result:
    """Exhaustive DFS from the initial world. A violating branch is
    recorded and pruned (the protocol is already broken there);
    distinct (invariant, detail) pairs are kept."""
    model = Model(scope, mutations)
    root = World(scope)
    seen = {root.canon()}
    stack = [root]
    states = transitions = terminals = 0
    violations: List[Violation] = []
    vseen: set = set()

    def note(vs: Sequence[Violation]) -> None:
        for v in vs:
            if v.key() not in vseen:
                vseen.add(v.key())
                violations.append(v)

    while stack:
        w = stack.pop()
        states += 1
        if states > scope.max_states:
            raise ExplosionError(
                f"{scenario or 'scope'}: exceeded max_states="
                f"{scope.max_states}")
        acts = model.enabled(w)
        if not acts:
            terminals += 1
            note(model.at_quiescence(w))
            continue
        if por and all(a[0] == "deliver" for a in acts):
            # all-delivery states: deliveries to distinct destinations
            # commute (each mutates only its destination, none
            # enqueues), so expanding one destination suffices
            dst_min = min(a[1][1] for a in acts)
            acts = [a for a in acts if a[1][1] == dst_min]
        for act in acts:
            nxt, vs = model.apply(w, act)
            transitions += 1
            if vs:
                note(vs)
                continue    # prune: already off the protocol
            c = nxt.canon()
            if c not in seen:
                seen.add(c)
                stack.append(nxt)
    return Result(scenario, tuple(sorted(mutations)), states,
                  transitions, terminals, violations)


def run_clean(por: bool = True, only: Optional[str] = None,
              scenarios: Optional[Dict[str, Scope]] = None
              ) -> Dict[str, Result]:
    out = {}
    for name, scope in (scenarios or SCENARIOS).items():
        if only is not None and name != only:
            continue
        out[name] = explore(scope, frozenset(), por=por, scenario=name)
    return out


def run_mutants(por: bool = True,
                scenarios: Optional[Dict[str, Scope]] = None
                ) -> Dict[str, Tuple[Result, str]]:
    """Each mutant explored under its scenario; the caller checks the
    hit-set equals exactly {expected}."""
    scenarios = scenarios or SCENARIOS
    out = {}
    for name, (flag, scenario, expected) in MUTANTS.items():
        res = explore(scenarios[scenario], frozenset([flag]), por=por,
                      scenario=scenario)
        out[name] = (res, expected)
    return out


# ---------------------------------------------------------------------------
# replay: flight-recorder dumps through the model's monotonicity checks
# ---------------------------------------------------------------------------


def replay_events(events: Sequence[dict]) -> List[str]:
    """Offline conformance over one dump's event ring: per-peer wire
    epochs never regress, scheduler declare_dead epochs strictly
    increase, and the recorded dead set only shrinks on a revival
    (exactly what the runtime sanitizer latches live)."""
    problems: List[str] = []
    wire_epoch: Dict[int, int] = {}
    decl_epoch = 0
    for ev in sorted(events, key=lambda e: e.get("seq", 0)):
        kind = ev.get("kind")
        if kind in ("sent", "recv"):
            peer = ev.get("peer")
            epoch = ev.get("epoch") or 0
            if peer is None or epoch <= 0:
                continue
            last = wire_epoch.get(peer, 0)
            if kind == "recv" and epoch < last:
                problems.append(
                    f"seq {ev.get('seq')}: recv from peer {peer} at "
                    f"epoch {epoch} after seeing {last}")
            wire_epoch[peer] = max(last, epoch)
        elif kind == "membership" \
                and ev.get("event") == "declare_dead":
            epoch = ev.get("epoch") or 0
            if epoch <= decl_epoch:
                problems.append(
                    f"seq {ev.get('seq')}: declare_dead epoch {epoch} "
                    f"not above {decl_epoch}")
            decl_epoch = max(decl_epoch, epoch)
    return problems


def replay_paths(paths: Sequence[Path]) -> dict:
    """Replay every ``flightrec_*.json`` under the given files/dirs."""
    files: List[Path] = []
    for p in paths:
        if p.is_dir():
            files.extend(sorted(p.glob("flightrec_*.json")))
        elif p.exists():
            files.append(p)
    report = {"files": [], "violations": 0}
    for f in files:
        try:
            dump = json.loads(f.read_text(encoding="utf-8"))
        except (OSError, ValueError) as exc:
            report["files"].append({"path": str(f),
                                    "error": str(exc)})
            continue
        problems = replay_events(dump.get("events", []))
        report["violations"] += len(problems)
        report["files"].append({"path": str(f),
                                "node": dump.get("node"),
                                "events": len(dump.get("events", [])),
                                "problems": problems})
    return report


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.modelcheck",
        description="small-scope exploration of the geomx-statecheck "
                    "protocol model (docs/static-analysis.md)")
    ap.add_argument("--scenario", default=None,
                    help="explore one scenario from: %s"
                         % ",".join(SCENARIOS))
    ap.add_argument("--mutants", action="store_true",
                    help="run the mutation suite: each seeded fence "
                         "removal must trip exactly its invariant")
    ap.add_argument("--replay", nargs="+", metavar="PATH",
                    help="replay flightrec_*.json dumps (files or "
                         "dirs) through the model's conformance "
                         "checks instead of exploring")
    ap.add_argument("--no-por", action="store_true",
                    help="disable partial-order reduction")
    ap.add_argument("--max-states", type=int, default=None,
                    help="override the per-scenario state cap")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable results")
    args = ap.parse_args(argv)
    por = not args.no_por

    if args.replay:
        report = replay_paths([Path(p) for p in args.replay])
        if args.json:
            print(json.dumps(report, indent=1))
        else:
            for f in report["files"]:
                tag = (f"ERROR {f['error']}" if "error" in f else
                       f"{f['events']} events, "
                       f"{len(f['problems'])} problem(s)")
                print(f"{f['path']}: {tag}")
                for p in f.get("problems", []):
                    print(f"  VIOLATION {p}")
            print(f"{len(report['files'])} dump(s), "
                  f"{report['violations']} violation(s)")
        return 1 if report["violations"] else 0

    if args.scenario is not None and args.scenario not in SCENARIOS:
        print(f"unknown scenario: {args.scenario}", file=sys.stderr)
        return 2

    scenarios = SCENARIOS
    if args.max_states is not None:
        scenarios = {n: dataclasses.replace(s,
                                            max_states=args.max_states)
                     for n, s in SCENARIOS.items()}

    rc = 0
    payload = {"clean": {}, "mutants": {}}
    if not args.mutants:
        for name, res in run_clean(por=por, only=args.scenario,
                                   scenarios=scenarios).items():
            ok = not res.violations
            payload["clean"][name] = res.to_json()
            if not args.json:
                print(f"{'OK  ' if ok else 'FAIL'} {name}: "
                      f"{res.states} states, {res.transitions} "
                      f"transitions, {res.terminals} terminal(s)"
                      + ("" if ok else
                         f" — invariants {res.invariants_hit}"))
                for v in res.violations[:5]:
                    print(f"      {v.invariant}: {v.detail}")
            if not ok:
                rc = 1
    if args.mutants:
        for name, (res, expected) in run_mutants(
                por=por, scenarios=scenarios).items():
            hit = res.invariants_hit
            ok = hit == [expected]
            payload["mutants"][name] = {**res.to_json(),
                                        "expected": expected,
                                        "ok": ok}
            if not args.json:
                print(f"{'OK  ' if ok else 'FAIL'} mutant {name}: "
                      f"expected [{expected}] tripped {hit} "
                      f"({res.states} states)")
            if not ok:
                rc = 1
    if args.json:
        print(json.dumps(payload, indent=1))
    return rc


if __name__ == "__main__":
    sys.exit(main())
