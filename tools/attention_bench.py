#!/usr/bin/env python
"""Flash vs dense attention sweep on the local accelerator.

Prints a JSON line per (T, D, causal) config with forward and
forward+backward wall times for the XLA dense einsum and the Pallas
FlashAttention-2 kernels (geomx_tpu.ops.flash_attention). Run on TPU;
on CPU the flash path is interpret-mode (correctness only) and is
skipped unless --force-cpu.

    python tools/attention_bench.py --seqs 512,1024,2048,4096
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _time(fn, q, k, v, iters=20):
    """Value-fenced timing (round-5: block_until_ready on the axon
    tunnel platform returns WITHOUT waiting — tools/chip_sanity.py
    blocking probe — which is how r4 published impossible numbers).
    Iterations thread the output back into q so the dispatched chain is
    data-dependent end to end, and the clock stops on a SCALAR fetch of
    the last output; the fetch round-trip is measured separately and
    subtracted."""
    import jax
    import jax.numpy as jnp

    def _head(out):
        return out[0] if isinstance(out, tuple) else out

    def _fence(x):
        return float(jnp.sum(x.astype(jnp.float32)))

    x = _head(fn(q, k, v))
    _fence(x)                                   # warm compile + fence
    t0 = time.perf_counter()
    _fence(x)                                   # already computed:
    rtt = time.perf_counter() - t0              # pure fetch round-trip
    t0 = time.perf_counter()
    for _ in range(iters):
        x = _head(fn(x, k, v))
    _fence(x)
    return max(time.perf_counter() - t0 - rtt, 1e-9) / iters * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", type=str, default="512,1024,2048,4096")
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--force-cpu", action="store_true")
    ap.add_argument("--sweep-blocks", action="store_true",
                    help="sweep flash block_q x block_k per seq len and "
                         "report the fastest fwd+bwd combo vs dense")
    ap.add_argument("--blocks", type=str, default="128,256,512",
                    help="candidate block sizes for --sweep-blocks")
    args = ap.parse_args()

    import jax

    if args.force_cpu:
        # the axon plugin ignores JAX_PLATFORMS; pin before any device
        # query (a dead tunnel otherwise hangs backend init)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from geomx_tpu.models.transformer import dense_attention
    from geomx_tpu.ops.flash_attention import flash_attention

    if jax.default_backend() != "tpu" and not args.force_cpu:
        print("not on TPU (flash would run interpret-mode); "
              "--force-cpu to override", file=sys.stderr)
        return

    B, H, D = args.batch, args.heads, args.head_dim
    for T in [int(s) for s in args.seqs.split(",")]:
        q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (B, T, H, D),
                                     jnp.bfloat16) for i in range(3))

        dense_f = jax.jit(lambda q, k, v: dense_attention(q, k, v))
        flash_f = jax.jit(lambda q, k, v: flash_attention(q, k, v))
        dense_g = jax.jit(jax.grad(
            lambda q, k, v: dense_attention(q, k, v).astype(
                jnp.float32).sum(), argnums=(0, 1, 2)))
        flash_g = jax.jit(jax.grad(
            lambda q, k, v: flash_attention(q, k, v).astype(
                jnp.float32).sum(), argnums=(0, 1, 2)))

        row = {"T": T, "B": B, "H": H, "D": D, "causal": True,
               "dense_fwd_ms": round(_time(dense_f, q, k, v), 3),
               "flash_fwd_ms": round(_time(flash_f, q, k, v), 3),
               "dense_fwdbwd_ms": round(_time(dense_g, q, k, v), 3),
               "flash_fwdbwd_ms": round(_time(flash_g, q, k, v), 3)}
        row["fwd_speedup"] = round(
            row["dense_fwd_ms"] / row["flash_fwd_ms"], 2)
        row["fwdbwd_speedup"] = round(
            row["dense_fwdbwd_ms"] / row["flash_fwdbwd_ms"], 2)
        print(json.dumps(row), flush=True)

        if not args.sweep_blocks:
            continue
        # block-size sweep: the fwd+bwd time is what a train step pays
        cands = [int(b) for b in args.blocks.split(",")]
        best = None
        for bq in cands:
            for bk in cands:
                if bq > T or bk > T:
                    continue
                fg = jax.jit(jax.grad(
                    lambda q, k, v, _bq=bq, _bk=bk: flash_attention(
                        q, k, v, block_q=_bq, block_k=_bk).astype(
                        jnp.float32).sum(), argnums=(0, 1, 2)))
                try:
                    ms = _time(fg, q, k, v, iters=10)
                except Exception as e:  # noqa: BLE001 — report and move on
                    print(json.dumps({"T": T, "block_q": bq,
                                      "block_k": bk,
                                      "error": str(e)[:200]}), flush=True)
                    continue
                print(json.dumps({"T": T, "block_q": bq, "block_k": bk,
                                  "flash_fwdbwd_ms": round(ms, 3)}),
                      flush=True)
                if best is None or ms < best[0]:
                    best = (ms, bq, bk)
        if best:
            print(json.dumps({
                "T": T, "best_block_q": best[1], "best_block_k": best[2],
                "best_flash_fwdbwd_ms": round(best[0], 3),
                "dense_fwdbwd_ms": row["dense_fwdbwd_ms"],
                "best_speedup": round(
                    row["dense_fwdbwd_ms"] / best[0], 2)}), flush=True)


if __name__ == "__main__":
    main()
