"""geomx_top: live terminal dashboard over the cluster health board.

``geomx_tpu.ps.linkstate.ClusterHealthBoard`` exports one JSON board
per scheduler into ``GEOMX_HEALTH_DIR`` each time the cluster round
clock advances (``board_<node>_round<N>.json``). This tool renders the
freshest board per scheduler as a top(1)-style screen — node liveness /
round progress / straggler flags, per-link RTT/bandwidth/loss, and the
recent anomaly events — refreshing in place until interrupted.

Usage::

    python -m tools.geomx_top /tmp/health            # live view
    python -m tools.geomx_top /tmp/health --once     # one frame, no ANSI
    python -m tools.geomx_top /tmp/health --once --json   # raw boards

With no directory argument the ``GEOMX_HEALTH_DIR`` environment
variable is used.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
from typing import Dict, List, Optional, Tuple

_BOARD_RE = re.compile(r"^board_(?P<node>.+)_round(?P<round>\d+)\.json$")
_PLAN_RE = re.compile(r"^plan_(?P<tier>[^_]+)_(?P<node>.+)\.json$")


def find_boards(health_dir: str) -> Dict[str, Tuple[int, str]]:
    """Freshest export per scheduler node: {node: (round, path)}."""
    latest: Dict[str, Tuple[int, str]] = {}
    try:
        names = os.listdir(health_dir)
    except OSError:
        return latest
    for name in names:
        m = _BOARD_RE.match(name)
        if m is None:
            continue
        node, rnd = m.group("node"), int(m.group("round"))
        if node not in latest or rnd > latest[node][0]:
            latest[node] = (rnd, os.path.join(health_dir, name))
    return latest


def load_boards(health_dir: str) -> List[dict]:
    """Parse the freshest board per scheduler, skipping torn reads
    (exports are atomic renames, so a parse failure means the file
    vanished mid-scan — the next refresh gets it)."""
    boards = []
    for node, (_rnd, path) in sorted(find_boards(health_dir).items()):
        try:
            with open(path, "r") as f:
                boards.append(json.load(f))
        except (OSError, ValueError):
            continue
    return boards


def load_plans(health_dir: str) -> Dict[Tuple[str, int], dict]:
    """Active transport plans: the controller (kvstore/controller.py)
    exports ``plan_<tier>_<node>.json`` atomically alongside the board
    files. Keyed {(tier, src_node_id): plan dict} — local and global
    van ids overlap, so the tier disambiguates."""
    plans: Dict[Tuple[str, int], dict] = {}
    try:
        names = os.listdir(health_dir)
    except OSError:
        return plans
    for name in names:
        if _PLAN_RE.match(name) is None:
            continue
        try:
            with open(os.path.join(health_dir, name), "r") as f:
                doc = json.load(f)
            plans[(str(doc["tier"]), int(doc["node"]))] = doc
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return plans


def _plan_cell(plans: Dict[int, dict], link_name: str) -> str:
    """Controller decision for one board link row ("src>dst"): the
    assigned codec + last decision reason from src's exported plan."""
    src, _, dst = link_name.partition(">")
    try:
        plan = plans.get(int(src))
    except ValueError:
        return ""
    if plan is None:
        return ""
    lk = (plan.get("links") or {}).get(dst)
    if lk is None:
        return ""
    codec = lk.get("codec") or "static"
    cell = f"{codec}[{lk.get('reason', '')}]"
    return cell


def _bar(value: float, full: float, width: int = 10) -> str:
    if full <= 0:
        return " " * width
    n = max(0, min(width, int(round(width * value / full))))
    return "#" * n + "." * (width - n)


def render_board(board: dict, now: Optional[float] = None,
                 plans: Optional[Dict[Tuple[str, int], dict]] = None
                 ) -> str:
    """One board as a text block (pure function: tested directly).
    ``plans`` (from :func:`load_plans`) adds the active TransportPlan —
    per-link codec + decision reason next to the link rows, plus each
    sender's live slice budget. Only plans from this board's tier
    apply (van ids overlap across tiers)."""
    tier = str(board.get("tier", ""))
    plans = {n: p for (t, n), p in (plans or {}).items() if t == tier}
    out: List[str] = []
    counts = board.get("event_counts", {})
    badge = ("  !! " + " ".join(f"{k}={v}" for k, v in sorted(counts.items()))
             if counts else "")
    out.append(f"== {board.get('tier', '?')} board @ {board.get('node', '?')}"
               f"  round={board.get('max_round', -1)}"
               f"  v{board.get('version', 0)}{badge}")
    nodes = board.get("nodes", {})
    if nodes:
        out.append("  node      round  epoch   age_s  flags")
        for n in sorted(nodes, key=lambda s: int(s) if s.isdigit() else 0):
            st = nodes[n]
            flags = "STRAGGLER" if st.get("straggler") else ""
            out.append(f"  {n:>6}  {st.get('round', -1):>7}"
                       f"  {st.get('epoch', 0):>5}"
                       f"  {st.get('age_s', 0.0):>6.1f}  {flags}")
    links = board.get("links", {})
    if links:
        peak = max((lk.get("bw_mbps", 0.0) for lk in links.values()),
                   default=0.0)
        out.append("  link        rtt_ms   bw_mbps  "
                   + "bw".ljust(10) + "  rtx  gu  plan            flags")
        for name in sorted(links):
            lk = links[name]
            flags = "DEGRADED" if lk.get("degraded") else ""
            out.append(
                f"  {name:>8}  {lk.get('rtt_ms', 0.0):>8.1f}"
                f"  {lk.get('bw_mbps', 0.0):>8.1f}"
                f"  {_bar(lk.get('bw_mbps', 0.0), peak)}"
                f"  {lk.get('rtx', 0):>3}  {lk.get('give_ups', 0):>2}"
                f"  {_plan_cell(plans, name):<14}  {flags}")
    if plans:
        slices = [(n, p.get("slice_bytes", 0), p.get("round", -1))
                  for n, p in sorted(plans.items())
                  if p.get("slice_bytes")]
        if slices:
            out.append("  transport plan slice budgets:")
            for n, sb, rnd in slices:
                out.append(f"    node {n}: {sb // 1024} KB/chunk "
                           f"(round {rnd})")
    events = board.get("events", [])
    if events:
        out.append("  recent events:")
        for ev in events[-8:]:
            fields = " ".join(f"{k}={v}" for k, v in ev.items()
                              if k not in ("kind", "t"))
            out.append(f"    t+{ev.get('t', 0.0):<8.1f}"
                       f" {ev.get('kind', '?'):<14} {fields}")
    return "\n".join(out)


def render_screen(boards: List[dict], health_dir: str) -> str:
    head = (f"geomx_top — {health_dir} — "
            f"{time.strftime('%H:%M:%S')} — {len(boards)} board(s)")
    if not boards:
        return (head + "\n  (no board_*.json yet — is GEOMX_HEALTH=1 "
                "and GEOMX_HEALTH_DIR set on the scheduler?)")
    plans = load_plans(health_dir)
    return "\n\n".join([head] + [render_board(b, plans=plans)
                                 for b in boards])


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="live dashboard over GEOMX_HEALTH_DIR board exports")
    ap.add_argument("health_dir", nargs="?",
                    default=os.environ.get("GEOMX_HEALTH_DIR", ""),
                    help="board export dir (default: $GEOMX_HEALTH_DIR)")
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit (no ANSI)")
    ap.add_argument("--json", action="store_true",
                    help="with --once: dump the raw board dicts as JSON")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="refresh period in seconds (default 1.0)")
    args = ap.parse_args(argv)
    if not args.health_dir:
        ap.error("no health dir: pass one or set GEOMX_HEALTH_DIR")
    if args.json and not args.once:
        ap.error("--json requires --once")
    try:
        if args.once:
            boards = load_boards(args.health_dir)
            if args.json:
                plans = {f"{t}:{n}": p for (t, n), p
                         in load_plans(args.health_dir).items()}
                print(json.dumps({"boards": boards, "plans": plans},
                                 indent=2))
            else:
                print(render_screen(boards, args.health_dir))
            return 0 if boards else 1
        while True:
            frame = render_screen(load_boards(args.health_dir),
                                  args.health_dir)
            # home + clear-below keeps the refresh flicker-free
            sys.stdout.write("\x1b[H\x1b[2J" + frame + "\n")
            sys.stdout.flush()
            time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        return 0
    except BrokenPipeError:
        # downstream pager/head closed the pipe — a normal way out
        try:
            sys.stdout.close()
        except OSError:
            pass
        return 0


if __name__ == "__main__":
    sys.exit(main())
