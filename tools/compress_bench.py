#!/usr/bin/env python
"""Microbench: host (numpy) vs device (jax) compression kernels.

The WAN hop this framework exists to optimize compresses the party
aggregate every global round; for real model sizes the compress time
competes with the transfer itself (round-2 verdict, missing #1). And
the quantized combined wire (compression.device) packs EVERY round's
gradients, so pack throughput per codec (fp16 cast, 2-bit residual
quantize, BSC top-k) is a first-class number: bench.py's ``compress``
phase embeds it in BENCH_*.json via :func:`run_compress_bench`.

Prints one JSON line per size with host/device times, pack throughput
(MB/s of fp32 input consumed) and speedups; ``--json`` emits a single
machine-readable document instead.

Usage: python tools/compress_bench.py [--sizes 262144,1048576,8388608]
                                      [--json]
       GEOMX_BENCH_PLATFORM=cpu to force the device path onto CPU.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, repeat=5):
    fn()  # warmup / compile
    ts = []
    for _ in range(repeat):
        t0 = time.perf_counter()
        out = fn()
        ts.append(time.perf_counter() - t0)
    return min(ts), out


def _mbps(nbytes: int, secs: float) -> float:
    return round(nbytes / max(secs, 1e-12) / 1e6, 1)


def run_compress_bench(sizes, threshold: float = 0.01,
                       repeat: int = 5):
    """Host-vs-device pack benchmark for each codec of the quantized
    wire; returns one result dict per size (the ``--json`` document's
    ``results`` and bench.py's ``compress`` phase payload). Device
    timings include the D2H of the packed wire payload — the number
    that matters is bytes-ready-to-send, exactly like the server and
    combined-wire paths."""
    import jax
    import jax.numpy as jnp

    from geomx_tpu import compression as host
    from geomx_tpu import ops

    results = []
    for n in sizes:
        rng = np.random.default_rng(0)
        grad = rng.normal(size=n).astype(np.float32)
        nbytes = grad.nbytes
        dg = jnp.asarray(grad)

        # fp16: the half-width cast (wire codec "fp16")
        t_hf, _ = timeit(lambda: grad.astype(np.float16), repeat)
        t_df, _ = timeit(lambda: np.asarray(dg.astype(jnp.float16)),
                         repeat)

        # 2-bit with error-feedback residual (wire codec "2bit")
        hres = np.zeros(n, np.float32)
        t_h2, _ = timeit(
            lambda: host.two_bit_quantize(grad, hres, 0.5), repeat)
        dres = jnp.zeros(n, jnp.float32)

        def dev2():
            packed, _r = ops.two_bit_quantize(dg, dres, 0.5)
            return np.asarray(packed)

        t_d2, _ = timeit(dev2, repeat)

        # BSC top-k (server WAN compressor / "bsc16" sparse wire)
        hu, hv = np.zeros(n, np.float32), np.zeros(n, np.float32)
        t_hb, _ = timeit(
            lambda: host.bsc_compress(grad, hu, hv, threshold), repeat)
        du = jnp.zeros(n, jnp.float32)
        dv = jnp.zeros(n, jnp.float32)

        def devb():
            vals, idx, _u, _v = ops.bsc_compress(dg, du, dv, threshold)
            return np.asarray(vals), np.asarray(idx)

        t_db, _ = timeit(devb, repeat)

        results.append({
            "size": n,
            "backend": jax.default_backend(),
            "fp16_host_ms": round(t_hf * 1e3, 3),
            "fp16_device_ms": round(t_df * 1e3, 3),
            "fp16_host_mbps": _mbps(nbytes, t_hf),
            "fp16_device_mbps": _mbps(nbytes, t_df),
            "fp16_speedup": round(t_hf / t_df, 2),
            "2bit_host_ms": round(t_h2 * 1e3, 3),
            "2bit_device_ms": round(t_d2 * 1e3, 3),
            "2bit_host_mbps": _mbps(nbytes, t_h2),
            "2bit_device_mbps": _mbps(nbytes, t_d2),
            "2bit_speedup": round(t_h2 / t_d2, 2),
            "bsc_host_ms": round(t_hb * 1e3, 3),
            "bsc_device_ms": round(t_db * 1e3, 3),
            "bsc_host_mbps": _mbps(nbytes, t_hb),
            "bsc_device_mbps": _mbps(nbytes, t_db),
            "bsc_speedup": round(t_hb / t_db, 2),
        })
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="262144,1048576,8388608")
    ap.add_argument("--threshold", type=float, default=0.01)
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON document instead of per-size "
                         "lines (machine-readable; what bench.py embeds)")
    args = ap.parse_args()

    plat = os.environ.get("GEOMX_BENCH_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)

    import jax

    sizes = [int(s) for s in args.sizes.split(",")]
    results = run_compress_bench(sizes, args.threshold)
    if args.json:
        print(json.dumps({"backend": jax.default_backend(),
                          "threshold": args.threshold,
                          "results": results}))
        return
    for r in results:
        print(json.dumps(r))


if __name__ == "__main__":
    main()
