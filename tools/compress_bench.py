#!/usr/bin/env python
"""Microbench: host (numpy) vs device (jax) compression kernels.

The WAN hop this framework exists to optimize compresses the party
aggregate every global round; for real model sizes the compress time
competes with the transfer itself (round-2 verdict, missing #1). Prints
one JSON line per size with host/device times and speedup.

Usage: python tools/compress_bench.py [--sizes 262144,1048576,8388608]
       GEOMX_BENCH_PLATFORM=cpu to force the device path onto CPU.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, repeat=5):
    fn()  # warmup / compile
    ts = []
    for _ in range(repeat):
        t0 = time.perf_counter()
        out = fn()
        ts.append(time.perf_counter() - t0)
    return min(ts), out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="262144,1048576,8388608")
    ap.add_argument("--threshold", type=float, default=0.01)
    args = ap.parse_args()

    plat = os.environ.get("GEOMX_BENCH_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)

    import jax

    from geomx_tpu import compression as host
    from geomx_tpu import ops

    for n in [int(s) for s in args.sizes.split(",")]:
        rng = np.random.default_rng(0)
        grad = rng.normal(size=n).astype(np.float32)

        # host BSC
        hu, hv = np.zeros(n, np.float32), np.zeros(n, np.float32)
        t_host, _ = timeit(lambda: host.bsc_compress(
            grad, hu, hv, args.threshold))

        # device BSC (state resident on device; includes wire transfer
        # of the compressed pair back to host, as the server path does)
        import jax.numpy as jnp

        du = jnp.zeros(n, jnp.float32)
        dv = jnp.zeros(n, jnp.float32)
        dg = jnp.asarray(grad)

        def dev():
            vals, idx, _u, _v = ops.bsc_compress(dg, du, dv, args.threshold)
            return np.asarray(vals), np.asarray(idx)

        t_dev, _ = timeit(dev)

        # 2-bit
        hres = np.zeros(n, np.float32)
        t_host2, _ = timeit(lambda: host.two_bit_quantize(grad, hres, 0.5))
        dres = jnp.zeros(n, jnp.float32)

        def dev2():
            packed, _r = ops.two_bit_quantize(dg, dres, 0.5)
            return np.asarray(packed)

        t_dev2, _ = timeit(dev2)

        print(json.dumps({
            "size": n,
            "backend": jax.default_backend(),
            "bsc_host_ms": round(t_host * 1e3, 3),
            "bsc_device_ms": round(t_dev * 1e3, 3),
            "bsc_speedup": round(t_host / t_dev, 2),
            "2bit_host_ms": round(t_host2 * 1e3, 3),
            "2bit_device_ms": round(t_dev2 * 1e3, 3),
            "2bit_speedup": round(t_host2 / t_dev2, 2),
        }))


if __name__ == "__main__":
    main()
