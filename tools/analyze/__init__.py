"""geomx-lint: project-native static analysis for geomx_tpu.

AST passes over the tree (no imports of the analyzed code, no
process spawns — safe to run anywhere, including CI on a box with no
accelerator):

- **concurrency** (GX-L0xx): lock inventory, per-class lock-acquisition
  graph, order inversions, unguarded writes to guarded attributes,
  blocking calls under a lock, re-entrant ``Lock`` acquisition.
- **traced** (GX-J1xx): hazards in code reachable from
  ``jax.jit``/``pjit``/``shard_map``: implicit host syncs, per-call
  retrace patterns, missing ``donate_argnums`` on train steps.
- **config-drift** (GX-C2xx): env_* registrations vs raw ``os.environ``
  reads vs docs/env-var-summary.md vs scripts/*.sh.
- **protocol** (GX-P3xx): the wire-protocol model — Control verb
  send/dispatch consistency, droppable requests, bare-key response
  routing, unfenced countdown mutations, static-count countdowns, and
  the binary-meta schema lock.
- **metrics** (GX-M4xx): raw ``profiler.instant``/``profiler.counter``
  calls outside the telemetry funnel (geomx_tpu/telemetry.py) — events
  the metrics registry would silently miss.
- **lockmodel** (GX-L005..L007): the geomx-racecheck shared model —
  lock inventory + ``@guarded_by`` declarations frozen into
  ``tools/analyze/locks.lock.json`` (drift fails GX-L007, the runtime
  witness in ``geomx_tpu/ps/locks.py`` loads the same json), unguarded
  multi-thread-root writes, ``Condition.wait`` outside a while loop.
- **statemodel** (GX-S501..S504): the geomx-statecheck shared model —
  the membership/epoch/recovery/round-release state machine as an
  executable model plus per-transition code anchors frozen into
  ``tools/analyze/state.lock.json`` (drift fails GX-S501; the small-
  scope explorer ``tools/modelcheck.py`` and the runtime conformance
  sanitizer ``geomx_tpu/ps/conformance.py`` run the SAME model),
  out-of-transition state mutations, unrealized transitions, dropped
  ``is_stale``/live-view/epoch fences.

Run ``python -m tools.analyze`` from the repo root; see
docs/static-analysis.md for the rule catalogue, baseline workflow and
suppression syntax.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Sequence

from .core import (Finding, SEV_ERROR, SEV_WARNING, SourceFile,
                   apply_suppressions, load_baseline, load_sources,
                   save_baseline, sort_findings, split_by_baseline)
from .concurrency import run_concurrency
from .config_drift import run_config_drift
from .lockmodel import run_lockmodel, write_lock_model
from .metrics import run_metrics
from .protocol import run_protocol, write_binmeta_lock
from .statemodel import run_statemodel, write_state_model
from .traced import run_traced

__all__ = [
    "Finding", "SEV_ERROR", "SEV_WARNING", "SourceFile",
    "run_concurrency", "run_traced", "run_config_drift", "run_protocol",
    "run_metrics", "run_lockmodel", "run_statemodel", "run_all",
    "write_binmeta_lock", "write_lock_model", "write_state_model",
    "load_baseline", "save_baseline", "split_by_baseline",
    "sort_findings", "pass_fingerprints", "DEFAULT_BASELINE",
]

DEFAULT_BASELINE = Path(__file__).with_name("baseline.json")

PASSES = {
    "concurrency": lambda sources, root: run_concurrency(sources),
    "traced": lambda sources, root: run_traced(sources),
    "config-drift": run_config_drift,
    "protocol": run_protocol,
    "metrics": lambda sources, root: run_metrics(sources),
    "lockmodel": run_lockmodel,
    "statemodel": run_statemodel,
}


def pass_fingerprints(sources, root) -> dict:
    """One short fingerprint per pass model, so CI can diff a single
    ``--json`` stream across runs: a changed fingerprint means the
    extracted surface that pass reasons about (lock inventory, traced
    entry set, env-knob registry, wire schema, metric funnel, protocol
    state machine) changed — findings or not."""
    from .concurrency import concurrency_surface
    from .config_drift import config_drift_surface
    from .lockmodel import extract_lock_model, model_fingerprint
    from .metrics import metrics_surface
    from .protocol import extract_meta_schema, meta_schema_fingerprint
    from .statemodel import extract_state_model, state_model_fingerprint
    from .traced import traced_surface

    def _fp(surface) -> str:
        return model_fingerprint(surface)

    schema = extract_meta_schema(sources)
    return {
        "concurrency": _fp(concurrency_surface(sources)),
        "traced": _fp(traced_surface(sources)),
        "config-drift": _fp(config_drift_surface(sources, Path(root))),
        "protocol": (meta_schema_fingerprint(schema[3])[:16]
                     if schema is not None else ""),
        "metrics": _fp(metrics_surface(sources)),
        "lockmodel": _fp(extract_lock_model(sources)),
        "statemodel": _fp(extract_state_model(sources)),
    }


def run_all(paths: Sequence[Path], root: Path,
            passes: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the selected passes (default: all) and return suppressed-
    filtered, sorted findings. Syntax errors in analyzed files surface
    as GX-E000 findings rather than crashing the run."""
    sources = load_sources([Path(p) for p in paths], Path(root))
    findings: List[Finding] = []
    for src in sources:
        if src.parse_error is not None:
            findings.append(Finding(
                "GX-E000", SEV_ERROR, src.rel,
                src.parse_error.lineno or 0, symbol="<parse>",
                message=f"syntax error: {src.parse_error.msg}"))
    for name in (passes or list(PASSES)):
        findings += PASSES[name](sources, Path(root))
    return sort_findings(apply_suppressions(findings, sources))
