"""Traced-code pass: hazards in functions reachable from jit/pjit/shard_map.

Rules
-----
GX-J101 (error)   implicit host sync inside traced code: ``float()``/
                  ``int()``/``bool()`` on a traced value, ``np.asarray``/
                  ``np.array``, ``.item()``/``.tolist()``/``.numpy()``,
                  ``jax.device_get``. Each forces the tracer to the host —
                  a ConcretizationTypeError at best, a silent device->host
                  transfer and pipeline bubble at worst.
GX-J102 (warning) recompilation hazard: a fresh ``jax.jit(...)`` created
                  inside a loop, or created-and-immediately-called
                  (``jax.jit(f)(x)``) — the cache keys on function
                  identity, so every iteration/call retraces.
GX-J103 (warning) train-step-shaped jitted function (name contains
                  ``step``/``update``, returns its own parameter state)
                  without ``donate_argnums`` — the old parameter buffers
                  stay live across the update, doubling peak memory.
GX-J104 (error)   host transfer on a mesh rank's round path: round-shaped
                  methods (name contains ``step``/``push``/``pull``/
                  ``round``) of Mesh-named classes — closed over
                  same-module calls — calling ``np.asarray``/``np.array``/
                  ``jax.device_get``/``.addressable_data`` outside an
                  ``is_global_worker`` guard. In the mesh-party tier
                  (kvstore.mesh_party) only the party's global worker may
                  materialize host arrays; an unguarded transfer makes
                  EVERY mesh rank fetch device data it must never touch.
GX-J105 (error)   host transfer inside a mesh codec: codec-shaped methods
                  (name contains ``reduce``/``quant``/``encode``/
                  ``decode``/``hop``/``reset``/``zero``/``residual``) of
                  Ring/MeshCodec-named classes — closed over same-module
                  calls — calling the same host-transfer set outside an
                  ``is_global_worker`` guard. The quantized ring
                  (parallel.quant_collectives) runs on EVERY rank of the
                  party and its residual streams are device-resident by
                  design; a host materialization there stalls all ranks
                  every round. NOT the van wire codec (compression.device
                  ``WireCodec``): host arrays are that codec's product,
                  and only the global worker drives it.

Reachability: seeds are functions decorated with (or wrapped by a call
to) ``jax.jit``/``jit``/``pjit``/``jax.shard_map``/``shard_map`` —
including ``functools.partial(jax.jit, ...)`` forms — closed over
same-module calls (``f(...)`` to a module/local function, ``self.m(...)``
to a sibling method). Arguments whose expression involves
``.shape``/``.ndim``/``.size``/``.dtype``/``len()`` are static under
tracing and never flagged.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SEV_ERROR, SEV_WARNING, SourceFile, call_name

_JIT_NAMES = {
    "jax.jit", "jit", "pjit", "jax.pjit", "jax.experimental.pjit.pjit",
    "jax.shard_map", "shard_map", "jax.experimental.shard_map.shard_map",
}
_PARTIAL_NAMES = {"partial", "functools.partial"}
_HOST_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray",
                    "numpy.array", "onp.asarray", "onp.array",
                    "jax.device_get", "device_get"}
_HOST_SYNC_METHODS = (".item", ".tolist", ".numpy", ".block_until_ready")
_SCALAR_CASTS = {"float", "int", "bool", "complex"}
_STEP_NAME_RE = re.compile(r"step|update", re.IGNORECASE)
_MESH_ROUND_RE = re.compile(r"step|push|pull|round", re.IGNORECASE)
_RING_CLS_RE = re.compile(r"Ring|MeshCodec|MeshQuant")
_RING_CODEC_RE = re.compile(
    r"reduce|quant|encode|decode|hop|reset|zero|residual", re.IGNORECASE)
_HOST_XFER_METHODS = (".addressable_data",)


def _jit_target(node: ast.Call) -> Tuple[Optional[ast.AST], bool]:
    """(wrapped-function expr, is_jit_call) for ``jax.jit(f, ...)`` and
    ``partial(jax.jit, f)`` forms; (None, True) for a jit call whose
    target is not a simple reference (lambda, call result, …)."""
    name = call_name(node.func)
    if name in _JIT_NAMES:
        return (node.args[0] if node.args else None), True
    if name in _PARTIAL_NAMES and node.args:
        if call_name(node.args[0]) in _JIT_NAMES:
            return (node.args[1] if len(node.args) > 1 else None), True
    return None, False


def _has_donate(node: ast.Call) -> bool:
    return any(kw.arg and kw.arg.startswith("donate")
               for kw in node.keywords)


def _is_static_expr(node: ast.AST) -> bool:
    """True when the expression is compile-time static under tracing."""
    if isinstance(node, ast.Constant):
        return True
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
                "shape", "ndim", "size", "dtype"):
            return True
        if isinstance(sub, ast.Call) and call_name(sub.func) == "len":
            return True
    return False


def _mentions_global_worker(test: ast.AST) -> bool:
    """True when the guard expression consults the global-worker flag
    (``self.is_global_worker``, ``kv.is_global_worker``, a bare local,
    or a ``getattr(..., "is_global_worker", ...)``)."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Attribute) and sub.attr == "is_global_worker":
            return True
        if isinstance(sub, ast.Name) and sub.id == "is_global_worker":
            return True
        if isinstance(sub, ast.Constant) and sub.value == "is_global_worker":
            return True
    return False


def _scan_mesh_calls(node: ast.AST, hits: List[Tuple[ast.Call, str]]):
    """Collect host-transfer calls under ``node``; an ``if`` whose test
    consults is_global_worker suspends collection in its body (that
    branch runs on the global worker only — its else branch does not)."""
    if isinstance(node, ast.If) and _mentions_global_worker(node.test):
        for c in node.orelse:
            _scan_mesh_calls(c, hits)
        return
    if isinstance(node, ast.Call):
        nm = call_name(node.func)
        if nm in _HOST_SYNC_CALLS or nm.endswith(_HOST_XFER_METHODS):
            hits.append((node, nm))
    for child in ast.iter_child_nodes(node):
        _scan_mesh_calls(child, hits)


def _scan_mesh_body(stmts: Sequence[ast.stmt], guarded: bool,
                    hits: List[Tuple[ast.Call, str]]):
    """Scan a statement suite for unguarded host transfers. Two guard
    shapes count: the transfer sits inside ``if ...is_global_worker...``,
    or it follows an early-exit fence ``if not ...is_global_worker...:
    return/raise`` in the same suite."""
    g = guarded
    for st in stmts:
        if isinstance(st, ast.If) and _mentions_global_worker(st.test):
            _scan_mesh_body(st.body, True, hits)
            _scan_mesh_body(st.orelse, g, hits)
            if (isinstance(st.test, ast.UnaryOp)
                    and isinstance(st.test.op, ast.Not)
                    and st.body
                    and isinstance(st.body[-1], (ast.Return, ast.Raise))):
                g = True
            continue
        if not g:
            _scan_mesh_calls(st, hits)


class _FnInfo:
    def __init__(self, node, qualname: str, cls: Optional[str]):
        self.node = node
        self.qualname = qualname
        self.cls = cls


def _index_functions(tree: ast.Module) -> List[_FnInfo]:
    out: List[_FnInfo] = []

    def walk(node: ast.AST, prefix: str, cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append(_FnInfo(child, q, cls))
                walk(child, f"{q}.<locals>.", cls)
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.", child.name)
            else:
                walk(child, prefix, cls)

    walk(tree, "", None)
    return out


def run_traced(sources: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for src in sources:
        if src.tree is None:
            continue
        fns = _index_functions(src.tree)
        by_name: Dict[str, List[_FnInfo]] = {}
        for fi in fns:
            by_name.setdefault(fi.node.name, []).append(fi)
        node_to_info = {fi.node: fi for fi in fns}

        # ---- seeds: decorated or wrapped by jit-ish callables --------
        seeds: Set[ast.AST] = set()
        jit_wraps: List[Tuple[_FnInfo, Optional[ast.Call]]] = []

        def resolve(expr: ast.AST, near: Optional[_FnInfo]) -> \
                Optional[_FnInfo]:
            nm = call_name(expr)
            if not nm:
                return None
            if nm.startswith("self.") and nm.count(".") == 1 and near:
                nm = nm.split(".", 1)[1]
                cands = [f for f in by_name.get(nm, [])
                         if f.cls and f.cls == near.cls]
                return cands[0] if cands else None
            if "." in nm:
                return None
            cands = by_name.get(nm, [])
            return cands[0] if cands else None

        for fi in fns:
            node = fi.node
            for dec in node.decorator_list:
                if call_name(dec) in _JIT_NAMES:
                    seeds.add(node)
                    jit_wraps.append((fi, None))
                elif isinstance(dec, ast.Call):
                    tgt, is_jit = _jit_target(dec)
                    if is_jit or call_name(dec.func) in _JIT_NAMES:
                        seeds.add(node)
                        jit_wraps.append((fi, dec))

        # enclosing function of every AST node (for loop/wrap context)
        encl: Dict[ast.AST, Optional[_FnInfo]] = {}

        def mark(node: ast.AST, cur: Optional[_FnInfo]):
            encl[node] = cur
            nxt = node_to_info.get(node, cur)
            for child in ast.iter_child_nodes(node):
                mark(child, nxt)

        mark(src.tree, None)

        loop_depth: Dict[ast.AST, int] = {}

        def mark_loops(node: ast.AST, depth: int):
            loop_depth[node] = depth
            d = depth + 1 if isinstance(node, (ast.For, ast.While)) else depth
            for child in ast.iter_child_nodes(node):
                mark_loops(child, d)

        mark_loops(src.tree, 0)

        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            tgt, is_jit = _jit_target(node)
            if not is_jit:
                continue
            near = encl.get(node)
            target = resolve(tgt, near) if tgt is not None else None
            if target is not None:
                seeds.add(target.node)
                jit_wraps.append((target, node))
            if loop_depth.get(node, 0) > 0:
                findings.append(Finding(
                    "GX-J102", SEV_WARNING, src.rel, node.lineno,
                    symbol=near.qualname if near else "<module>",
                    detail=f"loop:{call_name(node.func)}",
                    message=("jit/shard_map constructed inside a loop — "
                             "the trace cache keys on function identity, "
                             "so each iteration retraces; hoist the "
                             "wrapped function out of the loop")))

        # jit(f)(x): the wrapper is born and dies per call — retrace
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call) and isinstance(node.func,
                                                         ast.Call):
                _tgt, is_jit = _jit_target(node.func)
                if is_jit:
                    near = encl.get(node)
                    findings.append(Finding(
                        "GX-J102", SEV_WARNING, src.rel, node.lineno,
                        symbol=near.qualname if near else "<module>",
                        detail="inline-call",
                        message=("jax.jit(...) created and immediately "
                                 "called — a fresh wrapper per call "
                                 "means a retrace per call; bind the "
                                 "jitted function once and reuse it")))

        # ---- close reachability over same-module calls ---------------
        traced: Set[ast.AST] = set(seeds)
        frontier = list(seeds)
        while frontier:
            fn = frontier.pop()
            fi = node_to_info[fn]
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    callee = resolve(sub.func, fi)
                    if callee is not None and callee.node not in traced:
                        traced.add(callee.node)
                        frontier.append(callee.node)

        # ---- GX-J101 host syncs in traced bodies ---------------------
        for fn in traced:
            fi = node_to_info[fn]
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                nm = call_name(sub.func)
                hit = None
                if nm in _SCALAR_CASTS and sub.args \
                        and not _is_static_expr(sub.args[0]):
                    hit = nm
                elif nm in _HOST_SYNC_CALLS:
                    hit = nm
                elif nm.endswith(_HOST_SYNC_METHODS):
                    hit = nm
                if hit is not None:
                    findings.append(Finding(
                        "GX-J101", SEV_ERROR, src.rel, sub.lineno,
                        symbol=fi.qualname, detail=f"{hit}:{sub.lineno}",
                        message=(f"{hit}() inside jit-traced "
                                 f"{fi.qualname} forces a host sync "
                                 f"(ConcretizationTypeError or silent "
                                 f"device->host transfer)")))

        # ---- GX-J103 donate_argnums on train-step shapes -------------
        seen_j103: Set[str] = set()
        for fi, wrap in jit_wraps:
            if not _STEP_NAME_RE.search(fi.node.name):
                continue
            if wrap is not None and _has_donate(wrap):
                continue
            if wrap is None and any(
                    isinstance(d, ast.Call) and _has_donate(d)
                    for d in fi.node.decorator_list):
                continue
            params = [a.arg for a in fi.node.args.args
                      if a.arg not in ("self", "cls")]
            if not params:
                continue
            state_params = set(params[:2])
            returns_state = False
            for sub in ast.walk(fi.node):
                if isinstance(sub, ast.Return) and sub.value is not None:
                    elts = sub.value.elts if isinstance(sub.value,
                                                       ast.Tuple) \
                        else [sub.value]
                    # only a param returned as a DIRECT tuple element is
                    # pass-through state worth donating; a param merely
                    # referenced inside the return expression is an input
                    # the caller still owns
                    for n in elts:
                        if isinstance(n, ast.Name) and n.id in state_params:
                            returns_state = True
            if not returns_state or fi.qualname in seen_j103:
                continue
            seen_j103.add(fi.qualname)
            findings.append(Finding(
                "GX-J103", SEV_WARNING, src.rel, fi.node.lineno,
                symbol=fi.qualname,
                message=(f"jitted train-step {fi.qualname} returns its "
                         f"parameter state but donates nothing — pass "
                         f"donate_argnums for the state args so XLA can "
                         f"reuse the old buffers in place")))

        # ---- GX-J104 host transfers on a mesh rank's round path ------
        mesh_nodes: Set[ast.AST] = set()
        mfrontier: List[ast.AST] = []
        for fi in fns:
            if fi.cls and "Mesh" in fi.cls \
                    and _MESH_ROUND_RE.search(fi.node.name):
                mesh_nodes.add(fi.node)
                mfrontier.append(fi.node)
        while mfrontier:
            fn = mfrontier.pop()
            fi = node_to_info[fn]
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    callee = resolve(sub.func, fi)
                    if callee is not None and callee.node not in mesh_nodes:
                        mesh_nodes.add(callee.node)
                        mfrontier.append(callee.node)
        for fn in sorted(mesh_nodes, key=lambda n: n.lineno):
            fi = node_to_info[fn]
            hits: List[Tuple[ast.Call, str]] = []
            _scan_mesh_body(list(fn.body), False, hits)
            for call, nm in hits:
                findings.append(Finding(
                    "GX-J104", SEV_ERROR, src.rel, call.lineno,
                    symbol=fi.qualname, detail=f"{nm}:{call.lineno}",
                    message=(f"{nm}() on the mesh round path "
                             f"{fi.qualname} materializes device data on "
                             f"the host; only the party's global worker "
                             f"may — guard with is_global_worker")))

        # ---- GX-J105 host transfers inside a mesh codec --------------
        ring_nodes: Set[ast.AST] = set()
        rfrontier: List[ast.AST] = []
        for fi in fns:
            if fi.cls and _RING_CLS_RE.search(fi.cls) \
                    and _RING_CODEC_RE.search(fi.node.name):
                ring_nodes.add(fi.node)
                rfrontier.append(fi.node)
        while rfrontier:
            fn = rfrontier.pop()
            fi = node_to_info[fn]
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    callee = resolve(sub.func, fi)
                    if callee is not None and callee.node not in ring_nodes:
                        ring_nodes.add(callee.node)
                        rfrontier.append(callee.node)
        # a node already on a GX-J104 round path reports there, not twice
        for fn in sorted(ring_nodes - mesh_nodes, key=lambda n: n.lineno):
            fi = node_to_info[fn]
            hits = []
            _scan_mesh_body(list(fn.body), False, hits)
            for call, nm in hits:
                findings.append(Finding(
                    "GX-J105", SEV_ERROR, src.rel, call.lineno,
                    symbol=fi.qualname, detail=f"{nm}:{call.lineno}",
                    message=(f"{nm}() inside mesh codec {fi.qualname} "
                             f"drags device-resident ring state to the "
                             f"host on every rank, every round; keep the "
                             f"codec on device or guard the transfer "
                             f"with is_global_worker")))
    return findings


def traced_surface(sources: Sequence[SourceFile]) -> dict:
    """The surface this pass reasons about, for the unified ``--json``
    fingerprint stream: per file, the set of jit/pjit/shard_map entry
    points (the traced-code frontier the GX-J1xx rules walk from)."""
    out: Dict[str, List[str]] = {}
    for src in sources:
        if src.tree is None:
            continue
        entries: Set[str] = set()
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                target, is_jit = _jit_target(node)
                if is_jit:
                    entries.add(call_name(target) if target is not None
                                else "<dynamic>")
        for fn, qual in _index_functions_flat(src.tree):
            for deco in fn.decorator_list:
                name = call_name(deco.func if isinstance(deco, ast.Call)
                                 else deco)
                if name in _JIT_NAMES:
                    entries.add(qual)
        if entries:
            out[src.rel] = sorted(entries)
    return out


def _index_functions_flat(tree: ast.Module):
    """(node, qualname) for every function def, any nesting."""
    out = []

    def walk(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append((child, q))
                walk(child, f"{q}.<locals>.")
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            else:
                walk(child, prefix)

    walk(tree, "")
    return out
