"""Concurrency pass: lock inventory, acquisition graph, hazard rules.

Rules
-----
GX-L001 (error)   lock-order inversion: within one class, lock A is taken
                  while holding B somewhere and B while holding A elsewhere.
GX-L002 (warning) attribute written both under a guarding lock and outside
                  any lock (excluding ``__init__``-time construction).
GX-L003 (warning) blocking call (sleep, socket send/recv/accept/connect,
                  queue get/put, thread join, Condition.wait on a *different*
                  lock) made while holding a lock.
GX-L004 (error)   re-entrant acquisition of a non-reentrant ``Lock`` — a
                  ``with self.x`` nested (lexically, or one call level deep)
                  inside a region already holding ``self.x``.

Scope is intentionally per-class (plus module-level locks used by
module-level functions): ``self.X`` attributes assigned from
``threading.Lock()/RLock()/Condition()``. A ``Condition(self.y)`` aliases
its underlying lock, so holding the condition counts as holding ``y``.
Locks passed across objects or stored in tuples are out of scope — this
is a linter, not a model checker.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (Finding, SEV_ERROR, SEV_WARNING, SourceFile, call_name)

_LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "Lock": "Lock",
    "RLock": "RLock",
    "Condition": "Condition",
    # geomx-racecheck traced drop-ins (geomx_tpu/ps/locks.py): the
    # factories return raw primitives when GEOMX_LOCK_SANITIZER=0, so
    # statically they ARE the lock they wrap. make_condition's first
    # positional arg is the underlying lock, exactly like Condition's.
    "locks.make_lock": "Lock",
    "make_lock": "Lock",
    "locks.make_rlock": "RLock",
    "make_rlock": "RLock",
    "locks.make_condition": "Condition",
    "make_condition": "Condition",
    "locks.TracedLock": "Lock",
    "TracedLock": "Lock",
    "locks.TracedRLock": "RLock",
    "TracedRLock": "RLock",
    "locks.TracedCondition": "Condition",
    "TracedCondition": "Condition",
}
_THREAD_CTORS = {"threading.Thread", "Thread"}
_QUEUE_CTORS = {"queue.Queue", "Queue", "queue.SimpleQueue", "SimpleQueue",
                "queue.PriorityQueue", "PriorityQueue"}

# call-name suffixes that block the calling thread
_BLOCKING_SUFFIXES = (
    ".recv", ".recv_into", ".recvfrom", ".send", ".sendall", ".sendto",
    ".accept", ".connect",
)
_SLEEP_NAMES = {"time.sleep", "sleep"}


@dataclasses.dataclass
class _LockDef:
    name: str          # attribute / variable name
    kind: str          # Lock | RLock | Condition
    canonical: str     # underlying lock for Condition(self.x); else name
    line: int


@dataclasses.dataclass
class _Write:
    method: str
    line: int
    held: Tuple[str, ...]


class _ScopeInfo:
    """One analyzed scope: a class (self.X locks) or a module
    (bare-name locks used by module-level functions)."""

    def __init__(self, qualname: str, prefix: str):
        self.qualname = qualname          # "van.Van" or "van.<module>"
        self.prefix = prefix              # "self." or ""
        self.locks: Dict[str, _LockDef] = {}
        self.threads: Set[str] = set()
        self.queues: Set[str] = set()
        # per-method direct info
        self.direct_acquires: Dict[str, Set[str]] = {}
        # (holder, acquired) -> first site line
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self.guarded_writes: Dict[str, List[_Write]] = {}
        self.unguarded_writes: Dict[str, List[_Write]] = {}
        # blocking calls: (method, line, callname, held)
        self.blocking: List[Tuple[str, int, str, Tuple[str, ...]]] = []
        # call sites: method -> [(callee, held, line)]
        self.calls: Dict[str, List[Tuple[str, Tuple[str, ...], int]]] = {}
        # lexically nested re-acquisitions: (method, line, lock)
        self.reacquired: List[Tuple[str, int, str]] = []

    def canon(self, name: str) -> Optional[str]:
        d = self.locks.get(name)
        return d.canonical if d else None

    def kind_of(self, canonical: str) -> str:
        d = self.locks.get(canonical)
        return d.kind if d else "Lock"


def _target_attr(node: ast.AST, prefix_self: bool) -> Optional[str]:
    """Attribute name written by an assignment target (``self.x``,
    ``self.x[...]``), or bare name for module scope."""
    if isinstance(node, (ast.Subscript,)):
        return _target_attr(node.value, prefix_self)
    if prefix_self:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None
    return node.id if isinstance(node, ast.Name) else None


def _lock_ref(expr: ast.AST, scope: _ScopeInfo) -> Optional[str]:
    """Canonical lock name when ``expr`` references a known lock
    (``self.x`` in a class scope, ``x`` in module scope)."""
    if scope.prefix == "self.":
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return scope.canon(expr.attr)
        return None
    if isinstance(expr, ast.Name):
        return scope.canon(expr.id)
    return None


def _collect_locks(scope: _ScopeInfo, bodies: Sequence[ast.AST],
                   prefix_self: bool) -> None:
    for body in bodies:
        for node in ast.walk(body):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            attr = _target_attr(node.targets[0], prefix_self)
            if attr is None or not isinstance(node.value, ast.Call):
                continue
            cname = call_name(node.value.func)
            kind = _LOCK_CTORS.get(cname)
            if kind is not None:
                canonical = attr
                if kind == "Condition" and node.value.args:
                    under = _target_attr(node.value.args[0], prefix_self)
                    if under is not None:
                        canonical = under
                scope.locks[attr] = _LockDef(attr, kind, canonical,
                                             node.lineno)
            elif cname in _THREAD_CTORS:
                scope.threads.add(attr)
            elif cname in _QUEUE_CTORS:
                scope.queues.add(attr)
    # re-canonicalize conditions declared before their underlying lock
    for d in scope.locks.values():
        seen = set()
        while (d.canonical in scope.locks
               and scope.locks[d.canonical].canonical != d.canonical
               and d.canonical not in seen):
            seen.add(d.canonical)
            d.canonical = scope.locks[d.canonical].canonical


def _is_blocking(scope: _ScopeInfo, node: ast.Call,
                 held: Tuple[str, ...]) -> Optional[str]:
    """Return a printable call name when ``node`` may block."""
    name = call_name(node.func)
    if not name:
        return None
    if name in _SLEEP_NAMES:
        return name
    if name.endswith(_BLOCKING_SUFFIXES):
        return name
    if name.endswith((".wait", ".wait_for")):
        owner = name.rsplit(".", 1)[0]
        # Condition.wait RELEASES the lock it owns: waiting on the only
        # held lock is the normal pattern; waiting while holding another
        # lock keeps that other lock across the sleep.
        attr = owner.split(".", 1)[1] if owner.startswith("self.") \
            else owner
        canonical = scope.canon(attr)
        others = [h for h in held if h != canonical]
        if others:
            return name
        return None
    if name.endswith(".join"):
        owner = name.rsplit(".", 1)[0]
        attr = owner.split(".", 1)[1] if owner.startswith("self.") \
            else owner
        if attr in scope.threads:
            return name
        return None
    if name.endswith((".get", ".put")):
        owner = name.rsplit(".", 1)[0]
        attr = owner.split(".", 1)[1] if owner.startswith("self.") \
            else owner
        if attr in scope.queues:
            return name
        return None
    return None


def _scan_method(scope: _ScopeInfo, method_name: str,
                 fn: ast.AST) -> None:
    """Walk one method/function body tracking the held-lock stack."""
    scope.direct_acquires.setdefault(method_name, set())
    scope.calls.setdefault(method_name, [])
    is_init = method_name.rsplit(".", 1)[-1] in ("__init__", "__post_init__",
                                                 "__new__")

    def record_write(attr: str, line: int, held: Tuple[str, ...]):
        if attr in scope.locks or is_init:
            return
        w = _Write(method_name, line, held)
        if held:
            scope.guarded_writes.setdefault(attr, []).append(w)
        else:
            scope.unguarded_writes.setdefault(attr, []).append(w)

    def visit(node: ast.AST, held: Tuple[str, ...]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a closure's body runs when *called*, not where defined —
            # scan it as its own pseudo-method with nothing held
            _scan_method(scope, f"{method_name}.<locals>.{node.name}", node)
            return
        if isinstance(node, ast.With):
            new_held = held
            for item in node.items:
                lk = _lock_ref(item.context_expr, scope)
                if lk is not None:
                    if lk in new_held and scope.kind_of(lk) != "RLock":
                        scope.reacquired.append(
                            (method_name, item.context_expr.lineno, lk))
                    for h in new_held:
                        if h != lk:
                            scope.edges.setdefault(
                                (h, lk),
                                (method_name, item.context_expr.lineno))
                    scope.direct_acquires[method_name].add(lk)
                    new_held = new_held + (lk,)
                else:
                    visit(item.context_expr, held)
                if item.optional_vars is not None:
                    visit(item.optional_vars, held)
            for st in node.body:
                visit(st, new_held)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = _target_attr(t, scope.prefix == "self.")
                if attr is not None and scope.prefix == "self.":
                    record_write(attr, node.lineno, held)
                visit(t, held)
            if getattr(node, "value", None) is not None:
                visit(node.value, held)
            return
        if isinstance(node, ast.Call):
            if held:
                blk = _is_blocking(scope, node, held)
                if blk is not None:
                    scope.blocking.append(
                        (method_name, node.lineno, blk, held))
            name = call_name(node.func)
            if scope.prefix == "self." and name.startswith("self.") \
                    and name.count(".") == 1:
                scope.calls[method_name].append(
                    (name.split(".", 1)[1], held, node.lineno))
            elif scope.prefix == "" and name and "." not in name:
                scope.calls[method_name].append((name, held, node.lineno))
            for child in ast.iter_child_nodes(node):
                visit(child, held)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for st in fn.body if isinstance(fn, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) else []:
        visit(st, ())


def _close_over_calls(scope: _ScopeInfo) -> None:
    """Fixpoint ``may_acquire`` over same-scope calls, adding edges for
    locks acquired by callees while the caller holds something, and
    one-call-deep re-entrancy findings."""
    may: Dict[str, Set[str]] = {m: set(a)
                                for m, a in scope.direct_acquires.items()}
    changed = True
    while changed:
        changed = False
        for m, callees in scope.calls.items():
            for callee, _held, _line in callees:
                for cand in (callee, f"{m}.<locals>.{callee}"):
                    if cand in may and not may[cand] <= may[m]:
                        may[m] |= may[cand]
                        changed = True
    for m, callees in scope.calls.items():
        for callee, held, line in callees:
            if not held:
                continue
            acq = may.get(callee) or may.get(f"{m}.<locals>.{callee}")
            if not acq:
                continue
            for lk in acq:
                if lk in held:
                    # one CALL level deep only for the hard-deadlock rule:
                    # deeper chains get noisy with conditional acquires
                    direct = scope.direct_acquires.get(callee) or \
                        scope.direct_acquires.get(
                            f"{m}.<locals>.{callee}") or set()
                    if lk in direct and scope.kind_of(lk) != "RLock":
                        scope.reacquired.append((m, line, lk))
                else:
                    for h in held:
                        scope.edges.setdefault((h, lk), (m, line))


def _scope_findings(scope: _ScopeInfo, rel: str) -> List[Finding]:
    out: List[Finding] = []
    _close_over_calls(scope)

    reported = set()
    for (a, b), (meth, line) in sorted(scope.edges.items(),
                                       key=lambda kv: kv[1][1]):
        if (b, a) in scope.edges and frozenset((a, b)) not in reported:
            reported.add(frozenset((a, b)))
            meth2, line2 = scope.edges[(b, a)]
            out.append(Finding(
                "GX-L001", SEV_ERROR, rel, line,
                symbol=scope.qualname,
                detail=":".join(sorted((a, b))),
                message=(f"lock-order inversion in {scope.qualname}: "
                         f"{meth} takes {b!r} while holding {a!r} "
                         f"(line {line}) but {meth2} takes {a!r} while "
                         f"holding {b!r} (line {line2})")))

    for attr, writes in sorted(scope.unguarded_writes.items()):
        guarded = scope.guarded_writes.get(attr)
        if not guarded:
            continue
        locks = sorted({h for w in guarded for h in w.held})
        w = writes[0]
        out.append(Finding(
            "GX-L002", SEV_WARNING, rel, w.line,
            symbol=f"{scope.qualname}.{attr}",
            message=(f"attribute {attr!r} is written under lock(s) "
                     f"{locks} (e.g. {guarded[0].method}:"
                     f"{guarded[0].line}) but also written with no lock "
                     f"held in {w.method}:{w.line}")))

    for meth, line, cname, held in scope.blocking:
        out.append(Finding(
            "GX-L003", SEV_WARNING, rel, line,
            symbol=meth, detail=cname,
            message=(f"blocking call {cname}() while holding lock(s) "
                     f"{sorted(set(held))} in {meth}")))

    for meth, line, lk in scope.reacquired:
        out.append(Finding(
            "GX-L004", SEV_ERROR, rel, line,
            symbol=meth, detail=lk,
            message=(f"{meth} re-acquires non-reentrant lock {lk!r} "
                     f"already held on this path (use RLock or "
                     f"restructure) — self-deadlock")))
    return out


def run_concurrency(sources: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for src in sources:
        if src.tree is None:
            continue
        modname = Path(src.rel).stem
        # module scope: bare-name locks + module-level functions
        mod_scope = _ScopeInfo(f"{modname}.<module>", "")
        _collect_locks(mod_scope, [src.tree], prefix_self=False)
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _scan_method(mod_scope, node.name, node)
        if mod_scope.locks:
            findings += _scope_findings(mod_scope, src.rel)
        for cls in [n for n in ast.walk(src.tree)
                    if isinstance(n, ast.ClassDef)]:
            scope = _ScopeInfo(f"{modname}.{cls.name}", "self.")
            methods = [n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            _collect_locks(scope, methods, prefix_self=True)
            if not scope.locks:
                continue
            for m in methods:
                _scan_method(scope, m.name, m)
            findings += _scope_findings(scope, src.rel)
    return findings


def concurrency_surface(sources: Sequence[SourceFile]) -> dict:
    """The surface this pass reasons about, for the unified ``--json``
    fingerprint stream: per file, per scope, the lock inventory and the
    lock-acquisition order edges. A changed fingerprint means the lock
    graph moved even when no inversion (yet) fires."""
    out: Dict[str, dict] = {}
    for src in sources:
        if src.tree is None:
            continue
        modname = Path(src.rel).stem
        scopes: Dict[str, dict] = {}
        mod_scope = _ScopeInfo(f"{modname}.<module>", "")
        _collect_locks(mod_scope, [src.tree], prefix_self=False)
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _scan_method(mod_scope, node.name, node)
        candidates = [mod_scope]
        for cls in [n for n in ast.walk(src.tree)
                    if isinstance(n, ast.ClassDef)]:
            scope = _ScopeInfo(f"{modname}.{cls.name}", "self.")
            methods = [n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            _collect_locks(scope, methods, prefix_self=True)
            for m in methods:
                _scan_method(scope, m.name, m)
            candidates.append(scope)
        for scope in candidates:
            if not scope.locks:
                continue
            scopes[scope.qualname] = {
                "locks": {name: d.kind
                          for name, d in sorted(scope.locks.items())},
                "edges": sorted(f"{a}->{b}" for a, b in scope.edges),
            }
        if scopes:
            out[src.rel] = scopes
    return out
