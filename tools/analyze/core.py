"""Shared plumbing for geomx-lint: findings, suppression, baseline.

A finding is (rule, severity, path, line, symbol, message). The baseline
stores *fingerprints* — ``rule:path:symbol:detail`` — deliberately without
line numbers, so unrelated edits that shift a file do not invalidate an
accepted finding. ``symbol`` is the enclosing qualname (``Class.method``,
``Class.attr``, a variable name, …) and ``detail`` disambiguates multiple
findings of one rule inside one symbol (the called name, the env var, …).

Suppression: a finding is dropped when a ``geomx-lint:
disable=RULE[,RULE...]`` (or ``disable=all``) comment sits on the
finding's line, the line directly above it, any line of the enclosing
*statement* (so a trailing comment on the last line of a multi-line
call works), or the line directly above that statement — where "the
statement" is the header only for compound statements (a ``def``'s
signature plus its decorators, an ``if``'s test, ...), so a comment
inside a body never suppresses findings anchored to the header and
vice versa.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SEV_ERROR = "error"
SEV_WARNING = "warning"

_SEV_RANK = {SEV_ERROR: 0, SEV_WARNING: 1}

_DISABLE_RE = re.compile(r"geomx-lint:\s*disable=([A-Za-z0-9_,\-]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str          # repo-relative, posix separators
    line: int
    symbol: str        # enclosing qualname / attribute / env-var name
    message: str
    detail: str = ""   # extra fingerprint component within one symbol

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}:{self.path}:{self.symbol}:{self.detail}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} [{self.severity}] "
                f"{self.message}")


def sort_findings(findings: Iterable[Finding]) -> List[Finding]:
    return sorted(findings,
                  key=lambda f: (_SEV_RANK.get(f.severity, 9), f.path,
                                 f.line, f.rule, f.detail))


class SourceFile:
    """One parsed python file: AST + raw lines (for suppression checks)."""

    def __init__(self, path: Path, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        self._spans: Optional[Dict[int, Tuple[int, int]]] = None
        try:
            self.tree = ast.parse(text, filename=str(path))
        except SyntaxError as e:  # surfaced as a finding, not a crash
            self.parse_error = e

    def _statement_spans(self) -> Dict[int, Tuple[int, int]]:
        """line -> (start, end) of the innermost enclosing statement.
        Compound statements (def/class/if/for/...) span their HEADER
        only — decorators through the line before the first body
        statement — so body comments don't leak onto the header."""
        if self._spans is not None:
            return self._spans
        spans: Dict[int, Tuple[int, int]] = {}
        if self.tree is not None:
            # ast.walk is breadth-first: children overwrite parents, so
            # the innermost statement wins for every line
            for node in ast.walk(self.tree):
                if not isinstance(node, ast.stmt):
                    continue
                start = min([node.lineno] +
                            [d.lineno for d in
                             getattr(node, "decorator_list", [])])
                body = getattr(node, "body", None)
                if isinstance(body, list) and body:
                    end = body[0].lineno - 1
                else:
                    end = node.end_lineno or node.lineno
                for ln in range(start, end + 1):
                    spans[ln] = (start, end)
        self._spans = spans
        return spans

    def suppressed(self, line: int, rule: str) -> bool:
        candidates = {line, line - 1}
        span = self._statement_spans().get(line)
        if span is not None:
            start, end = span
            candidates.update(range(start - 1, end + 1))
        for ln in candidates:
            if 1 <= ln <= len(self.lines):
                m = _DISABLE_RE.search(self.lines[ln - 1])
                if m:
                    rules = {r.strip() for r in m.group(1).split(",")}
                    if "all" in rules or rule in rules:
                        return True
        return False


def load_sources(paths: Sequence[Path], root: Path) -> List[SourceFile]:
    """Collect .py files under ``paths`` (files or directories), with
    repo-relative names computed against ``root``."""
    out: List[SourceFile] = []
    seen = set()
    for p in paths:
        p = Path(p)
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            f = f.resolve()
            if f in seen:
                continue
            seen.add(f)
            try:
                rel = f.relative_to(root.resolve()).as_posix()
            except ValueError:
                rel = f.as_posix()
            out.append(SourceFile(f, rel, f.read_text(encoding="utf-8")))
    return out


def apply_suppressions(findings: Iterable[Finding],
                       sources: Sequence[SourceFile]) -> List[Finding]:
    by_rel: Dict[str, SourceFile] = {s.rel: s for s in sources}
    kept = []
    for f in findings:
        src = by_rel.get(f.path)
        if src is not None and src.suppressed(f.line, f.rule):
            continue
        kept.append(f)
    return kept


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: Path) -> set:
    if not path.exists():
        return set()
    data = json.loads(path.read_text(encoding="utf-8"))
    return set(data.get("findings", []))

def save_baseline(path: Path, findings: Iterable[Finding]) -> None:
    fps = sorted({f.fingerprint for f in findings})
    path.write_text(
        json.dumps({"version": 1, "findings": fps}, indent=1) + "\n",
        encoding="utf-8")


def split_by_baseline(findings: Iterable[Finding],
                      baseline: set) -> Tuple[List[Finding], List[Finding]]:
    """(new, accepted) partition against a set of fingerprints."""
    new, accepted = [], []
    for f in findings:
        (accepted if f.fingerprint in baseline else new).append(f)
    return new, accepted


# ---------------------------------------------------------------------------
# small AST helpers shared by the passes
# ---------------------------------------------------------------------------

def call_name(node: ast.AST) -> str:
    """Dotted name of a call target: ``jax.jit`` -> "jax.jit",
    ``self._lock.acquire`` -> "self._lock.acquire"; "" when dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        inner = call_name(node.func)
        parts.append(f"{inner}()" if inner else "()")
    else:
        return ""
    return ".".join(reversed(parts))


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
