"""CLI: ``python -m tools.analyze [paths...]``.

Exit codes: 0 = no findings beyond the baseline; 1 = new findings;
2 = usage error. ``--update-baseline`` rewrites the committed baseline
to exactly the current findings (do this after fixing or accepting);
``--prune-baseline`` drops only the stale entries; ``--update-binmeta-
lock`` refreshes the wire-schema lock after a BINMETA_VERSION bump;
``--update-lock-model`` refreshes the geomx-racecheck lock model
(tools/analyze/locks.lock.json) after a deliberate lock/@guarded_by
change; ``--update-state-model`` refreshes the geomx-statecheck
protocol state model (tools/analyze/state.lock.json) after a reviewed
membership/epoch/recovery protocol change (re-explore with
``python -m tools.modelcheck`` first)."""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import (DEFAULT_BASELINE, PASSES, load_baseline, load_sources,
               pass_fingerprints, run_all, save_baseline,
               split_by_baseline, write_binmeta_lock, write_lock_model,
               write_state_model)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="geomx-lint: lock/lock-model, traced-code, "
                    "config-drift, protocol and metrics static analysis "
                    "(docs/static-analysis.md)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to analyze (default: geomx_tpu/)")
    ap.add_argument("--root", default=".",
                    help="project root holding docs/ and scripts/ "
                         "(default: cwd)")
    ap.add_argument("--passes", default=None,
                    help="comma list from: %s" % ",".join(PASSES))
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="baseline file (default: tools/analyze/"
                         "baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, accepted or not")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current findings")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="drop baseline entries whose fingerprints no "
                         "longer match any finding")
    ap.add_argument("--update-binmeta-lock", action="store_true",
                    help="refresh tools/analyze/binmeta.lock.json from "
                         "the current Meta wire schema")
    ap.add_argument("--update-lock-model", action="store_true",
                    help="refresh tools/analyze/locks.lock.json from "
                         "the current lock inventory + @guarded_by "
                         "declarations")
    ap.add_argument("--update-state-model", action="store_true",
                    help="refresh tools/analyze/state.lock.json from "
                         "the current membership/epoch protocol "
                         "transition signatures")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings (rule, file, line, "
                         "fingerprint) for CI / chaos-matrix diffing")
    args = ap.parse_args(argv)

    root = Path(args.root)
    paths = [Path(p) for p in args.paths] or [root / "geomx_tpu"]
    passes = args.passes.split(",") if args.passes else None
    unknown = set(passes or []) - set(PASSES)
    if unknown:
        print(f"unknown pass(es): {sorted(unknown)}", file=sys.stderr)
        return 2

    if args.update_binmeta_lock:
        lock = write_binmeta_lock(load_sources(paths, root), root)
        print(f"binmeta lock updated -> {lock}")
        return 0

    if args.update_lock_model:
        lock = write_lock_model(load_sources(paths, root), root)
        print(f"lock model updated -> {lock}")
        return 0

    if args.update_state_model:
        lock = write_state_model(load_sources(paths, root), root)
        print(f"state model updated -> {lock}")
        return 0

    findings = run_all(paths, root, passes)

    if args.update_baseline:
        save_baseline(Path(args.baseline), findings)
        print(f"baseline updated: {len(findings)} finding(s) accepted "
              f"-> {args.baseline}")
        return 0

    if args.prune_baseline:
        bl_path = Path(args.baseline)
        baseline = load_baseline(bl_path)
        live = {f.fingerprint for f in findings}
        kept = sorted(baseline & live)
        bl_path.write_text(
            json.dumps({"version": 1, "findings": kept}, indent=1) + "\n",
            encoding="utf-8")
        print(f"baseline pruned: {len(baseline) - len(kept)} stale "
              f"entrie(s) dropped, {len(kept)} kept -> {bl_path}")
        return 0

    baseline = set() if args.no_baseline else load_baseline(
        Path(args.baseline))
    new, accepted = split_by_baseline(findings, baseline)

    if args.json:
        # fingerprint included so CI / the chaos matrix can diff runs
        # by identity instead of grepping rendered stderr lines; the
        # per-pass model fingerprints let one stream also flag surface
        # drift (lock inventory, knob registry, protocol model, ...)
        # that produced no finding
        print(json.dumps({
            "new": [{**vars(f), "fingerprint": f.fingerprint}
                    for f in new],
            "accepted": [{**vars(f), "fingerprint": f.fingerprint}
                         for f in accepted],
            "fingerprints": pass_fingerprints(
                load_sources(paths, root), root),
        }, indent=1))
    else:
        for f in new:
            print(f.render())
        tail = (f"{len(new)} new finding(s), {len(accepted)} accepted "
                f"in baseline")
        print(("FAIL: " if new else "OK: ") + tail)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
