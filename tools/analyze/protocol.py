"""Protocol pass (GX-P3xx): the wire protocol, machine-checked.

Extracts a model of the ps wire protocol from the AST — the ``Control``
verb set, every ``Meta(control_cmd=...)`` construction (send sites),
every ``Control.X`` comparison (dispatch sites), the request-bearing
handler tree, countdown/aggregation mutations and their epoch fences,
and the binary-meta field schema — then checks the invariants every
protocol rewrite so far has broken by hand:

- **GX-P301** dead/unhandled Control verb: a verb that is sent but has
  no dispatch branch (the receiver silently ignores it), dispatched but
  never sent (dead protocol surface), or neither (dead enum member).
- **GX-P302** droppable request: a request-bearing handler (a function
  named ``*handle*``/``*push*``/``*pull*`` with a parameter literally
  named ``req``) has a ``return`` path that neither forwarded ``req``
  anywhere nor responded to it. Exempt: ``return`` under an
  ``is_stale(...)`` fence (the one legal drop-without-ack), ``return
  False`` (the handler-chain "not mine" decline), and ``raise`` exits.
  Limitation: a loop that acks per-iteration but can run zero
  iterations is NOT caught (lexical may-analysis) — audit those by
  hand (see ``_pull_global_store``).
- **GX-P303** bare-key response routing: a function that iterates a
  ``.keys`` payload attribute and routes/completes per key without ever
  consulting ``offset_of``/``.offsets`` — the PR-3 bug class where two
  slices of one key alias the same completion slot.
- **GX-P304** unfenced countdown mutation: a ``req``-bearing method
  mutates aggregation state (``+=`` on an attribute, ``.append``/
  ``.extend`` on attribute state) without an ``is_stale``/epoch fence
  on its call path — the PR-5 zombie-push bug class. The fence
  propagates: a method is "fenced" if it calls ``is_stale`` itself or
  is (transitively) called by a same-class method that does.
- **GX-P305** static-count countdown: a round/countdown target sized
  from a static topology attribute (``num_workers`` & friends) instead
  of the live view (``num_live_workers``/``live_worker_ids``). Flagged
  where it matters: compared against an arrival count, or passed as a
  ``tgt``/``expected``/``target``/``count`` keyword.
- **GX-P306** meta schema drift: the ``_META_FIELDS`` wire schema is
  fingerprinted into ``tools/analyze/binmeta.lock.json``; changing the
  schema without bumping ``BINMETA_VERSION`` (or bumping without
  refreshing the lock via ``--update-binmeta-lock``) fails the gate.
- **GX-P307** codec without its sidecar: a send site stamping a
  literal ``compr=`` tag whose payload is undecodable without an aux
  operand (``2bit`` needs its threshold, ``rsp`` its row ids,
  ``bsc16`` its indices — ``compression.device._AUX_REQUIRED``)
  without an ``aux=`` keyword in the same call. The receiver would
  KeyError mid-decode or, worse, decode garbage at a default
  threshold. Dynamic tags (``compr=tag``) are out of scope — the
  runtime wire sanitizer owns those.

Pure AST, like every geomx-lint pass: the analyzed code is never
imported.
"""

from __future__ import annotations

import ast
import hashlib
import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SEV_ERROR, SourceFile, call_name, const_str

BINMETA_LOCK_NAME = "binmeta.lock.json"

# enum members that legitimately never travel as a stamped verb:
# EMPTY is the "this is a data message" default, not a command.
_P301_EXEMPT = {"EMPTY"}

_HANDLER_NAME_RE = re.compile(r"(^|_)(handle|push|pull)")
_COUNT_NAME_RE = re.compile(r"(received|arrived|count|nm|stops|elems)",
                            re.IGNORECASE)
_TGT_KWARG_RE = re.compile(r"(tgt|expected|target|count)", re.IGNORECASE)
_STATIC_COUNT_ATTRS = {"num_workers", "num_servers", "num_global_workers",
                       "num_all_workers"}


def run_protocol(sources: Sequence[SourceFile],
                 root: Path) -> List[Finding]:
    findings: List[Finding] = []
    findings += _check_control_set(sources)
    for src in sources:
        if src.tree is None:
            continue
        findings += _check_droppable_requests(src)
        findings += _check_bare_key_routing(src)
        findings += _check_unfenced_mutations(src)
        findings += _check_static_counts(src)
        findings += _check_compr_aux(src)
    findings += _check_binmeta(sources, root)
    return findings


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _control_member(node: ast.AST) -> Optional[str]:
    """``Control.X`` -> "X" (also matches dotted prefixes ending in
    ``Control``, e.g. ``message.Control.X``)."""
    if isinstance(node, ast.Attribute):
        base = node.value
        if isinstance(base, ast.Name) and base.id == "Control":
            return node.attr
        if isinstance(base, ast.Attribute) and base.attr == "Control":
            return node.attr
    return None


def _iter_functions(tree: ast.Module):
    """Yield (node, qualname, enclosing ClassDef or None) for every
    function, with ``Class.method`` / ``fn.<locals>.inner`` qualnames."""
    out = []

    def walk(node, prefix: str, cls: Optional[ast.ClassDef]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append((child, q, cls))
                walk(child, f"{q}.<locals>.", None)
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.", child)
            else:
                walk(child, prefix, cls)

    walk(tree, "", None)
    return out


def _contains_is_stale(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Call)
               and call_name(n.func).split(".")[-1] == "is_stale"
               for n in ast.walk(node))


# ---------------------------------------------------------------------------
# GX-P301: Control verb consistency
# ---------------------------------------------------------------------------

def _check_control_set(sources: Sequence[SourceFile]) -> List[Finding]:
    # the enum definition (first `class Control` found wins)
    members: Dict[str, Tuple[SourceFile, int]] = {}
    for src in sources:
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef) and node.name == "Control":
                for stmt in node.body:
                    if (isinstance(stmt, ast.Assign)
                            and len(stmt.targets) == 1
                            and isinstance(stmt.targets[0], ast.Name)):
                        members.setdefault(stmt.targets[0].id,
                                           (src, stmt.lineno))
                break
        if members:
            break
    if not members:
        return []

    sent: Dict[str, Tuple[SourceFile, int]] = {}
    dispatched: Dict[str, Tuple[SourceFile, int]] = {}
    for src in sources:
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg != "control_cmd":
                        continue
                    for sub in ast.walk(kw.value):  # incl. IfExp arms
                        m = _control_member(sub)
                        if m:
                            sent.setdefault(m, (src, sub.lineno))
            elif isinstance(node, ast.Compare):
                for sub in [node.left] + list(node.comparators):
                    for leaf in ast.walk(sub):  # incl. `in (A, B)` tuples
                        m = _control_member(leaf)
                        if m:
                            dispatched.setdefault(m, (src, leaf.lineno))

    findings = []
    for name, (src, line) in sorted(members.items()):
        if name in _P301_EXEMPT:
            continue
        if name in sent and name not in dispatched:
            ssrc, sline = sent[name]
            findings.append(Finding(
                "GX-P301", SEV_ERROR, ssrc.rel, sline,
                symbol=f"Control.{name}", detail="sent-unhandled",
                message=f"Control.{name} is sent here but no dispatch "
                        f"branch receives it"))
        elif name in dispatched and name not in sent:
            dsrc, dline = dispatched[name]
            findings.append(Finding(
                "GX-P301", SEV_ERROR, dsrc.rel, dline,
                symbol=f"Control.{name}", detail="dispatched-unsent",
                message=f"Control.{name} has a dispatch branch but is "
                        f"never sent"))
        elif name not in sent and name not in dispatched:
            findings.append(Finding(
                "GX-P301", SEV_ERROR, src.rel, line,
                symbol=f"Control.{name}", detail="unused",
                message=f"Control.{name} is neither sent nor dispatched"))
    return findings


# ---------------------------------------------------------------------------
# GX-P302: droppable requests
# ---------------------------------------------------------------------------

def _check_droppable_requests(src: SourceFile) -> List[Finding]:
    findings = []
    for fn, qual, _cls in _iter_functions(src.tree):
        if not _HANDLER_NAME_RE.search(fn.name):
            continue
        argnames = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                    + fn.args.kwonlyargs)}
        if "req" not in argnames:
            continue
        # sink = any use of bare `req` that is not a plain attribute
        # read: passed to a call, stored in a tuple/list, returned, ...
        attr_reads = {id(n.value) for n in ast.walk(fn)
                      if isinstance(n, ast.Attribute)}
        sink_lines = sorted(
            n.lineno for n in ast.walk(fn)
            if isinstance(n, ast.Name) and n.id == "req"
            and isinstance(n.ctx, ast.Load) and id(n) not in attr_reads)

        parents: Dict[int, ast.AST] = {}
        for node in ast.walk(fn):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node

        def fenced(ret: ast.Return) -> bool:
            node: ast.AST = ret
            while id(node) in parents and node is not fn:
                node = parents[id(node)]
                if (isinstance(node, ast.If)
                        and _contains_is_stale(node.test)):
                    return True
            return False

        for node in ast.walk(fn):
            if not isinstance(node, ast.Return):
                continue
            if any(ln <= node.lineno for ln in sink_lines):
                continue
            v = node.value
            if (isinstance(v, ast.Constant) and v.value is False):
                continue  # handler-chain decline: "not my traffic"
            if v is not None and any(
                    isinstance(n, ast.Name) and n.id == "req"
                    for n in ast.walk(v)):
                continue
            if fenced(node):
                continue
            findings.append(Finding(
                "GX-P302", SEV_ERROR, src.rel, node.lineno, symbol=qual,
                detail=f"return@{node.lineno - fn.lineno}",
                message=f"{fn.name} can return without forwarding or "
                        f"responding to req (silent request drop; fence "
                        f"with is_stale if the drop is intentional)"))
    return findings


# ---------------------------------------------------------------------------
# GX-P303: bare-key response routing
# ---------------------------------------------------------------------------

def _walk_own(fn: ast.AST):
    """Walk a function body WITHOUT descending into nested function
    definitions (those are analyzed as functions in their own right)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _check_bare_key_routing(src: SourceFile) -> List[Finding]:
    findings = []
    for fn, qual, _cls in _iter_functions(src.tree):
        uses_range = any(
            (isinstance(n, ast.Attribute) and n.attr in ("offsets",
                                                         "offset_of"))
            or (isinstance(n, ast.Name) and n.id == "offset_of")
            for n in ast.walk(fn))
        if uses_range:
            continue
        for node in _walk_own(fn):
            if not isinstance(node, ast.For):
                continue
            it = node.iter
            if (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                    and it.func.id == "enumerate" and it.args):
                it = it.args[0]
                key_var = (node.target.elts[1]
                           if isinstance(node.target, ast.Tuple)
                           and len(node.target.elts) == 2 else None)
            else:
                key_var = node.target
            if not (isinstance(it, ast.Attribute) and it.attr == "keys"
                    and isinstance(key_var, ast.Name)):
                continue
            # routing = indexing per-key state by the BARE key variable
            routed = any(
                isinstance(n, ast.Subscript)
                and isinstance(n.slice, ast.Name)
                and n.slice.id == key_var.id
                for n in ast.walk(node))
            if routed:
                findings.append(Finding(
                    "GX-P303", SEV_ERROR, src.rel, node.lineno,
                    symbol=qual, detail=f"{call_name(it)}",
                    message=f"{fn.name} routes per bare key over "
                            f"{call_name(it)} without consulting offsets "
                            f"— sliced keys alias one completion slot; "
                            f"route by (key, range)"))
                break
    return findings


# ---------------------------------------------------------------------------
# GX-P304: unfenced countdown mutation
# ---------------------------------------------------------------------------

def _mutates_agg_state(fn: ast.AST) -> Optional[int]:
    """Line of the first aggregation-state mutation in ``fn``:
    ``x.attr += ...`` or ``x.attr.append/extend(...)`` where the
    receiver is attribute state (not a bare local)."""
    for node in ast.walk(fn):
        if (isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Add)
                and isinstance(node.target, ast.Attribute)):
            return node.lineno
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("append", "extend")
                and isinstance(node.func.value, ast.Attribute)):
            return node.lineno
    return None


def _check_unfenced_mutations(src: SourceFile) -> List[Finding]:
    findings = []
    by_class: Dict[Optional[str], List[Tuple[ast.AST, str]]] = {}
    cls_of: Dict[str, Optional[str]] = {}
    for fn, qual, cls in _iter_functions(src.tree):
        cname = cls.name if cls is not None else None
        by_class.setdefault(cname, []).append((fn, qual))
        cls_of[qual] = cname

    for cname, fns in by_class.items():
        if cname is None:
            continue
        methods = {fn.name: fn for fn, _q in fns}
        # fence roots: methods that themselves call is_stale
        fenced: Set[str] = {name for name, fn in methods.items()
                            if _contains_is_stale(fn)}
        # propagate: callees of a fenced method run behind its fence
        frontier = list(fenced)
        while frontier:
            m = frontier.pop()
            for node in ast.walk(methods[m]):
                if isinstance(node, ast.Call):
                    cn = call_name(node.func)
                    if cn.startswith("self."):
                        callee = cn.split(".", 1)[1]
                        if callee in methods and callee not in fenced:
                            fenced.add(callee)
                            frontier.append(callee)
        for fn, qual in fns:
            if fn.name in fenced:
                continue
            argnames = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                        + fn.args.kwonlyargs)}
            if "req" not in argnames:
                continue
            line = _mutates_agg_state(fn)
            if line is None:
                continue
            findings.append(Finding(
                "GX-P304", SEV_ERROR, src.rel, fn.lineno, symbol=qual,
                detail="unfenced-mutation",
                message=f"{fn.name} mutates aggregation state (line "
                        f"{line}) from a request without an "
                        f"is_stale/epoch fence on its call path (zombie "
                        f"senders can corrupt countdowns)"))
    return findings


# ---------------------------------------------------------------------------
# GX-P305: static-count countdowns
# ---------------------------------------------------------------------------

def _involves_len_or_count(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == "len"):
            return True
        name = (getattr(n, "attr", None) if isinstance(n, ast.Attribute)
                else getattr(n, "id", None) if isinstance(n, ast.Name)
                else None)
        if name and _COUNT_NAME_RE.search(name):
            return True
    return False


def _check_static_counts(src: SourceFile) -> List[Finding]:
    findings = []
    for fn, qual, _cls in _iter_functions(src.tree):
        for node in ast.walk(fn):
            if isinstance(node, ast.Compare):
                sides = [node.left] + list(node.comparators)
                for i, side in enumerate(sides):
                    for leaf in ast.walk(side):
                        if (isinstance(leaf, ast.Attribute)
                                and leaf.attr in _STATIC_COUNT_ATTRS):
                            others = sides[:i] + sides[i + 1:]
                            if any(_involves_len_or_count(o)
                                   for o in others):
                                findings.append(Finding(
                                    "GX-P305", SEV_ERROR, src.rel,
                                    leaf.lineno, symbol=qual,
                                    detail=f"compare:{leaf.attr}",
                                    message=f"countdown compared against "
                                            f"static {leaf.attr}; size "
                                            f"rounds from the live view "
                                            f"(num_live_workers / "
                                            f"live_worker_ids)"))
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg is None or not _TGT_KWARG_RE.search(kw.arg):
                        continue
                    for leaf in ast.walk(kw.value):
                        if (isinstance(leaf, ast.Attribute)
                                and leaf.attr in _STATIC_COUNT_ATTRS):
                            findings.append(Finding(
                                "GX-P305", SEV_ERROR, src.rel,
                                leaf.lineno, symbol=qual,
                                detail=f"kwarg:{kw.arg}:{leaf.attr}",
                                message=f"{kw.arg}= sized from static "
                                        f"{leaf.attr}; pass the live "
                                        f"view (num_live_workers / a "
                                        f"callable) instead"))
    return findings


# ---------------------------------------------------------------------------
# GX-P307: compr codec stamped without its aux sidecar
# ---------------------------------------------------------------------------

# codecs whose wire payload cannot be decoded without an aux operand
# (the 2-bit threshold, row-sparse ids, bsc16 indices) — mirrors
# compression.device._AUX_REQUIRED, restated here because geomx-lint
# never imports the analyzed tree
_P307_AUX_REQUIRED = {"2bit", "rsp", "bsc16"}


def _check_compr_aux(src: SourceFile) -> List[Finding]:
    findings = []
    seen: Set[int] = set()

    def check_call(node: ast.Call, qual: str) -> None:
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        tag = const_str(kw.get("compr"))
        if tag not in _P307_AUX_REQUIRED or "aux" in kw:
            return
        findings.append(Finding(
            "GX-P307", SEV_ERROR, src.rel, node.lineno, symbol=qual,
            detail=f"{call_name(node.func)}:{tag}",
            message=f"compr=\"{tag}\" stamped without its aux sidecar "
                    f"— the {tag} payload is undecodable without it; "
                    f"pass aux= in the same call"))

    for fn, qual, _cls in _iter_functions(src.tree):
        for node in _walk_own(fn):
            if isinstance(node, ast.Call):
                check_call(node, qual)
                seen.add(id(node))
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call) and id(node) not in seen:
            check_call(node, "<module>")
    return findings


# ---------------------------------------------------------------------------
# GX-P306: binary-meta schema fingerprint
# ---------------------------------------------------------------------------

def extract_meta_schema(sources: Sequence[SourceFile]):
    """-> (src, line, version, [(name, kind), ...]) or None."""
    for src in sources:
        if src.tree is None:
            continue
        fields = version = None
        line = 0
        for node in ast.walk(src.tree):
            # both `X = [...]` and the annotated `X: List[...] = [...]`
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                tgt = node.target
            else:
                continue
            name = tgt.id if isinstance(tgt, ast.Name) else None
            if name == "_META_FIELDS" and isinstance(node.value,
                                                     (ast.List, ast.Tuple)):
                out = []
                for elt in node.value.elts:
                    if (isinstance(elt, ast.Tuple) and len(elt.elts) == 2
                            and all(isinstance(e, ast.Constant)
                                    for e in elt.elts)):
                        out.append((elt.elts[0].value, elt.elts[1].value))
                fields, line = out, node.lineno
            elif name == "BINMETA_VERSION" and isinstance(
                    node.value, ast.Constant):
                version = node.value.value
        if fields is not None:
            return src, line, version, fields
    return None


def meta_schema_fingerprint(fields) -> str:
    blob = ";".join(f"{n}:{k}" for n, k in fields)
    return hashlib.sha256(blob.encode()).hexdigest()


def binmeta_lock_path(root: Path) -> Path:
    return Path(root) / "tools" / "analyze" / BINMETA_LOCK_NAME


def write_binmeta_lock(sources: Sequence[SourceFile], root: Path) -> Path:
    schema = extract_meta_schema(sources)
    if schema is None:
        raise ValueError("no _META_FIELDS definition in the analyzed tree")
    _src, _line, version, fields = schema
    path = binmeta_lock_path(root)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(
        {"version": version,
         "fingerprint": meta_schema_fingerprint(fields)},
        indent=1) + "\n", encoding="utf-8")
    return path


def _check_binmeta(sources: Sequence[SourceFile],
                   root: Path) -> List[Finding]:
    schema = extract_meta_schema(sources)
    if schema is None:
        return []  # tree has no binary meta codec: nothing to lock
    src, line, version, fields = schema
    fp = meta_schema_fingerprint(fields)
    lock_file = binmeta_lock_path(root)
    if not lock_file.exists():
        return [Finding(
            "GX-P306", SEV_ERROR, src.rel, line, symbol="_META_FIELDS",
            detail="lock-missing",
            message="no binmeta schema lock; run `python -m tools.analyze "
                    "--update-binmeta-lock` and commit it")]
    lock = json.loads(lock_file.read_text(encoding="utf-8"))
    if version != lock.get("version"):
        return [Finding(
            "GX-P306", SEV_ERROR, src.rel, line, symbol="_META_FIELDS",
            detail="version-changed",
            message=f"BINMETA_VERSION is {version} but the lock holds "
                    f"{lock.get('version')}; refresh the lock with "
                    f"--update-binmeta-lock")]
    if fp != lock.get("fingerprint"):
        return [Finding(
            "GX-P306", SEV_ERROR, src.rel, line, symbol="_META_FIELDS",
            detail="schema-changed",
            message="Meta wire schema changed without a BINMETA_VERSION "
                    "bump — a mixed-version cluster would mis-decode "
                    "frames; bump BINMETA_VERSION, then refresh the lock")]
    return []
