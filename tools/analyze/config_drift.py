"""Config-drift pass: env-var surface vs docs vs launch scripts.

The project's entire topology/feature surface is environment variables
(config.py), documented in docs/env-var-summary.md and exercised by
scripts/*.sh. These three drift independently; this pass cross-checks.

Rules
-----
GX-C201 (error)   knob read by the code (an ``env_*`` registration in
                  config.py, or a raw ``os.environ`` read anywhere in the
                  package) that docs/env-var-summary.md does not mention.
GX-C202 (error)   variable documented in docs/env-var-summary.md that no
                  code reads any more — a stale doc row.
GX-C203 (warning) raw ``os.environ``/``os.getenv`` read outside config.py
                  — bypasses the one place tests/operators can audit.
GX-C204 (warning) knob-prefixed variable set in scripts/*.sh that the
                  code never reads — a launch script exporting dead air.

Doc parsing understands the summary table's shorthand: a cell like
``DMLC_K`` / ``_K_MIN`` or ``...ROOT_URI`` / ``_PORT`` expands the
leading-underscore form against the previous variable's prefix.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SEV_ERROR, SEV_WARNING, SourceFile, call_name, \
    const_str

_ENV_HELPERS = {"env_str", "env_int", "env_float", "env_bool"}
# prefixes that mark a shell variable as a knob of ours (GX-C204 scope);
# everything else in a script (PYTHONPATH, loop counters, …) is ignored
_KNOB_PREFIXES = ("DMLC_", "PS_", "GEOMX_", "MXNET_", "ENABLE_", "DGT_",
                  "ADAPTIVE_", "MAX_GREED", "UDP_")
_EXACT_KNOBS = {"PORT"}

_VAR_TOKEN = re.compile(r"`(_?[A-Z][A-Z0-9_]+)`")
_SH_ASSIGN = re.compile(r"(?:^|[\s;(\"'])(?:export\s+)?"
                        r"([A-Z][A-Z0-9_]+)=", re.M)


def _is_knob(name: str) -> bool:
    return name in _EXACT_KNOBS or name.startswith(_KNOB_PREFIXES)


def _expand_doc_shorthand(tokens: List[str]) -> List[str]:
    """[`DMLC_PS_GLOBAL_ROOT_URI`, `_PORT`] -> both full names: a
    leading-underscore token replaces the longest matching tail of the
    previous full name segment-wise."""
    out: List[str] = []
    for tok in tokens:
        if tok.startswith("_") and out:
            prev = out[-1]
            segs = prev.split("_")
            add = tok.lstrip("_").split("_")
            # drop as many trailing segments from prev as the shorthand
            # carries, then append the shorthand
            base = segs[:-len(add)] if len(add) < len(segs) else segs[:1]
            out.append("_".join(base + add))
        else:
            out.append(tok)
    return out


def parse_doc_vars(doc_path: Path) -> Dict[str, int]:
    """Documented variable -> first line number."""
    if not doc_path.exists():
        return {}
    vars_: Dict[str, int] = {}
    for lineno, line in enumerate(
            doc_path.read_text(encoding="utf-8").splitlines(), 1):
        if not line.lstrip().startswith("|"):
            continue
        tokens = _VAR_TOKEN.findall(line)
        for name in _expand_doc_shorthand(tokens):
            vars_.setdefault(name, lineno)
    return vars_


def parse_registrations(config_src: SourceFile) -> Dict[str, int]:
    """env_*("NAME", ...) registrations in config.py -> line."""
    regs: Dict[str, int] = {}
    if config_src.tree is None:
        return regs
    for node in ast.walk(config_src.tree):
        if isinstance(node, ast.Call) \
                and call_name(node.func) in _ENV_HELPERS and node.args:
            name = const_str(node.args[0])
            if name:
                regs.setdefault(name, node.lineno)
    return regs


def parse_raw_reads(sources: Sequence[SourceFile],
                    config_rel: str) -> List[Tuple[SourceFile, int, str]]:
    """(source, line, var) for os.environ.get/os.getenv/os.environ[...]
    with a constant name, outside config.py."""
    out = []
    for src in sources:
        if src.tree is None or src.rel == config_rel:
            continue
        for node in ast.walk(src.tree):
            name: Optional[str] = None
            line = 0
            if isinstance(node, ast.Call):
                cn = call_name(node.func)
                if cn in ("os.environ.get", "os.getenv", "environ.get",
                          "getenv") and node.args:
                    name = const_str(node.args[0])
                    line = node.lineno
            elif isinstance(node, ast.Subscript):
                if call_name(node.value) in ("os.environ", "environ"):
                    name = const_str(node.slice)
                    line = node.lineno
            if name:
                out.append((src, line, name))
    return out


def parse_script_vars(script_paths: Sequence[Path],
                      root: Path) -> Dict[str, Tuple[str, int]]:
    """Knob-prefixed shell assignments -> (rel path, line)."""
    vars_: Dict[str, Tuple[str, int]] = {}
    for sp in script_paths:
        try:
            rel = sp.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = sp.as_posix()
        for lineno, line in enumerate(
                sp.read_text(encoding="utf-8").splitlines(), 1):
            for m in _SH_ASSIGN.finditer(line):
                name = m.group(1)
                if _is_knob(name):
                    vars_.setdefault(name, (rel, lineno))
    return vars_


def run_config_drift(sources: Sequence[SourceFile], root: Path,
                     config_rel: str = "geomx_tpu/config.py",
                     doc_rel: str = "docs/env-var-summary.md",
                     scripts_glob: str = "scripts/*.sh") -> List[Finding]:
    findings: List[Finding] = []
    config_src = next((s for s in sources if s.rel == config_rel), None)
    regs = parse_registrations(config_src) if config_src else {}
    raw = parse_raw_reads(sources, config_rel)
    doc = parse_doc_vars(root / doc_rel)
    scripts = parse_script_vars(sorted(root.glob(scripts_glob)), root)

    code_reads: Dict[str, Tuple[str, int]] = {}
    for name, line in regs.items():
        code_reads.setdefault(name, (config_rel, line))
    for src, line, name in raw:
        code_reads.setdefault(name, (src.rel, line))

    for name, (rel, line) in sorted(code_reads.items()):
        if name not in doc:
            findings.append(Finding(
                "GX-C201", SEV_ERROR, rel, line, symbol=name,
                message=(f"env knob {name!r} is read by the code but "
                         f"missing from {doc_rel} — document it or "
                         f"delete the read")))

    for name, line in sorted(doc.items()):
        if name not in code_reads and _is_knob(name):
            findings.append(Finding(
                "GX-C202", SEV_ERROR, doc_rel, line, symbol=name,
                message=(f"{doc_rel} documents {name!r} but no code "
                         f"reads it — stale doc row")))

    for src, line, name in raw:
        findings.append(Finding(
            "GX-C203", SEV_WARNING, src.rel, line, symbol=name,
            message=(f"raw os.environ read of {name!r} outside "
                     f"config.py — register it through "
                     f"config.env_str/env_int/env_bool so the knob "
                     f"surface stays auditable")))

    for name, (rel, line) in sorted(scripts.items()):
        if name not in code_reads and name not in doc:
            findings.append(Finding(
                "GX-C204", SEV_WARNING, rel, line, symbol=name,
                message=(f"launch script sets {name!r} but no code "
                         f"reads it — dead knob or typo")))
    return findings


def config_drift_surface(sources: Sequence[SourceFile], root: Path,
                         config_rel: str = "geomx_tpu/config.py",
                         doc_rel: str = "docs/env-var-summary.md") -> dict:
    """The surface this pass reasons about, for the unified ``--json``
    fingerprint stream: the registered env-knob names and the documented
    rows. A changed fingerprint means the knob registry moved."""
    config_src = next((s for s in sources if s.rel == config_rel), None)
    regs = parse_registrations(config_src) if config_src else {}
    return {
        "registered": sorted(regs),
        "documented": sorted(parse_doc_vars(Path(root) / doc_rel)),
    }
