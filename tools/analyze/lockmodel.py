"""Lock-model pass: the shared model behind geomx-racecheck.

Extracts the concurrency model from the AST — every class's lock
inventory (raw ``threading`` primitives AND the traced
``locks.make_lock``/``make_rlock``/``make_condition`` factories) plus
its ``@guarded_by("lock", "field", ...)`` declarations — and freezes it
into ``tools/analyze/locks.lock.json``, the same lock-file workflow as
the binary-meta schema (GX-P306): drift fails GX-L007 and
``python -m tools.analyze --update-lock-model`` moves the lock. The
runtime witness (``geomx_tpu/ps/locks.py``) loads the SAME json and
cross-checks every runtime ``@guarded_by`` registration against it, so
the static declarations and the runtime locksets cannot diverge.

Rules
-----
GX-L005 (warning) a ``self.<field>`` written with no lock held from two
                  or more distinct thread roots — a method spawned as a
                  thread target (``Thread(target=self.m)`` /
                  ``self._spawn(self.m)`` / ``run``) or anything it
                  calls, plus the external-caller root — with no
                  ``@guarded_by`` declaration. The untyped cousin of
                  GX-L002: no guarding lock exists anywhere, so the
                  write-side race is invisible to the inversion rules.
GX-L006 (error)   ``Condition.wait()`` outside a ``while`` predicate
                  loop — wakeups are spurious-wakeup- and missed-
                  signal-prone unless re-checked in a loop.
                  ``wait_for`` carries its own predicate loop and is
                  exempt.
GX-L007 (error)   the extracted lock model of an analyzed file drifted
                  from ``tools/analyze/locks.lock.json`` (entry
                  missing, stale, or fingerprint changed). After a
                  deliberate change: ``--update-lock-model`` and commit
                  the lock diff.
"""

from __future__ import annotations

import ast
import hashlib
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .concurrency import _ScopeInfo, _collect_locks, _scan_method
from .core import (Finding, SEV_ERROR, SEV_WARNING, SourceFile, call_name,
                   const_str)

_GUARDED_DECOS = {"guarded_by", "locks.guarded_by"}
_EXTERNAL_ROOT = "<caller>"


# ---------------------------------------------------------------------------
# model extraction
# ---------------------------------------------------------------------------

def _guarded_map(cls: ast.ClassDef) -> Dict[str, str]:
    """``@guarded_by("lock", "f1", "f2")`` decorators -> {field: lock}."""
    out: Dict[str, str] = {}
    for deco in cls.decorator_list:
        if not isinstance(deco, ast.Call):
            continue
        if call_name(deco.func) not in _GUARDED_DECOS or not deco.args:
            continue
        lock = const_str(deco.args[0])
        if lock is None:
            continue
        for arg in deco.args[1:]:
            field = const_str(arg)
            if field is not None:
                out[field] = lock
    return out


def _thread_entries(cls: ast.ClassDef) -> Set[str]:
    """Methods handed to a thread: ``Thread(target=self.m)``, a
    ``*spawn*``-named helper's ``self.m`` argument, or ``run``."""
    entries: Set[str] = set()
    methods = {n.name for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

    def self_method(node: ast.AST) -> Optional[str]:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and node.attr in methods):
            return node.attr
        return None

    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        cname = call_name(node.func)
        if cname.rsplit(".", 1)[-1] == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    m = self_method(kw.value)
                    if m:
                        entries.add(m)
        elif "spawn" in cname.rsplit(".", 1)[-1].lower():
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                m = self_method(arg)
                if m:
                    entries.add(m)
    if "run" in methods and entries | {"run"} != {"run"}:
        # a class that both spawns threads and defines run(): run is a
        # plausible extra entry; a lone run() without spawning is not
        entries.add("run")
    return entries


def _class_scope(src: SourceFile, cls: ast.ClassDef) -> _ScopeInfo:
    modname = Path(src.rel).stem
    scope = _ScopeInfo(f"{modname}.{cls.name}", "self.")
    methods = [n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    _collect_locks(scope, methods, prefix_self=True)
    for m in methods:
        _scan_method(scope, m.name, m)
    return scope


def extract_lock_model(sources: Sequence[SourceFile]
                       ) -> Dict[str, Dict[str, dict]]:
    """rel path -> {"classes": {name: {"locks": {attr: kind},
    "guarded": {field: lock}}}} for files with any lock content."""
    model: Dict[str, Dict[str, dict]] = {}
    for src in sources:
        if src.tree is None:
            continue
        classes: Dict[str, dict] = {}
        for cls in [n for n in ast.walk(src.tree)
                    if isinstance(n, ast.ClassDef)]:
            scope = _class_scope(src, cls)
            guarded = _guarded_map(cls)
            if not scope.locks and not guarded:
                continue
            classes[cls.name] = {
                "locks": {name: d.kind
                          for name, d in sorted(scope.locks.items())},
                "guarded": dict(sorted(guarded.items())),
            }
        if classes:
            model[src.rel] = {"classes": classes}
    return model


def model_fingerprint(entry: dict) -> str:
    return hashlib.sha256(
        json.dumps(entry, sort_keys=True).encode("utf-8")).hexdigest()[:16]


def lockmodel_lock_path(root: Path) -> Path:
    return Path(root) / "tools" / "analyze" / "locks.lock.json"


def write_lock_model(sources: Sequence[SourceFile], root: Path) -> Path:
    """Freeze the current model — the ``--update-lock-model`` action."""
    model = extract_lock_model(sources)
    doc = {
        "version": 1,
        "files": {
            rel: {"fingerprint": model_fingerprint(entry), **entry}
            for rel, entry in sorted(model.items())
        },
    }
    path = lockmodel_lock_path(root)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n",
                    encoding="utf-8")
    return path


# ---------------------------------------------------------------------------
# GX-L005: unguarded multi-root writes
# ---------------------------------------------------------------------------

def _reachable(scope: _ScopeInfo, roots: Set[str]) -> Dict[str, Set[str]]:
    """method -> set of entry roots that (transitively) reach it."""
    reach: Dict[str, Set[str]] = {}
    for root in roots:
        stack, seen = [root], set()
        while stack:
            m = stack.pop()
            if m in seen:
                continue
            seen.add(m)
            reach.setdefault(m, set()).add(root)
            for callee, _held, _line in scope.calls.get(m, ()):
                for cand in (callee, f"{m}.<locals>.{callee}"):
                    if cand in scope.calls or cand in scope.direct_acquires:
                        stack.append(cand)
    return reach


def _l005_findings(src: SourceFile, cls: ast.ClassDef, scope: _ScopeInfo,
                   guarded: Dict[str, str],
                   entries: Set[str]) -> List[Finding]:
    if not entries:
        return []
    out: List[Finding] = []
    reach = _reachable(scope, entries)
    for attr, writes in sorted(scope.unguarded_writes.items()):
        if attr in guarded or attr in scope.locks \
                or attr in scope.threads or attr in scope.queues:
            continue
        if scope.guarded_writes.get(attr):
            continue  # mixed guarded/unguarded is GX-L002's finding
        roots: Set[str] = set()
        for w in writes:
            roots |= reach.get(w.method, {_EXTERNAL_ROOT})
        if len(roots) < 2 or not (roots & entries):
            continue
        w = writes[0]
        out.append(Finding(
            "GX-L005", SEV_WARNING, src.rel, w.line,
            symbol=f"{scope.qualname}.{attr}",
            detail=":".join(sorted(roots)),
            message=(f"{scope.qualname}.{attr} is written with no lock "
                     f"held from {len(roots)} thread roots "
                     f"({', '.join(sorted(roots))}) and carries no "
                     f"@guarded_by declaration — racy write; guard it "
                     f"or declare the lock")))
    return out


# ---------------------------------------------------------------------------
# GX-L006: Condition.wait outside a while loop
# ---------------------------------------------------------------------------

def _l006_findings(src: SourceFile, cls: ast.ClassDef,
                   scope: _ScopeInfo) -> List[Finding]:
    conds = {name for name, d in scope.locks.items()
             if d.kind == "Condition"}
    if not conds:
        return []
    out: List[Finding] = []

    def visit(node: ast.AST, method: str, in_while: bool) -> None:
        if isinstance(node, ast.While):
            for child in ast.iter_child_nodes(node):
                visit(child, method, True)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and method is not None:
            visit_method(node, f"{method}.<locals>.{node.name}")
            return
        if isinstance(node, ast.Call) and not in_while:
            name = call_name(node.func)
            if name.startswith("self.") and name.endswith(".wait"):
                attr = name[len("self."):-len(".wait")]
                if attr in conds:
                    out.append(Finding(
                        "GX-L006", SEV_ERROR, src.rel, node.lineno,
                        symbol=f"{scope.qualname}.{method}", detail=attr,
                        message=(f"Condition {attr!r}.wait() outside a "
                                 f"while predicate loop in {method} — "
                                 f"spurious wakeups and missed signals "
                                 f"break this; loop on the predicate or "
                                 f"use wait_for()")))
        for child in ast.iter_child_nodes(node):
            visit(child, method, in_while)

    def visit_method(fn: ast.AST, name: str) -> None:
        for st in fn.body:
            visit(st, name, False)

    for m in [n for n in cls.body
              if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        visit_method(m, m.name)
    return out


# ---------------------------------------------------------------------------
# GX-L007: lock-file drift
# ---------------------------------------------------------------------------

def _l007_findings(model: Dict[str, dict], root: Path) -> List[Finding]:
    if not model:
        return []
    lock_path = lockmodel_lock_path(root)
    rel_lock = "tools/analyze/locks.lock.json"
    if not lock_path.exists():
        return [Finding(
            "GX-L007", SEV_ERROR, rel_lock, 0, symbol="locks.lock.json",
            detail="lock-missing",
            message=("lock model file is missing — freeze the current "
                     "model with `python -m tools.analyze "
                     "--update-lock-model` and commit it"))]
    try:
        doc = json.loads(lock_path.read_text(encoding="utf-8"))
    except ValueError:
        return [Finding(
            "GX-L007", SEV_ERROR, rel_lock, 0, symbol="locks.lock.json",
            detail="lock-unreadable",
            message="lock model file is not valid json — regenerate it "
                    "with --update-lock-model")]
    files = doc.get("files", {})
    out: List[Finding] = []
    for rel, entry in sorted(model.items()):
        frozen = files.get(rel)
        if frozen is None:
            out.append(Finding(
                "GX-L007", SEV_ERROR, rel, 0, symbol=rel,
                detail="entry-missing",
                message=(f"{rel} now carries locks/@guarded_by but has "
                         f"no entry in {rel_lock} — run "
                         f"--update-lock-model and commit the diff")))
        elif frozen.get("fingerprint") != model_fingerprint(entry):
            out.append(Finding(
                "GX-L007", SEV_ERROR, rel, 0, symbol=rel,
                detail="model-changed",
                message=(f"lock model of {rel} drifted from {rel_lock} "
                         f"(lock inventory or @guarded_by declarations "
                         f"changed) — review, then --update-lock-model "
                         f"and commit the diff")))
    return out


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def run_lockmodel(sources: Sequence[SourceFile],
                  root: Path) -> List[Finding]:
    findings: List[Finding] = []
    model: Dict[str, dict] = {}
    for src in sources:
        if src.tree is None:
            continue
        classes: Dict[str, dict] = {}
        for cls in [n for n in ast.walk(src.tree)
                    if isinstance(n, ast.ClassDef)]:
            scope = _class_scope(src, cls)
            guarded = _guarded_map(cls)
            if scope.locks or guarded:
                classes[cls.name] = {
                    "locks": {name: d.kind
                              for name, d in sorted(scope.locks.items())},
                    "guarded": dict(sorted(guarded.items())),
                }
            entries = _thread_entries(cls)
            findings += _l005_findings(src, cls, scope, guarded, entries)
            findings += _l006_findings(src, cls, scope)
        if classes:
            model[src.rel] = {"classes": classes}
    findings += _l007_findings(model, Path(root))
    return findings
