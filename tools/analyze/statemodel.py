"""State-model pass: the shared model behind geomx-statecheck.

The membership/epoch/recovery/round-release protocol lives smeared
across three files — ``ps/van.py`` (scheduler heartbeat-lapse →
``declare_dead`` → epoch bump → DEAD_NODE broadcast; member mirroring;
``_rejoin_epoch`` zombie fencing), ``kvstore/server.py`` (live-view
countdown sizing, ``_on_membership`` round release, ``is_stale`` push
fencing) and ``kvstore/replication.py`` (snapshot/replica restore on
``is_recovery``). This module makes that state machine EXPLICIT twice
over:

1. An **executable model** (:class:`MemberView` / :class:`SchedulerView`)
   — the pure-python transition functions that ``tools/modelcheck.py``
   explores exhaustively at small scope and that the runtime conformance
   sanitizer (``geomx_tpu/ps/conformance.py``, ``GEOMX_STATE_SANITIZER``)
   runs in lock-step against the live van.

2. A **transition table** (:data:`TRANSITIONS`) binding every modeled
   transition to its anchor method in the real tree, with the state
   fields it must write, the protocol verbs it must call and the fences
   (``is_stale`` / live-view countdown / epoch guard) it must carry.
   The extracted per-file signature is frozen into
   ``tools/analyze/state.lock.json`` (same lock-file workflow as the
   binary-meta schema and the racecheck lock model); drift fails
   GX-S501 and ``python -m tools.analyze --update-state-model`` moves
   the lock after a reviewed protocol change.

State machine (scheduler on the left, every member mirrors on the right)::

    heartbeat lapse > grace          DEAD_NODE(epoch, full dead set)
    ──────────────────────▶ declare_dead ────────────────────────▶ adopt
         epoch += 1                                   (stale/dup dropped)
    re-registration          ADD_NODE table(epoch, is_recovery slots)
    ──────────────────────▶ revive_rejoin ──────────────────────▶ adopt
         epoch += 1, _rejoin_epoch[id] = epoch        (old holder fenced)

    server: push ──▶ is_stale fence ──▶ countdown (live view) ──▶ release
    server: epoch bump ──▶ _on_membership re-checks countdowns ──▶ release
    server: start(is_recovery) ──▶ replication.restore (before serving)

Rules
-----
GX-S501 (error) the extracted transition signatures of an analyzed file
                drifted from ``tools/analyze/state.lock.json`` (lock
                missing, unreadable, entry missing, or fingerprint
                changed). After a deliberate protocol change:
                ``--update-state-model`` and commit the lock diff.
GX-S502 (error) a modeled state field (``membership_epoch``,
                ``_declared_dead``, ``_rejoin_epoch``, ``is_recovery``)
                is mutated outside a modeled transition — an epoch bump
                or dead-set edit the model (and therefore modelcheck and
                the runtime sanitizer) cannot see.
GX-S503 (error) a modeled transition is unreachable in code: its anchor
                method is gone, or a required state write / protocol
                call / state read no longer appears in it.
GX-S504 (error) a modeled transition lost its fence: the ``is_stale``
                zombie fence, the live-view countdown sizing, or the
                epoch monotonicity guard.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SEV_ERROR, SourceFile, call_name

# ---------------------------------------------------------------------------
# the executable model — shared by modelcheck (exploration) and the
# runtime conformance sanitizer (lock-step mirroring)
# ---------------------------------------------------------------------------


class MemberView:
    """One node's view of cluster membership: the epoch, the dead set
    and the per-id rejoin fence. The transition methods mirror
    ``ps/van.py`` exactly — ``adopt_broadcast`` is ``_process_dead_node``,
    ``adopt_table`` is the member branch of ``_process_add_node``,
    ``is_stale`` is ``Van.is_stale``."""

    __slots__ = ("epoch", "dead", "rejoin")

    def __init__(self, epoch: int = 0, dead=(), rejoin=()):
        self.epoch = epoch
        self.dead: Set[int] = set(dead)
        self.rejoin: Dict[int, int] = dict(rejoin)

    # -- transitions -----------------------------------------------------

    def adopt_broadcast(self, epoch: int, new_dead) -> str:
        """DEAD_NODE arrival (full dead set + epoch). Returns the
        outcome the real handler takes: "stale" (older epoch, dropped),
        "duplicate" (same epoch + same set, side effects already fired)
        or "adopt"."""
        new_dead = set(new_dead)
        if epoch < self.epoch:
            return "stale"
        if epoch == self.epoch and new_dead == self.dead:
            return "duplicate"
        # ids leaving the dead set were revived: fence the previous
        # holder's in-flight traffic at the broadcast epoch
        for nid in self.dead - new_dead:
            self.rejoin[nid] = epoch
        self.dead = new_dead
        self.epoch = epoch
        return "adopt"

    def adopt_table(self, epoch: int, revived) -> bool:
        """ADD_NODE table broadcast: adopt a newer epoch; recovery
        entries revive their slot (the previous holder stays fenced).
        Returns True when the view changed (callers re-run membership
        side effects exactly then)."""
        changed = False
        if epoch > self.epoch:
            self.epoch = epoch
            changed = True
        for nid in revived:
            if nid in self.dead:
                self.dead.discard(nid)
                self.rejoin[nid] = self.epoch
                changed = True
        return changed

    def is_stale(self, sender: int, epoch: int) -> bool:
        """The zombie fence: a message is stale when its sender is in
        the dead set, or its epoch predates the sender id's rejoin."""
        return sender in self.dead or epoch < self.rejoin.get(sender, 0)

    # -- plumbing --------------------------------------------------------

    def live(self, ids) -> List[int]:
        return sorted(i for i in ids if i not in self.dead)

    def snapshot(self) -> tuple:
        return (self.epoch, tuple(sorted(self.dead)),
                tuple(sorted(self.rejoin.items())))

    def copy(self) -> "MemberView":
        return MemberView(self.epoch, self.dead, self.rejoin)


class SchedulerView(MemberView):
    """The scheduler's authoritative view: it ORIGINATES epochs.
    ``declare_dead`` mirrors ``Van.declare_dead``; ``revive`` mirrors
    the recovery branch of ``Van._scheduler_register``."""

    def declare_dead(self, ids, known=None) -> Optional[Tuple[int, frozenset]]:
        fresh = [i for i in ids if i not in self.dead
                 and (known is None or i in known)]
        if not fresh:
            return None
        self.dead.update(fresh)
        self.epoch += 1
        return self.epoch, frozenset(self.dead)

    def revive(self, nid: int) -> int:
        """Hand a dead slot to a rejoining node: prune the dead set,
        bump the epoch, arm the rejoin fence for the OLD holder."""
        if nid in self.dead:
            self.dead.discard(nid)
            self.epoch += 1
            self.rejoin[nid] = self.epoch
        return self.epoch

    def copy(self) -> "SchedulerView":
        return SchedulerView(self.epoch, self.dead, self.rejoin)


# ---------------------------------------------------------------------------
# the transition table: model <-> code anchors
# ---------------------------------------------------------------------------

#: state fields owned by the membership plane; any store outside a
#: modeled transition is GX-S502
MODELED_FIELDS = ("_declared_dead", "_rejoin_epoch", "is_recovery",
                  "membership_epoch")

#: the class that owns the modeled fields (file suffix, class name)
FIELD_OWNER = ("ps/van.py", "Van")

FENCE_EPOCH_GUARD = "epoch-guard"
FENCE_IS_STALE = "is_stale"
FENCE_LIVE_VIEW = "live-view"

_LIVE_VIEW_CALLS = {"num_live_workers", "num_live_servers",
                    "live_worker_ids", "live_server_ids", "live_ids"}
_MUTATOR_CALLS = {"add", "discard", "remove", "update", "pop", "clear",
                  "setdefault", "extend", "append"}


@dataclasses.dataclass(frozen=True)
class Transition:
    name: str
    file: str            # rel-path suffix of the anchor file
    cls: str
    method: str
    writes: tuple = ()   # modeled fields the anchor must store
    calls: tuple = ()    # protocol verbs the anchor must call
    reads: tuple = ()    # modeled fields the anchor must read
    fences: tuple = ()   # FENCE_* the anchor must carry


TRANSITIONS: Tuple[Transition, ...] = (
    # -- scheduler side (ps/van.py) -------------------------------------
    Transition("declare_dead", "ps/van.py", "Van", "declare_dead",
               writes=("_declared_dead", "membership_epoch"),
               calls=("_broadcast_membership", "_membership_side_effects")),
    Transition("revive_rejoin", "ps/van.py", "Van", "_scheduler_register",
               writes=("_declared_dead", "membership_epoch",
                       "_rejoin_epoch"),
               calls=("_broadcast_membership",)),
    # -- member mirroring (ps/van.py) -----------------------------------
    Transition("adopt_broadcast", "ps/van.py", "Van", "_process_dead_node",
               writes=("_declared_dead", "membership_epoch",
                       "_rejoin_epoch"),
               calls=("_membership_side_effects",),
               fences=(FENCE_EPOCH_GUARD,)),
    Transition("adopt_table", "ps/van.py", "Van", "_process_add_node",
               writes=("membership_epoch", "_declared_dead",
                       "_rejoin_epoch", "is_recovery"),
               calls=("_membership_side_effects",),
               fences=(FENCE_EPOCH_GUARD,)),
    Transition("stale_fence", "ps/van.py", "Van", "is_stale",
               reads=("_declared_dead", "_rejoin_epoch")),
    # -- server round machine (kvstore/server.py) -----------------------
    Transition("stale_push_drop", "kvstore/server.py",
               "KVStoreDistServer", "_handle_data",
               fences=(FENCE_IS_STALE,)),
    Transition("stale_command_drop", "kvstore/server.py",
               "KVStoreDistServer", "_handle_command",
               fences=(FENCE_IS_STALE,)),
    Transition("local_countdown", "kvstore/server.py",
               "KVStoreDistServer", "_expected_local_pushes",
               fences=(FENCE_LIVE_VIEW,)),
    Transition("global_countdown", "kvstore/server.py",
               "KVStoreDistServer", "_expected_global_elems",
               fences=(FENCE_LIVE_VIEW,)),
    Transition("membership_release", "kvstore/server.py",
               "KVStoreDistServer", "_on_membership",
               calls=("_expected_local_pushes", "_expected_global_elems",
                      "_complete_local_round", "_complete_fsa_round")),
    Transition("restore_on_recovery", "kvstore/server.py",
               "KVStoreDistServer", "start",
               reads=("is_recovery",), calls=("restore",)),
    # -- recovery (kvstore/replication.py) ------------------------------
    Transition("restore_merge", "kvstore/replication.py",
               "ReplicationManager", "restore",
               calls=("_fetch_from_peer", "_apply")),
)

#: every protocol verb any transition requires — the extraction records
#: which of these each anchor calls, so ADDING a vocab call to an anchor
#: changes its frozen signature too
_CALL_VOCAB = frozenset(c for t in TRANSITIONS for c in t.calls)


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------

def _class_methods(tree: ast.Module) -> Dict[str, Dict[str, ast.AST]]:
    """class name -> {method name -> def node} (top-level methods)."""
    out: Dict[str, Dict[str, ast.AST]] = {}
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        out[cls.name] = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    return out


def _self_store_field(node: ast.AST) -> Optional[str]:
    """Modeled field stored through ``self``: ``self.f = ...``,
    ``self.f += ...``, ``self.f[k] = ...``; else None."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in MODELED_FIELDS):
        return node.attr
    return None


def _extract_signature(fn: ast.AST) -> Dict[str, List[str]]:
    """The anchor's observable protocol surface: modeled-field writes
    and reads, vocabulary calls, fences."""
    writes: Set[str] = set()
    reads: Set[str] = set()
    calls: Set[str] = set()
    fences: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                for el in (t.elts if isinstance(t, (ast.Tuple, ast.List))
                           else [t]):
                    f = _self_store_field(el)
                    if f is not None:
                        writes.add(f)
        elif isinstance(node, ast.Call):
            name = call_name(node.func)
            parts = name.split(".")
            last = parts[-1] if parts else ""
            if (last in _MUTATOR_CALLS and len(parts) >= 3
                    and parts[0] == "self" and parts[1] in MODELED_FIELDS):
                writes.add(parts[1])
            if last in _CALL_VOCAB:
                calls.add(last)
            if last == "is_stale":
                fences.add(FENCE_IS_STALE)
            if last in _LIVE_VIEW_CALLS:
                fences.add(FENCE_LIVE_VIEW)
        elif isinstance(node, ast.Compare):
            for sub in [node.left] + list(node.comparators):
                for inner in ast.walk(sub):
                    if (isinstance(inner, ast.Attribute)
                            and inner.attr == "membership_epoch"):
                        fences.add(FENCE_EPOCH_GUARD)
        if isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load) \
                and node.attr in MODELED_FIELDS:
            reads.add(node.attr)
    return {"writes": sorted(writes), "calls": sorted(calls),
            "reads": sorted(reads), "fences": sorted(fences)}


def extract_state_model(sources: Sequence[SourceFile]
                        ) -> Dict[str, Dict[str, dict]]:
    """rel path -> {"transitions": {name: signature}} for every
    analyzed file realizing at least one modeled transition."""
    model: Dict[str, Dict[str, dict]] = {}
    for src in sources:
        if src.tree is None:
            continue
        hits: Dict[str, dict] = {}
        classes = None
        for t in TRANSITIONS:
            if not src.rel.endswith(t.file):
                continue
            if classes is None:
                classes = _class_methods(src.tree)
            fn = classes.get(t.cls, {}).get(t.method)
            if fn is not None:
                hits[t.name] = _extract_signature(fn)
        if hits:
            model[src.rel] = {"transitions": dict(sorted(hits.items()))}
    return model


def state_model_fingerprint(entry: dict) -> str:
    return hashlib.sha256(
        json.dumps(entry, sort_keys=True).encode("utf-8")).hexdigest()[:16]


def statemodel_lock_path(root: Path) -> Path:
    return Path(root) / "tools" / "analyze" / "state.lock.json"


def write_state_model(sources: Sequence[SourceFile], root: Path) -> Path:
    """Freeze the current model — the ``--update-state-model`` action."""
    model = extract_state_model(sources)
    doc = {
        "version": 1,
        "files": {
            rel: {"fingerprint": state_model_fingerprint(entry), **entry}
            for rel, entry in sorted(model.items())
        },
    }
    path = statemodel_lock_path(root)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n",
                    encoding="utf-8")
    return path


# ---------------------------------------------------------------------------
# GX-S501: lock-file drift
# ---------------------------------------------------------------------------

def _s501_findings(model: Dict[str, dict], root: Path) -> List[Finding]:
    if not model:
        return []
    lock_path = statemodel_lock_path(root)
    rel_lock = "tools/analyze/state.lock.json"
    if not lock_path.exists():
        return [Finding(
            "GX-S501", SEV_ERROR, rel_lock, 0, symbol="state.lock.json",
            detail="lock-missing",
            message=("protocol state-model lock is missing — freeze the "
                     "current model with `python -m tools.analyze "
                     "--update-state-model` and commit it"))]
    try:
        doc = json.loads(lock_path.read_text(encoding="utf-8"))
    except ValueError:
        return [Finding(
            "GX-S501", SEV_ERROR, rel_lock, 0, symbol="state.lock.json",
            detail="lock-unreadable",
            message="state-model lock is not valid json — regenerate it "
                    "with --update-state-model")]
    files = doc.get("files", {})
    out: List[Finding] = []
    for rel, entry in sorted(model.items()):
        frozen = files.get(rel)
        if frozen is None:
            out.append(Finding(
                "GX-S501", SEV_ERROR, rel, 0, symbol=rel,
                detail="entry-missing",
                message=(f"{rel} realizes modeled protocol transitions "
                         f"but has no entry in {rel_lock} — run "
                         f"--update-state-model and commit the diff")))
        elif frozen.get("fingerprint") != state_model_fingerprint(entry):
            out.append(Finding(
                "GX-S501", SEV_ERROR, rel, 0, symbol=rel,
                detail="model-changed",
                message=(f"protocol transitions of {rel} drifted from "
                         f"{rel_lock} (state writes, verb calls or "
                         f"fences changed) — review the change against "
                         f"the executable model, re-explore with "
                         f"tools/modelcheck.py, then --update-state-model "
                         f"and commit the diff")))
    return out


# ---------------------------------------------------------------------------
# GX-S502: modeled fields mutated outside a modeled transition
# ---------------------------------------------------------------------------

def _s502_findings(src: SourceFile) -> List[Finding]:
    owner_file, owner_cls = FIELD_OWNER
    if not src.rel.endswith(owner_file):
        return []
    allowed = {t.method for t in TRANSITIONS
               if t.file == owner_file and t.cls == owner_cls}
    allowed.add("__init__")
    out: List[Finding] = []
    for cls in [n for n in ast.walk(src.tree)
                if isinstance(n, ast.ClassDef) and n.name == owner_cls]:
        for m in cls.body:
            if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if m.name in allowed:
                continue
            sig = _extract_signature(m)
            for field in sig["writes"]:
                out.append(Finding(
                    "GX-S502", SEV_ERROR, src.rel, m.lineno,
                    symbol=f"{owner_cls}.{m.name}", detail=field,
                    message=(f"{owner_cls}.{m.name} mutates modeled "
                             f"membership state {field!r} outside a "
                             f"modeled transition — the state model "
                             f"(and the runtime conformance sanitizer) "
                             f"cannot see this change; move it into a "
                             f"modeled transition or extend the model")))
    return out


# ---------------------------------------------------------------------------
# GX-S503 / GX-S504: unrealized transitions, missing fences
# ---------------------------------------------------------------------------

def _s503_s504_findings(src: SourceFile) -> List[Finding]:
    out: List[Finding] = []
    classes = None
    for t in TRANSITIONS:
        if not src.rel.endswith(t.file):
            continue
        if classes is None:
            classes = _class_methods(src.tree)
        fn = classes.get(t.cls, {}).get(t.method)
        symbol = f"{t.cls}.{t.method}"
        if fn is None:
            out.append(Finding(
                "GX-S503", SEV_ERROR, src.rel, 0, symbol=symbol,
                detail=f"{t.name}:anchor-missing",
                message=(f"modeled transition {t.name!r} is unreachable: "
                         f"anchor {symbol} no longer exists in {src.rel} "
                         f"— retarget the transition in "
                         f"tools/analyze/statemodel.py or restore the "
                         f"handler")))
            continue
        sig = _extract_signature(fn)
        missing = (
            [("write", w) for w in t.writes if w not in sig["writes"]]
            + [("call", c) for c in t.calls if c not in sig["calls"]]
            + [("read", r) for r in t.reads if r not in sig["reads"]])
        for kind, name in missing:
            out.append(Finding(
                "GX-S503", SEV_ERROR, src.rel, fn.lineno, symbol=symbol,
                detail=f"{t.name}:missing-{kind}:{name}",
                message=(f"modeled transition {t.name!r} is no longer "
                         f"realized by {symbol}: required {kind} "
                         f"{name!r} is gone — the code and the "
                         f"executable model have diverged; fix the "
                         f"handler or update the model AND re-explore "
                         f"(tools/modelcheck.py)")))
        for fence in t.fences:
            if fence not in sig["fences"]:
                out.append(Finding(
                    "GX-S504", SEV_ERROR, src.rel, fn.lineno,
                    symbol=symbol, detail=f"{t.name}:{fence}",
                    message=(f"transition {t.name!r} lost its "
                             f"{fence} fence in {symbol} — zombie "
                             f"traffic can aggregate / countdowns size "
                             f"from dead members / stale epochs adopt; "
                             f"restore the fence (modelcheck's mutation "
                             f"suite shows the exact invariant this "
                             f"breaks)")))
    return out


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def run_statemodel(sources: Sequence[SourceFile],
                   root: Path) -> List[Finding]:
    findings: List[Finding] = []
    for src in sources:
        if src.tree is None:
            continue
        findings += _s502_findings(src)
        findings += _s503_s504_findings(src)
    findings += _s501_findings(extract_state_model(sources), Path(root))
    return findings
