"""Metrics-funnel pass: raw profiler events that bypass telemetry.

Rule
----
GX-M401 (warning) ``profiler.instant(...)`` or ``profiler.counter(...)``
called anywhere except ``telemetry.py``. PR-7 routed operational events
through ``geomx_tpu.telemetry`` (``telemetry.event`` / ``telemetry.
sample``), which forwards to the profiler AND feeds the metrics
registry — a raw profiler call produces a trace marker that the metrics
snapshot, ``kv.metrics()`` and the per-round exports never see, so
dashboards silently undercount. ``profiler.record``/``scope`` (timed
spans) stay first-class: spans are trace-only by design.

The three ``replication.py`` instants predate the funnel and are
accepted in the committed baseline; new code must use the funnel or
carry an explicit ``geomx-lint: disable=GX-M401``.

GX-M402 (warning) a ``link.*`` metric set outside ``ps/linkstate.py``.
The measurement plane (geomx-healthd) is single-sourced: every
per-link gauge/counter — measured goodput, emulated shaping holds,
estimator RTT/bandwidth — goes through the ``linkstate`` note_*
helpers so link metric names and label shapes (src/dst/tier) cannot
drift per call site, and the health board's consumers can trust one
emitter. Same spirit as GX-M401, scoped to the ``link.`` name prefix
on ``telemetry.gauge_set``/``telemetry.counter_inc``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from .core import Finding, SEV_WARNING, SourceFile, call_name, const_str

_RAW_CALLS = {"profiler.instant", "profiler.counter"}

# GX-M402: the telemetry mutators whose first (name) argument is checked
# for the reserved ``link.`` metric namespace
_LINK_CALLS = {"telemetry.gauge_set", "gauge_set",
               "telemetry.counter_inc", "counter_inc",
               "telemetry.sample", "sample"}


def _index_functions(tree: ast.Module):
    """(node, qualname) for every function, nested or method."""
    out = []

    def walk(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append((child, q))
                walk(child, f"{q}.<locals>.")
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def _enclosing(fns, line: int) -> Optional[str]:
    best = None
    for node, q in fns:
        if node.lineno <= line <= (node.end_lineno or node.lineno):
            if best is None or node.lineno > best[0].lineno:
                best = (node, q)
    return best[1] if best else None


def run_metrics(sources: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for src in sources:
        if src.tree is None:
            continue
        fname = src.rel.rsplit("/", 1)[-1]
        # each rule exempts its own funnel: telemetry.py is the one
        # legitimate raw profiler caller (M401), linkstate.py the one
        # legitimate link.* emitter (M402)
        is_telemetry = fname == "telemetry.py"
        is_linkstate = fname == "linkstate.py"
        fns = _index_functions(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            nm = call_name(node.func)
            evname = const_str(node.args[0]) if node.args else None
            if nm in _RAW_CALLS and not is_telemetry:
                findings.append(Finding(
                    "GX-M401", SEV_WARNING, src.rel, node.lineno,
                    symbol=_enclosing(fns, node.lineno) or "<module>",
                    detail=f"{nm}:{evname or node.lineno}",
                    message=(f"{nm}"
                             f"({evname!r}) " if evname else f"{nm}() ")
                    + ("bypasses the telemetry funnel — the event never "
                       "reaches the metrics registry (kv.metrics(), "
                       "per-round snapshots); use telemetry.event() / "
                       "telemetry.sample() instead")))
            elif (nm in _LINK_CALLS and not is_linkstate
                    and evname is not None
                    and evname.startswith("link.")):
                findings.append(Finding(
                    "GX-M402", SEV_WARNING, src.rel, node.lineno,
                    symbol=_enclosing(fns, node.lineno) or "<module>",
                    detail=f"{nm}:{evname}",
                    message=(f"{nm}({evname!r}) sets a link.* metric "
                             "outside ps/linkstate.py — the measurement "
                             "plane is single-sourced; route it through "
                             "a linkstate note_* helper so link metric "
                             "names and src/dst/tier labels cannot "
                             "drift per call site")))
    return findings


def metrics_surface(sources: Sequence[SourceFile]) -> dict:
    """The surface this pass reasons about, for the unified ``--json``
    fingerprint stream: every raw-profiler and link-metric call site
    (file + enclosing symbol + metric name, line-free)."""
    out: Dict[str, List[str]] = {}
    for src in sources:
        if src.tree is None:
            continue
        sites: Set[str] = set()
        for fn, qual in _index_functions(src.tree):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node.func)
                metric = (const_str(node.args[0])
                          if node.args else None) or "<dynamic>"
                if name in _RAW_CALLS or (name in _LINK_CALLS
                                          and metric.startswith("link.")):
                    sites.add(f"{qual}:{name}:{metric}")
        if sites:
            out[src.rel] = sorted(sites)
    return out
