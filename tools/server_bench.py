#!/usr/bin/env python
"""Microbench: server state-machine throughput under multi-key load.

Drives KVStoreDistServer._handle directly (no sockets) from N handler
threads hammering disjoint keys, the way concurrent transport readers do
in production. With the per-(key,offset) locking this scales with
threads; the round-2 single global RLock flattened it (Weak #4).

Prints one JSON line per configuration.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from geomx_tpu.config import Config                       # noqa: E402
from geomx_tpu.kvstore.base import DATA_INIT              # noqa: E402
from geomx_tpu.kvstore.server import KVStoreDistServer    # noqa: E402
from geomx_tpu.optimizer import SGD                       # noqa: E402
from geomx_tpu.ps.kv_app import KVPairs, ReqMeta          # noqa: E402

N_ELEMS = 262_144          # 1 MiB fp32 per key
DURATION = 3.0


class _FakeSrv:
    def response(self, req, kvs=None, body=""):
        pass


def make_server(num_workers: int) -> KVStoreDistServer:
    srv = KVStoreDistServer(Config(role="server", num_workers=num_workers,
                                   num_servers=1))
    srv._ready.set()              # skip transport startup
    srv.updater = SGD(learning_rate=0.01)
    return srv


def push_req(push=True, head=0):
    return ReqMeta(sender=9, timestamp=0, customer_id=0, push=push,
                   pull=not push, simple_app=False, head=head, body="",
                   priority=0, version=0, iters=0, compr="", num_merge=1)


def drive(n_threads: int, keys_per_thread: int) -> float:
    server = make_server(num_workers=1)
    fake = _FakeSrv()
    grad = np.random.default_rng(0).normal(
        size=N_ELEMS).astype(np.float32)

    # init every key
    for t in range(n_threads):
        for k in range(keys_per_thread):
            key = t * keys_per_thread + k
            kvs = KVPairs(keys=[key], vals=[grad], offsets=[0],
                          totals=[N_ELEMS], lens=[N_ELEMS])
            server._handle(push_req(head=DATA_INIT), kvs, fake,
                           global_tier=False)

    counts = [0] * n_threads
    stop = threading.Event()

    def worker(tidx):
        kvss = []
        for k in range(keys_per_thread):
            key = tidx * keys_per_thread + k
            kvss.append(KVPairs(keys=[key], vals=[grad], offsets=[0],
                                totals=[N_ELEMS], lens=[N_ELEMS]))
        i = 0
        while not stop.is_set():
            server._handle(push_req(), kvss[i % keys_per_thread], fake,
                           global_tier=False)
            counts[tidx] += 1
            i += 1

    ts = [threading.Thread(target=worker, args=(t,), daemon=True)
          for t in range(n_threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    time.sleep(DURATION)
    stop.set()
    for t in ts:
        t.join(10)
    dt = time.perf_counter() - t0
    return sum(counts) / dt


def main():
    base = drive(1, 1)
    for n_threads in (1, 2, 4, 8):
        rate = drive(n_threads, keys_per_thread=2)
        print(json.dumps({
            "threads": n_threads,
            "keys": n_threads * 2,
            "elems_per_key": N_ELEMS,
            "rounds_per_s": round(rate, 1),
            "scaling_vs_1thread": round(rate / base, 2),
        }))


if __name__ == "__main__":
    main()
