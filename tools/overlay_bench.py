#!/usr/bin/env python
"""TSEngine overlay vs direct N-to-1 incast on a shaped WAN topology.

Times full global FSA rounds (push + pull + wait, every byte over the
real transport) on an in-process N-party HiPS cluster whose global
tier is shaped by a ShapePlan (ps/shaping.py), twice: once over the
direct wire (every party server pushes its aggregate straight at the
global server — an N-to-1 incast through the server's shared access
pipe) and once with the inter-DC TSEngine overlay (party-to-party
reduction tree up, multicast tree down; only the final merged gradient
and the first model copy cross the shared pipe). Reproduces the
PERF.md "overlay vs incast" capture:

    python tools/overlay_bench.py --parties 16 \
        --shape scripts/shapes/hetero16.json

The run asserts the two wires agree BIT-EXACTLY: gradients are
integer-valued, so float32 summation is exact in any merge order and
``np.array_equal`` must hold between the direct and overlay results.

``--controller`` adds a third pass — the overlay with the self-tuning
transport controller on (per-link codec + slice decisions from live
health estimates, degraded-link schedule bias; health plane + resender
ride along) — for the static-vs-adaptive capture in PERF.md. The
controller may assign lossy codecs, so THAT pass is checked for
finiteness, not bit-exactness.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def run(parties: int, size: int, rounds: int, extra_cfg: dict,
        inter_ts: bool, controller: bool = False):
    """One pass; returns (per-round ms, final weights)."""
    from geomx_tpu.optimizer import SGD
    from geomx_tpu.simulate import InProcessHiPS

    extra = dict(extra_cfg, enable_inter_ts=inter_ts)
    if controller:
        extra.update(transport_controller=True, health=True,
                     resend=True, resend_timeout_ms=3000,
                     resend_deadline_s=180.0)
    w0 = np.zeros(size, np.float32)
    topo = InProcessHiPS(num_parties=parties, workers_per_party=1,
                         extra_cfg=extra).start()
    per_round = {}
    finals = []
    try:
        def master_init(kv):
            kv.set_optimizer(SGD(learning_rate=1.0))
            kv.init(0, w0.copy())
            kv.wait()

        def worker(kv):
            out = w0.copy()
            kv.init(0, w0.copy())
            kv.pull(0, out=out)
            kv.wait()
            ts = []
            for r in range(rounds):
                # integer-valued so any merge order is bit-exact
                grad = np.full(size, float(r + 1), np.float32)
                t0 = time.perf_counter()
                kv.push(0, grad)
                kv.pull(0, out=out)
                kv.wait()
                ts.append((time.perf_counter() - t0) * 1e3)
            per_round[id(kv)] = ts
            finals.append(out.copy())

        topo.run_workers(worker, include_master=master_init,
                         timeout=1200)
    finally:
        topo.stop()
    for f in finals[1:]:
        assert np.array_equal(finals[0], f), \
            "workers disagree on the final model"
    # the round completes when the SLOWEST party has its model back
    worst = [max(ts[r] for ts in per_round.values())
             for r in range(rounds)]
    return worst, finals[0]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--parties", type=int, default=16)
    ap.add_argument("--size", type=int, default=262144,
                    help="elements per gradient (float32); default 1MB")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--shape", default="scripts/shapes/hetero16.json",
                    help="ShapePlan JSON path or inline JSON; '' = off")
    ap.add_argument("--shape-seed", type=int, default=-1)
    ap.add_argument("--controller", action="store_true",
                    help="add an overlay pass with the self-tuning "
                         "transport controller on (static-vs-adaptive "
                         "A/B; that pass skips the bit-exact bar — the "
                         "controller may assign lossy codecs)")
    args = ap.parse_args()

    extra = {}
    if args.shape:
        plan = args.shape.strip()
        extra["shape_plan"] = plan if plan.startswith(("{", "[", "@")) \
            else "@" + plan
    if args.shape_seed >= 0:
        extra["shape_seed"] = args.shape_seed

    print(f"# {args.parties} parties, {args.size * 4 // 1024} KB "
          f"gradient, {args.rounds} rounds, "
          f"shape={args.shape or 'off'}")
    direct_ms, direct_w = run(args.parties, args.size, args.rounds,
                              extra, inter_ts=False)
    overlay_ms, overlay_w = run(args.parties, args.size, args.rounds,
                                extra, inter_ts=True)
    assert np.array_equal(direct_w, overlay_w), \
        "overlay result diverges from the direct wire"

    d, o = np.median(direct_ms), np.median(overlay_ms)
    print(f"direct incast : {d:8.1f} ms/round   "
          f"(rounds: {', '.join(f'{t:.0f}' for t in direct_ms)})")
    print(f"TS overlay    : {o:8.1f} ms/round   "
          f"(rounds: {', '.join(f'{t:.0f}' for t in overlay_ms)})")
    print(f"speedup       : {d / o:8.2f}x   (bit-exact: True)")

    if args.controller:
        ctrl_ms, ctrl_w = run(args.parties, args.size, args.rounds,
                              extra, inter_ts=True, controller=True)
        assert np.all(np.isfinite(ctrl_w)), \
            "adaptive overlay produced non-finite weights"
        c = np.median(ctrl_ms)
        print(f"TS + controller: {c:7.1f} ms/round   "
              f"(rounds: {', '.join(f'{t:.0f}' for t in ctrl_ms)})")
        print(f"speedup vs direct: {d / c:5.2f}x   "
              f"(lossy codecs allowed: finite-only bar)")


if __name__ == "__main__":
    main()
