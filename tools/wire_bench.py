#!/usr/bin/env python
"""Protocol-only round time: batched list wire vs per-key messages.

Times full two-tier FSA rounds (push + pull + wait, every byte over
the real transport) with compute excluded, on an in-process 2-party
topology. The batched wire sends ONE message per server per direction
(kvstore.server._BatchResponder merges the per-key acks); per-key
sends 2*n_keys messages. Reproduces the PERF.md captures:

    python tools/wire_bench.py --layout cnn          # 10 keys, 178k
    python tools/wire_bench.py --layout transformer  # 75 keys, mixed

``--shape scripts/shapes/wan2_50ms_100mbps.json`` replays any mode on
an emulated WAN (ps/shaping.py): per-link RTT + token-bucket
bandwidth on every global-tier data frame. This is the PERF.md
"shaped pipelined round" capture:

    python tools/wire_bench.py --overlap \
        --shape scripts/shapes/wan2_50ms_100mbps.json \
        --trace-out /tmp/shaped_round.json

``--trace-out`` dumps the in-process chrome trace (all nodes, one
file) — feed it to ``python -m tools.trace_merge`` for the Perfetto
artifact showing chunks in flight across rounds.

``--loss-bench`` is the self-tuning-transport A/B (PERF.md
"Self-tuning transport"): an N-party quadratic fit — every worker
pushes ``grad = w - t`` and the server runs SGD, so
``f(w) = 0.5 * ||w - t||^2`` contracts by a known factor per exact
round — timed to a relative loss target on a shaped WAN, once per
static codec policy (raw / fp16 / 2bit / mpq via
``GEOMX_WIRE_CODEC_WAN``) and once with the transport controller
choosing per-link (``--policy adaptive``):

    python tools/wire_bench.py --loss-bench \
        --shape scripts/shapes/hetero16.json --parties 16

``--controller`` runs any of the OTHER modes with the controller on
(health plane + resender come along) for a static-vs-adaptive capture
of the protocol-only benches.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

LAYOUTS = {
    "cnn": [(800,), (32,), (25600,), (64,), (51200,), (128,), (65536,),
            (10,), (1176,), (84,)],
    "transformer": None,   # 75 keys, mixed sizes (seeded below)
}


def run(shapes, batched: bool, rounds: int, extra_cfg=None) -> float:
    from geomx_tpu.optimizer import SGD
    from geomx_tpu.simulate import InProcessHiPS

    keys = list(range(len(shapes)))
    topo = InProcessHiPS(num_parties=2, workers_per_party=1,
                         extra_cfg=extra_cfg).start()
    times = {}
    try:
        def master_init(kv):
            kv.set_optimizer(SGD(learning_rate=0.01))
            for k, sh in zip(keys, shapes):
                kv.init(k, np.zeros(sh, np.float32))
            kv.wait()

        def worker(kv):
            outs = [np.zeros(sh, np.float32) for sh in shapes]
            grads = [np.ones(sh, np.float32) for sh in shapes]
            for k, o in zip(keys, outs):
                kv.init(k, o.copy())
                kv.pull(k, out=o)
            kv.wait()
            t0 = time.perf_counter()
            for _ in range(rounds):
                if batched:
                    kv.push_pull(keys, grads, out=outs)
                else:
                    for k, g, o in zip(keys, grads, outs):
                        kv.push(k, g)
                        kv.pull(k, out=o)
                kv.wait()
            times[id(kv)] = (time.perf_counter() - t0) / rounds * 1e3

        topo.run_workers(worker, include_master=master_init, timeout=600)
    finally:
        topo.stop()
    return max(times.values())


def run_sparse(shapes, threshold: float, rounds: int,
               extra_cfg=None) -> float:
    """Protocol-only round time of the HEADLINE sparse path: the
    combined element-sparse BSC wire (push_pull_bsc_batch — what the
    device-resident trainer sends per round), aggregator-mode PS, top-k
    payloads of ceil(size*threshold) per key."""
    from geomx_tpu.simulate import InProcessHiPS

    keys = list(range(len(shapes)))
    topo = InProcessHiPS(num_parties=2, workers_per_party=1,
                         extra_cfg=extra_cfg).start()
    times = {}
    try:
        def master_init(kv):
            for k, sh in zip(keys, shapes):
                kv.init(k, np.zeros(sh, np.float32))
            kv.wait()

        def worker(kv):
            rng = np.random.RandomState(3)
            sel = []
            for sh in shapes:
                n = int(np.prod(sh))
                k = max(int(n * threshold), 1)
                idx = np.sort(rng.choice(n, size=k, replace=False))
                sel.append((rng.rand(k).astype(np.float32), idx))
            for k, sh in zip(keys, shapes):
                kv.init(k, np.zeros(sh, np.float32))
            kv.wait()
            t0 = time.perf_counter()
            for _ in range(rounds):
                join = kv.push_pull_bsc_batch(
                    keys, [v for v, _ in sel], [i for _, i in sel])
                agg = join()
                assert len(agg) == len(keys)
            times[id(kv)] = (time.perf_counter() - t0) / rounds * 1e3

        topo.run_workers(worker, include_master=master_init, timeout=600)
    finally:
        topo.stop()
    return max(times.values())


def run_overlap(shapes, rounds: int, slice_bytes: int,
                extra_cfg=None, trace_out: str = ""):
    """Serial vs pipelined combined round: the same dense push_pull
    payloads, once through the blocking wire (push_pull + wait) and
    once through the async chunked wire (push_pull_async at
    ``slice_bytes``-budget P3 chunks, joined per round). Per-key host
    work between dispatch and join is what the pipeline hides — on a
    shaped link (``--shape``) so is the link latency itself: chunk k+1
    serializes while chunk k is in flight."""
    from geomx_tpu import profiler
    from geomx_tpu.optimizer import SGD
    from geomx_tpu.simulate import InProcessHiPS

    keys = list(range(len(shapes)))
    cfg = dict(extra_cfg or {})
    cfg["p3_slice_bytes"] = slice_bytes
    topo = InProcessHiPS(num_parties=2, workers_per_party=1,
                         extra_cfg=cfg).start()
    if trace_out:
        profiler.set_config(filename=trace_out)
        profiler.set_state("run")
    times = {}
    nchunks = [0]
    try:
        def master_init(kv):
            kv.set_optimizer(SGD(learning_rate=0.01))
            for k, sh in zip(keys, shapes):
                kv.init(k, np.zeros(sh, np.float32))
            kv.wait()

        def worker(kv):
            outs = [np.zeros(sh, np.float32) for sh in shapes]
            grads = [np.ones(sh, np.float32) for sh in shapes]
            for k, o in zip(keys, outs):
                kv.init(k, o.copy())
                kv.pull(k, out=o)
            kv.wait()
            from geomx_tpu.kvstore.frontier import plan_chunks
            entries = []
            for k in keys:
                info = kv._key_info[k]
                entries.extend((sh.length * 4,) for sh in info.shards)
            nchunks[0] = len(plan_chunks(
                list(range(len(entries))), [e[0] for e in entries],
                slice_bytes))
            t0 = time.perf_counter()
            for _ in range(rounds):
                kv.push_pull(keys, grads, out=outs)
                kv.wait()
            serial = (time.perf_counter() - t0) / rounds * 1e3
            t0 = time.perf_counter()
            for _ in range(rounds):
                fut = kv.push_pull_async(keys, grads, outs,
                                         slice_bytes=slice_bytes)
                fut.wait()
            piped = (time.perf_counter() - t0) / rounds * 1e3
            times[id(kv)] = (serial, piped)

        topo.run_workers(worker, include_master=master_init, timeout=600)
    finally:
        topo.stop()
        if trace_out:
            profiler.set_state("stop")
            profiler.dump(filename=trace_out)
    serial = max(t[0] for t in times.values())
    piped = max(t[1] for t in times.values())
    return serial, piped, nchunks[0]


# the controller rides the health plane, which rides the resender
# (spans come from send->ack); identical base config in every loss-bench
# pass so the ONLY variable is the codec decision mechanism
CONTROLLER_CFG = dict(
    resend=True, resend_timeout_ms=3000, resend_deadline_s=180.0,
    health=True,
)

LOSS_POLICIES = ("raw", "fp16", "2bit", "mpq", "adaptive")


def run_loss(parties: int, size: int, policy: str, target_frac: float,
             max_rounds: int, extra_cfg=None, prime: int = 2):
    """Time-to-loss-target for one codec policy. Every worker pushes
    ``grad = w - t`` (identical across workers: same target, same pulled
    model), the server applies SGD at ``lr = 0.5 / parties``, so an
    exact round halves the error and lossy codecs show up as extra
    rounds. Workers break on the same round (the loss is computed from
    the shared pulled model), so the FSA barrier never half-empties.

    Returns ``(rounds_to_target | None, wall_s | None, loss_trace)``
    where the wall time is the SLOWEST worker's."""
    from geomx_tpu.optimizer import SGD
    from geomx_tpu.simulate import InProcessHiPS

    cfg = dict(extra_cfg or {})
    cfg.update(CONTROLLER_CFG)
    if policy == "adaptive":
        cfg["transport_controller"] = True
    elif policy != "raw":
        cfg["wire_codec_wan"] = policy
    topo = InProcessHiPS(num_parties=parties, workers_per_party=1,
                         extra_cfg=cfg).start()
    res = {}
    try:
        rng = np.random.RandomState(11)
        t_vec = rng.standard_normal(size).astype(np.float32)
        lr = 0.5 / parties

        def master_init(kv):
            kv.set_optimizer(SGD(learning_rate=lr))
            kv.init(0, np.zeros(size, np.float32))
            kv.wait()

        def worker(kv):
            out = np.zeros(size, np.float32)
            kv.init(0, np.zeros(size, np.float32))
            kv.pull(0, out=out)
            kv.wait()
            # untimed warmup, identical for every policy: zero gradients
            # leave the model untouched (SGD no-op; 2bit codes zeros
            # exactly, residuals stay zero) but put full-size frames on
            # the wire — steady-state comparison, connection setup and
            # the controller's link-classification both happen here
            zero = np.zeros(size, np.float32)
            for _ in range(prime):
                fut = kv.push_pull_async(0, zero, out)
                fut.wait()
            loss0 = 0.5 * float(np.sum((out - t_vec) ** 2))
            target = loss0 * target_frac
            trace = []
            hit = None
            t0 = time.perf_counter()
            for r in range(max_rounds):
                fut = kv.push_pull_async(0, out - t_vec, out)
                fut.wait()
                loss = 0.5 * float(np.sum((out - t_vec) ** 2))
                trace.append(loss / loss0)
                if loss <= target:
                    hit = (r + 1, time.perf_counter() - t0)
                    break
            res[id(kv)] = (hit, trace)

        topo.run_workers(worker, include_master=master_init,
                         timeout=1800)
    finally:
        topo.stop()
    hits = [h for h, _ in res.values()]
    trace = max((t for _, t in res.values()), key=len, default=[])
    if any(h is None for h in hits) or not hits:
        return None, None, trace
    return max(h[0] for h in hits), max(h[1] for h in hits), trace


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layout", choices=sorted(LAYOUTS), default="cnn")
    ap.add_argument("--rounds", type=int, default=100)
    ap.add_argument("--sparse", action="store_true",
                    help="measure the combined element-sparse BSC wire "
                         "(the device-resident trainer's round) instead "
                         "of the dense push/pull wire")
    ap.add_argument("--threshold", type=float, default=0.01,
                    help="--sparse: top-k fraction per key")
    ap.add_argument("--overlap", action="store_true",
                    help="measure serial vs pipelined combined round "
                         "(push_pull vs async chunked push_pull_async)")
    ap.add_argument("--slice-bytes", type=int, default=131072,
                    help="--overlap: P3 chunk budget in bytes")
    ap.add_argument("--shape", default="",
                    help="shape-plan JSON path (GEOMX_SHAPE_PLAN): "
                         "replay the capture on an emulated WAN; "
                         "canonical plans under scripts/shapes/")
    ap.add_argument("--shape-seed", type=int, default=-1,
                    help="--shape: jitter-stream seed "
                         "(GEOMX_SHAPE_SEED; plan-embedded seed wins)")
    ap.add_argument("--trace-out", default="",
                    help="--overlap: dump the in-process chrome trace "
                         "here (merge with tools/trace_merge.py)")
    ap.add_argument("--controller", action="store_true",
                    help="run with the self-tuning transport controller "
                         "on (health plane + resender ride along) for a "
                         "static-vs-adaptive A/B of any mode")
    ap.add_argument("--loss-bench", action="store_true",
                    help="time-to-loss-target A/B across codec policies "
                         "(raw/fp16/2bit/mpq/adaptive) on the shaped WAN")
    ap.add_argument("--parties", type=int, default=16,
                    help="--loss-bench: party count (default 16)")
    ap.add_argument("--size", type=int, default=65536,
                    help="--loss-bench: model elements (default 256KB)")
    ap.add_argument("--target", type=float, default=3e-2,
                    help="--loss-bench: relative loss target")
    ap.add_argument("--max-rounds", type=int, default=40,
                    help="--loss-bench: round cap; a policy that never "
                         "reaches the target reports null")
    ap.add_argument("--policy", default="",
                    choices=("",) + LOSS_POLICIES,
                    help="--loss-bench: run one policy only")
    ap.add_argument("--prime", type=int, default=2,
                    help="--loss-bench: untimed zero-gradient warmup "
                         "rounds before the clock starts, same for "
                         "every policy (steady-state comparison; 0 = "
                         "include cold start)")
    args = ap.parse_args()

    extra_cfg = {}
    shape_tag = ""
    if args.shape:
        extra_cfg = {"shape_plan": "@" + args.shape,
                     "shape_seed": args.shape_seed}
        shape_tag = os.path.splitext(os.path.basename(args.shape))[0]
    if args.controller:
        extra_cfg.update(CONTROLLER_CFG, transport_controller=True)

    if args.loss_bench:
        if args.controller:
            ap.error("--loss-bench runs its own adaptive policy; "
                     "drop --controller")
        # mpq's size rule must engage at this model size, or "mpq"
        # degenerates to fp16 and the A/B loses a policy
        extra_cfg.setdefault("size_lower_bound",
                             min(200000, max(1, args.size // 2)))
        rows = {}
        for pol in ([args.policy] if args.policy else LOSS_POLICIES):
            rounds, wall, trace = run_loss(
                args.parties, args.size, pol, args.target,
                args.max_rounds, extra_cfg=extra_cfg, prime=args.prime)
            rows[pol] = {
                "rounds_to_target": rounds,
                "time_to_target_s": None if wall is None
                else round(wall, 2),
                "final_rel_loss": round(trace[-1], 6) if trace else None,
            }
            print(json.dumps({"policy": pol, **rows[pol]}),
                  flush=True)
        print(json.dumps({
            "loss_bench": True, "shape": shape_tag,
            "parties": args.parties, "size": args.size,
            "target_rel": args.target, "max_rounds": args.max_rounds,
            "prime": args.prime, "policies": rows}))
        return

    shapes = LAYOUTS[args.layout]
    if shapes is None:
        rng = np.random.RandomState(0)
        shapes = [(int(s),)
                  for s in rng.choice([64, 512, 2048, 8192], 75)]
    if args.overlap:
        serial, piped, nchunks = run_overlap(
            shapes, args.rounds, args.slice_bytes,
            extra_cfg=extra_cfg, trace_out=args.trace_out)
        print(json.dumps({
            "layout": args.layout, "keys": len(shapes), "overlap": True,
            "shape": shape_tag,
            "slice_bytes": args.slice_bytes, "chunks": nchunks,
            "serial_ms_per_round": round(serial, 2),
            "pipelined_ms_per_round": round(piped, 2),
            "speedup": round(serial / piped, 2)}))
        return
    if args.sparse:
        ms = run_sparse(shapes, args.threshold, args.rounds,
                        extra_cfg=extra_cfg)
        print(json.dumps({
            "layout": args.layout, "keys": len(shapes), "sparse": True,
            "shape": shape_tag, "threshold": args.threshold,
            "bsc_push_pull_ms_per_round": round(ms, 2)}))
        return
    per_key = run(shapes, batched=False, rounds=args.rounds,
                  extra_cfg=extra_cfg)
    batched = run(shapes, batched=True, rounds=args.rounds,
                  extra_cfg=extra_cfg)
    print(json.dumps({
        "layout": args.layout, "keys": len(shapes), "shape": shape_tag,
        "per_key_ms_per_round": round(per_key, 2),
        "batched_ms_per_round": round(batched, 2),
        "speedup": round(per_key / batched, 2)}))


if __name__ == "__main__":
    main()
