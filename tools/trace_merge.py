"""Stitch per-node chrome-trace dumps into ONE cross-node round trace.

Each GeoMX process dumps its own chrome-trace JSON (geomx_tpu.profiler)
with van.send/van.recv transport spans stamped by ps/van.py:_span_args.
Every process measures time on its OWN monotonic clock (profiler._t0 is
per-process), so the dumps cannot be overlaid directly — a van.recv
would routinely appear *before* the van.send that caused it.

This tool:

1. loads every input dump and splits events by ``args.node`` (an
   InProcessHiPS run writes several nodes into one file; a real
   deployment writes one node per file — both shapes are accepted; a
   file whose events carry no ``node`` tag is treated as one anonymous
   node named after the file);
2. pairs each ``van.send`` on node A with the matching ``van.recv`` on
   node B by the wire identity ``(ovl, from, to, mts, req)`` — the
   overlay string disambiguates the local tiers of different parties,
   which reuse node ids;
3. estimates each node's clock offset to a reference node NTP-style:
   for a request/response pair crossing the same link in both
   directions, ``off ≈ (min(recv_B - send_A) - min(recv_A - send_B))/2``
   cancels the (assumed symmetric) one-way latency. Minima over many
   pairs reject queueing noise. Nodes reachable only via other nodes
   get offsets by BFS accumulation along observed links;
4. emits a single chrome-trace JSON where each node is a separate pid
   (with ``process_name`` metadata so Perfetto labels the rows) and all
   timestamps are shifted onto the reference node's clock — a round is
   then visible end-to-end: worker push -> local server -> global
   server -> responses flowing back.

Usage::

    python -m tools.trace_merge node0.json node1.json ... -o merged.json
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import sys
from typing import Dict, List, Tuple

# wire identity of one frame: (ovl, from, to, mts, req) — see
# ps/van.py:_span_args. `req` keeps a request and its response (which
# share ovl/mts and swap from/to) from pairing with each other's echo.
WireKey = Tuple[str, int, int, int, bool]

_PAIRABLE = ("van.send", "van.recv")


def load_nodes(paths: List[str]) -> Dict[str, List[dict]]:
    """Events grouped by node tag, from one or many dump files."""
    nodes: Dict[str, List[dict]] = collections.defaultdict(list)
    for path in paths:
        with open(path) as f:
            doc = json.load(f)
        for ev in doc.get("traceEvents", []):
            node = (ev.get("args") or {}).get("node")
            if node is None:
                node = os.path.splitext(os.path.basename(path))[0]
            nodes[node].append(ev)
    return dict(nodes)


def _wire_key(ev: dict) -> WireKey | None:
    a = ev.get("args") or {}
    if "ovl" not in a or "mts" not in a:
        return None
    return (a["ovl"], a["from"], a["to"], a["mts"], bool(a.get("req")))


def _link_deltas(nodes: Dict[str, List[dict]]):
    """For every (sender_node, recver_node) link: min(recv_ts - send_ts)
    over all frames observed crossing it. On synchronized clocks this
    is the one-way latency; on skewed clocks it is latency + skew."""
    sends: Dict[WireKey, Tuple[str, float]] = {}
    recvs: Dict[WireKey, Tuple[str, float]] = {}
    for node, evs in nodes.items():
        for ev in evs:
            if ev.get("name") not in _PAIRABLE:
                continue
            key = _wire_key(ev)
            if key is None:
                continue
            # the send's wire time is its END (pack+write duration is
            # on-node work, not flight time)
            if ev["name"] == "van.send":
                sends[key] = (node, ev["ts"] + ev.get("dur", 0))
            else:
                recvs[key] = (node, ev["ts"])
    deltas: Dict[Tuple[str, str], float] = {}
    matched = 0
    for key, (snode, sts) in sends.items():
        hit = recvs.get(key)
        if hit is None:
            continue
        rnode, rts = hit
        if rnode == snode:
            continue  # loopback: same clock, no skew information
        matched += 1
        link = (snode, rnode)
        d = rts - sts
        if link not in deltas or d < deltas[link]:
            deltas[link] = d
    return deltas, matched


def _solve(nodes: Dict[str, List[dict]],
           reference: str | None = None):
    """offset[node]: subtract from that node's timestamps to land on
    the reference clock. NTP pairing per bidirectional link, BFS from
    the reference for transitive reach. Also returns the nodes the BFS
    never reached (no matched send/recv pair connects them to the
    reference, even transitively) — they stay on their own clock at
    offset 0 rather than failing the whole merge."""
    deltas, matched = _link_deltas(nodes)
    # symmetric-link offset: delta(A->B) = lat + off_B - off_A and
    # delta(B->A) = lat + off_A - off_B  =>  off_B - off_A =
    # (delta(A->B) - delta(B->A)) / 2
    rel: Dict[Tuple[str, str], float] = {}
    for (a, b), d_ab in deltas.items():
        d_ba = deltas.get((b, a))
        if d_ba is None:
            # one-directional link (e.g. a node that only ever
            # responded after crash): assume zero one-way latency —
            # biased, but keeps the node on the timeline
            rel[(a, b)] = d_ab
            rel[(b, a)] = -d_ab
        else:
            off = (d_ab - d_ba) / 2.0
            rel[(a, b)] = off
            rel[(b, a)] = -off
    if reference is None:
        reference = sorted(nodes)[0]
    offsets: Dict[str, float] = {reference: 0.0}
    frontier = [reference]
    while frontier:
        cur = frontier.pop()
        for (a, b), off in rel.items():
            if a == cur and b not in offsets:
                offsets[b] = offsets[a] + off
                frontier.append(b)
    unanchored = sorted(n for n in nodes if n not in offsets)
    for node in unanchored:
        offsets[node] = 0.0  # unreachable: best effort, own clock
    return offsets, matched, unanchored


def solve_offsets(nodes: Dict[str, List[dict]],
                  reference: str | None = None):
    """Public 2-tuple form of :func:`_solve` (offsets, matched)."""
    offsets, matched, _unanchored = _solve(nodes, reference)
    return offsets, matched


def merge(nodes: Dict[str, List[dict]],
          reference: str | None = None) -> dict:
    """One chrome-trace doc: pid per node, timestamps clock-aligned.
    Nodes disconnected from the reference are kept (offset 0, flagged
    in ``metadata.unanchored_nodes`` and warned about) — a crashed node
    whose dump never matched a wire pair still shows on the timeline."""
    offsets, matched, unanchored = _solve(nodes, reference)
    for node in unanchored:
        print(f"warning: node {node} has no matched send/recv pair "
              f"connecting it to the reference clock — keeping it at "
              f"offset 0 (its rows may be skewed)", file=sys.stderr)
    out: List[dict] = []
    for pid, node in enumerate(sorted(nodes)):
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": node}})
        out.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                    "args": {"sort_index": pid}})
        off = offsets[node]
        for ev in nodes[node]:
            ev = dict(ev)
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = ev["ts"] - off
            out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "metadata": {"clock_offsets_us": offsets,
                         "matched_wire_pairs": matched,
                         "unanchored_nodes": unanchored}}


def rounds_spanning(doc: dict) -> Dict[int, set]:
    """round id -> set of node tags whose van spans carry it (the
    acceptance probe: a round traced end-to-end touches worker, local
    server and global tier nodes)."""
    seen: Dict[int, set] = collections.defaultdict(set)
    for ev in doc.get("traceEvents", []):
        a = ev.get("args") or {}
        if "round" in a and "node" in a:
            seen[a["round"]].add(a["node"])
    return dict(seen)


def main(argv: List[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("dumps", nargs="+", help="per-node chrome-trace JSON")
    p.add_argument("-o", "--output", default="merged_trace.json")
    p.add_argument("--reference", default=None,
                   help="node tag whose clock wins (default: first "
                        "sorted node)")
    args = p.parse_args(argv)
    nodes = load_nodes(args.dumps)
    if not nodes:
        print("no trace events found", file=sys.stderr)
        return 1
    doc = merge(nodes, args.reference)
    tmp = f"{args.output}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, args.output)
    spans = rounds_spanning(doc)
    print(f"merged {len(nodes)} node(s), "
          f"{doc['metadata']['matched_wire_pairs']} wire pair(s) "
          f"matched -> {args.output}")
    for rid in sorted(spans):
        print(f"  round {rid}: {len(spans[rid])} node(s) "
              f"[{', '.join(sorted(spans[rid]))}]")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
