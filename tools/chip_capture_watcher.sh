#!/bin/bash
# Detached watcher: probe the TPU tunnel; on recovery, capture the full
# bench + flash block-size sweep into the repo so the round records real
# chip numbers even if recovery happens unattended.
#
# The bench runs with --resume against a persistent partial file: every
# completed phase survives a tunnel flap, so successive recovery windows
# FILL IN the capture instead of restarting it. Safe to re-run; exits
# after one complete capture or when the deadline passes.
cd /root/repo
PARTIAL=.bench_chip_partial.json
DEADLINE=$(( $(date +%s) + ${WATCH_HOURS:-10} * 3600 ))
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if timeout 120 python -c "import jax, numpy as np; \
x=jax.device_put(np.ones(8,'f4')); jax.block_until_ready(x); \
import sys; sys.exit(0 if 'tpu' in jax.devices()[0].device_kind.lower() else 1)" \
      > /dev/null 2>&1; then
    if pgrep -f "python.*bench\.py" > /dev/null 2>&1; then
      # never contend with another bench on the one chip (e.g. the
      # driver's round-end capture) — its numbers take precedence
      echo "$(date -Is) another bench.py is running; standing down" \
          >> /tmp/chip_watch.log
      sleep 300
      continue
    fi
    echo "$(date -Is) tunnel healthy — capturing" >> /tmp/chip_watch.log
    timeout 3600 python bench.py --resume --partial "$PARTIAL" \
        --budget 3300 > CHIP_CAPTURE_BENCH.json.tmp 2>> /tmp/chip_watch.log
    bench_rc=$?
    echo "bench rc=$bench_rc" >> /tmp/chip_watch.log
    # publish only COMPLETE captures (rc=0): a degraded run must never
    # overwrite a previously complete CHIP_CAPTURE_BENCH.json. Errored
    # phases stay errored in the partial and are retried on the next
    # recovery window.
    if [ "$bench_rc" -eq 0 ] && [ -s CHIP_CAPTURE_BENCH.json.tmp ]; then
      mv CHIP_CAPTURE_BENCH.json.tmp CHIP_CAPTURE_BENCH.json
    else
      rm -f CHIP_CAPTURE_BENCH.json.tmp
    fi
    # the sweep is NOT gated on a complete bench: short recovery
    # windows should still produce flash-tuning data (round-4 verdict
    # item 4 has waited two rounds for this capture)
    if [ ! -s CHIP_CAPTURE_ATTENTION.jsonl ]; then
      timeout 1800 python tools/attention_bench.py --sweep-blocks \
          > CHIP_CAPTURE_ATTENTION.jsonl.tmp 2>> /tmp/chip_watch.log
      sweep_rc=$?
      echo "sweep rc=$sweep_rc" >> /tmp/chip_watch.log
      if [ "$sweep_rc" -eq 0 ] && [ -s CHIP_CAPTURE_ATTENTION.jsonl.tmp ]; then
        mv CHIP_CAPTURE_ATTENTION.jsonl.tmp CHIP_CAPTURE_ATTENTION.jsonl
      else
        rm -f CHIP_CAPTURE_ATTENTION.jsonl.tmp
        echo "$(date -Is) sweep incomplete; resuming watch" \
            >> /tmp/chip_watch.log
      fi
    fi
    if [ "$bench_rc" -ne 0 ] || [ ! -s CHIP_CAPTURE_ATTENTION.jsonl ]; then
      echo "$(date -Is) capture incomplete; resuming watch" \
          >> /tmp/chip_watch.log
      sleep 300
      continue
    fi
    echo "$(date -Is) capture complete" >> /tmp/chip_watch.log
    exit 0
  fi
  sleep 300
done
echo "$(date -Is) watcher deadline passed, tunnel never recovered" \
    >> /tmp/chip_watch.log
