#!/bin/bash
# Detached watcher: probe the TPU tunnel; on recovery, capture the full
# bench + flash block-size sweep into the repo so the round records real
# chip numbers even if recovery happens unattended. Safe to re-run;
# exits after one successful capture or when the deadline passes.
cd /root/repo
DEADLINE=$(( $(date +%s) + ${WATCH_HOURS:-8} * 3600 ))
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if timeout 120 python -c "import jax, numpy as np; \
x=jax.device_put(np.ones(8,'f4')); jax.block_until_ready(x); \
import sys; sys.exit(0 if 'tpu' in jax.devices()[0].device_kind.lower() else 1)" \
      > /dev/null 2>&1; then
    echo "$(date -Is) tunnel healthy — capturing" >> /tmp/chip_watch.log
    timeout 3600 python bench.py > CHIP_CAPTURE_BENCH.json.tmp \
        2>> /tmp/chip_watch.log
    bench_rc=$?
    echo "bench rc=$bench_rc" >> /tmp/chip_watch.log
    timeout 1800 python tools/attention_bench.py --sweep-blocks \
        > CHIP_CAPTURE_ATTENTION.jsonl.tmp 2>> /tmp/chip_watch.log
    sweep_rc=$?
    echo "sweep rc=$sweep_rc" >> /tmp/chip_watch.log
    # publish only complete captures; a tunnel flap mid-capture leaves
    # the watch running for the next recovery instead of exiting with
    # truncated files
    ok=1
    if [ "$bench_rc" -eq 0 ] && [ -s CHIP_CAPTURE_BENCH.json.tmp ]; then
      mv CHIP_CAPTURE_BENCH.json.tmp CHIP_CAPTURE_BENCH.json
    else
      rm -f CHIP_CAPTURE_BENCH.json.tmp; ok=0
    fi
    if [ "$sweep_rc" -eq 0 ] && [ -s CHIP_CAPTURE_ATTENTION.jsonl.tmp ]; then
      mv CHIP_CAPTURE_ATTENTION.jsonl.tmp CHIP_CAPTURE_ATTENTION.jsonl
    else
      rm -f CHIP_CAPTURE_ATTENTION.jsonl.tmp; ok=0
    fi
    [ "$ok" -eq 1 ] && exit 0
    echo "$(date -Is) capture incomplete; resuming watch" \
        >> /tmp/chip_watch.log
  fi
  sleep 600
done
echo "$(date -Is) watcher deadline passed, tunnel never recovered" \
    >> /tmp/chip_watch.log
