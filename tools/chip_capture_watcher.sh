#!/bin/bash
# Detached watcher: probe the TPU tunnel; on recovery, capture the full
# bench + flash block-size sweep into the repo so the round records real
# chip numbers even if recovery happens unattended. Safe to re-run;
# exits after one successful capture or when the deadline passes.
cd /root/repo
DEADLINE=$(( $(date +%s) + ${WATCH_HOURS:-8} * 3600 ))
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if timeout 120 python -c "import jax, numpy as np; \
x=jax.device_put(np.ones(8,'f4')); jax.block_until_ready(x); \
import sys; sys.exit(0 if 'tpu' in jax.devices()[0].device_kind.lower() else 1)" \
      > /dev/null 2>&1; then
    echo "$(date -Is) tunnel healthy — capturing" >> /tmp/chip_watch.log
    timeout 3600 python bench.py > CHIP_CAPTURE_BENCH.json \
        2>> /tmp/chip_watch.log
    echo "bench rc=$?" >> /tmp/chip_watch.log
    timeout 1800 python tools/attention_bench.py --sweep-blocks \
        > CHIP_CAPTURE_ATTENTION.jsonl 2>> /tmp/chip_watch.log
    echo "sweep rc=$?" >> /tmp/chip_watch.log
    exit 0
  fi
  sleep 600
done
echo "$(date -Is) watcher deadline passed, tunnel never recovered" \
    >> /tmp/chip_watch.log
