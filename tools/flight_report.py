"""Render flight-recorder dumps as a readable crash narrative.

``geomx_tpu.ps.flightrec`` dumps a bounded ring of recent wire and
membership events as JSON when a van crashes, a sanitizer violation
fires or a round aborts. This tool turns one or more dumps (or a whole
``GEOMX_FLIGHTREC_DIR``) into the story a person actually wants at
3am: who dumped, why, and what the last frames on the wire were — with
trace rounds called out so the in-flight round is obvious.

Usage::

    python -m tools.flight_report /tmp/geomx_flightrec
    python -m tools.flight_report flightrec_g8p9011_pid123.json --tail 40
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List


def _fmt_time(t: float) -> str:
    return time.strftime("%H:%M:%S", time.localtime(t)) + f".{int(t % 1 * 1000):03d}"


def _fmt_event(ev: dict) -> str:
    seq = ev.get("seq", "?")
    kind = ev.get("kind", "?")
    when = _fmt_time(ev["t"]) if "t" in ev else "?"
    extras = {k: v for k, v in ev.items()
              if k not in ("seq", "t", "kind")}
    # wire events read as a sentence, the rest as key=value
    if kind in ("sent", "recv"):
        arrow = "->" if kind == "sent" else "<-"
        line = (f"{extras.pop('verb', '?'):7s} {arrow} peer "
                f"{extras.pop('peer', '?'):>3} "
                f"{extras.pop('bytes', 0):>8}B")
        rnd = extras.pop("round", -1)
        if rnd is not None and rnd >= 0:
            line += f"  round={rnd}"
            chunk = extras.pop("chunk", -1)
            if chunk is not None and chunk >= 0:
                line += f" chunk={chunk}"
            extras.pop("origin", None)
        extras.pop("req", None)
        extras.pop("ts", None)
        tail = " ".join(f"{k}={v}" for k, v in sorted(extras.items()))
        return f"  {seq:>6} {when} {kind:10s} {line}  {tail}".rstrip()
    tail = " ".join(f"{k}={v}" for k, v in sorted(extras.items()))
    return f"  {seq:>6} {when} {kind:10s} {tail}".rstrip()


def report(doc: dict, tail: int = 0) -> str:
    events = doc.get("events", [])
    shown = events[-tail:] if tail else events
    lines = [
        f"flight recorder dump: node {doc.get('node', '?')} "
        f"pid {doc.get('pid', '?')}",
        f"  reason:    {doc.get('reason', '?')}",
        f"  dumped at: "
        f"{_fmt_time(doc['dumped_at']) if 'dumped_at' in doc else '?'}",
        f"  events:    {len(events)}"
        + (f" (showing last {len(shown)})" if tail and tail < len(events)
           else ""),
    ]
    rounds = sorted({ev.get("round") for ev in events
                     if ev.get("round", -1) is not None
                     and ev.get("round", -1) >= 0})
    if rounds:
        lines.append(f"  rounds in flight: {rounds}")
    lines.append("")
    lines.extend(_fmt_event(ev) for ev in shown)
    return "\n".join(lines)


def _collect(paths: List[str]) -> List[str]:
    files = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if f.startswith("flightrec_") and f.endswith(".json")))
        else:
            files.append(p)
    return files


def main(argv: List[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="+",
                   help="dump file(s) or a GEOMX_FLIGHTREC_DIR")
    p.add_argument("--tail", type=int, default=0,
                   help="show only the last N events per dump")
    p.add_argument("--conformance", action="store_true",
                   help="instead of rendering, replay every dump "
                        "through the protocol state-model checks "
                        "(tools/modelcheck.py --replay): per-peer epoch "
                        "monotonicity, strictly increasing declare_dead "
                        "epochs; exit 1 on any violation")
    args = p.parse_args(argv)
    if args.conformance:
        from pathlib import Path

        from tools.modelcheck import replay_paths

        rep = replay_paths([Path(p_) for p_ in args.paths])
        print(json.dumps(rep, indent=1))
        return 1 if rep["violations"] or not rep["files"] else 0
    files = _collect(args.paths)
    if not files:
        print("no flight recorder dumps found", file=sys.stderr)
        return 1
    rc = 0
    for i, path in enumerate(files):
        if i:
            print()
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"unreadable dump {path}: {e}", file=sys.stderr)
            rc = 1
            continue
        print(report(doc, tail=args.tail))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
