#!/usr/bin/env python
"""PS protocol benchmark: the HiPS stack's intrinsic round latency and
throughput, NO accelerator in the loop.

Measures full two-tier rounds (2 parties x 1 worker -> party servers ->
global server -> pull-back) for numpy payloads of several sizes. This
isolates the framework's own speed from device/tunnel effects — the
complement of bench.py's framework-in-the-loop numbers.

Prints one JSON line per payload size:
  {"elems": N, "rounds_per_s": R, "round_ms": L, "mb_per_s": B}
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from geomx_tpu.optimizer import SGD                 # noqa: E402
from geomx_tpu.simulate import InProcessHiPS       # noqa: E402

SIZES = [1_024, 65_536, 1_048_576]
SECONDS = 5.0


def bench_size(n_elems: int) -> dict:
    topo = InProcessHiPS(num_parties=2, workers_per_party=1).start()
    try:
        topo.master.set_optimizer(SGD(learning_rate=0.01))
        time.sleep(0.3)
        w0 = np.zeros(n_elems, np.float32)
        rounds = [0, 0]
        stop_round = [None]
        errs: list = []

        def master(kv):
            kv.init(0, w0)
            kv.wait()

        def worker(kv):
            widx = 0 if kv is topo.workers[0] else 1
            kv.init(0, w0)
            kv.pull(0, out=np.zeros_like(w0))
            kv.wait()
            grad = np.ones(n_elems, np.float32)
            out = np.zeros(n_elems, np.float32)
            while stop_round[0] is None or rounds[widx] < stop_round[0]:
                kv.push(0, grad)
                kv.pull(0, out=out)
                kv.wait()
                rounds[widx] += 1

        def run():
            try:
                topo.run_workers(worker, include_master=master,
                                 timeout=600.0)
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        deadline = time.monotonic() + 60
        while sum(rounds) < 4 and time.monotonic() < deadline:
            if errs:
                raise errs[0]
            time.sleep(0.05)
        r0 = sum(rounds)
        t0 = time.perf_counter()
        time.sleep(SECONDS)
        made = sum(rounds) - r0
        dt = time.perf_counter() - t0
        if errs:
            raise errs[0]
        stop_round[0] = max(rounds) + 2
        t.join(60)
        # per-worker round rate (both workers advance in lockstep)
        rps = made / 2 / dt
        # bytes per ROUND per worker: push grad + pull params on the LAN
        # hop, plus the party->global->party WAN exchange (counted once
        # per party = per worker here)
        bytes_per_round = 4 * n_elems * 4
        return {
            "elems": n_elems,
            "rounds_per_s": round(rps, 1),
            "round_ms": round(1000.0 / rps, 3) if rps else None,
            "mb_per_s": round(rps * bytes_per_round / 1e6, 1),
        }
    finally:
        topo.stop()


def main():
    for n in SIZES:
        print(json.dumps(bench_size(n)), flush=True)


if __name__ == "__main__":
    main()
