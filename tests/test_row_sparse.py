"""Row-sparse push/pull (reference: include/mxnet/kvstore.h:59
PullRowSparse; src/kvstore/kvstore_dist.h:906 EncodeRowSparseKey).

Embedding-style updates: push only the touched rows, pull only the
requested rows; overlapping rows from different workers aggregate by
sum before the optimizer applies."""

import numpy as np
import pytest

from geomx_tpu.kvstore.local import KVStoreLocal
from geomx_tpu.optimizer import SGD
from tests.test_hips import Topology, _parallel


def test_local_row_sparse_roundtrip():
    kv = KVStoreLocal()
    kv.set_optimizer(SGD(learning_rate=1.0))
    w0 = np.arange(20, dtype=np.float32).reshape(5, 4)
    kv.init(0, w0)
    kv.push_row_sparse(0, [1, 3, 1], np.ones((3, 4), np.float32))
    rows = kv.pull_row_sparse(0, [0, 1, 3])
    np.testing.assert_allclose(rows[0], w0[0])          # untouched
    np.testing.assert_allclose(rows[1], w0[1] - 2.0)    # pushed twice
    np.testing.assert_allclose(rows[2], w0[3] - 1.0)


def test_dist_row_sparse_hips_topology():
    """Full two-tier path: rsp pushes scatter to dense at the party
    server, aggregate through the global tier, and rsp pulls gather the
    fresh rows."""
    topo = Topology().start(sync_global=True)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.arange(48, dtype=np.float32).reshape(12, 4)
        _parallel([lambda kv=kv: kv.init(0, w0)
                   for kv in topo.workers + [topo.master]])

        def train(kv):
            # every worker touches rows {2, 7}; worker-distinct row =
            # 2 + rank to also cover non-overlapping rows
            ids = np.array([2, 7], np.int64)
            kv.push_row_sparse(0, ids, np.ones((2, 4), np.float32))
            rows = kv.pull_row_sparse(0, [2, 7, 0])
            kv.wait()
            np.testing.assert_allclose(rows[0], w0[2] - 4.0)  # 4 workers
            np.testing.assert_allclose(rows[1], w0[7] - 4.0)
            np.testing.assert_allclose(rows[2], w0[0])        # untouched

        _parallel([lambda kv=kv: train(kv) for kv in topo.workers])

        # dense pull sees the same state
        def check(kv):
            out = np.zeros((12, 4), np.float32)
            kv.pull(0, out=out)
            kv.wait()
            expect = w0.copy()
            expect[2] -= 4.0
            expect[7] -= 4.0
            np.testing.assert_allclose(out, expect)

        _parallel([lambda kv=kv: check(kv) for kv in topo.workers])
    finally:
        topo.stop()


def test_dist_row_sparse_rejects_sharded_key():
    topo = Topology(servers_per_party=2, bigarray_bound=16).start(
        sync_global=True)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.zeros((12, 4), np.float32)   # 48 elems > bound: sharded
        _parallel([lambda kv=kv: kv.init(0, w0)
                   for kv in topo.workers + [topo.master]])
        with pytest.raises(AssertionError, match="sharded"):
            topo.workers[0].push_row_sparse(
                0, [1], np.ones((1, 4), np.float32))
    finally:
        topo.stop()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
