"""P3 priority-propagation tests (reference: P3_EncodeDefaultKey,
kvstore_dist.h:768-805 + the priority send thread, van.cc:548,851)."""

import threading

import numpy as np
import pytest

from geomx_tpu.config import Config
from geomx_tpu.kvstore import sharding
from geomx_tpu.kvstore.dist import KVStoreDist
from geomx_tpu.kvstore.server import KVStoreDistServer
from geomx_tpu.optimizer import SGD
from geomx_tpu.ps import base as psbase
from geomx_tpu.ps.message import Role
from geomx_tpu.ps.postoffice import Postoffice
from tests.test_hips import _parallel, free_port  # shared scaffolding


def test_assign_p3_covers_and_respects_canonical_ranges():
    shards = sharding.assign_p3(3, 100, 4, 16)
    assert sum(s.length for s in shards) == 100
    offs = [s.offset for s in shards]
    assert offs == sorted(offs)
    assert all(s.length <= 16 for s in shards)
    # contiguous coverage
    pos = 0
    for s in shards:
        assert s.offset == pos
        pos += s.length
    # every slice lies INSIDE its server's canonical assign() range — the
    # global-store server validates offsets against these (server.py
    # _canonical_ranges), so P3 slicing must not re-route across servers
    canon = {c.server_rank: c for c in sharding.assign(3, 100, 4, 16)}
    for s in shards:
        c = canon[s.server_rank]
        assert c.offset <= s.offset
        assert s.offset + s.length <= c.offset + c.length
    # zero-size keys still get one shard
    z = sharding.assign_p3(1, 0, 4, 16)
    assert len(z) == 1 and z[0].length == 0


def test_assign_p3_small_key_single_slice():
    shards = sharding.assign_p3(7, 10, 4, 16)
    assert len(shards) == 1
    assert shards[0].server_rank == (7 * 9973) % 4
    assert shards[0].length == 10


def test_p3_single_tier_push_pull():
    """Single-tier PS with ENABLE_P3: keys sliced at bigarray granularity,
    per-slice messages through the priority queue; results must be exact."""
    port = free_port()
    threads = []
    errors = []

    def run(fn):
        def w():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
        t = threading.Thread(target=w, daemon=True)
        t.start()
        threads.append(t)

    def mkcfg(role):
        return Config(role=role, ps_root_uri="127.0.0.1", ps_root_port=port,
                      num_workers=2, num_servers=1, enable_p3=True,
                      bigarray_bound=16)

    sched_po = Postoffice(my_role=Role.SCHEDULER, is_global=False,
                          root_uri="127.0.0.1", root_port=port,
                          num_workers=2, num_servers=1, cfg=Config())

    def sched():
        sched_po.start(60)
        sched_po.barrier(psbase.ALL_GROUP, timeout=60)
        sched_po.barrier(psbase.ALL_GROUP, timeout=120)
        sched_po.van.stop()

    run(sched)
    srv = KVStoreDistServer(mkcfg("server"))
    run(srv.run)
    boxes = [[], []]
    for i in range(2):
        run(lambda b=boxes[i]: b.append(KVStoreDist(cfg=mkcfg("worker"))))
    for _ in range(300):
        if errors:
            raise errors[0]
        if all(len(b) == 1 for b in boxes):
            break
        threading.Event().wait(0.1)
    kvs = [b[0] for b in boxes]
    try:
        rank0 = next(kv for kv in kvs if kv.rank == 0)
        rank0.set_optimizer(SGD(learning_rate=0.5))
        # key 0 is big (sliced into 3 slices of <=16), key 1 small
        w = {0: np.arange(40, dtype=np.float32), 1: np.ones(8, np.float32)}
        _parallel([lambda kv=kv: [kv.init(k, v) for k, v in w.items()]
                   for kv in kvs])

        def train(kv):
            # later keys get higher priority (reference: push(idx, g,
            # priority=-idx) in examples/cnn.py:123)
            for k in w:
                kv.push(k, np.ones_like(w[k]), priority=-k)
            outs = {k: np.zeros_like(w[k]) for k in w}
            for k in w:
                kv.pull(k, out=outs[k], priority=-k)
            kv.wait()
            for k in w:
                np.testing.assert_allclose(outs[k], w[k] - 1.0)  # 0.5*2 workers

        _parallel([lambda kv=kv: train(kv) for kv in kvs])
    finally:
        _parallel([kv.close for kv in kvs])
        for t in threads:
            t.join(30)
        if errors:
            raise errors[0]


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
