"""KVStore factory type strings (reference: kvstore.cc:41-82 Create)."""

import pytest

import geomx_tpu.kvstore as kvmod
from geomx_tpu.kvstore.local import KVStoreLocal


def test_local_default():
    assert isinstance(kvmod.create(), KVStoreLocal)
    assert isinstance(kvmod.create("local"), KVStoreLocal)


@pytest.mark.parametrize("name,expect_sync", [
    ("dist", True),
    ("dist_sync", True),
    ("dist_sync_tpu", True),      # the driver's target config string
    ("dist_sync_device", True),
    ("dist_async", False),        # MixedSync: async global tier
])
def test_dist_aliases_map_to_sync_mode(monkeypatch, name, expect_sync):
    import geomx_tpu.kvstore.dist as dist_mod

    captured = {}

    class FakeDist:
        def __init__(self, sync_global):
            captured["sync_global"] = sync_global

    monkeypatch.setattr(dist_mod, "KVStoreDist", FakeDist)
    kvmod.create(name)
    assert captured["sync_global"] is expect_sync


def test_nccl_store_type():
    from geomx_tpu.kvstore.device import KVStoreDeviceAllreduce

    kv = kvmod.create("nccl")
    assert isinstance(kv, KVStoreDeviceAllreduce)


def test_timeout_env_knobs(monkeypatch):
    """Round-4 verdict item 2: barrier/op deadlines are env-tunable
    (reference pattern: env-tunable transport deadlines, van.cc:527-533)
    — a 59M bootstrap over a slow link needs minutes per worker."""
    from geomx_tpu import config as cfg_mod

    assert cfg_mod.load().barrier_timeout_s == 600.0
    assert cfg_mod.load().op_timeout_s == 300.0
    monkeypatch.setenv("PS_BARRIER_TIMEOUT", "1800")
    monkeypatch.setenv("PS_OP_TIMEOUT", "45.5")
    cfg = cfg_mod.load()
    assert cfg.barrier_timeout_s == 1800.0
    assert cfg.op_timeout_s == 45.5


def test_push_pull_on_local_store():
    """The combined op exists on every store type (reference: ZPushPull
    on all stores); local = the two-op sequence."""
    import numpy as np

    from geomx_tpu.kvstore import create
    from geomx_tpu.optimizer import SGD

    kv = create("local")
    kv.set_optimizer(SGD(learning_rate=1.0))
    kv.init(0, np.full(4, 5.0, np.float32))
    kv.init(1, np.full(2, 1.0, np.float32))
    outs = [np.zeros(4, np.float32), np.zeros(2, np.float32)]
    kv.push_pull([0, 1], [np.ones(4, np.float32),
                          np.ones(2, np.float32)], out=outs)
    kv.wait()
    np.testing.assert_allclose(outs[0], np.full(4, 4.0))
    np.testing.assert_allclose(outs[1], np.full(2, 0.0))
