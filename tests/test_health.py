"""geomx-healthd: estimator physics, board detectors, and the
closed-loop acceptance test — on a shaped plan the board's measured
per-link RTT/bandwidth must converge to the ShapePlan's ground truth,
and a mid-run degradation must show up within 3 rounds with exactly
one anomaly event.
"""

import json
import os
import time

import numpy as np
import pytest

from geomx_tpu import telemetry
from geomx_tpu.optimizer import SGD
from geomx_tpu.ps import linkstate
from geomx_tpu.ps.shaping import ShapeLink
from geomx_tpu.simulate import InProcessHiPS
from tools import geomx_top

from tests.test_hips import _parallel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SHAPE_PLAN = os.path.join(REPO, "scripts", "shapes",
                          "wan2_50ms_100mbps.json")


@pytest.fixture(autouse=True)
def _clean_registry():
    telemetry.reset()
    yield
    telemetry.reset()


# ---------------------------------------------------------------------------
# estimator physics
# ---------------------------------------------------------------------------

def test_estimator_rtt_from_small_frames():
    est = linkstate.LinkEstimator(lambda: 9, "global")
    # send->ack of a small frame is ~ one-way delay: rtt = 2 * min(dt);
    # the min rejects spans that queued behind big frames
    for dt in (0.027, 0.025, 0.031, 0.090):
        est.note_span(8, 100, dt)
    d = est.digest()
    assert d["lk"]["8"][0] == pytest.approx(50.0, rel=0.01)  # rtt_ms


def test_estimator_bw_median_flips_within_three_samples():
    est = linkstate.LinkEstimator(lambda: 9, "global")
    est.note_span(8, 100, 0.025)  # pin rtt/2 = 25 ms
    frame = 256_000  # bytes; at 100 Mbps ser = ~20.5 ms
    for _ in range(5):
        est.note_span(8, frame, 0.025 + frame * 8 / 100e6)
    assert est.digest()["lk"]["8"][1] == pytest.approx(100.0, rel=0.05)
    # link drops to 10 Mbps: the 5-wide median flips by the 3rd sample
    for i in range(3):
        est.note_span(8, frame, 0.025 + frame * 8 / 10e6)
    assert est.digest()["lk"]["8"][1] == pytest.approx(10.0, rel=0.1)


def test_estimator_digest_shape_and_loss_counts():
    est = linkstate.LinkEstimator(lambda: 9, "global")
    est.note_span(8, 100, 0.025)
    est.note_retransmit(8)
    est.note_give_up(8)
    est.note_sent(8, 1000, "2bit", trace_round=4)
    est.note_recv(8, trace_round=5)
    est.note_recv(8, trace_round=-1)  # untraced frames are ignored
    d = json.loads(est.digest_json(epoch=2))
    assert d["v"] == linkstate.DIGEST_VERSION
    assert (d["id"], d["ep"], d["rd"]) == (9, 2, 5)
    row = d["lk"]["8"]
    assert (row[5], row[6]) == (1, 1)        # rtx, give_ups
    assert d["pr"] == {"8": 5}               # arrival rounds
    assert d["cx"] == {"2bit": 1000}         # codec byte mix


# ---------------------------------------------------------------------------
# board detectors (driven with synthetic digests)
# ---------------------------------------------------------------------------

def _digest(nid, rd, lk=None, pr=None):
    d = {"v": 1, "id": nid, "ep": 0, "rd": rd}
    if lk:
        d["lk"] = lk
    if pr:
        d["pr"] = pr
    return json.dumps(d)


def _row(bw, rtx=0, nb=8):
    return [50.0, bw, 0.0, 0.0, bw / 8.0, rtx, 0, 4, nb]


def test_board_degradation_latched_per_episode():
    b = linkstate.ClusterHealthBoard("global", lambda: "sched",
                                     degrade_factor=0.5)
    for r in range(4):  # healthy baseline
        b.ingest(9, _digest(9, r, lk={"8": _row(100.0)}))
    assert b.render()["event_counts"] == {}
    b.ingest(9, _digest(9, 4, lk={"8": _row(9.7)}))
    b.ingest(9, _digest(9, 5, lk={"8": _row(9.7)}))  # still degraded
    board = b.render()
    assert board["event_counts"] == {"link_degraded": 1}  # latched
    ev = board["events"][-1]
    assert (ev["src"], ev["dst"], ev["cause"]) == (9, 8, "bw")
    assert board["links"]["9>8"]["degraded"]
    # recovery unlatches; a second episode fires a second event
    for r in range(6, 10):
        b.ingest(9, _digest(9, r, lk={"8": _row(100.0)}))
    assert not b.render()["links"]["9>8"]["degraded"]
    b.ingest(9, _digest(9, 10, lk={"8": _row(9.7)}))
    assert b.render()["event_counts"] == {"link_degraded": 2}


def test_board_degradation_needs_big_samples():
    b = linkstate.ClusterHealthBoard("global", lambda: "sched")
    b.ingest(9, _digest(9, 0, lk={"8": _row(100.0, nb=8)}))
    # nb below min_big_samples: the thin estimate must not fire
    b.ingest(9, _digest(9, 1, lk={"8": _row(9.0, nb=2)}))
    assert b.render()["event_counts"] == {}


def test_board_rtx_burst_fires_loss_event():
    b = linkstate.ClusterHealthBoard("global", lambda: "sched",
                                     rtx_burst=5)
    b.ingest(9, _digest(9, 0, lk={"8": _row(100.0, rtx=0)}))
    b.ingest(9, _digest(9, 1, lk={"8": _row(100.0, rtx=6)}))
    board = b.render()
    assert board["event_counts"] == {"link_degraded": 1}
    assert board["events"][-1]["cause"] == "loss"


def test_board_straggler_needs_persistence_and_prior_parity():
    b = linkstate.ClusterHealthBoard("global", lambda: "sched",
                                     straggler_rounds=1,
                                     straggler_persist=3)
    # startup ramp: node 11 has NEVER been current — a lag relative to
    # the cluster it never matched is joining, not straggling
    b.ingest(9, _digest(9, 5))
    for _ in range(4):
        b.ingest(11, _digest(11, 3))
    assert b.render()["event_counts"] == {}
    # parity arms the detector; then a lag must persist 3 refreshes
    b.ingest(11, _digest(11, 5))                     # current: armed
    b.ingest(9, _digest(9, 6))                       # cluster moves on
    b.ingest(11, _digest(11, 5))                     # streak = 1
    b.ingest(11, _digest(11, 5))                     # streak = 2
    assert b.render()["event_counts"] == {}
    b.ingest(11, _digest(11, 5))                     # streak = 3: fires
    board = b.render()
    assert board["event_counts"] == {"straggler": 1}
    assert board["events"][-1]["node"] == 11
    assert board["nodes"]["11"]["straggler"]
    # catching up clears the flag without a new event
    b.ingest(11, _digest(11, 7))
    assert not b.render()["nodes"]["11"]["straggler"]
    assert b.render()["event_counts"] == {"straggler": 1}


def test_board_epoch_stall_fires_once():
    b = linkstate.ClusterHealthBoard("global", lambda: "sched",
                                     stall_s=0.15)
    b.ingest(9, _digest(9, 1))
    time.sleep(0.3)
    b.ingest(9, _digest(9, 1))   # no progress past the stall budget
    b.ingest(9, _digest(9, 1))   # latched: still one event
    board = b.render()
    assert board["event_counts"] == {"epoch_stall": 1}
    assert board["max_round"] == 1


def test_board_export_and_geomx_top_render(tmp_path):
    b = linkstate.ClusterHealthBoard("global", lambda: "g8sched",
                                     out_dir=str(tmp_path))
    b.ingest(9, _digest(9, 3, lk={"8": _row(100.0)}, pr={"8": 2}))
    files = list(tmp_path.iterdir())
    assert [f.name for f in files] == ["board_g8sched_round3.json"]
    doc = json.loads(files[0].read_text())
    assert doc["v"] == linkstate.BOARD_VERSION
    assert doc["links"]["9>8"]["bw_mbps"] == 100.0
    # the dashboard parses and renders the export
    boards = geomx_top.load_boards(str(tmp_path))
    assert len(boards) == 1
    text = geomx_top.render_board(boards[0])
    assert "g8sched" in text and "9>8" in text
    assert geomx_top.main([str(tmp_path), "--once", "--json"]) == 0


def test_health_off_overhead_is_a_none_check():
    """Acceptance bar: GEOMX_HEALTH=0 leaves only `van.linkstate is
    None` checks on the wire path — budgeting 400 of them per 10-key
    round (~40 messages x a handful of touch points) stays far under
    5% of even a loopback round (>= tens of ms)."""

    class _V:
        linkstate = None

    van = _V()
    N = 20000
    t0 = time.perf_counter()
    for _ in range(N):
        ls = van.linkstate
        if ls is not None:  # pragma: no cover — off path
            ls.note_round(0)
    per_call = (time.perf_counter() - t0) / N
    assert per_call * 400 < 0.05 * 0.010  # 400 checks vs 5% of 10 ms


# ---------------------------------------------------------------------------
# acceptance: closed loop against the ShapePlan ground truth
# ---------------------------------------------------------------------------

def test_closed_loop_board_matches_shape_plan(tmp_path):
    """2-party HiPS under scripts/shapes/wan2_50ms_100mbps.json (every
    global-tier link 50 ms / 100 Mbps). The global board — measured
    purely from send->ack spans and queried live via kv.health() — must
    land within +-20% RTT and +-30% bandwidth of the plan in <= 20
    rounds; a mid-run drop of link 9->8 to 10 Mbps must show on the
    board within 3 rounds and raise exactly one degradation event."""
    telemetry.enable(True)
    health_dir = str(tmp_path / "health")
    sim = InProcessHiPS(
        num_parties=2, workers_per_party=1,
        extra_cfg=dict(
            shape_plan="@" + SHAPE_PLAN,
            resend=True, resend_timeout_ms=2000, resend_deadline_s=120.0,
            heartbeat_interval_s=0.2, heartbeat_timeout_s=60,
            health=True, health_dir=health_dir,
        )).start(sync_global=True)
    try:
        sim.master.set_optimizer(SGD(learning_rate=1.0))
        small = np.zeros(512, np.float32)          # 2 KB: RTT probe
        big = np.zeros(65_536, np.float32)         # 256 KB: bw probe

        def init_on(kv):
            kv.init(0, small)
            kv.init(1, big)
            kv.wait()

        _parallel([lambda kv=kv: init_on(kv)
                   for kv in sim.workers + [sim.master]])

        def step(kv):
            kv.push_pull(0, np.ones(512, np.float32),
                         np.zeros(512, np.float32))
            kv.push_pull(1, np.ones(65_536, np.float32),
                         np.zeros(65_536, np.float32))
            kv.wait()

        wan_links = ("9>8", "11>8")

        def global_board():
            got = sim.workers[0].health()
            boards = [g for g in got["global"] if g.get("tier") == "global"]
            return boards[0] if boards else None

        def converged(board):
            if board is None:
                return False
            links = board["links"]
            for name in wan_links:
                lk = links.get(name)
                if lk is None or lk["n_big"] < 3:
                    return False
                if not (40.0 <= lk["rtt_ms"] <= 60.0):       # +-20%
                    return False
                if not (70.0 <= lk["bw_mbps"] <= 130.0):     # +-30%
                    return False
            return True

        board = None
        rounds_run = 0
        for r in range(10):  # 2 combined rounds per step: <= 20 rounds
            _parallel([lambda kv=kv: step(kv) for kv in sim.workers])
            rounds_run = r + 1
            time.sleep(0.45)  # two heartbeat periods: digests land
            board = global_board()
            if rounds_run >= 3 and converged(board):
                break
        assert board is not None, "no global board over kv.health()"
        assert converged(board), (
            f"board did not converge to the plan within {2 * rounds_run} "
            f"rounds: {json.dumps(board.get('links', {}), indent=1)}")
        assert board["event_counts"].get("link_degraded", 0) == 0
        # the worker's own query also sees its LOCAL tier's board
        assert sim.workers[0].health()["local"] is not None

        # -- mid-run degradation: 9->8 drops to 10 Mbps -----------------
        gsrv = sim.servers[0]
        assert gsrv.is_global_server
        shaper = gsrv.po_global.van._shaper
        shaper.plan.links.insert(0, ShapeLink(
            src=9, dst=8, tier="global", rtt_ms=50.0, bw_mbps=10.0))
        baseline_round = board["max_round"]
        seen = None
        for _ in range(3):  # must reflect within 3 rounds of big frames
            _parallel([lambda kv=kv: step(kv) for kv in sim.workers])
        time.sleep(0.6)
        for _ in range(20):  # heartbeat cadence: give digests a beat
            seen = global_board()
            if seen is not None and seen["links"]["9>8"]["bw_mbps"] < 35.0:
                break
            time.sleep(0.2)
        lk = seen["links"]["9>8"]
        assert lk["bw_mbps"] < 35.0, (
            f"degradation not reflected: {lk} (baseline round "
            f"{baseline_round}, now {seen['max_round']})")
        # exactly ONE degradation event, on the right link, latched
        assert seen["event_counts"].get("link_degraded", 0) == 1, \
            seen["events"]
        ev = [e for e in seen["events"] if e["kind"] == "link_degraded"][-1]
        assert (ev["src"], ev["dst"]) == (9, 8)
        assert seen["links"]["9>8"]["degraded"]
        # the untouched link kept its healthy estimate
        assert seen["links"]["11>8"]["bw_mbps"] >= 70.0
        # telemetry funnel carried the anomaly event. The registry is
        # process-global: the party schedulers' LOCAL boards watch real
        # localhost links whose implied bandwidth is CPU-scheduling
        # noise, and under contention one may (rarely, legitimately)
        # raise its own event — so the funnel check is >= 1 while the
        # exactly-one bar above stays on the global board.
        counts = telemetry.snapshot()["counters"]
        assert counts.get("event.health.link_degraded", 0) >= 1
    finally:
        sim.stop()

    # per-round exports landed and the dashboard renders them
    boards = geomx_top.load_boards(health_dir)
    assert boards, "no board exports in GEOMX_HEALTH_DIR"
    assert any("9>8" in geomx_top.render_board(b) for b in boards)
