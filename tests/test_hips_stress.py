"""Stress and regression tests for the HiPS synchronization core.

The round-2 flake (a worker pull returning stale or gradient data) was a
cross-round confusion in the party server's forward/pull-back state
machine: the init-time global pull-back — buffered at the global server
until the master's init — could arrive AFTER the party's workers had
already pushed a full training round, complete the wrong round, and ack
the training pushes early. These tests pin the fix (per-cycle tokens +
outbound staging + pull buffering, geomx_tpu/kvstore/server.py) under
deterministic reorderings, many rounds, CPU load, and message loss.
"""

import threading
import time

import numpy as np
import pytest

from tests.test_hips import Topology, _parallel
from geomx_tpu.optimizer import SGD


def test_init_training_race_master_delayed():
    """Deterministic reproduction of the round-2 flake's root cause: the
    master's init is delayed so every party's init pull-back is buffered
    at the global server while party workers race ahead into training.
    Before the cycle-token fix this failed nearly always (workers pulled
    w0 instead of w0 - 4)."""
    topo = Topology().start(sync_global=True)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.arange(64, dtype=np.float32)

        def worker_path(kv):
            kv.init(0, w0)
            # no cross-party synchronization: train immediately
            for r in range(1, 4):
                kv.push(0, np.ones(64, np.float32))
                out = np.zeros(64, np.float32)
                kv.pull(0, out=out)
                kv.wait()
                np.testing.assert_allclose(out, w0 - 4.0 * r)

        def master_path(kv):
            time.sleep(0.5)   # widen the init/training race window
            kv.init(0, w0)

        _parallel([lambda kv=kv: worker_path(kv) for kv in topo.workers]
                  + [lambda: master_path(topo.master)])
    finally:
        topo.stop()


def _stress_rounds(topo, keys, w0, rounds, n_workers):
    topo.master.set_optimizer(SGD(learning_rate=1.0))

    def init_on(kv):
        for k in keys:
            kv.init(k, w0[k])

    _parallel([lambda kv=kv: init_on(kv)
               for kv in topo.workers + [topo.master]])

    def train(kv):
        for r in range(1, rounds + 1):
            for k in keys:
                kv.push(k, np.ones_like(w0[k]))
            outs = {k: np.zeros_like(w0[k]) for k in keys}
            for k in keys:
                kv.pull(k, out=outs[k])
            kv.wait()
            for k in keys:
                np.testing.assert_allclose(
                    outs[k], w0[k] - n_workers * r,
                    err_msg=f"key {k} round {r}")

    _parallel([lambda kv=kv: train(kv) for kv in topo.workers])


def test_stress_many_rounds_multi_server_parties_under_load():
    """20 rounds x 3 keys x 2-server parties with background CPU load —
    the configuration and duration under which the round-1/2 freshness
    race reproduced. Values must be exact every round."""
    stop = threading.Event()

    def burn():
        x = np.random.rand(256, 256).astype(np.float32)
        while not stop.is_set():
            x = np.tanh(x @ x.T * 1e-3)

    burners = [threading.Thread(target=burn, daemon=True) for _ in range(4)]
    for b in burners:
        b.start()
    topo = Topology(servers_per_party=2, bigarray_bound=16).start(
        sync_global=True)
    try:
        keys = [0, 1, 2]
        w0 = {0: np.arange(40, dtype=np.float32),
              1: np.ones(8, np.float32) * 3,
              2: np.linspace(-5, 5, 33).astype(np.float32)}
        _stress_rounds(topo, keys, w0, rounds=20, n_workers=4)
    finally:
        stop.set()
        topo.stop()


def test_stress_under_drop_and_resend():
    """Message loss (PS_DROP_MSG) with the retransmit layer (PS_RESEND)
    enabled on every van: rounds must still complete with exact values —
    retransmit-induced duplicates must not double-count pushes or
    barriers (the receipt-time dedup in van._process)."""
    topo = Topology(extra_cfg={"drop_rate": 0.05, "resend": True,
                               "resend_timeout_ms": 200}).start(
        sync_global=True)
    try:
        keys = [0, 1]
        w0 = {0: np.arange(24, dtype=np.float32),
              1: np.full(10, 2.0, np.float32)}
        _stress_rounds(topo, keys, w0, rounds=8, n_workers=4)
    finally:
        topo.stop()


def test_wait_keys_per_key_semantics():
    """wait(keys=[k]) drains only k's outstanding ops (round-2 Weak #8:
    the argument was silently ignored)."""
    topo = Topology().start(sync_global=True)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.zeros(8, np.float32)
        _parallel([lambda kv=kv: (kv.init(0, w0), kv.init(1, w0))
                   for kv in topo.workers + [topo.master]])

        def train(kv):
            kv.push(0, np.ones(8, np.float32))
            kv.push(1, np.ones(8, np.float32))
            out0 = np.zeros(8, np.float32)
            out1 = np.zeros(8, np.float32)
            kv.pull(0, out=out0)
            kv.pull(1, out=out1)
            kv.wait(keys=0)
            np.testing.assert_allclose(out0, w0 - 4.0)
            kv.wait(keys=[1])
            np.testing.assert_allclose(out1, w0 - 4.0)
            kv.wait()

        _parallel([lambda kv=kv: train(kv) for kv in topo.workers])
    finally:
        topo.stop()


def test_optimizer_states_fetched_from_global_tier(tmp_path):
    """A PARTY worker's save_optimizer_states must return the LIVE
    (global-tier) updater states, not the party server's never-updated
    copy (round-2 advisor finding a)."""
    from geomx_tpu import checkpoint as ck
    from geomx_tpu.optimizer import Adam
    import json

    topo = Topology().start(sync_global=True)
    fname = str(tmp_path / "party.states")
    try:
        topo.master.set_optimizer(Adam(learning_rate=0.01))
        w0 = np.ones(16, np.float32)
        _parallel([lambda kv=kv: kv.init(0, w0)
                   for kv in topo.workers + [topo.master]])

        def push_pull(kv):
            kv.push(0, np.ones(16, np.float32))
            kv.pull(0)
            kv.wait()

        for _ in range(3):
            _parallel([lambda kv=kv: push_pull(kv) for kv in topo.workers])

        # save from a party worker (NOT the master): its local servers
        # must relay the GET to the global tier
        party_worker = topo.workers[0]
        assert not party_worker.is_master_worker
        party_worker.save_optimizer_states(fname)
        with open(fname) as f:
            per_server = json.load(f)
        assert per_server, "no states returned"
        states = ck.deserialize_states(
            bytes.fromhex(next(iter(per_server.values()))))
        assert states[(0, 0)]["t"] == 3, \
            "party worker fetched stale (non-global) optimizer states"
        assert np.abs(states[(0, 0)]["m"]).max() > 0

        # round-trip: restore through the party worker too
        party_worker.load_optimizer_states(fname)
        # one more round applies on top of the restored states
        _parallel([lambda kv=kv: push_pull(kv) for kv in topo.workers])
        topo.master.save_optimizer_states(fname)
        with open(fname) as f:
            per2 = json.load(f)
        states2 = ck.deserialize_states(
            bytes.fromhex(next(iter(per2.values()))))
        assert states2[(0, 0)]["t"] == 4
    finally:
        topo.stop()


def test_checkpoint_five_digit_epoch(tmp_path):
    """latest_checkpoint must find epochs >= 10000 ({:04d} renders them
    5 digits wide; round-2 advisor finding d)."""
    from geomx_tpu import checkpoint

    prefix = str(tmp_path / "big")
    for e in (3, 9999, 10001):
        checkpoint.save_checkpoint(prefix, e, [np.zeros(2, np.float32)])
    assert checkpoint.latest_checkpoint(prefix) == 10001


def test_resend_give_up_surfaces_error():
    """When the resender exhausts its retries, the requester's wait()
    must raise promptly instead of blocking to its own timeout (round-2
    advisor finding c). The server drops 100% of inbound DATA frames
    before they reach the resender's dedup/ACK layer, so the worker's
    push is never acknowledged."""
    from geomx_tpu.config import Config
    from geomx_tpu.ps.kv_app import KVPairs, KVWorker
    from geomx_tpu.ps.message import Role
    from geomx_tpu.ps.postoffice import Postoffice
    from tests.test_hips import free_port

    port = free_port()
    cfg = Config(resend=True, resend_timeout_ms=20)
    blackhole = Config(resend=True, resend_timeout_ms=20, drop_rate=1.0)
    vans = []

    def sched():
        po = Postoffice(my_role=Role.SCHEDULER, is_global=False,
                        root_uri="127.0.0.1", root_port=port,
                        num_workers=1, num_servers=1, cfg=cfg)
        po.start(30)
        vans.append(po.van)

    def server():
        po = Postoffice(my_role=Role.SERVER, is_global=False,
                        root_uri="127.0.0.1", root_port=port,
                        num_workers=1, num_servers=1, cfg=blackhole)
        po.start(30)
        vans.append(po.van)

    for fn in (sched, server):
        threading.Thread(target=fn, daemon=True).start()

    wpo = Postoffice(my_role=Role.WORKER, is_global=False,
                     root_uri="127.0.0.1", root_port=port,
                     num_workers=1, num_servers=1, cfg=cfg)
    wpo.start(30)
    kvw = KVWorker(wpo)
    # cap retries low so the test is fast
    wpo.van._resender.max_retries = 3

    ts = kvw.push(KVPairs(keys=[0], vals=[np.ones(4, np.float32)],
                          offsets=[0], totals=[4], lens=[4]), 0)
    t0 = time.monotonic()
    with pytest.raises((RuntimeError, TimeoutError)) as ei:
        kvw.wait(ts, timeout=30.0)
    elapsed = time.monotonic() - t0
    assert elapsed < 20.0, "give-up did not surface promptly"
    assert isinstance(ei.value, RuntimeError), \
        f"expected fast RuntimeError from give-up, got {ei.value!r}"
    assert "undeliverable" in str(ei.value)
    wpo.van.stop()
    for v in vans:
        v.stop()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))


def test_non_uniform_party_sizes_exact_counting():
    """Parties running DIFFERENT numbers of local servers: with
    DMLC_NUM_PARTY set (simulate sets it automatically for non-uniform
    topologies) the global server counts rounds exactly — the reference's
    aligned-key counting cannot express this topology at all."""
    topo = Topology(servers_per_party=[2, 1], bigarray_bound=16).start(
        sync_global=True)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        keys = [0, 1]
        w0 = {0: np.arange(40, dtype=np.float32),
              1: np.full(8, 3.0, np.float32)}
        _parallel([lambda kv=kv: [kv.init(k, w0[k]) for k in keys]
                   for kv in topo.workers + [topo.master]])

        def train(kv):
            for r in range(1, 4):
                for k in keys:
                    kv.push(k, np.ones_like(w0[k]))
                outs = {k: np.zeros_like(w0[k]) for k in keys}
                for k in keys:
                    kv.pull(k, out=outs[k])
                kv.wait()
                for k in keys:
                    np.testing.assert_allclose(
                        outs[k], w0[k] - 4.0 * r,
                        err_msg=f"key {k} round {r}")

        _parallel([lambda kv=kv: train(kv) for kv in topo.workers])
    finally:
        topo.stop()


def _stress_rounds_batched(topo, keys, w0, rounds, n_workers):
    """_stress_rounds through the BATCHED list wire (one message per
    server per direction; countdown-merged server responses)."""
    topo.master.set_optimizer(SGD(learning_rate=1.0))

    def init_on(kv):
        for k in keys:
            kv.init(k, w0[k])

    _parallel([lambda kv=kv: init_on(kv)
               for kv in topo.workers + [topo.master]])

    def train(kv):
        for r in range(1, rounds + 1):
            kv.push(keys, [np.ones_like(w0[k]) for k in keys])
            outs = [np.zeros_like(w0[k]) for k in keys]
            kv.pull(keys, out=outs)
            kv.wait()
            for k, out in zip(keys, outs):
                np.testing.assert_allclose(
                    out, w0[k] - n_workers * r,
                    err_msg=f"key {k} round {r}")

    _parallel([lambda kv=kv: train(kv) for kv in topo.workers])


def test_stress_batched_wire_multi_server_parties():
    """The batched multi-key wire under the freshness-race stress
    configuration (2-server parties, sharded keys, many rounds):
    values must be exact every round."""
    topo = Topology(servers_per_party=2, bigarray_bound=16).start(
        sync_global=True)
    try:
        keys = [0, 1, 2]
        w0 = {0: np.arange(40, dtype=np.float32),
              1: np.ones(8, np.float32) * 3,
              2: np.linspace(-5, 5, 33).astype(np.float32)}
        _stress_rounds_batched(topo, keys, w0, rounds=20, n_workers=4)
    finally:
        topo.stop()


def test_stress_batched_wire_under_drop_and_resend():
    """Batched rounds under message loss + retransmit: a dropped or
    duplicated multi-key message must neither double-count any key's
    contribution nor leave the countdown responder short."""
    topo = Topology(extra_cfg={"drop_rate": 0.05, "resend": True,
                               "resend_timeout_ms": 200}).start(
        sync_global=True)
    try:
        keys = [0, 1]
        w0 = {0: np.arange(24, dtype=np.float32),
              1: np.full(10, 2.0, np.float32)}
        _stress_rounds_batched(topo, keys, w0, rounds=8, n_workers=4)
    finally:
        topo.stop()


def _stress_rounds_push_pull(topo, keys, w0, rounds, n_workers):
    """_stress_rounds through the COMBINED push_pull wire (one message
    per server per round; the countdown-merged ack carries the
    post-round params)."""
    topo.master.set_optimizer(SGD(learning_rate=1.0))

    def init_on(kv):
        for k in keys:
            kv.init(k, w0[k])

    _parallel([lambda kv=kv: init_on(kv)
               for kv in topo.workers + [topo.master]])

    def train(kv):
        for r in range(1, rounds + 1):
            outs = [np.zeros_like(w0[k]) for k in keys]
            kv.push_pull(keys, [np.ones_like(w0[k]) for k in keys],
                         out=outs)
            kv.wait()
            for k, out in zip(keys, outs):
                np.testing.assert_allclose(
                    out, w0[k] - n_workers * r,
                    err_msg=f"key {k} round {r}")

    _parallel([lambda kv=kv: train(kv) for kv in topo.workers])


def test_stress_push_pull_multi_server_parties():
    """Combined push_pull under the freshness-race stress configuration
    (2-server parties, sharded keys, many rounds): exact every round."""
    topo = Topology(servers_per_party=2, bigarray_bound=16).start(
        sync_global=True)
    try:
        keys = [0, 1, 2]
        w0 = {0: np.arange(40, dtype=np.float32),
              1: np.ones(8, np.float32) * 3,
              2: np.linspace(-5, 5, 33).astype(np.float32)}
        _stress_rounds_push_pull(topo, keys, w0, rounds=20, n_workers=4)
    finally:
        topo.stop()


def test_stress_push_pull_under_drop_and_resend():
    """Combined push_pull rounds under message loss + retransmit: a
    dropped/duplicated combined message must neither double-count a
    push nor lose its data-carrying ack (the client falls back to an
    explicit pull only when a server acks without data)."""
    topo = Topology(extra_cfg={"drop_rate": 0.05, "resend": True,
                               "resend_timeout_ms": 200}).start(
        sync_global=True)
    try:
        keys = [0, 1]
        w0 = {0: np.arange(24, dtype=np.float32),
              1: np.full(10, 2.0, np.float32)}
        _stress_rounds_push_pull(topo, keys, w0, rounds=8, n_workers=4)
    finally:
        topo.stop()
