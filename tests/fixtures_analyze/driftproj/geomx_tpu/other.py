"""A module that bypasses the config registry."""

import os

RAW_FLAG = os.environ.get("PS_RAW_FLAG", "0")   # GX-C203 (+ GX-C201: undocumented)
