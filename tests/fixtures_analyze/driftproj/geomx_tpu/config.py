"""Miniature config registry for the config-drift fixture tree."""


def load():
    return dict(
        documented=env_int("PS_DOCUMENTED", 1),
        undocumented=env_str("PS_UNDOCUMENTED", ""),   # GX-C201: no doc row
    )
