#!/bin/bash
# Fixture launch script for the config-drift pass.
export PS_DOCUMENTED=2
DMLC_DEAD_KNOB=1 python -c 'pass'   # GX-C204: nothing in code/doc knows this knob
