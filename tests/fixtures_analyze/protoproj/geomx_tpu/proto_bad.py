"""Seeded GX-P3xx violations (protocol pass) with clean counterparts.

Each bad site is annotated with the rule it must trip; the GoodServer
mirror shows the fenced/range-aware/live-view versions that must stay
clean. tests/test_analyze.py asserts the exact finding set.
"""


class Control:
    EMPTY = 0    # exempt: the data-frame marker, never a stamped verb
    PING = 1     # sent AND dispatched: clean
    ORPHAN = 2   # GX-P301 sent-unhandled
    GHOST = 3    # GX-P301 dispatched-unsent
    UNUSED = 4   # GX-P301 unused


class Meta:
    def __init__(self, control_cmd=Control.EMPTY):
        self.control_cmd = control_cmd


def send_ping(van):
    van.send(Meta(control_cmd=Control.PING))


def send_orphan(van):
    van.send(Meta(control_cmd=Control.ORPHAN))


def dispatch(cmd, van):
    if cmd == Control.PING:
        van.pong()
    elif cmd in (Control.GHOST,):
        van.spook()


class BadServer:
    def __init__(self, van):
        self.van = van
        self.nm = 0
        self.pending = {}

    def handle_push(self, req):
        if req.head < 0:
            return None          # GX-P302: silent drop, no ack path
        self.nm += 1             # GX-P304: unfenced countdown mutation
        self.van.respond(req)

    def handle_pull(self, req):
        for k in req.keys:       # GX-P303: routes by bare key, no
            self.pending[k] = 1  # offset — sliced keys alias one slot
        self.van.respond(req)

    def check_round(self, received):
        # GX-P305 (compare): arrival count vs static membership
        if received >= self.van.num_workers:
            self.flush()

    def start_round(self):
        # GX-P305 (kwarg): countdown target sized from static count
        self.countdown(tgt=self.van.num_workers)

    def flush(self):
        self.nm = 0

    def countdown(self, tgt):
        self.nm = tgt


class GoodServer:
    def __init__(self, van):
        self.van = van
        self.nm = 0
        self.pending = {}

    def handle_push(self, req):
        if self.van.is_stale(req.sender, req.epoch):
            return               # fenced drop: the one legal no-ack exit
        self.nm += 1             # fenced mutation: clean
        self.van.respond(req)

    def handle_pull(self, req):
        for k in req.keys:
            off = self.offset_of(k, req.ranges)
            self.pending[(k, off)] = 1   # (key, range) routing: clean
        self.van.respond(req)

    def handle_other(self, req):
        if req.head != 7:
            return False         # handler-chain decline: clean
        self.van.respond(req)
        return True

    def check_round(self, received):
        if received >= self.van.num_live_workers():  # live view: clean
            self.flush()

    def flush(self):
        self.nm = 0

    def offset_of(self, key, ranges):
        return ranges.get(key, 0)


def send_quantized(van, payload):
    # GX-P307: aux-requiring codec stamped without its sidecar — the
    # receiver cannot recover the 2-bit threshold from the codes alone
    van.push(payload, compr="2bit")


def send_quantized_ok(van, payload, thr):
    van.push(payload, compr="2bit", aux=[thr])   # sidecar present: clean


def send_rows_ok(van, payload, ids):
    van.push(payload, compr="rsp", aux=[ids])    # clean


def send_dense_ok(van, payload, tag):
    van.push(payload, compr="fp16")              # self-describing: clean
    van.push(payload, compr=tag)                 # dynamic tag: out of scope


# GX-P306: the committed protoproj lock holds version 3 with a WRONG
# fingerprint for these fields -> schema-changed fires.
BINMETA_VERSION = 3

_META_FIELDS = [
    ("sender", "i"), ("timestamp", "i"), ("request", "b"),
]
