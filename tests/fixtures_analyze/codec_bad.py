"""GX-J105 fixture: host transfers inside mesh codec classes.

``PartyRingReducer`` violates the rule three ways (direct, transitive,
and via ``.addressable_data``); ``CleanRingReducer`` shows the guard
shapes that must stay clean; ``WireCodec`` proves the rule does NOT
extend to the van wire codec, whose host arrays are the product.
"""

import numpy as np

import jax


class PartyRingReducer:
    def reduce(self, x_stacked):
        # VIOLATION: every mesh rank drags the reduced vector to host
        return np.asarray(self._fn(x_stacked))

    def quantize_hop(self, partial):
        # VIOLATION (transitive): reached from a codec-shaped method
        return self._drain(partial)

    def _drain(self, partial):
        return jax.device_get(partial)

    def reset(self):
        # VIOLATION: residual stream materialized on every rank
        self._res = np.array(self._res.addressable_data(0))

    def wire_bytes(self):
        # not a codec-shaped method: never scanned
        return np.asarray([0.0]).nbytes


class CleanRingReducer:
    def __init__(self):
        self.is_global_worker = True

    def reduce(self, x_stacked):
        if self.is_global_worker:
            return np.asarray(self._fn(x_stacked))    # guarded: clean
        return self._fn(x_stacked)

    def decode_probe(self, wire):
        if not self.is_global_worker:
            raise RuntimeError("probe is global-worker only")
        return np.asarray(wire)                       # fenced: clean

    def zero_residual(self, n):
        # fresh host zeros are a constructor, not a device transfer
        return np.zeros((n,), np.float32)


class WireCodec:
    def encode(self, tag, arr):
        # same body as the violation above, but this is the VAN wire
        # codec — host arrays are its product, out of the rule's scope
        return np.asarray(arr, np.float32).ravel()
