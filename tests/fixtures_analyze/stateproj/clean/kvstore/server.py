"""Clean counterpart of ../../bad/kvstore/server.py."""


class KVStoreDistServer:
    def _handle_data(self, req):
        if self.po_local.van.is_stale(req.sender, req.epoch):
            return None
        return self._push_local_store(req)

    def _handle_command(self, req):
        if self.po_local.van.is_stale(req.sender, req.epoch):
            return None
        return self._run_command(req)

    def _expected_local_pushes(self):
        return max(self.po_local.num_live_workers(), 1)

    def _expected_global_elems(self):
        return max(self.po_global.num_live_workers(), 1)

    def _on_membership(self, epoch, dead):
        self._expected_local_pushes()
        self._expected_global_elems()
        self._complete_local_round(None, None)
        self._complete_fsa_round()

    def start(self):
        if self.po_local.van.is_recovery:
            self.replication.restore()
        self._ready.set()
