"""Clean restore_merge anchor: restore() consults both sources."""


class ReplicationManager:
    def restore(self):
        blob = self._read_snapshot()
        peer = self._fetch_from_peer(timeout=5.0)
        doc, entries, source = self._pick(blob, peer)
        if doc is None:
            return None
        self._apply(doc, entries, source)
        return source
