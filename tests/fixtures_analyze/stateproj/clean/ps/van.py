"""Clean counterpart of ../../bad/ps/van.py: every modeled transition
realized with its required writes, calls, reads and fences — must stay
silent under GX-S502/S503/S504."""


class Van:
    def __init__(self):
        self._declared_dead = set()
        self._rejoin_epoch = {}
        self.membership_epoch = 0
        self.is_recovery = False

    def declare_dead(self, ids):
        self._declared_dead.update(ids)
        self.membership_epoch += 1
        epoch = self.membership_epoch
        dead = frozenset(self._declared_dead)
        self._broadcast_membership(epoch, dead)
        self._membership_side_effects(epoch, dead)

    def _scheduler_register(self, node):
        if node.id in self._declared_dead:
            self._declared_dead.discard(node.id)
            self.membership_epoch += 1
            self._rejoin_epoch[node.id] = self.membership_epoch
            self._broadcast_membership(self.membership_epoch,
                                       frozenset(self._declared_dead))

    def _process_dead_node(self, msg):
        new_dead = {n.id for n in msg.nodes}
        if msg.epoch < self.membership_epoch:
            return
        for nid in self._declared_dead - new_dead:
            self._rejoin_epoch[nid] = msg.epoch
        self._declared_dead = set(new_dead)
        self.membership_epoch = msg.epoch
        self._membership_side_effects(msg.epoch, frozenset(new_dead))

    def _process_add_node(self, msg):
        if msg.epoch > self.membership_epoch:
            self.membership_epoch = msg.epoch
        for n in msg.nodes:
            if n.is_recovery and n.id in self._declared_dead:
                self._declared_dead.discard(n.id)
                self._rejoin_epoch[n.id] = self.membership_epoch
        self.is_recovery = False
        self._membership_side_effects(self.membership_epoch,
                                      frozenset(self._declared_dead))

    def is_stale(self, sender, epoch):
        return (sender in self._declared_dead
                or epoch < self._rejoin_epoch.get(sender, 0))

    def _broadcast_membership(self, epoch, dead):
        pass

    def _membership_side_effects(self, epoch, dead):
        pass
