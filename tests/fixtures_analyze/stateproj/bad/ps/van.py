"""Seeded GX-S50x violations: membership anchors that drifted from the
executable model (tools/analyze/statemodel.py). Each broken element is
marked; the clean counterpart lives in ../../clean/ps/van.py."""


class Van:
    def __init__(self):
        self._declared_dead = set()
        self._rejoin_epoch = {}
        self.membership_epoch = 0
        self.is_recovery = False

    # GX-S503: declare_dead stopped broadcasting (required call
    # `_broadcast_membership` is gone) — survivors never learn the epoch
    def declare_dead(self, ids):
        self._declared_dead.update(ids)
        self.membership_epoch += 1
        self._membership_side_effects(self.membership_epoch,
                                      frozenset(self._declared_dead))

    def _scheduler_register(self, node):
        if node.id in self._declared_dead:
            self._declared_dead.discard(node.id)
            self.membership_epoch += 1
            self._rejoin_epoch[node.id] = self.membership_epoch
            self._broadcast_membership(self.membership_epoch,
                                       frozenset(self._declared_dead))

    # GX-S504: the epoch guard is gone — stale DEAD_NODE broadcasts
    # (reordered/retransmitted) roll the dead set back
    def _process_dead_node(self, msg):
        new_dead = {n.id for n in msg.nodes}
        for nid in self._declared_dead - new_dead:
            self._rejoin_epoch[nid] = msg.epoch
        self._declared_dead = set(new_dead)
        self.membership_epoch = msg.epoch
        self._membership_side_effects(msg.epoch, frozenset(new_dead))

    def _process_add_node(self, msg):
        if msg.epoch > self.membership_epoch:
            self.membership_epoch = msg.epoch
        for n in msg.nodes:
            if n.is_recovery and n.id in self._declared_dead:
                self._declared_dead.discard(n.id)
                self._rejoin_epoch[n.id] = self.membership_epoch
        self.is_recovery = False
        self._membership_side_effects(self.membership_epoch,
                                      frozenset(self._declared_dead))

    # GX-S503: the rejoin-fence read is gone — a zombie whose slot was
    # re-filled passes the fence as long as it is not in the dead set
    def is_stale(self, sender, epoch):
        return sender in self._declared_dead

    # GX-S502: mutates modeled membership state outside any modeled
    # transition — invisible to the model and the conformance sanitizer
    def reset_membership(self):
        self._declared_dead.clear()
        self.membership_epoch = 0

    def _broadcast_membership(self, epoch, dead):
        pass

    def _membership_side_effects(self, epoch, dead):
        pass
