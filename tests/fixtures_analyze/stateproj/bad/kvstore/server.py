"""Seeded GX-S50x violations on the server side of the state model."""


class KVStoreDistServer:
    # GX-S504: the is_stale fence is gone — a declared-dead zombie's
    # push aggregates into the round
    def _handle_data(self, req):
        return self._push_local_store(req)

    def _handle_command(self, req):
        if self.po_local.van.is_stale(req.sender, req.epoch):
            return None
        return self._run_command(req)

    # GX-S504: countdown sized from the static worker count, not the
    # live membership view — a mid-round death wedges the round forever
    def _expected_local_pushes(self):
        return max(self.num_workers, 1)

    def _expected_global_elems(self):
        return max(self.po_global.num_live_workers(), 1)

    # GX-S503: the membership hook no longer re-checks the local
    # countdown — rounds already past the old threshold never release
    def _on_membership(self, epoch, dead):
        self._expected_global_elems()
        self._complete_fsa_round()

    def start(self):
        if self.po_local.van.is_recovery:
            self.replication.restore()
        self._ready.set()
