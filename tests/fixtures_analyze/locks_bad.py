"""Seeded concurrency violations — analyzed, never imported."""

import threading
import time


class Inverted:
    """GX-L001: ab() orders a->b, ba() orders b->a."""

    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()
        self.counter = 0
        self.t = threading.Thread(target=self.unguarded)

    def ab(self):
        with self.a:
            with self.b:
                self.counter += 1          # guarded write (under a, b)

    def ba(self):
        with self.b:
            with self.a:
                pass

    def unguarded(self):
        self.counter = 0                   # GX-L002: no lock held

    def blocking(self):
        with self.a:
            time.sleep(0.1)                # GX-L003: sleep under a
            self.t.join()                  # GX-L003: thread join under a

    def reenter_lexical(self):
        with self.a:
            with self.a:                   # GX-L004: Lock is not reentrant
                pass

    def reenter_via_call(self):
        with self.b:
            self._helper()                 # GX-L004: helper retakes b

    def _helper(self):
        with self.b:
            pass


class CvHolder:
    """Condition.wait released correctly vs while holding another lock."""

    def __init__(self):
        self.m = threading.Lock()
        self.cv = threading.Condition()

    def ok_wait(self):
        with self.cv:
            self.cv.wait()                 # fine: wait releases cv itself

    def bad_wait(self):
        with self.m:
            with self.cv:
                self.cv.wait()             # GX-L003: m stays held asleep


class CleanRLock:
    """Re-entry on an RLock is legal — must NOT fire GX-L004."""

    def __init__(self):
        self.r = threading.RLock()

    def nested(self):
        with self.r:
            with self.r:
                pass
