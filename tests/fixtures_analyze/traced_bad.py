"""Seeded traced-code violations — analyzed, never imported."""

import functools

import jax
import numpy as np


@jax.jit
def hot(x):
    y = helper(x)
    return float(y) + y.item()             # GX-J101 twice (float, .item)


def helper(x):
    # traced transitively: hot() calls it
    return np.asarray(x) * 2               # GX-J101 (np.asarray on tracer)


def looped(xs):
    out = []
    for x in xs:
        out.append(jax.jit(lambda v: v * 2)(x))   # GX-J102: loop + inline
    return out


@jax.jit
def train_step(params, opt_state, batch):  # GX-J103: returns state, no donate
    params = params
    return params, opt_state, 0.0


@functools.partial(jax.jit, donate_argnums=(0, 1))
def good_step(params, opt_state, batch):   # clean: donates its state
    return params, opt_state, 1.0


@jax.jit
def grad_like_step(params, batch):         # clean: param only used, not passed through
    return np.tanh


@jax.jit
def static_ok(x):
    return int(x.shape[0])                 # clean: shapes are static
