"""GX-M402 fixture: link.* metrics set outside the linkstate funnel."""

from geomx_tpu import telemetry
from geomx_tpu.ps import linkstate


class Shaper:
    def hold(self, src, dst, delay_s):
        telemetry.gauge_set("link.shaped_delay_ms", delay_s * 1e3,  # GX-M402
                            src=src, dst=dst, tier="local")

    def carried(self, src, dst, n):
        telemetry.counter_inc("link.shaped_bytes", n,  # GX-M402
                              src=src, dst=dst, tier="local")

    def suppressed(self, mb_s):
        # geomx-lint: disable=GX-M402
        telemetry.gauge_set("link.goodput_mb_s", mb_s)

    def clean(self, src, dst, delay_s, mb_s):
        # routed through the funnel: fine
        linkstate.note_shaped_delay(src, dst, delay_s, tier="local")
        linkstate.note_goodput(src, dst, mb_s, tier="local")
        # non-link namespaces are out of scope for M402
        telemetry.gauge_set("queue.depth", 3, tier="local")
        telemetry.counter_inc("van.bytes_sent", 10, tier="local")


def module_level(bw):
    telemetry.gauge_set("link.bw_mbps", bw, src=1, dst=2)  # GX-M402
