"""The link.* funnel itself: raw emitters here are the implementation."""

from geomx_tpu import telemetry


def note_goodput(src, dst, mb_s, tier):
    telemetry.gauge_set("link.goodput_mb_s", mb_s, src=src, dst=dst,
                        tier=tier)  # exempt: this IS the funnel


def note_shaped_bytes(src, dst, nbytes, tier):
    telemetry.counter_inc("link.shaped_bytes", nbytes, src=src, dst=dst,
                          tier=tier)  # exempt
