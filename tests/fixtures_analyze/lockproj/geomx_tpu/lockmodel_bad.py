"""Seeded lock-model violations (GX-L005/L006) — analyzed, never
imported — next to the clean counterparts that must stay clean."""

import threading

from geomx_tpu.ps import locks


class Bad005:
    """GX-L005: ``count`` written with no lock held from two thread
    roots (the spawned ``_loop`` plus the external caller of ``bump``)
    and never declared ``@guarded_by``."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        self.count += 1                    # unlocked, thread root _loop

    def bump(self):
        self.count += 1                    # unlocked, root <caller>


@locks.guarded_by("_lock", "count")
class Good005Declared:
    """Same write pattern, but the field is declared: the racy writes
    are the runtime lockset checker's business, not GX-L005's."""

    def __init__(self):
        self._lock = locks.make_lock("Good005Declared._lock")
        self.count = 0
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        self.count += 1

    def bump(self):
        self.count += 1


class Good005Locked:
    """Same roots, but every write holds the lock: clean."""

    def __init__(self):
        self._lock = threading.Lock()
        with self._lock:
            self.count = 0
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        with self._lock:
            self.count += 1

    def bump(self):
        with self._lock:
            self.count += 1


class Bad006:
    """GX-L006: ``Condition.wait()`` with an ``if`` instead of a
    ``while`` predicate loop."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._ready = False

    def take(self):
        with self._cv:
            if not self._ready:
                self._cv.wait()            # spurious wakeup slips through


class Good006:
    """The two sanctioned wait shapes: a while predicate loop, and
    ``wait_for`` (which carries its own loop)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._ready = False

    def take(self):
        with self._cv:
            while not self._ready:
                self._cv.wait()

    def take_for(self):
        with self._cv:
            self._cv.wait_for(lambda: self._ready)
