"""The funnel itself: raw profiler calls here are the implementation."""

from geomx_tpu import profiler


def event(name, cat="telemetry", **args):
    profiler.instant(name, cat=cat, **args)  # exempt: this IS the funnel


def sample(name, value, cat="telemetry"):
    profiler.counter(name, value, cat=cat)  # exempt
