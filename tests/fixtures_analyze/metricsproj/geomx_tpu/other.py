"""GX-M401 fixture: raw profiler events outside the telemetry funnel."""

from geomx_tpu import profiler, telemetry


class Thing:
    def flag(self):
        profiler.instant("thing.flagged", cat="test")  # GX-M401

    def count(self, n):
        profiler.counter("thing.count", n)  # GX-M401

    def suppressed(self):
        # geomx-lint: disable=GX-M401
        profiler.instant("thing.quiet")

    def clean(self):
        telemetry.event("thing.flagged", cat="test")
        telemetry.sample("thing.count", 3)
        with profiler.scope("thing.work"):  # spans are trace-only: fine
            pass


def module_level():
    profiler.instant("module.marker")  # GX-M401
