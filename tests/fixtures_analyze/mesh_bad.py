"""GX-J104 fixture: host transfers on mesh-party round paths.

``PartyMeshStore`` violates the rule three ways (direct, transitive,
and via jax.device_get); ``CleanMeshStore`` shows every guard shape
that must stay clean; ``PlainWireStore`` proves the rule keys on the class
name.
"""

import numpy as np

import jax


class PartyMeshStore:
    def push_round(self, glist):
        # VIOLATION: every mesh rank would materialize the gradient
        vals = [np.asarray(g) for g in glist]
        return vals

    def pull_results(self, out):
        # VIOLATION (transitive): reached from a round-shaped method
        return self._fetch(out)

    def _fetch(self, out):
        return jax.device_get(out)

    def step(self, x):
        # VIOLATION: first addressable shard fetched on every rank
        return np.array(x.addressable_data(0))

    def close(self):
        # not a round-shaped method: never scanned
        return np.asarray([0.0])


class CleanMeshStore:
    def __init__(self):
        self.is_global_worker = True

    def push_round(self, glist):
        if self.is_global_worker:
            return [np.asarray(g) for g in glist]    # guarded: clean
        return None

    def pull_round(self, out):
        if not self.is_global_worker:
            raise RuntimeError("van is global-worker only")
        return np.asarray(out)                        # fenced: clean

    def record_round(self, leaves):
        # shape metadata only — no host transfer at all
        return sum(int(getattr(x, "nbytes", 0)) for x in leaves)


class PlainWireStore:
    def push_round(self, glist):
        # same body as the violation above, but the class is not
        # Mesh-named — out of the rule's scope
        return [np.asarray(g) for g in glist]
