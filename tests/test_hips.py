"""Integration tests: the full HiPS two-tier topology, in one process.

Replicates the reference's 12-process, 3-party demo topology
(scripts/cpu/run_vanilla_hips.sh) as in-process threads: a central party
(global scheduler, global server, master worker, scheduler) plus two data
parties (scheduler, server, two workers each). Because Postoffices are
instance-scoped, no subprocesses or env vars are needed — configs are
passed explicitly.
"""

import threading

import numpy as np
import pytest

from geomx_tpu.config import Config
from geomx_tpu.kvstore.dist import KVStoreDist
from geomx_tpu.kvstore.server import KVStoreDistServer
from geomx_tpu.optimizer import SGD, Adam
from geomx_tpu.ps import base as psbase
from geomx_tpu.ps.message import Role
from geomx_tpu.ps.postoffice import Postoffice
from geomx_tpu.simulate import InProcessHiPS, free_port  # noqa: F401


class Topology(InProcessHiPS):
    """The product in-process topology (geomx_tpu.simulate.InProcessHiPS)
    with test-suite defaults: 2 workers per party, like the reference's
    12-process demo (scripts/cpu/run_vanilla_hips.sh)."""

    def __init__(self, num_parties=2, workers_per_party=2, **kw):
        super().__init__(num_parties=num_parties,
                         workers_per_party=workers_per_party, **kw)


def _parallel(fns):
    errs = []

    def wrap(fn):
        try:
            fn()
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=wrap, args=(fn,), daemon=True) for fn in fns]
    for t in ts:
        t.start()
    for t in ts:
        t.join(120)
    if errs:
        raise errs[0]


def test_hips_fsa_vanilla():
    """Vanilla dist_sync: SGD(lr=1) on the global server; 2 parties x 2
    workers each push ones -> after one round every worker pulls w0 - 4."""
    topo = Topology().start(sync_global=True)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.arange(40, dtype=np.float32).reshape(5, 8)

        def init_on(kv):
            kv.init(0, w0)
            if not kv.is_master_worker:
                got = kv.pull(0)
                np.testing.assert_allclose(got.reshape(5, 8), w0)

        _parallel([lambda kv=kv: init_on(kv)
                   for kv in topo.workers + [topo.master]])

        def train_step(kv):
            kv.push(0, np.ones((5, 8), np.float32))
            out = np.zeros((5, 8), np.float32)
            kv.pull(0, out=out)
            kv.wait()
            np.testing.assert_allclose(out, w0 - 4.0)

        _parallel([lambda kv=kv: train_step(kv) for kv in topo.workers])

        # second round: w0 - 8 everywhere
        def step2(kv):
            kv.push(0, np.ones((5, 8), np.float32))
            out = np.zeros((5, 8), np.float32)
            kv.pull(0, out=out)
            kv.wait()
            np.testing.assert_allclose(out, w0 - 8.0)

        _parallel([lambda kv=kv: step2(kv) for kv in topo.workers])
    finally:
        topo.stop()


def test_hips_multiple_keys_and_adam():
    topo = Topology().start(sync_global=True)
    try:
        topo.master.set_optimizer(Adam(learning_rate=0.01))
        shapes = {0: (4, 4), 1: (16,), 2: (3, 2, 2)}
        w0 = {k: np.random.RandomState(k).randn(*s).astype(np.float32)
              for k, s in shapes.items()}

        def init_on(kv):
            for k in shapes:
                kv.init(k, w0[k])

        _parallel([lambda kv=kv: init_on(kv)
                   for kv in topo.workers + [topo.master]])

        outs = {}
        lock = threading.Lock()

        def train(kv):
            grads = {k: np.full(shapes[k], 0.1, np.float32) for k in shapes}
            for k in shapes:
                kv.push(k, grads[k], priority=-k)
            res = {k: np.zeros(shapes[k], np.float32) for k in shapes}
            for k in shapes:
                kv.pull(k, out=res[k], priority=-k)
            kv.wait()
            with lock:
                outs[kv.rank, id(kv)] = res

        _parallel([lambda kv=kv: train(kv) for kv in topo.workers])
        vals = list(outs.values())
        for other in vals[1:]:
            for k in shapes:
                np.testing.assert_allclose(vals[0][k], other[k], rtol=1e-6)
        for k in shapes:  # Adam moved every weight
            assert not np.allclose(vals[0][k], w0[k])
    finally:
        topo.stop()


def test_hips_mixed_sync_async_global():
    """dist_async (MixedSync): global tier updates per party push."""
    topo = Topology().start(sync_global=False)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.zeros(8, np.float32)
        _parallel([lambda kv=kv: kv.init(0, w0)
                   for kv in topo.workers + [topo.master]])

        def train(kv):
            kv.push(0, np.ones(8, np.float32))
            out = np.zeros(8, np.float32)
            kv.pull(0, out=out)
            kv.wait()
            # each party contributes -2; depending on arrival order a worker
            # sees one or both parties applied
            assert out[0] in (-2.0, -4.0), out

        _parallel([lambda kv=kv: train(kv) for kv in topo.workers])
        # both parties' push acks returned, so the global store has both
        # updates; the master worker's local server IS the global server,
        # so its pull reads the global store directly
        final = topo.master.pull(0)
        np.testing.assert_allclose(final, np.full(8, -4.0))
    finally:
        topo.stop()


def test_hips_bsc_gradient_aggregation():
    """BSC mode: no global optimizer; the store carries the aggregated
    gradient; workers pull it (into param.grad() in the examples) and apply
    the optimizer locally (reference: examples/cnn_bsc.py:115-121)."""
    topo = Topology().start(sync_global=True)
    try:
        topo.master.set_gradient_compression({"type": "bsc", "threshold": 1.0})
        w0 = np.full(64, 7.0, np.float32)
        _parallel([lambda kv=kv: kv.init(0, w0)
                   for kv in topo.workers + [topo.master]])

        def train(kv):
            kv.push(0, np.full(64, 0.25, np.float32))
            out = np.zeros(64, np.float32)
            kv.pull(0, out=out)
            kv.wait()
            # 4 workers x 0.25, summed through both tiers
            np.testing.assert_allclose(out, np.full(64, 1.0), rtol=1e-5)

        _parallel([lambda kv=kv: train(kv) for kv in topo.workers])
    finally:
        topo.stop()


def test_hips_multi_server_parties():
    """Two local servers per party: big keys split across them, each server
    forwards its shard; the global server's party-weighted element counting
    must complete the round (the reference's aligned-key counting cannot)."""
    topo = Topology(servers_per_party=2, bigarray_bound=16).start(
        sync_global=True)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        # key 0: big (split across servers); key 1: small (hash-assigned)
        w = {0: np.arange(40, dtype=np.float32), 1: np.ones(8, np.float32)}

        def init_on(kv):
            for k, v in w.items():
                kv.init(k, v)

        _parallel([lambda kv=kv: init_on(kv)
                   for kv in topo.workers + [topo.master]])

        def train(kv):
            for k in w:
                kv.push(k, np.ones_like(w[k]))
            outs = {k: np.zeros_like(w[k]) for k in w}
            for k in w:
                kv.pull(k, out=outs[k])
            kv.wait()
            for k in w:
                np.testing.assert_allclose(outs[k], w[k] - 4.0)

        _parallel([lambda kv=kv: train(kv) for kv in topo.workers])
    finally:
        topo.stop()


def test_single_tier_classic_ps():
    """No global tier: a classic 1-scheduler/1-server/2-worker PS where the
    local server applies the optimizer (stock-MXNet dist behavior)."""
    port = free_port()
    threads = []
    errors = []

    def run(fn):
        def w():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
        t = threading.Thread(target=w, daemon=True)
        t.start()
        threads.append(t)

    sched_po = Postoffice(my_role=Role.SCHEDULER, is_global=False,
                          root_uri="127.0.0.1", root_port=port,
                          num_workers=2, num_servers=1, cfg=Config())

    def sched():
        sched_po.start(60)
        sched_po.barrier(psbase.ALL_GROUP, timeout=60)
        sched_po.barrier(psbase.ALL_GROUP, timeout=120)
        sched_po.van.stop()

    run(sched)
    scfg = Config(role="server", ps_root_uri="127.0.0.1", ps_root_port=port,
                  num_workers=2, num_servers=1)
    srv = KVStoreDistServer(scfg)
    run(srv.run)
    boxes = [[], []]
    for i in range(2):
        wcfg = Config(role="worker", ps_root_uri="127.0.0.1",
                      ps_root_port=port, num_workers=2, num_servers=1)
        run(lambda b=boxes[i], c=wcfg: b.append(KVStoreDist(cfg=c)))
    for _ in range(300):
        if errors:
            raise errors[0]
        if all(len(b) == 1 for b in boxes):
            break
        threading.Event().wait(0.1)
    kvs = [b[0] for b in boxes]
    try:
        rank0 = next(kv for kv in kvs if kv.rank == 0)
        rank0.set_optimizer(SGD(learning_rate=0.5))
        w0 = np.ones(10, np.float32)
        _parallel([lambda kv=kv: kv.init(3, w0) for kv in kvs])

        def train(kv):
            kv.push(3, np.ones(10, np.float32))
            out = np.zeros(10, np.float32)
            kv.pull(3, out=out)
            kv.wait()
            np.testing.assert_allclose(out, np.zeros(10))  # 1 - 0.5*2

        _parallel([lambda kv=kv: train(kv) for kv in kvs])
    finally:
        _parallel([kv.close for kv in kvs])
        for t in threads:
            t.join(30)
        if errors:
            raise errors[0]


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
