"""KVStoreDeviceAllreduce — the KVStoreNCCL equivalent (reference:
src/kvstore/kvstore_nccl.h:62), on the 8-device virtual CPU mesh."""

import numpy as np
import pytest

import geomx_tpu as gx
from geomx_tpu.optimizer import SGD


def test_nccl_store_allreduce_and_update():
    import jax

    kv = gx.kv.create("nccl")
    assert kv.type == "nccl"
    assert kv.num_devices == len(jax.local_devices())
    n = kv.num_devices

    kv.set_optimizer(SGD(learning_rate=1.0))
    w0 = np.arange(12, dtype=np.float32).reshape(3, 4)
    kv.init(0, w0)

    # one gradient per device; allreduce = sum -> SGD applies the sum
    grads = [np.full((3, 4), 0.5, np.float32) for _ in range(n)]
    kv.push(0, grads)
    np.testing.assert_allclose(kv.pull(0), w0 - 0.5 * n)

    # device-resident pull keeps it on device
    dev_val = kv.pull_device(0)
    assert hasattr(dev_val, "sharding")
    np.testing.assert_allclose(np.asarray(dev_val), w0 - 0.5 * n)


def test_nccl_store_single_array_push_and_out():
    kv = gx.kv.create("nccl")
    kv.init(1, np.zeros(8, np.float32))
    kv.push(1, np.ones(8, np.float32))    # already-reduced push
    out = np.zeros(8, np.float32)
    kv.pull(1, out=out)
    np.testing.assert_allclose(out, np.ones(8))   # no updater: overwrite


def test_nccl_store_wrong_device_count_rejected():
    kv = gx.kv.create("nccl")
    kv.init(2, np.zeros(4, np.float32))
    with pytest.raises(AssertionError, match="per-device"):
        kv.push(2, [np.ones(4, np.float32)] * (kv.num_devices + 1))




def test_nccl_store_single_key_list_push_reduces_all_devices():
    """Regression (review repro): push([k], per_device_list) must
    allreduce all devices' gradients, not silently use the first."""
    kv = gx.kv.create("nccl")
    n = kv.num_devices
    kv.init(3, np.zeros(4, np.float32))
    kv.push([3], [np.ones(4, np.float32)] * n)
    np.testing.assert_allclose(kv.pull(3), np.full(4, float(n)))


def test_nccl_store_init_length_mismatch_rejected():
    kv = gx.kv.create("nccl")
    with pytest.raises(AssertionError):
        kv.init([5, 6], np.zeros(4, np.float32))


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
