"""tools/trace_merge: clock alignment on synthetic skew + the
acceptance scenario — one 2-party HiPS round visible end-to-end in the
merged trace.
"""

import json

import numpy as np
import pytest

from geomx_tpu import profiler
from geomx_tpu.optimizer import SGD
from geomx_tpu.simulate import InProcessHiPS
from tools import trace_merge

from tests.test_hips import _parallel


@pytest.fixture(autouse=True)
def _clean_profiler():
    profiler.reset()
    yield
    profiler.reset()


# ---------------------------------------------------------------------------
# synthetic clock alignment
# ---------------------------------------------------------------------------

def _span(name, node, ts, dur, *, ovl="127.0.0.1:5000:l", frm, to, mts,
          req, **extra):
    return {"name": name, "cat": "transport", "ph": "X", "ts": ts,
            "dur": dur, "pid": 1, "tid": 1,
            "args": {"node": node, "ovl": ovl, "from": frm, "to": to,
                     "mts": mts, "req": req, **extra}}


def _skewed_pair(skew_us=50_000.0, lat_us=100.0):
    """Node A at true time; node B's clock runs ``skew_us`` ahead. Two
    request/response exchanges cross the link, each leg taking
    ``lat_us`` of flight time. All send spans have dur=10 (the wire time
    is the span END)."""
    a_evs, b_evs = [], []
    for i, t0 in enumerate((1000.0, 5000.0)):
        mts = 100 + i
        # A sends a request at t0 (10us of pack time), B receives it
        # lat_us after the send completes — on B's clock, +skew
        a_evs.append(_span("van.send", "A", t0, 10,
                           frm=9, to=8, mts=mts, req=True))
        b_evs.append(_span("van.recv", "B", t0 + 10 + lat_us + skew_us, 5,
                           frm=9, to=8, mts=mts, req=True))
        # B responds 50us later; A receives lat_us after that
        bt = t0 + 10 + lat_us + skew_us + 50
        b_evs.append(_span("van.send", "B", bt, 10,
                           frm=8, to=9, mts=mts, req=False))
        a_evs.append(_span("van.recv", "A", bt + 10 + lat_us - skew_us, 5,
                           frm=8, to=9, mts=mts, req=False))
    return {"A": a_evs, "B": b_evs}


def test_solve_offsets_recovers_synthetic_skew():
    nodes = _skewed_pair(skew_us=50_000.0, lat_us=100.0)
    offsets, matched = trace_merge.solve_offsets(nodes, reference="A")
    assert matched == 4
    assert offsets["A"] == 0.0
    # symmetric latency cancels exactly: the offset IS the skew
    assert offsets["B"] == pytest.approx(50_000.0)


def test_merge_reorders_recv_after_send():
    nodes = _skewed_pair(skew_us=50_000.0, lat_us=100.0)
    doc = trace_merge.merge(nodes, reference="A")
    assert doc["metadata"]["clock_offsets_us"]["B"] == pytest.approx(50_000)
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    by = {}
    for e in evs:
        by.setdefault((e["args"]["mts"], e["args"]["req"]), {})[
            e["name"]] = e
    # after alignment every recv lands after its send's wire end, by
    # exactly the synthetic one-way latency
    for pair in by.values():
        send, recv = pair["van.send"], pair["van.recv"]
        flight = recv["ts"] - (send["ts"] + send["dur"])
        assert flight == pytest.approx(100.0)
    # per-node pids + process_name metadata rows for Perfetto
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("name") == "process_name"}
    assert names == {"A", "B"}
    pids = {e["pid"] for e in evs}
    assert len(pids) == 2


def test_one_directional_link_keeps_node_on_timeline():
    nodes = _skewed_pair()
    # drop B's responses: only A->B frames remain
    nodes["B"] = [e for e in nodes["B"] if e["name"] == "van.recv"]
    nodes["A"] = [e for e in nodes["A"] if e["name"] == "van.send"]
    offsets, matched = trace_merge.solve_offsets(nodes, reference="A")
    assert matched == 2
    # zero-latency assumption: the whole observed delta becomes offset
    assert offsets["B"] == pytest.approx(50_000.0 + 100.0 + 0, abs=20)


def test_unlinked_node_defaults_to_zero_offset():
    nodes = _skewed_pair()
    nodes["C"] = [{"name": "other", "ph": "X", "ts": 1.0, "dur": 1.0,
                   "args": {"node": "C"}}]
    offsets, _ = trace_merge.solve_offsets(nodes, reference="A")
    assert offsets["C"] == 0.0


def test_disconnected_node_warns_and_still_merges(capsys):
    """A node with no matched send/recv pair to the reference (e.g. it
    crashed before answering anything) must not fail the merge: it is
    kept at offset 0, warned about on stderr, and flagged in the
    metadata for downstream consumers."""
    nodes = _skewed_pair()
    # C talks only to itself: wire pairs exist but never cross to A/B
    nodes["C"] = [
        _span("van.send", "C", 100.0, 10, frm=5, to=5, mts=900, req=True),
        _span("van.recv", "C", 200.0, 5, frm=5, to=5, mts=900, req=True),
    ]
    doc = trace_merge.merge(nodes, reference="A")
    err = capsys.readouterr().err
    assert "node C" in err and "offset 0" in err
    assert doc["metadata"]["unanchored_nodes"] == ["C"]
    assert doc["metadata"]["clock_offsets_us"]["C"] == 0.0
    # C's events made it into the merged trace on their own pid
    c_pids = {e["pid"] for e in doc["traceEvents"]
              if (e.get("args") or {}).get("node") == "C"}
    assert len(c_pids) == 1
    # the connected pair still aligns normally, and nothing else is
    # flagged
    assert doc["metadata"]["clock_offsets_us"]["B"] == pytest.approx(50_000)


def test_load_nodes_splits_by_node_arg(tmp_path):
    merged = tmp_path / "all.json"
    merged.write_text(json.dumps({"traceEvents": [
        _span("van.send", "A", 1, 1, frm=1, to=2, mts=1, req=True),
        _span("van.recv", "B", 2, 1, frm=1, to=2, mts=1, req=True),
        {"name": "anon", "ph": "X", "ts": 0, "dur": 1},
    ]}))
    nodes = trace_merge.load_nodes([str(merged)])
    # tagged events split by node; untagged fall to the file's name
    assert set(nodes) == {"A", "B", "all"}


def test_rounds_spanning_reads_round_args():
    doc = {"traceEvents": [
        _span("van.send", "A", 1, 1, frm=1, to=2, mts=1, req=True,
              round=3),
        _span("van.recv", "B", 2, 1, frm=1, to=2, mts=1, req=True,
              round=3),
        _span("van.send", "B", 9, 1, frm=2, to=1, mts=2, req=True),
    ]}
    assert trace_merge.rounds_spanning(doc) == {3: {"A", "B"}}


# ---------------------------------------------------------------------------
# acceptance: a 2-party round merges into one trace, visible end-to-end
# ---------------------------------------------------------------------------

def test_two_party_round_traces_end_to_end(tmp_path):
    """Run one traced push_pull round on a 2-party HiPS sim, split the
    profiler dump per node, merge with trace_merge, and assert one
    round id shows up on worker, local-server and global-tier nodes —
    the PR's core acceptance criterion."""
    profiler.set_state("run")
    sim = InProcessHiPS(num_parties=2, workers_per_party=1).start(
        sync_global=True)
    try:
        sim.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.zeros(64, np.float32)

        def init_on(kv):
            kv.init(0, w0)
            kv.wait()

        _parallel([lambda kv=kv: init_on(kv)
                   for kv in sim.workers + [sim.master]])

        def step(kv):
            kv.push_pull(0, np.ones(64, np.float32),
                         np.zeros(64, np.float32))
            kv.wait()

        _parallel([lambda kv=kv: step(kv) for kv in sim.workers])
    finally:
        sim.stop()
    profiler.set_state("stop")
    all_path = tmp_path / "all.json"
    profiler.dump(filename=str(all_path))

    # split the in-process dump into per-node files (a real deployment's
    # shape) and merge them back through the CLI entry point
    nodes = trace_merge.load_nodes([str(all_path)])
    van_nodes = {n: evs for n, evs in nodes.items()
                 if any(e.get("name") in ("van.send", "van.recv")
                        for e in evs)}
    assert len(van_nodes) >= 5, f"expected a full topology, got {van_nodes.keys()}"
    paths = []
    for node, evs in van_nodes.items():
        p = tmp_path / f"{node}.json"
        p.write_text(json.dumps({"traceEvents": evs}))
        paths.append(str(p))
    out = tmp_path / "merged.json"
    assert trace_merge.main([*paths, "-o", str(out)]) == 0

    doc = json.loads(out.read_text())
    assert doc["metadata"]["matched_wire_pairs"] > 0
    spans = trace_merge.rounds_spanning(doc)
    assert spans, "no round ids in the merged trace"
    best = max(spans.values(), key=len)
    # end-to-end: both parties' worker and server nodes plus the global
    # tier carry the same round id
    assert len(best) >= 5
    assert any(n.startswith("g") for n in best), f"no global node in {best}"
    assert any(n.startswith("l") for n in best), f"no local node in {best}"


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
