"""Pytest gate for the statemodel pass (tools/analyze/statemodel.py,
GX-S501..S504) and its executable model.

Jobs:

1. Prove every rule fires against the seeded fixtures in
   tests/fixtures_analyze/stateproj/bad/ (and that the clean
   counterparts stay clean).
2. Lock workflow round-trip: missing lock -> freeze -> clean -> drift.
3. Gate the real tree — the committed state.lock.json must match the
   transition signatures extracted from the live sources, and a
   deliberate epoch-handling edit to the real van.py must fail GX-S503.
4. Model-unit checks: the MemberView/SchedulerView transitions the
   explorer and the runtime conformance sanitizer both rely on.
"""

import shutil
from pathlib import Path

import pytest

from tools.analyze import load_sources
from tools.analyze.statemodel import (MemberView, SchedulerView,
                                      extract_state_model,
                                      run_statemodel,
                                      state_model_fingerprint,
                                      statemodel_lock_path,
                                      write_state_model)

REPO = Path(__file__).resolve().parents[1]
FIXTURES = Path(__file__).resolve().parent / "fixtures_analyze" / "stateproj"


def _findings(tree: Path, root=None):
    sources = load_sources([tree], tree)
    return run_statemodel(sources, root if root is not None else tree)


def _details(findings, rule):
    return {f.detail for f in findings if f.rule == rule}


# ---------------------------------------------------------------------------
# seeded violations fire
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def bad(tmp_path_factory):
    # freeze a lock for the bad tree so S501 noise doesn't mix into the
    # S502/S503/S504 assertions
    root = tmp_path_factory.mktemp("badroot")
    shutil.copytree(FIXTURES / "bad", root / "src")
    sources = load_sources([root / "src"], root / "src")
    write_state_model(sources, root)
    return run_statemodel(sources, root)


def test_lost_broadcast_fires_s503(bad):
    assert "declare_dead:missing-call:_broadcast_membership" \
        in _details(bad, "GX-S503")


def test_lost_rejoin_fence_read_fires_s503(bad):
    assert "stale_fence:missing-read:_rejoin_epoch" \
        in _details(bad, "GX-S503")


def test_membership_hook_losing_recheck_fires_s503(bad):
    d = _details(bad, "GX-S503")
    assert "membership_release:missing-call:_expected_local_pushes" in d
    assert "membership_release:missing-call:_complete_local_round" in d


def test_lost_epoch_guard_fires_s504(bad):
    assert "adopt_broadcast:epoch-guard" in _details(bad, "GX-S504")


def test_lost_stale_push_fence_fires_s504(bad):
    assert "stale_push_drop:is_stale" in _details(bad, "GX-S504")


def test_static_countdown_fires_s504(bad):
    assert "local_countdown:live-view" in _details(bad, "GX-S504")


def test_out_of_transition_mutation_fires_s502(bad):
    hits = [f for f in bad if f.rule == "GX-S502"]
    assert {h.symbol for h in hits} == {"Van.reset_membership"}
    assert {h.detail for h in hits} == {"_declared_dead",
                                        "membership_epoch"}


def test_clean_fixtures_stay_clean(tmp_path):
    shutil.copytree(FIXTURES / "clean", tmp_path / "src")
    sources = load_sources([tmp_path / "src"], tmp_path / "src")
    write_state_model(sources, tmp_path)
    assert run_statemodel(sources, tmp_path) == []


# ---------------------------------------------------------------------------
# lock workflow round-trip (GX-S501)
# ---------------------------------------------------------------------------

def test_lock_round_trip(tmp_path):
    shutil.copytree(FIXTURES / "clean", tmp_path / "src")
    sources = load_sources([tmp_path / "src"], tmp_path / "src")

    # 1. no lock: S501 lock-missing
    out = run_statemodel(sources, tmp_path)
    assert _details(out, "GX-S501") == {"lock-missing"}

    # 2. freeze: clean
    lock = write_state_model(sources, tmp_path)
    assert lock == statemodel_lock_path(tmp_path)
    assert run_statemodel(sources, tmp_path) == []

    # 3. drift: change a transition's protocol surface (drop the
    #    broadcast from declare_dead) -> S501 model-changed
    van = tmp_path / "src" / "ps" / "van.py"
    text = van.read_text()
    assert "self._broadcast_membership(epoch, dead)" in text
    van.write_text(text.replace(
        "self._broadcast_membership(epoch, dead)", "pass", 1))
    sources = load_sources([tmp_path / "src"], tmp_path / "src")
    out = run_statemodel(sources, tmp_path)
    assert "model-changed" in _details(out, "GX-S501")


# ---------------------------------------------------------------------------
# real-tree gate
# ---------------------------------------------------------------------------

def test_committed_state_lock_matches_tree():
    """The committed lock must equal what the live sources extract —
    i.e. `python -m tools.analyze --update-state-model` was run after
    the last membership-protocol change."""
    import json

    sources = load_sources([REPO / "geomx_tpu"], REPO)
    model = extract_state_model(sources)
    assert model, "no modeled transitions extracted from geomx_tpu/"
    doc = json.loads(statemodel_lock_path(REPO).read_text())
    frozen = doc["files"]
    assert sorted(frozen) == sorted(model)
    for rel, entry in model.items():
        assert frozen[rel]["fingerprint"] == state_model_fingerprint(
            entry), f"state.lock.json stale for {rel}"


def test_real_tree_is_clean():
    sources = load_sources([REPO / "geomx_tpu"], REPO)
    assert run_statemodel(sources, REPO) == []


def test_deliberate_epoch_edit_fails_gate(tmp_path):
    """Strip the epoch bump from the REAL declare_dead: the gate must
    fail with GX-S503 (the code no longer realizes the modeled
    transition)."""
    dst = tmp_path / "src" / "ps"
    dst.mkdir(parents=True)
    text = (REPO / "geomx_tpu" / "ps" / "van.py").read_text()
    needle = "self.membership_epoch += 1\n            epoch = self.membership_epoch"
    assert needle in text, "declare_dead epoch bump moved — update test"
    (dst / "van.py").write_text(text.replace(
        needle, "epoch = self.membership_epoch", 1))
    sources = load_sources([tmp_path / "src"], tmp_path / "src")
    out = run_statemodel(sources, tmp_path)
    assert "declare_dead:missing-write:membership_epoch" \
        in _details(out, "GX-S503")


# ---------------------------------------------------------------------------
# executable model units (shared by modelcheck + conformance)
# ---------------------------------------------------------------------------

def test_member_adopt_broadcast_outcomes():
    v = MemberView()
    assert v.adopt_broadcast(1, {11}) == "adopt"
    assert (v.epoch, v.dead) == (1, {11})
    assert v.adopt_broadcast(1, {11}) == "duplicate"
    assert v.adopt_broadcast(0, set()) == "stale"
    # revival via broadcast arms the rejoin fence at the new epoch
    assert v.adopt_broadcast(2, set()) == "adopt"
    assert v.rejoin == {11: 2}
    assert v.is_stale(11, 1) and not v.is_stale(11, 2)


def test_member_adopt_table_reports_change():
    v = MemberView()
    assert v.adopt_table(0, []) is False         # initial table: no-op
    v.adopt_broadcast(1, {11})
    assert v.adopt_table(2, [11]) is True        # revival via table
    assert v.dead == set() and v.rejoin == {11: 2}
    assert v.adopt_table(2, []) is False         # idempotent re-delivery


def test_scheduler_declare_and_revive():
    s = SchedulerView()
    assert s.declare_dead([11, 12]) == (1, frozenset({11, 12}))
    assert s.declare_dead([11]) is None          # already dead: no bump
    assert s.revive(11) == 2
    assert s.rejoin == {11: 2} and s.dead == {12}
    assert s.is_stale(11, 1) and not s.is_stale(11, 2)
    assert s.is_stale(12, 2)                     # still dead
