"""FaultPlan parsing + deterministic injection primitives.

Each primitive (drop / dup / delay / reorder / partition / crash) is
exercised against a stub van with a scripted message stream, twice, and
the two injectors' ``decision_log`` audit trails must match exactly:
same seed + same plan + same traffic => the identical schedule. That is
the contract the chaos matrix (scripts/run_chaos_matrix.sh) and the
crash-resume acceptance test lean on.
"""

import json
import threading
import time
import types

import pytest

from geomx_tpu import config as cfg_mod
from geomx_tpu.config import Config
from geomx_tpu.ps import faults
from geomx_tpu.ps.faults import FaultPlan, FaultRule

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------------------
# parsing / validation


def test_rule_rejects_unknown_type():
    with pytest.raises(ValueError, match="type must be one of"):
        FaultRule.from_dict({"type": "scramble"})


def test_rule_rejects_unknown_fields():
    with pytest.raises(ValueError, match="unknown fault rule fields"):
        FaultRule.from_dict({"type": "drop", "probability": 0.5})


def test_partition_requires_between_pair():
    with pytest.raises(ValueError, match="between"):
        FaultRule.from_dict({"type": "partition"})
    with pytest.raises(ValueError, match="between"):
        FaultRule.from_dict({"type": "partition", "between": [9]})


def test_reorder_requires_window():
    with pytest.raises(ValueError, match="window >= 2"):
        FaultRule.from_dict({"type": "reorder", "window": 1})


def test_crash_requires_valid_side():
    with pytest.raises(ValueError, match="'recv' or 'send'"):
        FaultRule.from_dict({"type": "crash", "at": 1, "on": "wire"})


def test_parse_dict_with_embedded_seed():
    plan = FaultPlan.parse(
        '{"seed": 42, "rules": [{"type": "drop", "p": 0.5}]}', seed=7)
    assert plan.seed == 42            # embedded seed wins over PS_SEED
    assert len(plan.rules) == 1
    assert plan.rules[0].kind == "drop"


def test_parse_bare_list():
    plan = FaultPlan.parse('[{"type": "dup", "p": 0.1}]', seed=7)
    assert plan.seed == 7
    assert plan.rules[0].kind == "dup"


def test_parse_at_file(tmp_path):
    p = tmp_path / "plan.json"
    p.write_text(json.dumps(
        {"seed": 3, "rules": [{"type": "delay", "delay_s": 0.5}]}))
    plan = FaultPlan.parse("@" + str(p))
    assert plan.seed == 3
    assert plan.rules[0].delay_s == 0.5


def test_plan_from_config_seed_precedence():
    # no plan -> None
    assert faults.plan_from_config(Config()) is None
    # PS_SEED flows into a seedless plan
    plan = faults.plan_from_config(
        Config(fault_plan='[{"type": "drop", "p": 0.3}]', ps_seed=11))
    assert plan.seed == 11
    # unseeded everywhere -> None seed (wall-clock entropy)
    plan = faults.plan_from_config(
        Config(fault_plan='[{"type": "drop", "p": 0.3}]'))
    assert plan.seed is None


def test_env_round_trip(monkeypatch):
    monkeypatch.setenv("PS_FAULT_PLAN",
                       '[{"type": "drop", "p": 0.25, "dst": 9}]')
    monkeypatch.setenv("PS_SEED", "5")
    cfg = cfg_mod.load()
    plan = faults.plan_from_config(cfg)
    assert plan.seed == 5
    assert plan.rules[0].p == 0.25
    assert plan.rules[0].dst == 9


def test_van_seed_stable_and_distinct():
    cfg = Config(ps_seed=7)
    a = faults.van_seed(cfg, my_role=1, is_global=False)
    assert a == faults.van_seed(cfg, my_role=1, is_global=False)
    assert a != faults.van_seed(cfg, my_role=2, is_global=False)
    assert a != faults.van_seed(cfg, my_role=1, is_global=True)
    assert faults.van_seed(Config(), my_role=1, is_global=False) is None


# ---------------------------------------------------------------------------
# injection primitives against a stub van


class StubVan:
    """Just enough van surface for FaultInjector: identity, a stopped
    event, and a _process sink recording re-injected frames."""

    def __init__(self, my_id=9, is_global=False):
        self.my_id = my_id
        self.is_global = is_global
        self.stopped = threading.Event()
        self.delivered = []
        self.crashed = []

    def _process(self, msg):
        self.delivered.append(msg)

    def _crash_from_fault(self, reason):
        self.crashed.append(reason)
        self.stopped.set()


def msg(sender=8, control=False, tag=None):
    m = types.SimpleNamespace()
    m.meta = types.SimpleNamespace(sender=sender)
    m.is_control = control
    m.tag = tag
    return m


def run_stream(plan_json, n=40, seed=123, sender=8, my_id=9):
    """Feed n identical frames through a fresh injector; return
    (injector, [on_inbound results], van)."""
    plan = FaultPlan.parse(plan_json, seed=seed)
    van = StubVan(my_id=my_id)
    inj = plan.bind(van)
    inj.arm()
    results = [inj.on_inbound(msg(sender=sender, tag=i)) for i in range(n)]
    return inj, results, van


def test_drop_deterministic_and_partial():
    plan = '[{"type": "drop", "p": 0.5}]'
    inj1, res1, _ = run_stream(plan)
    inj2, res2, _ = run_stream(plan)
    assert res1 == res2
    assert inj1.decision_log == inj2.decision_log
    assert True in res1 and False in res1   # p=0.5 actually drops some
    # a different seed gives a different schedule
    _, res3, _ = run_stream(plan, seed=124)
    assert res1 != res3


def test_drop_spares_control_frames_by_default():
    plan = FaultPlan.parse('[{"type": "drop", "p": 1.0}]', seed=1)
    van = StubVan()
    inj = plan.bind(van)
    assert inj.on_inbound(msg(control=True)) is True
    assert inj.on_inbound(msg(control=False)) is False
    # opt-in faults the control plane too
    plan = FaultPlan.parse('[{"type": "drop", "p": 1.0, "control": true}]',
                           seed=1)
    inj = plan.bind(StubVan())
    assert inj.on_inbound(msg(control=True)) is False


def test_drop_scoping_by_src_dst():
    plan = FaultPlan.parse('[{"type": "drop", "p": 1.0, "src": 8, '
                           '"dst": [9, 11]}]', seed=1)
    inj = plan.bind(StubVan(my_id=9))
    assert inj.on_inbound(msg(sender=8)) is False    # matches
    assert inj.on_inbound(msg(sender=10)) is True    # wrong src
    inj = plan.bind(StubVan(my_id=13))
    assert inj.on_inbound(msg(sender=8)) is True     # wrong dst


def test_dup_redelivers_through_dispatch():
    plan = '[{"type": "dup", "p": 0.5}]'
    inj1, res1, van1 = run_stream(plan)
    inj2, res2, van2 = run_stream(plan)
    assert inj1.decision_log == inj2.decision_log
    assert all(res1)                   # dup never withholds the original
    n_dup = sum(1 for e in inj1.decision_log if e[5] == "dup")
    assert n_dup > 0
    deadline = time.monotonic() + 5
    while len(van1.delivered) < n_dup and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(van1.delivered) == n_dup   # each dup re-injected once


def test_delay_holds_then_redelivers():
    plan = '[{"type": "delay", "delay_s": 0.05, "jitter_s": 0.02}]'
    inj1, res1, van1 = run_stream(plan, n=10)
    inj2, res2, van2 = run_stream(plan, n=10)
    assert inj1.decision_log == inj2.decision_log   # incl. delay values
    assert not any(res1)               # all held for later delivery
    deadline = time.monotonic() + 5
    while len(van1.delivered) < 10 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert [m.tag for m in sorted(van1.delivered, key=lambda m: m.tag)] \
        == list(range(10))             # nothing lost


def test_reorder_flushes_permuted_window():
    plan = '[{"type": "reorder", "window": 4}]'
    inj1, res1, van1 = run_stream(plan, n=8)
    inj2, res2, van2 = run_stream(plan, n=8)
    assert inj1.decision_log == inj2.decision_log
    assert not any(res1)               # held or flushed via _process
    # two full windows flushed synchronously, all 8 frames delivered
    assert sorted(m.tag for m in van1.delivered) == list(range(8))
    assert [m.tag for m in van1.delivered] == \
        [m.tag for m in van2.delivered]
    # at least one window actually permuted (seed chosen accordingly)
    assert [m.tag for m in van1.delivered] != list(range(8))


def test_partition_window_is_time_scoped():
    plan = FaultPlan.parse(
        '[{"type": "partition", "between": [8, 9], "start_s": 0.0, '
        '"duration_s": 0.2}]', seed=1)
    van = StubVan(my_id=9)
    inj = plan.bind(van)
    inj.arm()
    assert inj.on_inbound(msg(sender=8)) is False   # inside the window
    assert inj.on_inbound(msg(sender=10)) is True   # unrelated link
    time.sleep(0.25)
    assert inj.on_inbound(msg(sender=8)) is True    # window closed


def test_crash_on_nth_recv():
    plan = FaultPlan.parse(
        '[{"type": "crash", "node": 9, "at": 3, "on": "recv"}]', seed=1)
    van = StubVan(my_id=9)
    inj = plan.bind(van)
    assert inj.on_inbound(msg()) is True
    assert inj.on_inbound(msg()) is True
    assert inj.on_inbound(msg()) is False           # third frame kills it
    assert van.stopped.wait(5)
    assert van.crashed and "crash rule #0" in van.crashed[0]
    assert inj.on_inbound(msg()) is False           # dead vans stay dead


def test_crash_on_send_side():
    plan = FaultPlan.parse(
        '[{"type": "crash", "node": 9, "at": 2, "on": "send"}]', seed=1)
    van = StubVan(my_id=9)
    inj = plan.bind(van)
    assert inj.on_send(10, msg(sender=9)) is True
    assert inj.on_send(10, msg(sender=9, control=True)) is True  # exempt
    assert inj.on_send(10, msg(sender=9)) is False
    assert van.stopped.wait(5)


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
