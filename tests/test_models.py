"""Model zoo shapes + trainability (reference: gluon model_zoo/vision)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from geomx_tpu.models import MLP, create_cnn, create_resnet
from geomx_tpu.models.transformer import Transformer

pytestmark = pytest.mark.slow  # compile-heavy: nightly tier


def test_cnn_shapes():
    m = create_cnn()
    p = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    out = m.apply(p, jnp.zeros((4, 28, 28, 1)))
    assert out.shape == (4, 10) and out.dtype == jnp.float32


@pytest.mark.parametrize("name,params_m", [("resnet18", 11.2),
                                           ("resnet50", 23.5)])
def test_resnet_shapes_and_param_counts(name, params_m):
    m = create_resnet(name, num_classes=10)
    vars_ = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    n = sum(x.size for x in jax.tree_util.tree_leaves(vars_["params"]))
    # within 10% of the canonical ImageNet-head counts (small head here)
    assert abs(n / 1e6 - params_m) / params_m < 0.1, n
    out = m.apply(vars_, jnp.zeros((2, 32, 32, 3)))
    assert out.shape == (2, 10)


def test_resnet_trains_one_step():
    m = create_resnet("resnet18")
    vars_ = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    x = jax.random.uniform(jax.random.PRNGKey(1), (8, 32, 32, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 10)

    def loss_fn(params):
        logits, updates = m.apply(
            {"params": params, "batch_stats": vars_["batch_stats"]},
            x, train=True, mutable=["batch_stats"])
        oh = jax.nn.one_hot(y, 10)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * oh, -1))

    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(vars_["params"])
    assert np.isfinite(float(loss))
    gnorm = sum(float(jnp.sum(g ** 2))
                for g in jax.tree_util.tree_leaves(grads))
    assert gnorm > 0


def test_mlp_and_transformer_smoke():
    mlp = MLP(features=(32, 10))
    p = mlp.init(jax.random.PRNGKey(0), jnp.zeros((1, 16)))
    assert mlp.apply(p, jnp.zeros((3, 16))).shape == (3, 10)

    tr = Transformer(vocab=50, dim=32, depth=1, heads=2, max_len=16)
    toks = jnp.zeros((2, 16), jnp.int32)
    p = tr.init(jax.random.PRNGKey(0), toks)
    assert tr.apply(p, toks).shape == (2, 16, 50)


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))


def test_build_flat_step_matches_per_leaf():
    """build_flat_step fuses transfers without changing the math."""
    import numpy as np
    import jax.numpy as jnp
    from examples.utils import build_model_and_step, build_flat_step

    leaves, _td, grad_step, _ev = build_model_and_step(4)
    flat_step, pack, unpack = build_flat_step(leaves, grad_step)
    X = jnp.asarray(np.random.RandomState(0).rand(4, 28, 28, 1), jnp.float32)
    y = jnp.asarray(np.arange(4) % 10)
    loss_ref, grads_ref = grad_step([jnp.asarray(l) for l in leaves], X, y)
    loss_flat, gflat = flat_step(jnp.asarray(pack(leaves)), X, y)
    assert abs(float(loss_ref) - float(loss_flat)) < 1e-6
    for a, b in zip(unpack(np.asarray(gflat)), grads_ref):
        np.testing.assert_allclose(a, np.asarray(b), atol=1e-6)
    # pack/unpack round-trip preserves every leaf exactly
    for a, b in zip(unpack(pack(leaves)), leaves):
        np.testing.assert_array_equal(a, b)


def test_rnn_family_shapes_and_learning():
    """LSTM/GRU/RNN language models: shapes, and the LSTM learns a
    next-token copy task (recurrence actually carries state)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from geomx_tpu.models import get_model

    tok = jnp.asarray(np.arange(24).reshape(2, 12) % 16, jnp.int32)
    for kind in ("lstm_lm", "gru_lm", "rnn_lm"):
        for dt in (jnp.float32, jnp.bfloat16):
            m = get_model(kind, num_classes=16, hidden=32,
                          compute_dtype=dt)
            p = m.init(jax.random.PRNGKey(0), tok)
            out = m.apply(p, tok)
            assert out.shape == (2, 12, 16) and out.dtype == jnp.float32

    model = get_model("lstm_lm", num_classes=16, hidden=64)
    params = model.init(jax.random.PRNGKey(1), tok)
    opt = optax.adam(1e-2)
    st = opt.init(params)

    def loss_fn(p):
        logits = model.apply(p, tok[:, :-1])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tok[:, 1:]).mean()

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(loss_fn)(p)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, l

    losses = []
    for _ in range(60):
        params, st, l = step(params, st)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])


def test_build_model_and_step_zoo_models():
    """The example harness trains any vision-zoo name (BN and
    dropout-only nets both): one grad step + eval runs and params
    update."""
    import jax.numpy as jnp
    import numpy as np
    from examples.utils import build_model_and_step

    X = jnp.asarray(np.random.RandomState(0).rand(4, 32, 32, 3),
                    jnp.float32)
    y = jnp.asarray(np.arange(4) % 10)
    for name in ("mobilenet0.25", "vgg11"):
        leaves, _td, grad_step, eval_step = build_model_and_step(
            4, input_shape=(32, 32, 3), model=name)
        loss, grads = grad_step([jnp.asarray(l) for l in leaves], X, y)
        assert np.isfinite(float(loss))
        assert any(float(jnp.max(jnp.abs(g))) > 0 for g in grads)
        acc = eval_step([jnp.asarray(l) for l in leaves], X, y)
        assert 0.0 <= float(acc) <= 1.0
