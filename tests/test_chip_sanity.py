"""tools/chip_sanity.py probes on the CPU backend: correctness verdict
must hold and the CPU clock must be honest (these same probes diagnosed
the round-4 chip failures — denormal-flushed indices, dishonest
block_until_ready)."""

import pytest


@pytest.mark.slow
def test_chip_sanity_green_on_cpu():
    from tools.chip_sanity import run_chip_sanity

    out = run_chip_sanity(rounds=10)
    assert out["transfer_bitexact"]["ok"], out
    assert out["bitcast_in_jit"]["ok"], out
    assert out["bsc_oracle"]["ok"], out
    assert out["bsc_oracle"]["max_param_drift"] < 1e-3
    assert out["ok"] is True
    # CPU backends block honestly; the fence-required flag must be off
    assert out["timing_fence_required"] is False, out["blocking_honest"]
