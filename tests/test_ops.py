"""Device compression kernels (geomx_tpu.ops) vs host numpy kernels.

Property tests: the device kernels must satisfy the same contracts as
geomx_tpu.compression's numpy implementations (which the HiPS protocol
tests already pin end-to-end), and where the device version is EXACT
top-k (vs the reference's sampled boundary) we assert exactness
directly."""

import numpy as np
import pytest

from geomx_tpu import compression as host
from geomx_tpu import ops


def test_bsc_compress_exact_topk_and_state():
    rng = np.random.default_rng(0)
    n, thr = 4096, 0.05
    grad = rng.normal(size=n).astype(np.float32)
    u = rng.normal(size=n).astype(np.float32)
    v = rng.normal(size=n).astype(np.float32)

    vals, idx, u2, v2 = ops.bsc_compress(grad, u.copy(), v.copy(), thr)
    vals, idx, u2, v2 = map(np.asarray, (vals, idx, u2, v2))
    k = int(n * thr)
    assert vals.shape == (k,) and idx.shape == (k,)

    # state recurrence matches the host kernel's definition
    u_ref = host.BSC_MOMENTUM * u + grad
    v_ref = v + u_ref
    # exact top-k of |v_ref|
    expect_idx = np.argsort(-np.abs(v_ref), kind="stable")[:k]
    assert set(np.abs(v_ref)[idx].round(5)) == \
        set(np.abs(v_ref)[expect_idx].round(5))
    np.testing.assert_allclose(vals, v_ref[idx], rtol=1e-5, atol=1e-6)
    # transmitted coordinates reset, others kept
    np.testing.assert_allclose(u2[idx], 0.0)
    np.testing.assert_allclose(v2[idx], 0.0)
    mask = np.ones(n, bool)
    mask[idx] = False
    np.testing.assert_allclose(v2[mask], v_ref[mask], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(u2[mask], u_ref[mask], rtol=1e-5, atol=1e-6)


def test_bsc_device_roundtrip_matches_host_decompress():
    rng = np.random.default_rng(1)
    n = 1000
    grad = rng.normal(size=n).astype(np.float32)
    u = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    vals, idx, _, _ = ops.bsc_compress(grad, u, v, 0.1)
    dense_dev = np.asarray(ops.bsc_decompress(np.asarray(vals),
                                              np.asarray(idx), n))
    dense_host = host.bsc_decompress(np.asarray(vals), np.asarray(idx), n)
    np.testing.assert_allclose(dense_dev, dense_host)
    # first round: v = grad, so selected values are gradient entries
    np.testing.assert_allclose(dense_dev[np.asarray(idx)],
                               grad[np.asarray(idx)], rtol=1e-5, atol=1e-6)


def test_bsc_pull_compress_captures_all_nonzeros():
    arr = np.zeros(512, np.float32)
    nz = np.random.default_rng(2).choice(512, 20, replace=False)
    arr[nz] = np.random.default_rng(3).normal(size=20).astype(np.float32)
    vals, idx = ops.bsc_pull_compress(arr, 0.05, 4)  # cap=102 >= 20
    back = np.asarray(ops.bsc_decompress(np.asarray(vals),
                                         np.asarray(idx), 512))
    np.testing.assert_allclose(back, arr, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("n", [64, 1001])
@pytest.mark.parametrize("use_pallas", [False, True])
def test_two_bit_matches_host_kernel(n, use_pallas):
    rng = np.random.default_rng(4)
    grad = rng.normal(size=n).astype(np.float32)
    residual = rng.normal(scale=0.3, size=n).astype(np.float32)
    thr = 0.5

    res_host = residual.copy()
    packed_host = host.two_bit_quantize(grad, res_host, thr)
    packed_dev, res_dev = ops.two_bit_quantize(grad, residual, thr,
                                               use_pallas=use_pallas)
    np.testing.assert_array_equal(np.asarray(packed_dev), packed_host)
    np.testing.assert_allclose(np.asarray(res_dev), res_host, rtol=1e-5, atol=1e-6)

    deq_dev = np.asarray(ops.two_bit_dequantize(np.asarray(packed_dev),
                                                n, thr))
    deq_host = host.two_bit_dequantize(packed_host, n, thr)
    np.testing.assert_allclose(deq_dev, deq_host)


def test_dgt_block_contrib_ewma():
    grad = np.arange(10, dtype=np.float32) - 5.0   # |g| known
    prev = np.zeros(3, np.float32)
    out = np.asarray(ops.dgt_block_contrib(grad, prev, 4, 0.25))
    m0 = np.abs(grad[0:4]).mean()
    m1 = np.abs(grad[4:8]).mean()
    m2 = np.abs(grad[8:10]).mean()   # padded tail: mean over TRUE elems
    np.testing.assert_allclose(out, 0.75 * np.array([m0, m1, m2]),
                               rtol=1e-5, atol=1e-6)
    out2 = np.asarray(ops.dgt_block_contrib(grad, out, 4, 0.25))
    np.testing.assert_allclose(
        out2, 0.25 * out + 0.75 * np.array([m0, m1, m2]), rtol=1e-5, atol=1e-6)


def test_device_bsc_compressor_end_to_end_topology():
    """The device compressor slots into the live HiPS WAN hop."""
    from tests.test_hips import Topology, _parallel

    topo = Topology().start(sync_global=True)
    try:
        topo.master.set_gradient_compression(
            {"type": "bsc", "threshold": 1.0, "device": True})
        w0 = np.full(64, 7.0, np.float32)
        _parallel([lambda kv=kv: kv.init(0, w0)
                   for kv in topo.workers + [topo.master]])

        def train(kv):
            kv.push(0, np.full(64, 0.25, np.float32))
            out = np.zeros(64, np.float32)
            kv.pull(0, out=out)
            kv.wait()
            np.testing.assert_allclose(out, np.full(64, 1.0), rtol=1e-5)

        _parallel([lambda kv=kv: train(kv) for kv in topo.workers])
    finally:
        topo.stop()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
