"""Failure detection + elastic recovery, end-to-end.

Reference behavior (3rdparty/ps-lite/src/van.cc:176-193): when a node
re-registers and a registered node of the same role has missed its
heartbeats, the scheduler hands the dead slot's id to the newcomer with
``is_recovery=True`` and re-broadcasts the topology; recovering nodes
skip startup barriers (kvstore_dist.h:63). Server state is NOT persisted
(SURVEY.md §5.4) — resume after a server death is re-init + recovery.

These tests kill a node mid-training (hard van stop — no goodbye), wait
for heartbeat lapse, revive it, and assert id handover plus correct
values on resumed training. Single-tier PS topology (the reference's
global-tier recovery is explicitly unimplemented: van.cc:224 TODO).
"""

import threading
import time

import numpy as np
import pytest

from geomx_tpu.config import Config
from geomx_tpu.kvstore.dist import KVStoreDist
from geomx_tpu.kvstore.server import KVStoreDistServer
from geomx_tpu.optimizer import SGD
from geomx_tpu.ps import base as psbase
from geomx_tpu.ps.message import Role
from geomx_tpu.ps.postoffice import Postoffice
from geomx_tpu.simulate import free_port
from tests.test_hips import _parallel

HB = {"heartbeat_interval_s": 0.2, "heartbeat_timeout_s": 1.0}


class SingleTier:
    """scheduler + 1 server + 2 workers with fast heartbeats."""

    def __init__(self):
        self.port = free_port()
        self.threads = []
        self.errors = []
        self.sched_po = None
        self.server = None
        self.workers = []

    def _run(self, fn):
        def w():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001
                self.errors.append(e)

        t = threading.Thread(target=w, daemon=True)
        t.start()
        self.threads.append(t)

    def _cfg(self, **kw):
        base = dict(ps_root_uri="127.0.0.1", ps_root_port=self.port,
                    num_workers=2, num_servers=1, **HB)
        base.update(kw)
        return Config(**base)

    def start(self):
        self.sched_po = Postoffice(
            my_role=Role.SCHEDULER, is_global=False,
            root_uri="127.0.0.1", root_port=self.port,
            num_workers=2, num_servers=1, cfg=Config(**HB))

        def sched():
            self.sched_po.start(60)
            self.sched_po.barrier(psbase.ALL_GROUP, timeout=60)
            self.sched_po.barrier(psbase.ALL_GROUP, timeout=600)
            self.sched_po.van.stop()

        self._run(sched)
        self.server = KVStoreDistServer(self._cfg(role="server"))
        self._run(self.server.run)
        boxes = [[], []]
        for i in range(2):
            self._run(lambda b=boxes[i]: b.append(
                KVStoreDist(cfg=self._cfg(role="worker"))))
        for _ in range(300):
            if self.errors:
                raise self.errors[0]
            if all(len(b) == 1 for b in boxes):
                break
            time.sleep(0.1)
        assert all(len(b) == 1 for b in boxes), "workers failed to start"
        self.workers = [b[0] for b in boxes]
        return self


def _round(kv, key, w0, expect):
    kv.push(key, np.ones_like(w0))
    out = np.zeros_like(w0)
    kv.pull(key, out=out)
    kv.wait()
    np.testing.assert_allclose(out, expect)


def test_worker_dies_and_recovers_mid_training():
    topo = SingleTier().start()
    w0 = np.full(12, 10.0, np.float32)
    try:
        rank0 = next(kv for kv in topo.workers if kv.rank == 0)
        victim = next(kv for kv in topo.workers if kv.rank == 1)
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: kv.init(0, w0) for kv in topo.workers])

        # round 1: everyone alive
        _parallel([lambda kv=kv: _round(kv, 0, w0, w0 - 2.0)
                   for kv in topo.workers])

        # hard-kill the rank-1 worker (no goodbye, no barrier)
        dead_id = victim.po.my_id
        victim._closed = True          # disarm its atexit close
        victim.po.van.stop()

        # heartbeat lapse -> scheduler marks it dead
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if dead_id in topo.sched_po.van.dead_nodes():
                break
            time.sleep(0.1)
        assert dead_id in topo.sched_po.van.dead_nodes()

        # the survivor pushes round 2 and blocks on the missing peer
        results = []

        def survivor():
            _round(rank0, 0, w0, w0 - 4.0)
            results.append("survivor")

        t = threading.Thread(target=survivor, daemon=True)
        t.start()

        # revive: a fresh worker re-registers and takes the dead slot
        revived = KVStoreDist(cfg=topo._cfg(role="worker"))
        assert revived.po.van.is_recovery, "scheduler did not hand over slot"
        assert revived.po.my_id == dead_id
        assert revived.rank == 1
        revived.init(0, w0)            # key info only; store already live
        _round(revived, 0, w0, w0 - 4.0)
        t.join(60)
        assert results == ["survivor"], "survivor did not complete the round"

        # round 3 with the recovered pair
        _parallel([lambda kv=kv: _round(kv, 0, w0, w0 - 6.0)
                   for kv in (rank0, revived)])
        topo.workers = [rank0, revived]
    finally:
        _parallel([kv.close for kv in topo.workers])
        for t in topo.threads:
            t.join(30)
        if topo.errors:
            raise topo.errors[0]


def test_server_dies_and_recovers_mid_training():
    """Server store is volatile (reference: SURVEY §5.4): after the slot
    handover, workers re-init and re-ship the optimizer, then training
    resumes from the re-initialized weights."""
    topo = SingleTier().start()
    w0 = np.full(8, 4.0, np.float32)
    try:
        rank0 = next(kv for kv in topo.workers if kv.rank == 0)
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: kv.init(0, w0) for kv in topo.workers])
        _parallel([lambda kv=kv: _round(kv, 0, w0, w0 - 2.0)
                   for kv in topo.workers])

        dead_id = topo.server.po_local.my_id
        topo.server._stop.set()        # stop the run loop...
        topo.server.po_local.van.stop()  # ...and crash the van (no barrier)

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if dead_id in topo.sched_po.van.dead_nodes():
                break
            time.sleep(0.1)
        assert dead_id in topo.sched_po.van.dead_nodes()

        revived = KVStoreDistServer(topo._cfg(role="server"))
        rt = threading.Thread(target=revived.run, daemon=True)
        rt.start()
        for _ in range(100):
            if revived.po_local.van.ready.is_set():
                break
            time.sleep(0.1)
        assert revived.po_local.van.is_recovery
        assert revived.po_local.my_id == dead_id

        # resume: re-init (store was volatile), re-ship the optimizer
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: kv.init(0, w0) for kv in topo.workers])
        _parallel([lambda kv=kv: _round(kv, 0, w0, w0 - 2.0)
                   for kv in topo.workers])
        topo.server = revived
    finally:
        _parallel([kv.close for kv in topo.workers])
        for t in topo.threads:
            t.join(30)
        if topo.errors:
            raise topo.errors[0]


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))


def test_worker_recovery_with_batched_wire():
    """The batched list wire across a worker death/recovery: the
    surviving worker's batched round blocks on the missing peer, the
    revived worker joins the same round through batched messages, and
    values stay exact (one merged ack per server must survive the
    re-registration)."""
    topo = SingleTier().start()
    KEYS = [0, 1]
    W0 = {0: np.full(12, 10.0, np.float32),
          1: np.full(5, -3.0, np.float32)}
    try:
        rank0 = next(kv for kv in topo.workers if kv.rank == 0)
        victim = next(kv for kv in topo.workers if kv.rank == 1)
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: [kv.init(k, W0[k]) for k in KEYS]
                   for kv in topo.workers])

        def batched_round(kv, r):
            kv.push(KEYS, [np.ones_like(W0[k]) for k in KEYS])
            outs = [np.zeros_like(W0[k]) for k in KEYS]
            kv.pull(KEYS, out=outs)
            kv.wait()
            for k, o in zip(KEYS, outs):
                np.testing.assert_allclose(o, W0[k] - 2.0 * r)

        _parallel([lambda kv=kv: batched_round(kv, 1)
                   for kv in topo.workers])

        dead_id = victim.po.my_id
        victim._closed = True
        victim.po.van.stop()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if dead_id in topo.sched_po.van.dead_nodes():
                break
            time.sleep(0.1)
        assert dead_id in topo.sched_po.van.dead_nodes()

        results = []

        def survivor():
            batched_round(rank0, 2)
            results.append("survivor")

        t = threading.Thread(target=survivor, daemon=True)
        t.start()

        revived = KVStoreDist(cfg=topo._cfg(role="worker"))
        assert revived.po.van.is_recovery
        for k in KEYS:
            revived.init(k, W0[k])
        batched_round(revived, 2)
        t.join(60)
        assert results == ["survivor"], "survivor did not complete"

        _parallel([lambda kv=kv: batched_round(kv, 3)
                   for kv in (rank0, revived)])
        topo.workers = [rank0, revived]
    finally:
        _parallel([kv.close for kv in topo.workers])
        for t in topo.threads:
            t.join(30)
        if topo.errors:
            raise topo.errors[0]


def test_worker_recovery_with_push_pull_wire():
    """The COMBINED push_pull wire across a worker death/recovery: the
    survivor's combined round defers its data-carrying ack on the
    missing peer; the revived worker joins the same round; values stay
    exact (the merged ack carrying post-round params must survive the
    re-registration)."""
    topo = SingleTier().start()
    KEYS = [0, 1]
    W0 = {0: np.full(12, 10.0, np.float32),
          1: np.full(5, -3.0, np.float32)}
    try:
        rank0 = next(kv for kv in topo.workers if kv.rank == 0)
        victim = next(kv for kv in topo.workers if kv.rank == 1)
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: [kv.init(k, W0[k]) for k in KEYS]
                   for kv in topo.workers])

        def combined_round(kv, r):
            outs = [np.zeros_like(W0[k]) for k in KEYS]
            kv.push_pull(KEYS, [np.ones_like(W0[k]) for k in KEYS],
                         out=outs)
            kv.wait()
            for k, o in zip(KEYS, outs):
                np.testing.assert_allclose(o, W0[k] - 2.0 * r)

        _parallel([lambda kv=kv: combined_round(kv, 1)
                   for kv in topo.workers])

        dead_id = victim.po.my_id
        victim._closed = True
        victim.po.van.stop()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if dead_id in topo.sched_po.van.dead_nodes():
                break
            time.sleep(0.1)
        assert dead_id in topo.sched_po.van.dead_nodes()

        results = []

        def survivor():
            combined_round(rank0, 2)
            results.append("survivor")

        t = threading.Thread(target=survivor, daemon=True)
        t.start()

        revived = KVStoreDist(cfg=topo._cfg(role="worker"))
        assert revived.po.van.is_recovery
        for k in KEYS:
            revived.init(k, W0[k])
        combined_round(revived, 2)
        t.join(60)
        assert results == ["survivor"], "survivor did not complete"

        _parallel([lambda kv=kv: combined_round(kv, 3)
                   for kv in (rank0, revived)])
        topo.workers = [rank0, revived]
    finally:
        _parallel([kv.close for kv in topo.workers])
        for t in topo.threads:
            t.join(30)
        if topo.errors:
            raise topo.errors[0]
