"""Failure detection + elastic recovery, end-to-end.

Reference behavior (3rdparty/ps-lite/src/van.cc:176-193): when a node
re-registers and a registered node of the same role has missed its
heartbeats, the scheduler hands the dead slot's id to the newcomer with
``is_recovery=True`` and re-broadcasts the topology; recovering nodes
skip startup barriers (kvstore_dist.h:63). Server state is NOT persisted
(SURVEY.md §5.4) — resume after a server death is re-init + recovery.

These tests kill a node mid-training (hard van stop — no goodbye), wait
for heartbeat lapse, revive it, and assert id handover plus correct
values on resumed training. Single-tier PS topology (the reference's
global-tier recovery is explicitly unimplemented: van.cc:224 TODO).
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from geomx_tpu.config import Config
from geomx_tpu.kvstore.dist import KVStoreDist
from geomx_tpu.kvstore.server import KVStoreDistServer
from geomx_tpu.optimizer import SGD
from geomx_tpu.ps import base as psbase
from geomx_tpu.ps.message import Role
from geomx_tpu.ps.postoffice import Postoffice
from geomx_tpu.simulate import free_port
from tests.test_hips import _parallel

_CORES = os.cpu_count() or 1

# Per-op deadlines scale with the box: a healthy recovery round here
# finishes in seconds, so the 300 s default only ever fires when the
# round is genuinely wedged — and on a starved box that wedge used to
# burn the full deadline chain (~8 min per test). 60 s/core, capped at
# the stock default, keeps the give-up budget proportional to how much
# concurrency the survivor + revived threads can actually get.
HB = {"heartbeat_interval_s": 0.2, "heartbeat_timeout_s": 1.0,
      "op_timeout_s": min(300.0, 60.0 * _CORES)}

# The three worker mid-round recovery tests need the survivor round,
# the revived worker's round, and the server's deferred-ack machinery
# to interleave; with a single core the threads starve each other, the
# round never completes, and each test eats its whole timeout budget.
# They are pathological there, not informative — keep them out of
# tier-1 (`-m 'not slow'`) on boxes that cannot run them honestly.
_pathological_on_1core = (
    pytest.mark.slow if _CORES < 2 else (lambda f: f))


class SingleTier:
    """scheduler + N servers + 2 workers with fast heartbeats.

    ``extra`` merges into every node's Config (snapshot dirs, fault
    plans, resend knobs...) so robustness tests configure the whole tier
    the way a launch script would via environment variables."""

    def __init__(self, extra=None, num_servers=1, num_workers=2):
        self.port = free_port()
        self.extra = dict(extra or {})
        self.num_servers = num_servers
        self.num_workers = num_workers
        self.threads = []
        self.errors = []
        self.sched_po = None
        self.server = None
        self.servers = []
        self.workers = []

    def _run(self, fn):
        def w():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001
                self.errors.append(e)

        t = threading.Thread(target=w, daemon=True)
        t.start()
        self.threads.append(t)

    def _cfg(self, **kw):
        base = dict(ps_root_uri="127.0.0.1", ps_root_port=self.port,
                    num_workers=self.num_workers,
                    num_servers=self.num_servers, **HB)
        base.update(self.extra)
        base.update(kw)
        return Config(**base)

    def start(self):
        sched_cfg = dict(HB)
        sched_cfg.update(self.extra)
        self.sched_po = Postoffice(
            my_role=Role.SCHEDULER, is_global=False,
            root_uri="127.0.0.1", root_port=self.port,
            num_workers=self.num_workers, num_servers=self.num_servers,
            cfg=Config(**sched_cfg))

        def sched():
            self.sched_po.start(60)
            self.sched_po.barrier(psbase.ALL_GROUP, timeout=60)
            self.sched_po.barrier(psbase.ALL_GROUP, timeout=600)
            self.sched_po.van.stop()

        self._run(sched)
        self.servers = [KVStoreDistServer(self._cfg(role="server"))
                        for _ in range(self.num_servers)]
        self.server = self.servers[0]
        for s in self.servers:
            self._run(s.run)
        boxes = [[] for _ in range(self.num_workers)]
        for i in range(self.num_workers):
            self._run(lambda b=boxes[i]: b.append(
                KVStoreDist(cfg=self._cfg(role="worker"))))
        for _ in range(300):
            if self.errors:
                raise self.errors[0]
            if all(len(b) == 1 for b in boxes):
                break
            time.sleep(0.1)
        assert all(len(b) == 1 for b in boxes), "workers failed to start"
        self.workers = [b[0] for b in boxes]
        return self


def _round(kv, key, w0, expect):
    kv.push(key, np.ones_like(w0))
    out = np.zeros_like(w0)
    kv.pull(key, out=out)
    kv.wait()
    np.testing.assert_allclose(out, expect)


@_pathological_on_1core
def test_worker_dies_and_recovers_mid_training():
    topo = SingleTier().start()
    w0 = np.full(12, 10.0, np.float32)
    try:
        rank0 = next(kv for kv in topo.workers if kv.rank == 0)
        victim = next(kv for kv in topo.workers if kv.rank == 1)
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: kv.init(0, w0) for kv in topo.workers])

        # round 1: everyone alive
        _parallel([lambda kv=kv: _round(kv, 0, w0, w0 - 2.0)
                   for kv in topo.workers])

        # hard-kill the rank-1 worker (no goodbye, no barrier)
        dead_id = victim.po.my_id
        victim._closed = True          # disarm its atexit close
        victim.po.van.stop()

        # heartbeat lapse -> scheduler marks it dead
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if dead_id in topo.sched_po.van.dead_nodes():
                break
            time.sleep(0.1)
        assert dead_id in topo.sched_po.van.dead_nodes()

        # the survivor pushes round 2 and blocks on the missing peer
        results = []

        def survivor():
            _round(rank0, 0, w0, w0 - 4.0)
            results.append("survivor")

        t = threading.Thread(target=survivor, daemon=True)
        t.start()

        # revive: a fresh worker re-registers and takes the dead slot
        revived = KVStoreDist(cfg=topo._cfg(role="worker"))
        assert revived.po.van.is_recovery, "scheduler did not hand over slot"
        assert revived.po.my_id == dead_id
        assert revived.rank == 1
        revived.init(0, w0)            # key info only; store already live
        _round(revived, 0, w0, w0 - 4.0)
        t.join(60)
        assert results == ["survivor"], "survivor did not complete the round"

        # round 3 with the recovered pair
        _parallel([lambda kv=kv: _round(kv, 0, w0, w0 - 6.0)
                   for kv in (rank0, revived)])
        topo.workers = [rank0, revived]
    finally:
        _parallel([kv.close for kv in topo.workers])
        for t in topo.threads:
            t.join(30)
        if topo.errors:
            raise topo.errors[0]


def test_server_dies_and_recovers_mid_training():
    """Server store is volatile (reference: SURVEY §5.4): after the slot
    handover, workers re-init and re-ship the optimizer, then training
    resumes from the re-initialized weights."""
    topo = SingleTier().start()
    w0 = np.full(8, 4.0, np.float32)
    try:
        rank0 = next(kv for kv in topo.workers if kv.rank == 0)
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: kv.init(0, w0) for kv in topo.workers])
        _parallel([lambda kv=kv: _round(kv, 0, w0, w0 - 2.0)
                   for kv in topo.workers])

        dead_id = topo.server.po_local.my_id
        topo.server._stop.set()        # stop the run loop...
        topo.server.po_local.van.stop()  # ...and crash the van (no barrier)

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if dead_id in topo.sched_po.van.dead_nodes():
                break
            time.sleep(0.1)
        assert dead_id in topo.sched_po.van.dead_nodes()

        revived = KVStoreDistServer(topo._cfg(role="server"))
        rt = threading.Thread(target=revived.run, daemon=True)
        rt.start()
        for _ in range(100):
            if revived.po_local.van.ready.is_set():
                break
            time.sleep(0.1)
        assert revived.po_local.van.is_recovery
        assert revived.po_local.my_id == dead_id

        # resume: re-init (store was volatile), re-ship the optimizer
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: kv.init(0, w0) for kv in topo.workers])
        _parallel([lambda kv=kv: _round(kv, 0, w0, w0 - 2.0)
                   for kv in topo.workers])
        topo.server = revived
    finally:
        _parallel([kv.close for kv in topo.workers])
        for t in topo.threads:
            t.join(30)
        if topo.errors:
            raise topo.errors[0]


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))


@_pathological_on_1core
def test_worker_recovery_with_batched_wire():
    """The batched list wire across a worker death/recovery: the
    surviving worker's batched round blocks on the missing peer, the
    revived worker joins the same round through batched messages, and
    values stay exact (one merged ack per server must survive the
    re-registration)."""
    topo = SingleTier().start()
    KEYS = [0, 1]
    W0 = {0: np.full(12, 10.0, np.float32),
          1: np.full(5, -3.0, np.float32)}
    try:
        rank0 = next(kv for kv in topo.workers if kv.rank == 0)
        victim = next(kv for kv in topo.workers if kv.rank == 1)
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: [kv.init(k, W0[k]) for k in KEYS]
                   for kv in topo.workers])

        def batched_round(kv, r):
            kv.push(KEYS, [np.ones_like(W0[k]) for k in KEYS])
            outs = [np.zeros_like(W0[k]) for k in KEYS]
            kv.pull(KEYS, out=outs)
            kv.wait()
            for k, o in zip(KEYS, outs):
                np.testing.assert_allclose(o, W0[k] - 2.0 * r)

        _parallel([lambda kv=kv: batched_round(kv, 1)
                   for kv in topo.workers])

        dead_id = victim.po.my_id
        victim._closed = True
        victim.po.van.stop()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if dead_id in topo.sched_po.van.dead_nodes():
                break
            time.sleep(0.1)
        assert dead_id in topo.sched_po.van.dead_nodes()

        results = []

        def survivor():
            batched_round(rank0, 2)
            results.append("survivor")

        t = threading.Thread(target=survivor, daemon=True)
        t.start()

        revived = KVStoreDist(cfg=topo._cfg(role="worker"))
        assert revived.po.van.is_recovery
        for k in KEYS:
            revived.init(k, W0[k])
        batched_round(revived, 2)
        t.join(60)
        assert results == ["survivor"], "survivor did not complete"

        _parallel([lambda kv=kv: batched_round(kv, 3)
                   for kv in (rank0, revived)])
        topo.workers = [rank0, revived]
    finally:
        _parallel([kv.close for kv in topo.workers])
        for t in topo.threads:
            t.join(30)
        if topo.errors:
            raise topo.errors[0]


@_pathological_on_1core
def test_worker_recovery_with_push_pull_wire():
    """The COMBINED push_pull wire across a worker death/recovery: the
    survivor's combined round defers its data-carrying ack on the
    missing peer; the revived worker joins the same round; values stay
    exact (the merged ack carrying post-round params must survive the
    re-registration)."""
    topo = SingleTier().start()
    KEYS = [0, 1]
    W0 = {0: np.full(12, 10.0, np.float32),
          1: np.full(5, -3.0, np.float32)}
    try:
        rank0 = next(kv for kv in topo.workers if kv.rank == 0)
        victim = next(kv for kv in topo.workers if kv.rank == 1)
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: [kv.init(k, W0[k]) for k in KEYS]
                   for kv in topo.workers])

        def combined_round(kv, r):
            outs = [np.zeros_like(W0[k]) for k in KEYS]
            kv.push_pull(KEYS, [np.ones_like(W0[k]) for k in KEYS],
                         out=outs)
            kv.wait()
            for k, o in zip(KEYS, outs):
                np.testing.assert_allclose(o, W0[k] - 2.0 * r)

        _parallel([lambda kv=kv: combined_round(kv, 1)
                   for kv in topo.workers])

        dead_id = victim.po.my_id
        victim._closed = True
        victim.po.van.stop()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if dead_id in topo.sched_po.van.dead_nodes():
                break
            time.sleep(0.1)
        assert dead_id in topo.sched_po.van.dead_nodes()

        results = []

        def survivor():
            combined_round(rank0, 2)
            results.append("survivor")

        t = threading.Thread(target=survivor, daemon=True)
        t.start()

        revived = KVStoreDist(cfg=topo._cfg(role="worker"))
        assert revived.po.van.is_recovery
        for k in KEYS:
            revived.init(k, W0[k])
        combined_round(revived, 2)
        t.join(60)
        assert results == ["survivor"], "survivor did not complete"

        _parallel([lambda kv=kv: combined_round(kv, 3)
                   for kv in (rank0, revived)])
        topo.workers = [rank0, revived]
    finally:
        _parallel([kv.close for kv in topo.workers])
        for t in topo.threads:
            t.join(30)
        if topo.errors:
            raise topo.errors[0]


# ----------------------------------------------------------------------
# durable recovery (kvstore/replication.py): a revived server serves
# PRE-CRASH values — beyond the reference, whose store is volatile
# ----------------------------------------------------------------------


def _wait_dead(topo, dead_id, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if dead_id in topo.sched_po.van.dead_nodes():
            return
        time.sleep(0.1)
    assert dead_id in topo.sched_po.van.dead_nodes()


def _revive_server(topo, **cfg_kw):
    revived = KVStoreDistServer(topo._cfg(role="server", **cfg_kw))
    t = threading.Thread(target=revived.run, daemon=True)
    t.start()
    topo.threads.append(t)
    for _ in range(300):
        if revived._ready.is_set():
            break
        time.sleep(0.1)
    assert revived._ready.is_set(), "revived server never became ready"
    return revived


def _pull_now(kv, key, like):
    out = np.zeros_like(like)
    kv.pull(key, out=out)
    kv.wait()
    return out


def test_server_recovers_state_from_snapshot(tmp_path):
    """Durable recovery, single tier: the server dies AFTER training made
    progress; the replacement restores weights + optimizer from its
    periodic snapshot and serves the PRE-CRASH values with NO re-init and
    NO optimizer re-ship (contrast: test_server_dies_and_recovers_mid_
    training above documents the old volatile-store behavior)."""
    topo = SingleTier(extra={"snapshot_dir": str(tmp_path),
                             "snapshot_interval_s": 0.1}).start()
    w0 = np.full(8, 4.0, np.float32)
    try:
        rank0 = next(kv for kv in topo.workers if kv.rank == 0)
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: kv.init(0, w0) for kv in topo.workers])
        for r in (1, 2):
            _parallel([lambda kv=kv, r=r: _round(kv, 0, w0, w0 - 2.0 * r)
                       for kv in topo.workers])
        time.sleep(0.5)                  # several snapshot ticks
        assert topo.server.replication.num_snapshots > 0

        dead_id = topo.server.po_local.my_id
        topo.server.crash()              # hard kill: no flush, no barrier
        _wait_dead(topo, dead_id)

        revived = _revive_server(topo)
        assert revived.po_local.van.is_recovery
        assert revived.po_local.my_id == dead_id
        assert revived.replication.restored_from == "snapshot"

        # pre-crash weights, straight from the restored store
        for kv in topo.workers:
            np.testing.assert_allclose(_pull_now(kv, 0, w0), w0 - 4.0)
        # training continues (restored updater applies round 3)
        _parallel([lambda kv=kv: _round(kv, 0, w0, w0 - 6.0)
                   for kv in topo.workers])
        topo.server = revived
    finally:
        _parallel([kv.close for kv in topo.workers])
        for t in topo.threads:
            t.join(30)
        if topo.errors:
            raise topo.errors[0]


def test_server_recovers_state_from_peer_replica():
    """Diskless multi-server recovery: NO snapshot dir — each server
    replicates its dirty state to the next-rank peer every tick, and the
    revived server restores by fetching its replica from that peer
    (Command.REPLICA_FETCH)."""
    topo = SingleTier(extra={"snapshot_interval_s": 0.1},
                      num_servers=2).start()
    w0 = np.full(8, 4.0, np.float32)
    try:
        rank0 = next(kv for kv in topo.workers if kv.rank == 0)
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: kv.init(0, w0) for kv in topo.workers])
        for r in (1, 2):
            _parallel([lambda kv=kv, r=r: _round(kv, 0, w0, w0 - 2.0 * r)
                       for kv in topo.workers])
        time.sleep(0.6)                  # replica deltas propagate

        # the victim is whichever server actually holds key 0's shard
        from geomx_tpu.kvstore import sharding

        owner = sharding.assign(0, w0.size, 2,
                                topo._cfg().bigarray_bound)[0].server_rank
        victim = next(s for s in topo.servers
                      if s.po_local.my_rank == owner)
        dead_id = victim.po_local.my_id
        victim.crash()
        _wait_dead(topo, dead_id)

        revived = _revive_server(topo)
        assert revived.po_local.van.is_recovery
        assert revived.po_local.my_id == dead_id
        assert revived.replication.restored_from == "replica"

        for kv in topo.workers:
            np.testing.assert_allclose(_pull_now(kv, 0, w0), w0 - 4.0)
        _parallel([lambda kv=kv: _round(kv, 0, w0, w0 - 6.0)
                   for kv in topo.workers])
        topo.servers = [revived if s is victim else s
                        for s in topo.servers]
        topo.server = topo.servers[0]
    finally:
        _parallel([kv.close for kv in topo.workers])
        for t in topo.threads:
            t.join(30)
        if topo.errors:
            raise topo.errors[0]


def test_hips_party_server_recovers_state(tmp_path):
    """Two-tier HiPS: a party server dies between rounds; its replacement
    restores the party's cached model from its snapshot and serves the
    pre-crash values, then a full cross-party round completes."""
    from geomx_tpu.simulate import InProcessHiPS

    extra = dict(HB)
    extra.update(snapshot_dir=str(tmp_path), snapshot_interval_s=0.1)
    sim = InProcessHiPS(num_parties=2, workers_per_party=1,
                        extra_cfg=extra)
    sim.start(sync_global=True)
    try:
        w0 = np.full(6, 8.0, np.float32)
        sim.master.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: kv.init(0, w0)
                   for kv in sim.workers + [sim.master]])

        def step(kv, r):
            kv.push(0, np.ones_like(w0))
            out = np.zeros_like(w0)
            kv.pull(0, out=out)
            kv.wait()
            np.testing.assert_allclose(out, w0 - 2.0 * r)

        for r in (1, 2):
            sim.run_workers(lambda kv, r=r: step(kv, r))
        time.sleep(0.5)                  # snapshot ticks on every server

        # kill the SECOND party's server (servers[0] is the global server)
        victim = sim.servers[2]
        assert not victim.is_global_server
        victim.crash()
        time.sleep(3.0)                  # heartbeat lapse on BOTH tiers

        revived = KVStoreDistServer(victim.cfg)
        rt = threading.Thread(target=revived.run, daemon=True)
        rt.start()
        sim.threads.append(rt)
        for _ in range(300):
            if revived._ready.is_set():
                break
            time.sleep(0.1)
        assert revived._ready.is_set(), "revived party server not ready"
        assert revived.po_local.van.is_recovery
        assert revived.po_global is not None
        assert revived.po_global.van.is_recovery
        assert revived.replication.restored_from == "snapshot"

        # the party behind the revived server sees pre-crash values
        kv1 = sim.workers[1]
        out = np.zeros_like(w0)
        kv1.pull(0, out=out)
        kv1.wait()
        np.testing.assert_allclose(out, w0 - 4.0)

        # and a full cross-party round still completes exactly
        sim.run_workers(lambda kv: step(kv, 3))
        sim.servers[2] = revived
    finally:
        sim.stop()


@pytest.mark.chaos
def test_faultplan_crash_resume_matches_uninterrupted(tmp_path):
    """THE acceptance scenario: run A trains 3 rounds uninterrupted; run
    B is identical but a FaultPlan crash primitive kills the server on
    the first data frame of round 3. The replacement restores from the
    periodic snapshot, the workers' retransmits complete round 3, and
    the final pulled weights EQUAL run A's — restored from state, not
    re-initialized (no re-init or optimizer re-ship happens in run B
    after the crash)."""
    w0 = np.full(8, 4.0, np.float32)
    common = {
        "snapshot_dir": None,            # per-run below
        "snapshot_interval_s": 0.1,
        "resend": True,
        "resend_timeout_ms": 2000,       # generous: no spurious resends
        "ps_seed": 7,
        # crash->revival must win the race against the DEAD_NODE
        # broadcast: a declaration between the crash and the
        # replacement's registration fail-fasts the workers' pending
        # round-3 pushes ("peer declared dead") instead of letting them
        # retransmit to the revived slot. The recovery handover itself
        # keys off the heartbeat-lapse scan, not the declared set, so a
        # generous grace only defers the broadcast — on a loaded 1-core
        # box the replacement can need several seconds to register.
        "epoch_grace_s": 30.0,
    }
    server_id = psbase.server_rank_to_id(0)

    def train_two_rounds(topo):
        rank0 = next(kv for kv in topo.workers if kv.rank == 0)
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: kv.init(0, w0) for kv in topo.workers])
        for r in (1, 2):
            _parallel([lambda kv=kv, r=r: _round(kv, 0, w0, w0 - 2.0 * r)
                       for kv in topo.workers])
        time.sleep(0.5)                  # quiesce + snapshot ticks

    # -- run A: uninterrupted baseline ---------------------------------
    extra_a = dict(common, snapshot_dir=str(tmp_path / "a"))
    del extra_a["ps_seed"]               # seedless is fine without a plan
    topo_a = SingleTier(extra=extra_a).start()
    try:
        train_two_rounds(topo_a)
        # data frames the server received through rounds 1-2: the crash
        # point for run B is the NEXT one (round 3's first arrival)
        n_pre = topo_a.server.po_local.van.num_data_recv
        final_a = []
        _parallel([lambda kv=kv: final_a.append(
            _pull_now(kv, 0, w0)) for kv in topo_a.workers])
        _parallel([lambda kv=kv: _round(kv, 0, w0, w0 - 6.0)
                   for kv in topo_a.workers])
        expect = w0 - 6.0
    finally:
        _parallel([kv.close for kv in topo_a.workers])
        for t in topo_a.threads:
            t.join(30)
        if topo_a.errors:
            raise topo_a.errors[0]
    np.testing.assert_allclose(final_a[0], w0 - 4.0)

    # -- run B: same training, server crashed by the fault plan --------
    plan = json.dumps({"rules": [{
        "type": "crash", "node": server_id, "at": n_pre + 1,
        "on": "recv", "tier": "local"}]})
    extra_b = dict(common, snapshot_dir=str(tmp_path / "b"),
                   fault_plan=plan)
    topo_b = SingleTier(extra=extra_b).start()
    try:
        train_two_rounds(topo_b)
        dead_id = topo_b.server.po_local.my_id
        assert dead_id == server_id

        # round 3: the first data frame trips the crash rule
        outs = {}

        def round3(kv):
            kv.push(0, np.ones_like(w0))
            out = np.zeros_like(w0)
            kv.pull(0, out=out)
            kv.wait(timeout=120.0)
            outs[kv.rank] = out

        ts = [threading.Thread(target=round3, args=(kv,), daemon=True)
              for kv in topo_b.workers]
        for t in ts:
            t.start()
        _wait_dead(topo_b, dead_id, timeout=30.0)
        assert topo_b.server._crashed, "FaultPlan crash did not fire"

        # the replacement gets NO fault plan (fresh host) but the same
        # snapshot dir; workers' retransmits then complete round 3
        revived = _revive_server(topo_b, fault_plan="")
        assert revived.po_local.van.is_recovery
        assert revived.replication.restored_from == "snapshot", \
            "run B must resume from the snapshot, not re-init"
        for t in ts:
            t.join(120)
        assert set(outs) == {0, 1}, "round 3 did not complete after revival"
        for rank, out in outs.items():
            np.testing.assert_allclose(out, expect, err_msg=(
                f"worker {rank}: resumed weights diverge from the "
                f"uninterrupted run"))
        topo_b.server = revived
    finally:
        _parallel([kv.close for kv in topo_b.workers])
        for t in topo_b.threads:
            t.join(30)
        if topo_b.errors:
            raise topo_b.errors[0]
