"""Initializer library (reference: python/mxnet/initializer.py)."""

import numpy as np
import pytest

from geomx_tpu import initializer as init_mod
from geomx_tpu.initializer import (
    Bilinear, Constant, LSTMBias, Mixed, MSRAPrelu, Normal, One,
    Orthogonal, Uniform, Xavier, Zero, as_flax, create,
)


def test_name_dispatch_bias_gamma_beta():
    x = Xavier(seed=0)
    b = x.init((8,), name="fc1_bias")
    np.testing.assert_array_equal(b, 0.0)
    g = x.init((8,), name="bn0_gamma")
    np.testing.assert_array_equal(g, 1.0)
    var = x.init((8,), name="bn0_moving_var")
    np.testing.assert_array_equal(var, 1.0)


def test_zero_one_constant():
    assert float(Zero().init((3,)).sum()) == 0.0
    assert float(One().init((3,)).sum()) == 3.0
    np.testing.assert_array_equal(Constant(2.5).init((2, 2)), 2.5)


def test_uniform_normal_ranges():
    u = Uniform(scale=0.1, seed=1).init((1000,))
    assert float(np.max(np.abs(u))) <= 0.1
    n = Normal(sigma=0.5, seed=1).init((20000,))
    assert abs(float(np.std(n)) - 0.5) < 0.02


def test_orthogonal_rows_orthonormal():
    w = Orthogonal(scale=1.0, seed=2).init((16, 64))
    gram = w @ w.T
    np.testing.assert_allclose(gram, np.eye(16), atol=1e-5)


@pytest.mark.parametrize("factor_type,expect_fan", [
    ("in", 6 * 9), ("out", 4 * 9), ("avg", (6 * 9 + 4 * 9) / 2)])
def test_xavier_scale_follows_factor(factor_type, expect_fan):
    # conv kernel [out=4, in=6, 3, 3] — mxnet layout conventions
    x = Xavier(rnd_type="uniform", factor_type=factor_type,
               magnitude=3.0, seed=3)
    w = x.init((4, 6, 3, 3))
    bound = np.sqrt(3.0 / expect_fan)
    assert float(np.max(np.abs(w))) <= bound + 1e-7
    assert float(np.max(np.abs(w))) > bound * 0.8  # actually fills range


def test_xavier_rejects_vectors():
    with pytest.raises(ValueError, match="2D"):
        Xavier().init((8,), name="w_weight")


def test_msraprelu_magnitude():
    m = MSRAPrelu(slope=0.0, seed=4)
    assert m.rnd_type == "gaussian"
    assert abs(m.magnitude - 2.0) < 1e-12
    w = m.init((64, 64))
    assert abs(float(np.std(w)) - np.sqrt(2.0 / 64)) < 0.01


def test_bilinear_upsampling_kernel():
    w = Bilinear().init((1, 1, 4, 4))
    # symmetric, peak in the center block, matches the classic kernel
    np.testing.assert_allclose(w[0, 0], w[0, 0][::-1, ::-1], atol=1e-6)
    assert abs(float(w[0, 0, 1, 1]) - 0.5625) < 1e-6


def test_lstm_bias_forget_gate():
    # normal name dispatch must reach the forget-gate logic (the class
    # overrides the bias hook; a plain initializer still zeros biases)
    arr = LSTMBias(forget_bias=1.0).init((16,), name="lstm_i2h_bias")
    np.testing.assert_array_equal(arr[4:8], 1.0)
    assert float(np.abs(arr[:4]).sum()) == 0.0
    assert float(np.abs(arr[8:]).sum()) == 0.0
    plain = Xavier().init((16,), name="lstm_i2h_bias")
    np.testing.assert_array_equal(plain, 0.0)


def test_mixed_pattern_dispatch():
    mix = Mixed([".*fancy.*", ".*"], [Constant(7.0), Zero()])
    a = np.empty((2,), np.float32)
    mix("my_fancy_weight", a)
    np.testing.assert_array_equal(a, 7.0)
    mix("other_weight", a)
    np.testing.assert_array_equal(a, 0.0)


def test_create_factory():
    assert isinstance(create("xavier"), Xavier)
    assert create(Uniform(0.2)).scale == 0.2
    with pytest.raises(ValueError):
        create("nope")


def test_as_flax_adapter():
    import jax

    fn = as_flax("xavier")
    w = fn(jax.random.PRNGKey(0), (8, 8))
    w2 = fn(jax.random.PRNGKey(0), (8, 8))
    w3 = fn(jax.random.PRNGKey(1), (8, 8))
    np.testing.assert_array_equal(np.asarray(w), np.asarray(w2))
    assert not np.array_equal(np.asarray(w), np.asarray(w3))
    assert w.shape == (8, 8)
