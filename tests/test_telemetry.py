"""Metrics registry tests: semantics, cost-when-disabled, and the
kv.metrics() / wan_bytes() paths over a live 2-party topology.

The acceptance bar this file carries: disabled-telemetry overhead stays
under 5% of a 10-key loopback round, and wan_bytes() equals the manual
sum of the per-verb global-tier send counters (the figure bench.py
embeds as wan_bytes_per_round).
"""

import json
import threading
import time

import numpy as np
import pytest

from geomx_tpu import profiler, telemetry
from geomx_tpu.config import Config
from geomx_tpu.kvstore.dist import KVStoreDist
from geomx_tpu.kvstore.server import KVStoreDistServer
from geomx_tpu.optimizer import SGD
from geomx_tpu.ps import base as psbase
from geomx_tpu.ps.message import Role
from geomx_tpu.ps.postoffice import Postoffice
from geomx_tpu.simulate import InProcessHiPS

from test_hips import _parallel, free_port


@pytest.fixture(autouse=True)
def _clean_registry():
    telemetry.reset()
    profiler.reset()
    yield
    telemetry.reset()
    profiler.reset()


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_disabled_mutators_record_nothing():
    assert not telemetry.enabled()
    telemetry.counter_inc("c", 5, tier="local")
    telemetry.gauge_set("g", 7)
    telemetry.histogram_obs("h", 3)
    snap = telemetry.snapshot()
    assert snap["counters"] == {} and snap["gauges"] == {}
    assert snap["histograms"] == {}


def test_counter_labels_render_sorted():
    telemetry.enable(True)
    telemetry.counter_inc("van.bytes_sent", 10, verb="push", tier="local")
    telemetry.counter_inc("van.bytes_sent", 4, tier="local", verb="push")
    telemetry.counter_inc("plain")
    snap = telemetry.snapshot()
    # label order in the call does not matter: one key, sorted labels
    assert snap["counters"]["van.bytes_sent{tier=local,verb=push}"] == 14
    assert snap["counters"]["plain"] == 1


def test_gauge_last_value_wins():
    telemetry.enable(True)
    telemetry.gauge_set("epoch", 1)
    telemetry.gauge_set("epoch", 3)
    assert telemetry.snapshot()["gauges"]["epoch"] == 3


def test_histogram_buckets_and_overflow():
    telemetry.enable(True)
    telemetry.histogram_obs("lat", 3)        # -> bucket ub=5
    telemetry.histogram_obs("lat", 3)
    telemetry.histogram_obs("lat", 99999)    # -> overflow bucket
    h = telemetry.snapshot()["histograms"]["lat"]
    assert h["count"] == 3 and h["sum"] == 3 + 3 + 99999
    assert h["min"] == 3 and h["max"] == 99999
    idx5 = telemetry.BUCKETS.index(5)
    assert h["buckets"][idx5] == 2
    assert h["buckets"][-1] == 1
    assert sum(h["buckets"]) == h["count"]


def test_configure_none_leaves_settings_untouched():
    telemetry.enable(True)
    telemetry.configure(enabled=None, export_dir=None)
    assert telemetry.enabled()
    # the InProcessHiPS property: a later node's Config(telemetry=False)
    # must not switch off a registry another node enabled
    telemetry.configure(enabled=None)
    assert telemetry.enabled()
    telemetry.configure(enabled=False)
    assert not telemetry.enabled()


def test_event_counts_when_enabled_and_feeds_profiler():
    profiler.set_state("run")
    telemetry.event("sanitizer.violation", kind="unanswered")
    # profiler sees the instant even with telemetry off...
    names = [e["name"] for e in json.loads(profiler.dumps())["traceEvents"]]
    assert "sanitizer.violation" in names
    assert telemetry.snapshot()["counters"] == {}
    # ...and the registry counts it once enabled
    telemetry.enable(True)
    telemetry.event("sanitizer.violation", kind="unanswered")
    telemetry.event("sanitizer.violation", kind="unanswered")
    assert telemetry.snapshot()["counters"][
        "event.sanitizer.violation"] == 2


def test_sample_sets_gauge_and_counter_track():
    profiler.set_state("run")
    telemetry.enable(True)
    telemetry.sample("queue.depth", 4)
    assert telemetry.snapshot()["gauges"]["queue.depth"] == 4
    evs = json.loads(profiler.dumps())["traceEvents"]
    assert any(e["name"] == "queue.depth" and e["ph"] == "C" for e in evs)


def test_reset_clears_and_disables():
    telemetry.enable(True)
    telemetry.counter_inc("c")
    telemetry.reset()
    assert not telemetry.enabled()
    assert telemetry.snapshot()["counters"] == {}


def test_export_round_atomic(tmp_path):
    telemetry.enable(True)
    telemetry.counter_inc("c", 2)
    assert telemetry.export_round(1) == ""   # no dir configured
    path = telemetry.export_round(7, str(tmp_path))
    assert path.endswith("_pid") is False and "metrics_round7_pid" in path
    doc = json.loads(open(path).read())
    assert doc["counters"]["c"] == 2
    # atomic: no tmp files left behind
    assert all(".tmp." not in p.name for p in tmp_path.iterdir())


def test_snapshot_schema_pinned():
    """Gate: the snapshot document shape downstream consumers (health
    board, transport controller) parse. Changing the top-level keys, the
    histogram value shape, or the version REQUIRES bumping
    ``telemetry.SCHEMA_VERSION`` and updating this test in the same
    change."""
    telemetry.enable(True)
    telemetry.counter_inc("c", 1)
    telemetry.gauge_set("g", 2.0)
    telemetry.histogram_obs("h", 3.0)
    snap = telemetry.snapshot()
    assert snap["schema_version"] == telemetry.SCHEMA_VERSION == 1
    assert set(snap) == {"schema_version", "counters", "gauges",
                         "histograms", "bucket_bounds"}
    assert set(snap["histograms"]["h"]) == {"count", "sum", "min", "max",
                                            "buckets"}
    assert snap["bucket_bounds"] == list(telemetry.BUCKETS)
    # the JSON form carries the same version (what export_round writes)
    assert json.loads(telemetry.snapshot_json())["schema_version"] == 1


def test_wan_bytes_sums_global_send_counters_only():
    telemetry.enable(True)
    telemetry.counter_inc("van.bytes_sent", 100, tier="global", verb="push",
                          codec="raw")
    telemetry.counter_inc("van.bytes_sent", 40, tier="global", verb="pull",
                          codec="raw")
    telemetry.counter_inc("van.bytes_sent", 7, tier="global", verb="command",
                          codec="raw")
    telemetry.counter_inc("van.bytes_sent", 999, tier="local", verb="push",
                          codec="raw")           # LAN: not WAN traffic
    telemetry.counter_inc("van.bytes_recv", 888, tier="global", verb="push",
                          codec="raw")           # recv side: not counted
    snap = telemetry.snapshot()
    manual = sum(v for k, v in snap["counters"].items()
                 if k.startswith("van.bytes_sent{") and "tier=global" in k)
    assert manual == 147
    assert telemetry.wan_bytes() == manual
    assert telemetry.wan_bytes(snap) == manual


def test_wan_bytes_excludes_mesh_tier_counters():
    """The mesh-party tier's device collectives (kvstore.mesh_party)
    live under their own counter family: wan_bytes() must never count
    them — they cross ICI inside one DC, not the WAN — and
    mesh_bytes() must count exactly them."""
    telemetry.enable(True)
    telemetry.counter_inc("van.bytes_sent", 100, tier="global", verb="push",
                          codec="raw")
    telemetry.counter_inc("mesh.bytes", 4096, tier="mesh", op="psum")
    telemetry.counter_inc("mesh.bytes", 512, tier="mesh", op="all_gather")
    telemetry.counter_inc("mesh.messages", 2, tier="mesh", op="psum")
    snap = telemetry.snapshot()
    assert telemetry.wan_bytes(snap) == 100
    assert telemetry.mesh_bytes(snap) == 4608
    # and the families are disjoint by construction
    assert telemetry.wan_bytes(snap) + telemetry.mesh_bytes(snap) == 4708


def test_mesh_store_count_collective_counter_family():
    """KVStorePartyMesh.count_collective books ring-model bytes
    (2*(P-1)*nbytes) under tier=mesh only, plus a message count."""
    from geomx_tpu.kvstore.mesh_party import KVStorePartyMesh

    telemetry.enable(True)
    store = object.__new__(KVStorePartyMesh)
    store.party_size = 4
    store.mesh_codec = "none"
    KVStorePartyMesh.count_collective(store, 1000)
    snap = telemetry.snapshot()
    assert telemetry.mesh_bytes(snap) == 6000     # 2*(4-1)*1000
    assert telemetry.wan_bytes(snap) == 0
    msgs = [v for k, v in snap["counters"].items()
            if k.startswith("mesh.messages{")]
    assert msgs == [1]
    # quantized codec: bytes follow the ring wire model under its own
    # codec= label, still structurally outside the WAN bill
    store.mesh_codec = "int8"
    store.mesh_block = 256
    KVStorePartyMesh.count_collective(store, 1000, op="ring")
    snap = telemetry.snapshot()
    from geomx_tpu.parallel.quant_collectives import ring_wire_bytes

    assert telemetry.mesh_bytes(snap) == 6000 + ring_wire_bytes(
        "int8", 250, 4, 256)
    assert telemetry.wan_bytes(snap) == 0
    assert any("codec=int8" in k and "op=ring" in k
               for k in snap["counters"] if k.startswith("mesh.bytes{"))


# ---------------------------------------------------------------------------
# disabled-overhead microbench + live topology
# ---------------------------------------------------------------------------

def _ten_key_round_seconds():
    """Measure one 10-key push+pull round on a single-tier loopback PS
    (same harness as test_profiler's end-to-end test)."""
    port = free_port()
    threads, errors = [], []

    def run(fn):
        def w():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
        t = threading.Thread(target=w, daemon=True)
        t.start()
        threads.append(t)

    def sched():
        po = Postoffice(my_role=Role.SCHEDULER, is_global=False,
                        root_uri="127.0.0.1", root_port=port,
                        num_workers=1, num_servers=1, cfg=Config())
        po.start(60)
        po.barrier(psbase.ALL_GROUP, timeout=60)
        po.barrier(psbase.ALL_GROUP, timeout=120)
        po.van.stop()

    run(sched)
    scfg = Config(role="server", ps_root_uri="127.0.0.1", ps_root_port=port,
                  num_workers=1, num_servers=1)
    srv = KVStoreDistServer(scfg)
    run(srv.run)
    box = []
    wcfg = Config(role="worker", ps_root_uri="127.0.0.1", ps_root_port=port,
                  num_workers=1, num_servers=1)
    run(lambda: box.append(KVStoreDist(cfg=wcfg)))
    for _ in range(300):
        if errors:
            raise errors[0]
        if box:
            break
        threading.Event().wait(0.1)
    kv = box[0]
    try:
        kv.set_optimizer(SGD(learning_rate=1.0))
        for k in range(10):
            kv.init(k, np.ones(8, np.float32))
        kv.wait()
        t0 = time.perf_counter()
        for k in range(10):
            kv.push(k, np.ones(8, np.float32))
        for k in range(10):
            kv.pull(k)
        kv.wait()
        return time.perf_counter() - t0
    finally:
        kv.close()
        for t in threads:
            t.join(30)
        if errors:
            raise errors[0]


def test_disabled_overhead_under_5pct_of_ten_key_round():
    """Acceptance bar: with telemetry off, the registry's cost on a
    10-key round is <5% of the round. A 10-key round is ~40 wire
    messages; each message touches the registry a handful of times
    (enabled() gate + the _note_wire mutators), so 400 disabled calls
    per round is a generous over-estimate."""
    assert not telemetry.enabled()
    N = 20000
    t0 = time.perf_counter()
    for i in range(N):
        telemetry.enabled()
        telemetry.counter_inc("van.bytes_sent", i, tier="local", verb="push")
        telemetry.gauge_set("g", i)
        telemetry.histogram_obs("h", i)
    per_call = (time.perf_counter() - t0) / (4 * N)
    round_s = _ten_key_round_seconds()
    est_overhead = per_call * 400
    assert est_overhead < 0.05 * round_s, (
        f"disabled telemetry would cost {est_overhead * 1e6:.1f}us on a "
        f"{round_s * 1e3:.1f}ms round")


def test_kv_metrics_and_wan_bytes_over_hips():
    """2-party HiPS round with telemetry on: kv.metrics() answers with
    the worker's and the servers' snapshots, the global tier counted
    WAN bytes, and wan_bytes() matches the manual per-verb sum — the
    same cross-check bench.py's wan_bytes_per_round figure rests on."""
    telemetry.enable(True)
    sim = InProcessHiPS(num_parties=2, workers_per_party=1).start(
        sync_global=True)
    try:
        sim.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.zeros(64, np.float32)

        def init_on(kv):
            kv.init(0, w0)
            kv.wait()

        _parallel([lambda kv=kv: init_on(kv)
                   for kv in sim.workers + [sim.master]])

        def step(kv):
            kv.push_pull(0, np.ones(64, np.float32),
                         np.zeros(64, np.float32))
            kv.wait()

        _parallel([lambda kv=kv: step(kv) for kv in sim.workers])

        got = sim.workers[0].metrics()
        assert "worker" in got and got["servers"]
        wsnap = got["worker"]
        # in-process sim: every node feeds one registry, so the worker
        # snapshot already carries van counters from both tiers
        sent = {k: v for k, v in wsnap["counters"].items()
                if k.startswith("van.bytes_sent{")}
        assert sent, "no send byte counters recorded"
        assert any("tier=global" in k for k in sent), \
            "no WAN-tier traffic counted"
        assert any("tier=local" in k for k in sent)
        # per-verb cross-check: wan_bytes() == sum of global send counters
        manual = sum(v for k, v in sent.items() if "tier=global" in k)
        assert manual > 0
        assert telemetry.wan_bytes(wsnap) == manual
        assert telemetry.wan_bytes() == pytest.approx(
            sum(v for k, v in telemetry.snapshot()["counters"].items()
                if k.startswith("van.bytes_sent{") and "tier=global" in k))
        # message counters ride along with matching labels
        assert any(k.startswith("van.messages_sent{")
                   for k in wsnap["counters"])
        # the server's answer is a valid snapshot of the same registry
        assert all("counters" in s for s in got["servers"])
    finally:
        sim.stop()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
