"""Mesh-party tier (``dist_sync_mesh``): parity with the wire path.

The tentpole claim (docs/mesh-party.md): replacing a party's LAN PS hop
with a GSPMD psum over the party mesh changes WHERE the intra-party
aggregation runs, not WHAT it computes. These tests prove it bit-exactly
on the CPU 8-virtual-device mesh (tests/conftest.py sets
XLA_FLAGS=--xla_force_host_platform_device_count=8):

- dense FSA: a 2-party x 2-member wire run and a 2-party mesh run
  (party_mesh_size=2) fed the same per-member data end with IDENTICAL
  weights after N rounds. Exactness is by construction: every input is
  an integer multiple of a power of two and magnitudes stay far below
  2^24, so fp32 addition is exact in ANY order — the device psum order
  vs the server's arrival-order sum cannot diverge.
- BSC: DeviceResidentTrainer over the mesh store (party batch sharded
  over "dp", psum inside grad_fn's backward) matches the same trainer
  fed the full party batch on one device, bit-exactly. Here values go
  through 0.9-momentum BSC buffers (inexact fp32), so parity rests on
  determinism: identical inputs -> identical device programs -> the
  global tier adds exactly TWO party aggregates, and two-operand fp32
  addition is commutative.
- chaos: the party whose server survives must not hang when a REMOTE
  party's server is killed mid-training — the round either completes
  from the released aggregation or aborts with the RoundAborted family
  within a bounded wait (RoundFuture.abort_pending fan-out).
"""

import threading
import time
import weakref

import numpy as np
import pytest

from geomx_tpu import telemetry
from geomx_tpu.kvstore.frontier import RoundAborted, RoundFuture
from geomx_tpu.kvstore.mesh_party import KVStorePartyMesh, _ring_bytes
from geomx_tpu.optimizer import SGD
from geomx_tpu.simulate import InProcessHiPS
from geomx_tpu.trainer import Trainer
from geomx_tpu.trainer_device import DeviceResidentTrainer

ROUNDS = 5
SHAPES = [(4,), (2, 2)]
# per-(round, party, member) data: integers scaled by 2^-2 -> every
# gradient/weight below is an exact fp32 value (see module docstring)
_rng = np.random.RandomState(7)
DATA = [
    _rng.randint(-8, 9, size=(ROUNDS, 2, 2) + shp).astype(np.float32) * 0.25
    for shp in SHAPES
]


def _zeros():
    return [np.zeros(s, np.float32) for s in SHAPES]


def _master_init(kv):
    for i, w in enumerate(_zeros()):
        kv.init(i, w)
    kv.wait()


# -- dense FSA parity ------------------------------------------------------


def _run_wire_dense():
    """Baseline: 2 parties x 2 van workers, per-member host gradients
    (w - t)/2 — the party's two members sum to the party-mean gradient
    the mesh run computes on device."""
    sim = InProcessHiPS(num_parties=2, workers_per_party=2).start()
    out = {}
    try:
        sim.master.set_optimizer(SGD(learning_rate=0.25))
        time.sleep(0.5)

        def worker(kv):
            widx = sim.workers.index(kv)
            p, m = divmod(widx, 2)
            tr = Trainer(_zeros(), kv)
            for r in range(ROUNDS):
                w = tr.leaves
                grads = [((w[i] - DATA[i][r, p, m]) / 2).astype(np.float32)
                         for i in range(len(SHAPES))]
                tr.step(grads)
            out[widx] = [np.array(l) for l in tr.leaves]

        sim.run_workers(worker, include_master=_master_init, timeout=300)
    finally:
        sim.stop()
    return out


def _run_mesh_dense():
    """Mesh run: one KVStorePartyMesh per party over 2 devices; grads
    come out of a jitted value_and_grad whose mean over the dp-sharded
    batch IS the intra-party aggregation (XLA-inserted psum)."""
    import jax
    import jax.numpy as jnp

    def _loss(w0, w1, X0, X1):
        d0 = w0[None] - X0
        d1 = w1[None] - X1
        return 0.5 * (jnp.mean(jnp.sum(d0 * d0, axis=-1))
                      + jnp.mean(jnp.sum(d1 * d1, axis=(-2, -1))))

    gstep = jax.jit(jax.value_and_grad(_loss, argnums=(0, 1)))

    sim = InProcessHiPS(num_parties=2, workers_per_party=2,
                        party_mesh_size=2).start()
    out = {}
    try:
        sim.master.set_optimizer(SGD(learning_rate=0.25))
        time.sleep(0.5)

        def worker(kv):
            p = sim.workers.index(kv)
            assert kv.type == "dist_sync_mesh"
            assert kv.party_size == 2 and kv.num_workers == 1
            tr = Trainer(_zeros(), kv)
            for r in range(ROUNDS):
                w = tr.leaves
                wd = [kv.put_replicated(jnp.asarray(l)) for l in w]
                X0, X1 = kv.shard_batch(DATA[0][r, p], DATA[1][r, p])
                _loss_v, grads = gstep(wd[0], wd[1], X0, X1)
                tr.step([np.asarray(g) for g in grads])
            out[p] = [np.array(l) for l in tr.leaves]

        sim.run_workers(worker, include_master=_master_init, timeout=300)
    finally:
        sim.stop()
    return out


@pytest.mark.mesh
def test_dense_fsa_parity_bit_exact():
    was_enabled = telemetry.enabled()
    try:
        telemetry.reset()           # reset() also disables -> re-enable
        telemetry.enable(True)
        wire = _run_wire_dense()
        wire_snap = telemetry.snapshot()
        telemetry.reset()
        telemetry.enable(True)
        mesh = _run_mesh_dense()
        mesh_snap = telemetry.snapshot()
    finally:
        telemetry.reset()
        telemetry.enable(was_enabled)

    # every wire worker and every mesh party ends on the SAME bits
    ref = wire[0]
    for widx in range(4):
        for i in range(len(SHAPES)):
            np.testing.assert_array_equal(wire[widx][i], ref[i])
    for p in range(2):
        for i in range(len(SHAPES)):
            np.testing.assert_array_equal(mesh[p][i], ref[i])

    # and the weights actually moved (the parity is not vacuous)
    assert any(np.any(l != 0) for l in ref)

    # telemetry: the mesh tier's collectives are counted under
    # tier=mesh, excluded from wan_bytes, and the party members put
    # ZERO extra messages on the van — the mesh run's LAN traffic is
    # strictly below the wire run's (2 members collapsed into 1
    # van worker per party)
    assert telemetry.mesh_bytes(mesh_snap) > 0
    assert telemetry.mesh_bytes(wire_snap) == 0

    def _local_msgs(snap):
        return sum(v for k, v in snap["counters"].items()
                   if k.startswith("van.messages_sent{")
                   and "tier=local" in k)

    assert _local_msgs(mesh_snap) < _local_msgs(wire_snap)
    # wan_bytes counts only the global-tier van sends in both runs
    for snap in (wire_snap, mesh_snap):
        assert telemetry.wan_bytes(snap) > 0
        for key in snap["counters"]:
            if key.startswith("mesh."):
                assert "tier=mesh" in key


# -- BSC parity ------------------------------------------------------------


BSC_DIM = 8
BSC_ROUNDS = 5
_bsc_rng = np.random.RandomState(21)
# (round, party, member, dim) integer/4 batches
BSC_DATA = _bsc_rng.randint(-8, 9, size=(BSC_ROUNDS, 2, 2, BSC_DIM)
                            ).astype(np.float32) * 0.25


def _bsc_master_init(kv):
    kv.init(0, np.zeros(BSC_DIM, np.float32))
    kv.wait()


def _bsc_grad_fn(leaves, X, y):
    import jax.numpy as jnp

    w = leaves[0]
    d = w[None, :] - X
    return 0.5 * jnp.mean(jnp.sum(d * d, axis=-1)), [jnp.mean(d, axis=0)]


def _run_bsc_mesh(threshold):
    sim = InProcessHiPS(num_parties=2, workers_per_party=2,
                        party_mesh_size=2).start()
    out = {}
    try:
        def worker(kv):
            p = sim.workers.index(kv)
            tr = DeviceResidentTrainer(
                [np.zeros(BSC_DIM, np.float32)], kv, _bsc_grad_fn,
                threshold=threshold, learning_rate=0.25)
            for r in range(BSC_ROUNDS):
                # the party's full batch; _place_batch shards it over dp
                tr.step(BSC_DATA[r, p].reshape(2, BSC_DIM), None)
            out[p] = np.array(tr.leaves[0])

        sim.run_workers(worker, include_master=_bsc_master_init,
                        timeout=300)
    finally:
        sim.stop()
    return out


def _run_bsc_wire_partybatch(threshold):
    """Wire baseline shaped like the mesh run: ONE worker per party fed
    the party's FULL batch (2 members' rows) on a single device — the
    single-device mean it computes is the quantity the mesh run's psum
    produces."""
    sim = InProcessHiPS(num_parties=2, workers_per_party=1).start()
    out = {}
    try:
        def worker(kv):
            p = sim.workers.index(kv)
            tr = DeviceResidentTrainer(
                [np.zeros(BSC_DIM, np.float32)], kv, _bsc_grad_fn,
                threshold=threshold, learning_rate=0.25)
            for r in range(BSC_ROUNDS):
                tr.step(BSC_DATA[r, p].reshape(2, BSC_DIM), None)
            out[p] = np.array(tr.leaves[0])

        sim.run_workers(worker, include_master=_bsc_master_init,
                        timeout=300)
    finally:
        sim.stop()
    return out


@pytest.mark.mesh
def test_bsc_parity_bit_exact():
    """DeviceResidentTrainer over dist_sync_mesh == the same trainer
    over dist_sync fed the identical party batch, bit for bit — through
    the full BSC machinery (momentum buffers, per-key top-k, packed
    int32 wire, residual feedback). threshold=1.0 keeps selection
    total (k=n) so the parity covers every coordinate every round."""
    wire = _run_bsc_wire_partybatch(threshold=1.0)
    mesh = _run_bsc_mesh(threshold=1.0)
    for p in range(2):
        np.testing.assert_array_equal(mesh[p], wire[p])
    np.testing.assert_array_equal(wire[0], wire[1])
    assert np.any(wire[0] != 0)


@pytest.mark.mesh
def test_bsc_sparse_threshold_replicas_identical():
    """Sparse selection (k=2 of 8): mesh parties still end bit-identical
    to each other (the aggregated selection both apply is the same
    wire payload)."""
    mesh = _run_bsc_mesh(threshold=0.25)
    np.testing.assert_array_equal(mesh[0], mesh[1])
    assert np.any(mesh[0] != 0)


# -- abort fan-out / chaos -------------------------------------------------


def test_abort_pending_unblocks_joiners_immediately():
    """RoundFuture.abort_pending fails every pending key NOW: a joiner
    blocked with a long timeout wakes with RoundAborted in well under a
    second, and already-completed keys keep their results."""
    fut = RoundFuture([0, 1, 2])
    fut.complete_key(0, "done")
    woke = {}

    def join():
        t0 = time.monotonic()
        try:
            fut.wait(timeout=30.0)
        except RoundAborted as e:
            woke["exc"] = e
        woke["elapsed"] = time.monotonic() - t0

    t = threading.Thread(target=join, daemon=True)
    t.start()
    time.sleep(0.1)
    fut.abort_pending("round aborted: remote server declared dead")
    t.join(5.0)
    assert not t.is_alive()
    assert isinstance(woke.get("exc"), RoundAborted)
    assert woke["elapsed"] < 5.0
    assert fut.done()


def test_fail_fast_pending_aborts_watched_futures():
    """The mesh store's round_abort_hook fans the inner store's round
    death out to every live future it issued (and only live ones — the
    WeakSet drops collected futures) and re-seeds the ring residual
    streams so stale quantization error never replays into the retry."""
    store = object.__new__(KVStorePartyMesh)
    store._live_futs = weakref.WeakSet()
    store._reducers = {}
    store._residual_reset_hooks = []
    resets = []
    store.register_residual_reset_hook(lambda: resets.append(1))
    fut = store._watch(RoundFuture([0, 1]))
    gone = store._watch(RoundFuture([7]))
    del gone    # collected -> must not be touched (nor crash the hook)
    store._fail_fast_pending("server 9 declared dead")
    with pytest.raises(RoundAborted):
        fut.wait(timeout=1.0)
    assert resets == [1]


def test_ring_bytes_model():
    assert _ring_bytes(1, 1000) == 0       # single-device party: no links
    assert _ring_bytes(2, 1000) == 2000
    assert _ring_bytes(4, 1000) == 6000


@pytest.mark.mesh
@pytest.mark.chaos
def test_mesh_party_survives_remote_server_kill():
    """Chaos-matrix case: the global worker's party keeps its server;
    a REMOTE party's server is killed mid-training. The surviving mesh
    party's round must not hang — it either completes once the global
    tier releases the stalled aggregation (elastic membership) or
    raises the RoundAborted family, within a bounded wait."""
    from geomx_tpu.kvstore.server import KVStoreDistServer

    sim = InProcessHiPS(
        num_parties=2, workers_per_party=2, party_mesh_size=2,
        extra_cfg={"heartbeat_interval_s": 0.2,
                   "heartbeat_timeout_s": 1.0}).start()
    try:
        sim.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.zeros(6, np.float32)
        _g = np.ones(6, np.float32)

        def init_and_round(kv):
            kv.init(0, w0)
            outb = np.zeros_like(w0)
            kv.pull(0, out=outb)
            kv.wait()
            kv.push_pull(0, _g, outb, priority=0)
            kv.wait()

        sim.master.init(0, w0)
        sim.master.wait()
        sim.run_workers(init_and_round, timeout=120)

        # kill party 1's server (servers[0] is the global server);
        # party 0's mesh store keeps ITS server — the WAN gateway
        victim = sim.servers[2]
        assert not victim.is_global_server
        victim.crash()

        survivor = sim.workers[0]
        done = {}

        def survivor_round():
            outb = np.zeros_like(w0)
            t0 = time.monotonic()
            try:
                survivor.push_pull(0, _g, outb, priority=0)
                survivor.wait(timeout=60.0)
                done["outcome"] = "completed"
            except RoundAborted:
                done["outcome"] = "aborted"
            except TimeoutError:
                done["outcome"] = "timeout"
            done["elapsed"] = time.monotonic() - t0

        t = threading.Thread(target=survivor_round, daemon=True)
        t.start()
        t.join(90.0)
        assert not t.is_alive(), (
            "mesh party hung on the round after the remote server died")
        assert done["outcome"] in ("completed", "aborted", "timeout")

        # revive the dead server so the shutdown cascade completes
        revived = KVStoreDistServer(victim.cfg)
        rt = threading.Thread(target=revived.run, daemon=True)
        rt.start()
        sim.threads.append(rt)
        for _ in range(300):
            if revived._ready.is_set():
                break
            time.sleep(0.1)
        assert revived._ready.is_set(), "revived party server not ready"
        sim.servers[2] = revived
    finally:
        sim.stop()
