"""ESync (geomx_tpu.esync): state-server step balancing + synchronous
model averaging. Beyond parity — the reference documents the algorithm
("to be integrated", reference README.md:45) but ships no code; the
semantics here follow the cited paper (Li et al., IEEE TSC 2020)."""

import time

import numpy as np

from geomx_tpu.esync import ESyncStateServer, ESyncTrainer
from geomx_tpu.optimizer import SGD
from geomx_tpu.simulate import InProcessHiPS


def test_state_server_balances_reach_time():
    ss = ESyncStateServer()
    # slow worker: 100 ms/step; fast worker: 10 ms/step; equal RTT
    assert ss.report(1, 0.1, 0.01) == 1     # alone -> 1 step
    m_fast = ss.report(2, 0.01, 0.01)
    # fast worker fills the slow worker's reach time: ~(0.11-0.01)/0.01
    assert 8 <= m_fast <= 10
    # the slow worker stays at 1 local step
    assert ss.report(1, 0.1, 0.01) == 1


def test_state_server_cap_and_smoothing():
    ss = ESyncStateServer(cap=4)
    ss.report(1, 1.0, 0.0)                   # very slow peer
    assert ss.report(2, 0.001, 0.0) == 4     # capped
    # EMA: a transiently fast report does not whipsaw to the extreme
    ss2 = ESyncStateServer()
    ss2.report(1, 0.1, 0.0)
    ss2.report(2, 0.1, 0.0)
    m1 = ss2.report(2, 0.01, 0.0)            # smoothed tau ~0.055
    assert m1 <= 2


def _quad_grad(target):
    def grad_fn(leaves, X, y):
        # quadratic bowl: loss = 0.5*sum((w - target)^2)
        grads = [l - t for l, t in zip(leaves, target)]
        loss = sum(0.5 * float(np.sum(g * g)) for g in grads)
        return loss, grads
    return grad_fn


def test_esync_trains_and_balances_heterogeneity():
    """Two workers, one 5x slower: the fast one gets more local steps,
    replicas leave every sync identical, and the model converges."""
    # ONE party, two workers: ESync is intra-domain (the paper balances
    # workers within a data center; each party's rank-0 PS hosts its own
    # state server)
    topo = InProcessHiPS(num_parties=1, workers_per_party=2).start()
    target = [np.full((8,), 3.0, np.float32), np.full((3,), -2.0,
                                                      np.float32)]
    results = {}
    try:
        def master_init(kv):
            for i, t in enumerate(target):
                kv.init(i, np.zeros_like(t))
            kv.wait()

        def worker(kv):
            widx = 0 if kv is topo.workers[0] else 1
            slowdown = 0.05 if widx == 0 else 0.0

            def grad_fn(leaves, X, y):
                time.sleep(slowdown)
                return _quad_grad(target)(leaves, X, y)

            tr = ESyncTrainer([np.zeros_like(t) for t in target], kv,
                              grad_fn, SGD(learning_rate=0.3))
            batches = [(None, None)]
            losses = [tr.round(batches) for _ in range(12)]
            results[widx] = (tr, losses)

        topo.run_workers(worker, include_master=master_init, timeout=300)
    finally:
        topo.stop()
    (tr0, l0), (tr1, l1) = results[0], results[1]
    # replicas identical after the final sync
    for a, b in zip(tr0.leaves, tr1.leaves):
        np.testing.assert_array_equal(a, b)
    # converged toward the target
    assert l0[-1] < l0[0] / 10
    # the fast worker ran MORE local steps than the slow one; the slow
    # worker's count may wobble 1-2 under suite-load timing noise (a
    # sync-RTT spike legitimately raises its assignment), so the strong
    # claim is the RATIO, not an exact count
    assert tr1.local_steps_run > 2 * tr0.local_steps_run
