"""Gradient accumulation + transformer remat."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from geomx_tpu.compat import shard_map
from geomx_tpu.parallel.grad_accum import accumulate_gradients


def test_accum_matches_full_batch():
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.normal(size=(6,)), jnp.float32)
    X = jnp.asarray(rng.normal(size=(12, 6)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(12,)), jnp.float32)

    def grad_fn(params, X, y):
        def loss_fn(p):
            return jnp.mean((X @ p - y) ** 2)
        return jax.value_and_grad(loss_fn)(params)

    full_loss, full_grad = grad_fn(w, X, y)
    for m in (1, 2, 3, 4, 6):
        acc = jax.jit(accumulate_gradients(grad_fn, m))
        loss, grad = acc(w, X, y)
        np.testing.assert_allclose(float(loss), float(full_loss),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(full_grad),
                                   rtol=1e-4, atol=1e-6)


def test_accum_validation():
    def grad_fn(p, X, y):
        return jnp.float32(0), p

    with pytest.raises(ValueError, match=">= 1"):
        accumulate_gradients(grad_fn, 0)
    fn = accumulate_gradients(grad_fn, 5)
    with pytest.raises(ValueError, match="divisible"):
        fn(jnp.zeros(3), jnp.zeros((12, 2)), jnp.zeros(12))


def test_accum_with_mesh_pmean():
    from geomx_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(jax.devices())  # dp=8
    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.normal(size=(4,)), jnp.float32)
    X = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(16,)), jnp.float32)

    def grad_fn(params, X, y):
        def loss_fn(p):
            return jnp.mean((X @ p - y) ** 2)
        return jax.value_and_grad(loss_fn)(params)

    from jax.sharding import PartitionSpec as P

    inner = accumulate_gradients(grad_fn, 2, axis_name="dp")
    fn = jax.jit(shard_map(
        inner, mesh=mesh,
        in_specs=(P(), P("dp"), P("dp")), out_specs=(P(), P()),
        check_vma=False))
    loss, grad = fn(w, X, y)
    full_loss, full_grad = grad_fn(w, X, y)
    np.testing.assert_allclose(float(loss), float(full_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(full_grad),
                               rtol=1e-4, atol=1e-6)


def test_transformer_remat_same_values():
    from geomx_tpu.models.transformer import Transformer

    tok = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 64)
    plain = Transformer(vocab=64, dim=32, depth=2, heads=2, max_len=32)
    remat = Transformer(vocab=64, dim=32, depth=2, heads=2, max_len=32,
                        remat=True)
    p = plain.init(jax.random.PRNGKey(1), tok)
    np.testing.assert_allclose(np.asarray(plain.apply(p, tok)),
                               np.asarray(remat.apply(p, tok)),
                               rtol=1e-6, atol=1e-6)

    def loss(model, p):
        return jnp.mean(model.apply(p, tok) ** 2)

    gp = jax.grad(lambda p: loss(plain, p))(p)
    gr = jax.grad(lambda p: loss(remat, p))(p)
    for a, b in zip(jax.tree_util.tree_leaves(gp),
                    jax.tree_util.tree_leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_accum_preserves_param_dtype_and_single_array_batch():
    w = jnp.ones((4,), jnp.bfloat16)
    X = jnp.ones((8, 4), jnp.float32)

    def grad_fn(p, X):  # X-only loss: no labels needed
        def loss_fn(p):
            return jnp.mean((X @ p.astype(jnp.float32)) ** 2)
        return jax.value_and_grad(loss_fn)(p)

    loss, grad = accumulate_gradients(grad_fn, 4)(w, X)
    assert grad.dtype == jnp.bfloat16
    assert np.isfinite(float(loss))
