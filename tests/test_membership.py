"""Elastic membership: epochs, zombie fencing, survivor progress.

The reference's scheduler only LOGS heartbeat lapses and hands the dead
id to the next registrant (van.cc:176-193); nothing tells the survivors,
so a synchronous round sized for N workers waits forever on a corpse's
push. These tests cover the membership-epoch layer built on top
(docs/robustness.md "Elastic membership"): a sustained heartbeat lapse
becomes a DEAD_NODE declaration that every member converges on, servers
re-size pending aggregation countdowns to the live view, and pushes from
declared-dead (but still running) zombies are fenced by epoch.
"""

import json
import threading
import time

import numpy as np
import pytest

from geomx_tpu.optimizer import SGD
from geomx_tpu.ps import base as psbase
from tests.test_hips import _parallel
from tests.test_recovery import SingleTier, _round, _wait_dead


def _kill(kv):
    """Hard worker death: no goodbye, no barrier (disarm atexit close)."""
    kv._closed = True
    kv.po.van.stop()


def _wait_declared(vans, dead_id, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(dead_id in v.declared_dead_ids() for v in vans):
            return
        time.sleep(0.05)
    for v in vans:
        assert dead_id in v.declared_dead_ids(), (
            f"node {v.my_id} never learned that {dead_id} is dead")


def test_heartbeat_lapse_declares_dead_and_bumps_epoch():
    """Heartbeat lapse -> dead_nodes() -> declaration: the scheduler
    promotes the lapse to a DEAD_NODE broadcast (epoch bump) and every
    surviving member's van converges on the same dead set + epoch."""
    topo = SingleTier().start()
    w0 = np.full(6, 2.0, np.float32)
    try:
        rank0 = next(kv for kv in topo.workers if kv.rank == 0)
        victim = next(kv for kv in topo.workers if kv.rank == 1)
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: kv.init(0, w0) for kv in topo.workers])

        dead_id = victim.po.my_id
        _kill(victim)

        # raw heartbeat lapse first (the pre-existing detector)...
        _wait_dead(topo, dead_id)
        # ...then the declaration (grace is 0: promoted on the next tick)
        sched_van = topo.sched_po.van
        _wait_declared([sched_van], dead_id)
        assert sched_van.membership_epoch >= 1
        assert dead_id not in sched_van.live_ids()

        # the broadcast reaches the survivor worker AND the server
        members = [rank0.po.van, topo.server.po_local.van]
        _wait_declared(members, dead_id)
        for v in members:
            assert v.membership_epoch >= 1
            assert dead_id not in v.live_ids()

        # the postoffice live view + dead-node counters follow
        assert topo.server.po_local.num_live_workers() == 1
        assert dead_id not in topo.server.po_local.live_worker_ids()
        assert rank0.get_num_dead_node() == 1
        assert rank0.get_num_dead_node(role="worker") == 1
        assert rank0.get_num_dead_node(role="server") == 0
        assert rank0.membership_epoch() >= 1
        topo.workers = [rank0]
    finally:
        _parallel([kv.close for kv in topo.workers])
        for t in topo.threads:
            t.join(30)
        if topo.errors:
            raise topo.errors[0]


def test_stale_epoch_push_is_dropped():
    """Zombie fencing: a node the scheduler declared dead while it is
    STILL RUNNING (a partition, not a death) keeps pushing — the server
    must drop those pushes unacked instead of aggregating them."""
    topo = SingleTier().start()
    w0 = np.full(8, 10.0, np.float32)
    try:
        rank0 = next(kv for kv in topo.workers if kv.rank == 0)
        zombie = next(kv for kv in topo.workers if kv.rank == 1)
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: kv.init(0, w0) for kv in topo.workers])
        _parallel([lambda kv=kv: _round(kv, 0, w0, w0 - 2.0)
                   for kv in topo.workers])

        # declare the rank-1 worker dead by fiat (its heartbeats are
        # fine — this is the false-positive/partition case)
        zid = zombie.po.my_id
        topo.sched_po.van.declare_dead([zid])
        _wait_declared([rank0.po.van, topo.server.po_local.van], zid)

        # the zombie pushes a poison gradient; fenced -> no aggregation,
        # no ack (we never wait on it)
        zombie.push(0, np.full_like(w0, 100.0))
        time.sleep(0.5)

        # the survivor's round is sized to the live view (1 worker) and
        # must see ONLY its own gradient: -1, not -101
        _round(rank0, 0, w0, w0 - 3.0)

        # the poison push must not even have bumped the round version
        assert topo.server._states[(0, 0)].version == 2  # rounds 1+2 only
        topo.workers = [rank0]
        _kill(zombie)
    finally:
        _parallel([kv.close for kv in topo.workers])
        for t in topo.threads:
            t.join(30)
        if topo.errors:
            raise topo.errors[0]


@pytest.mark.chaos
def test_three_workers_lose_one_mid_round_survivors_continue():
    """THE acceptance scenario: 3 workers under a seeded FaultPlan whose
    crash rule kills the rank-2 worker at the start of round 2 (the new
    ``at_round`` primitive, driven by kv.notify_round). The survivors'
    round must complete once the declaration lands (the server re-sizes
    the pending countdown from 3 to the 2 live workers), and the pair
    then trains >= 5 further rounds with the key version advancing."""
    plan = json.dumps({"rules": [{
        "type": "crash", "node": psbase.worker_rank_to_id(2),
        "at_round": 2, "tier": "local"}]})
    topo = SingleTier(num_workers=3,
                      extra={"fault_plan": plan, "ps_seed": 11}).start()
    w0 = np.full(10, 30.0, np.float32)
    try:
        workers = sorted(topo.workers, key=lambda kv: kv.rank)
        rank0 = workers[0]
        victim = workers[2]
        survivors = workers[:2]
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: kv.init(0, w0) for kv in workers])

        # round 1: everyone alive (sum of 3 unit gradients)
        for kv in workers:
            kv.notify_round(1)
        _parallel([lambda kv=kv: _round(kv, 0, w0, w0 - 3.0)
                   for kv in workers])

        # round 2: survivors push and block on the missing third push
        outs = {}

        def survivor_round(kv):
            kv.notify_round(2)
            kv.push(0, np.ones_like(w0))
            out = np.zeros_like(w0)
            kv.pull(0, out=out)
            kv.wait(timeout=60.0)
            outs[kv.rank] = out

        ts = [threading.Thread(target=survivor_round, args=(kv,),
                               daemon=True) for kv in survivors]
        for t in ts:
            t.start()
        time.sleep(0.4)                  # survivors' pushes land: 2/3
        dead_id = victim.po.my_id
        # the fault plan kills the victim's van at its round-2 entry: no
        # goodbye, no barrier, no push — indistinguishable from death
        victim._closed = True            # disarm its atexit close
        victim.notify_round(2)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if victim.po.van.stopped.is_set():
                break
            time.sleep(0.05)
        assert victim.po.van.stopped.is_set(), \
            "at_round crash rule did not fire"

        # declaration -> the server releases the stalled round with the
        # survivors' gradients (no re-push, no timeout)
        for t in ts:
            t.join(60)
        assert set(outs) == {0, 1}, "survivors did not complete the round"
        for rank, out in outs.items():
            np.testing.assert_allclose(out, w0 - 5.0, err_msg=(
                f"worker {rank}: released round must carry exactly the "
                f"2 survivor gradients"))
        _wait_declared([topo.server.po_local.van], dead_id)
        assert topo.server.po_local.num_live_workers() == 2

        # >= 5 subsequent rounds: versions keep advancing
        v_before = topo.server._states[(0, 0)].version
        for r in range(1, 6):
            _parallel([lambda kv=kv, r=r:
                       _round(kv, 0, w0, w0 - 5.0 - 2.0 * r)
                       for kv in survivors])
        assert topo.server._states[(0, 0)].version >= v_before + 5
        topo.workers = survivors
    finally:
        _parallel([kv.close for kv in topo.workers])
        for t in topo.threads:
            t.join(30)
        if topo.errors:
            raise topo.errors[0]


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
