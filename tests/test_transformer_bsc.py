"""Transformer through HiPS + BSC device-resident (round-3 verdict #3).

The flagship config must carry a real transformer, not just the demo
CNN: at threshold=1.0 the BSC wire is lossless, so the distributed
loss curve must match single-process SGD on the mean gradient exactly;
at a sparse threshold the loss must still go down.
"""

import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

# two workers + a reference each jit-compile the transformer: nightly tier
pytestmark = pytest.mark.slow

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])

from examples.transformer_bsc_device import (  # noqa: E402
    build_transformer_grad_step, synth_batch)
from geomx_tpu.simulate import InProcessHiPS  # noqa: E402
from geomx_tpu.trainer_device import DeviceResidentTrainer  # noqa: E402

DIMS = dict(dim=32, depth=1, heads=2, vocab=64, seq_len=16)
ROUNDS = 8
LR = 0.1


def _batches(widx, n):
    rng = np.random.default_rng(100 + widx)
    return [jnp.asarray(synth_batch(rng, 4, DIMS["seq_len"],
                                    DIMS["vocab"])) for _ in range(n)]


def _run_distributed(threshold, momentum=0.0):
    topo = InProcessHiPS(num_parties=2, workers_per_party=1).start()
    losses = {}
    try:
        leaves0, grad_step = build_transformer_grad_step(
            **DIMS, compute_dtype=jnp.float32)

        def master_init(kv):
            for i, leaf in enumerate(leaves0):
                kv.init(i, leaf)
            kv.wait()

        def worker(kv):
            widx = 0 if kv is topo.workers[0] else 1
            _, gs = build_transformer_grad_step(
                **DIMS, compute_dtype=jnp.float32)
            tr = DeviceResidentTrainer(
                list(leaves0), kv, gs, threshold=threshold,
                learning_rate=LR, momentum=momentum)
            curve = []
            for toks in _batches(widx, ROUNDS):
                curve.append(tr.step(toks, None))
            losses[widx] = curve

        # run_workers joins with a timeout, surfaces worker errors,
        # and raises on hang — no wrapper thread needed
        topo.run_workers(worker, include_master=master_init, timeout=600)
    finally:
        topo.stop()
    return losses


def test_lossless_threshold_matches_mean_grad_sgd():
    """threshold=1.0: the distributed per-worker loss curves must equal
    a single-process simulation stepping on the MEAN of the two
    workers' gradients (what HiPS aggregation computes)."""
    dist = _run_distributed(threshold=1.0)

    leaves, grad_step = build_transformer_grad_step(
        **DIMS, compute_dtype=jnp.float32)
    lv = [jnp.asarray(l) for l in leaves]
    b0, b1 = _batches(0, ROUNDS), _batches(1, ROUNDS)
    expect0, expect1 = [], []
    for toks0, toks1 in zip(b0, b1):
        l0, g0 = grad_step(lv, toks0, None)
        l1, g1 = grad_step(lv, toks1, None)
        expect0.append(float(l0))
        expect1.append(float(l1))
        lv = [w - LR * (a + b) / 2 for w, a, b in zip(lv, g0, g1)]

    np.testing.assert_allclose(dist[0], expect0, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(dist[1], expect1, rtol=2e-4, atol=2e-4)


def test_sparse_threshold_learns():
    """threshold=0.05 (per-tensor top-k): loss must fall on both
    workers — sparsification slows but does not break learning."""
    dist = _run_distributed(threshold=0.05, momentum=0.9)
    for widx in (0, 1):
        curve = dist[widx]
        assert min(curve[-3:]) < curve[0], curve
