"""IO/data layer (reference: src/io/, examples/utils.py:39-118).

mnist/fashion-mnist load from IDX files, cifar10 from python-pickle
batches; absent files fall back to the deterministic synthetic dataset
with a LOUD warning (a silently-synthetic "cifar10" run is not a cifar10
run — round-2 missing #6)."""

import os
import pickle

import numpy as np
import pytest

from geomx_tpu.io.datasets import load_data


def _write_cifar_fixture(root, n_train_per_batch=20, n_test=10):
    d = os.path.join(root, "cifar10", "cifar-10-batches-py")
    os.makedirs(d)
    rng = np.random.RandomState(0)
    for name, n in [(f"data_batch_{i}", n_train_per_batch)
                    for i in range(1, 6)] + [("test_batch", n_test)]:
        with open(os.path.join(d, name), "wb") as f:
            pickle.dump({b"data": rng.randint(0, 256, (n, 3072), np.uint8),
                         b"labels": list(rng.randint(0, 10, n))}, f)


def test_cifar10_real_format(tmp_path):
    _write_cifar_fixture(str(tmp_path))
    tr, te, ntr, nte = load_data(10, data_type="cifar10",
                                 root=str(tmp_path))
    assert (ntr, nte) == (100, 10)
    X, y = next(iter(tr))
    assert X.shape == (10, 32, 32, 3)
    assert X.dtype == np.float32 and 0.0 <= X.min() and X.max() <= 1.0
    assert y.dtype == np.int32


def test_cifar10_synthetic_fallback_is_loud(tmp_path, caplog):
    import logging

    from geomx_tpu.io import datasets

    datasets._warned_synthetic.discard("cifar10")
    with caplog.at_level(logging.WARNING, logger="geomx.io"):
        tr, _te, _n, _m = load_data(8, data_type="cifar10",
                                    root=str(tmp_path / "nope"))
    assert any("SYNTHETIC" in r.message for r in caplog.records)
    X, _ = next(iter(tr))
    assert X.shape == (8, 32, 32, 3)   # cifar-shaped synthetic


def test_worker_slicing_partitions_data():
    per = []
    for widx in range(4):
        tr, _te, n, _m = load_data(8, num_workers=4, data_slice_idx=widx,
                                   root="/nonexistent")
        per.append(n)
    assert len(set(per)) == 1  # even split
    with pytest.raises(AssertionError):
        load_data(8, num_workers=2, data_slice_idx=2, root="/nonexistent")


def test_split_by_class_is_non_iid():
    tr, _te, _n, _m = load_data(64, num_workers=2, data_slice_idx=0,
                                split_by_class=True, root="/nonexistent")
    _X, y = next(iter(tr))
    # class-sorted halves: worker 0 sees only the lower classes
    assert len(np.unique(y)) <= 6


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
