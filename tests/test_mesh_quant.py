"""Quantized mesh collectives (GEOMX_MESH_CODEC): ring vs numpy oracle.

The tentpole claim (docs/mesh-party.md, quantized section): moving the
party's intra-mesh all-reduce from the fp32 GSPMD psum onto the
block-scaled ppermute ring changes the BYTES each hop moves, not the
replica coherence — and the device program is auditable bit-for-bit
against a host replay. These tests pin that down on the 8-virtual-device
CPU mesh (tests/conftest.py):

- **oracle bit-exactness**: for every codec the jitted shard_map ring
  must EQUAL a pure-numpy replay of the same schedule — quantize ->
  ppermute -> dequantize -> add per hop, residual slots carried across
  rounds. Exactness is by construction: int8 block scales are powers of
  two (quantize divide and dequant multiply are exact in f32, so LLVM's
  FMA contraction cannot perturb bits), 2-bit moves only {0, +thr, -thr}
  and fp16 narrowing is correctly-rounded — every wire value and every
  partial sum is reproducible on the host operation for operation.
- **"none" is the psum**: the codec-off build of the same collective is
  bitwise the GSPMD psum reference (the PR-8 path, untouched).
- **telemetry**: ring bytes land under ``mesh.bytes{codec=...}``,
  summed by mesh_bytes()/mesh_bytes_by_codec() and invisible to
  wan_bytes() — the WAN gate cannot absorb intra-DC traffic.
- **end-to-end replicas**: both trainers (DeviceResidentTrainer's fused
  step, HierarchicalTrainer's per-key reducers) keep parties
  bit-identical through quantized rounds — the all-gather phase relays
  the owner's codes verbatim, so every rank dequantizes the same bytes.
"""

import numpy as np
import pytest

from geomx_tpu import telemetry
from geomx_tpu.compression import device as dev
from geomx_tpu.parallel import quant_collectives as qc
from geomx_tpu.parallel.mesh import ring_chunk_layout

# -- numpy oracle ----------------------------------------------------------


def _np_quant(codec, e, res, block, thr):
    """Host twin of _HopCodec.quantize: (wire, deq, new_residual)."""
    if codec == "2bit":
        r = (res + e).astype(np.float32)
        t = np.float32(thr)
        pos = r > t
        neg = r < -t
        codes = np.where(pos, 1, np.where(neg, 2, 0)).astype(np.uint8)
        r = np.where(pos, r - t, np.where(neg, r + t, r)).astype(np.float32)
        c = codes.reshape(-1, 4)
        packed = (c[:, 0] | (c[:, 1] << 2) | (c[:, 2] << 4)
                  | (c[:, 3] << 6)).astype(np.uint8)
        return (packed,), _np_deq(codec, (packed,), e.size, block, thr), r
    e = (e + res).astype(np.float32)
    if codec == "int8":
        codes, exps = dev.block_quant_int8_np(e, block)
        deq = dev.block_dequant_int8_np(codes, exps, block)
        return (codes, exps), deq, (e - deq).astype(np.float32)
    if codec == "fp16":
        half = e.astype(np.float16)
        deq = half.astype(np.float32)
        return (half,), deq, (e - deq).astype(np.float32)
    raise AssertionError(codec)


def _np_deq(codec, wire, m, block, thr):
    if codec == "2bit":
        p = wire[0]
        c = np.stack([p & 3, (p >> 2) & 3, (p >> 4) & 3, (p >> 6) & 3],
                     axis=1).reshape(-1)[:m]
        t = np.float32(thr)
        return np.where(c == 1, t, np.where(c == 2, -t, 0.0)
                        ).astype(np.float32)
    if codec == "int8":
        return dev.block_dequant_int8_np(wire[0], wire[1], block)
    if codec == "fp16":
        return wire[0].astype(np.float32)
    raise AssertionError(codec)


def _oracle_round(xs, res, codec, block, thr):
    """Replay ONE quantized ring all-reduce on the host: ``xs`` is the
    (P, n) stack of rank contributions, ``res`` the (P, S, m) residual
    state (mutated to the new state). Returns the (P, n) per-rank
    outputs — which the test asserts are all identical."""
    P, n = xs.shape
    m, padded = ring_chunk_layout(n, P, qc._codec_multiple(codec, block))
    chunks = np.zeros((P, padded), np.float32)
    chunks[:, :n] = xs
    chunks = chunks.reshape(P, P, m)

    send = [chunks[r][r].copy() for r in range(P)]
    for s in range(P - 1):
        q = [_np_quant(codec, send[r], res[r, s], block, thr)
             for r in range(P)]
        for r in range(P):
            res[r, s] = q[r][2]
        # ppermute r -> r+1: rank r receives rank (r-1)'s wire
        for r in range(P):
            deq_rx = _np_deq(codec, q[(r - 1) % P][0], m, block, thr)
            send[r] = (deq_rx + chunks[r][(r - s - 1) % P]
                       ).astype(np.float32)

    out = np.zeros((P, P, m), np.float32)
    q = [_np_quant(codec, send[r], res[r, P - 1], block, thr)
         for r in range(P)]
    cur = [q[r][0] for r in range(P)]
    for r in range(P):
        res[r, P - 1] = q[r][2]
        out[r][(r + 1) % P] = q[r][1]
    for t in range(P - 1):
        cur = [cur[(r - 1) % P] for r in range(P)]
        for r in range(P):
            out[r][(r - t) % P] = _np_deq(codec, cur[r], m, block, thr)
    return out.reshape(P, padded)[:, :n]


def _mesh(size):
    import jax
    from geomx_tpu.parallel.mesh import make_mesh

    devs = jax.devices()
    assert len(devs) >= size, "tests need the 8-device virtual CPU mesh"
    return make_mesh(devs[:size])


# -- oracle bit-exactness --------------------------------------------------


@pytest.mark.mesh
@pytest.mark.parametrize("codec", ["int8", "2bit", "fp16"])
def test_ring_bit_exact_vs_oracle(codec):
    """3 rounds x 4 ranks: the jitted ring == the numpy replay, bit for
    bit, with the error-feedback residual carried across rounds (so a
    drifting residual stream would surface as a round-2+ mismatch)."""
    P, n, block, thr = 4, 1000, 64, 0.5
    mesh = _mesh(P)
    red = qc.QuantRingReducer(mesh, codec, n, block=block, threshold=thr)
    res_np = qc.zero_residual(P, n, codec, block)
    rng = np.random.RandomState(3)
    for rnd in range(3):
        xs = rng.randn(P, n).astype(np.float32)
        got = np.asarray(red.reduce(xs))
        want = _oracle_round(xs, res_np, codec, block, thr)
        # the oracle's ranks must agree with each other (verbatim-relay
        # all-gather) AND with the device ring
        for r in range(1, P):
            np.testing.assert_array_equal(want[r], want[0])
        np.testing.assert_array_equal(
            got, want[0],
            err_msg=f"codec={codec} round={rnd} device ring != oracle")
        np.testing.assert_array_equal(np.asarray(red._res), res_np)


@pytest.mark.mesh
@pytest.mark.parametrize("codec", ["int8", "2bit", "fp16"])
@pytest.mark.parametrize("n", [7, 64, 513])
def test_ring_odd_sizes_bit_exact(codec, n):
    """P=2 with sizes that don't divide the ring (padding + block
    rounding in play) — still bit-exact vs the oracle."""
    P, block, thr = 2, 32, 0.25
    mesh = _mesh(P)
    red = qc.QuantRingReducer(mesh, codec, n, block=block, threshold=thr)
    res_np = qc.zero_residual(P, n, codec, block)
    rng = np.random.RandomState(n)
    xs = rng.randn(P, n).astype(np.float32)
    got = np.asarray(red.reduce(xs))
    want = _oracle_round(xs, res_np, codec, block, thr)
    np.testing.assert_array_equal(got, want[0])


@pytest.mark.mesh
def test_residual_feedback_carries_error():
    """The int8 residual streams are non-trivial (quantization error is
    actually banked, not dropped) and a reset() zeroes them."""
    P, n = 4, 256
    mesh = _mesh(P)
    red = qc.QuantRingReducer(mesh, "int8", n, block=64)
    xs = np.random.RandomState(0).randn(P, n).astype(np.float32)
    red.reduce(xs)
    assert float(np.abs(np.asarray(red._res)).sum()) > 0
    red.reset()
    assert float(np.abs(np.asarray(red._res)).sum()) == 0.0


@pytest.mark.mesh
def test_mean_divides_by_ranks():
    P, n = 4, 64
    mesh = _mesh(P)
    xs = np.random.RandomState(1).randn(P, n).astype(np.float32)
    rs = qc.QuantRingReducer(mesh, "fp16", n)
    rm = qc.QuantRingReducer(mesh, "fp16", n, mean=True)
    np.testing.assert_array_equal(np.asarray(rs.reduce(xs)) / P,
                                  np.asarray(rm.reduce(xs)))


# -- "none" == the PR-8 psum ----------------------------------------------


@pytest.mark.mesh
def test_none_codec_is_psum_bitwise():
    """codec="none" degrades to the plain GSPMD psum — bitwise equal to
    the reference psum program, residual passed through untouched."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from geomx_tpu.compat import shard_map
    from geomx_tpu.parallel.mesh import P as Spec

    P_, n = 4, 333
    mesh = _mesh(P_)
    red = qc.QuantRingReducer(mesh, "none", n)
    xs = np.random.RandomState(2).randn(P_, n).astype(np.float32)
    res0 = np.asarray(red._res).copy()
    got = np.asarray(red.reduce(xs))

    ref_fn = jax.jit(shard_map(
        lambda v: jax.lax.psum(v[0], "dp"), mesh=mesh,
        in_specs=(Spec("dp"),), out_specs=Spec(), check_vma=False))
    ref = np.asarray(ref_fn(jax.device_put(
        jnp.asarray(xs), NamedSharding(mesh, Spec("dp")))))
    np.testing.assert_array_equal(got, ref)
    np.testing.assert_array_equal(np.asarray(red._res), res0)
    assert red.wire_bytes_per_round() == 2 * (P_ - 1) * 4 * n


# -- byte models -----------------------------------------------------------


def test_ring_wire_bytes_hits_compression_gates():
    """The ISSUE's bench gates, from the honest byte model: int8 >=3.5x
    below the fp32 ring, 2bit >=14x (codes + sidecar counted)."""
    n, P = 1 << 16, 4
    fp32 = qc.ring_wire_bytes("none", n, P)
    assert fp32 == 2 * (P - 1) * 4 * n
    assert fp32 / qc.ring_wire_bytes("int8", n, P, block=256) >= 3.5
    assert fp32 / qc.ring_wire_bytes("2bit", n, P) >= 14.0
    assert fp32 / qc.ring_wire_bytes("fp16", n, P) >= 1.9
    assert qc.ring_wire_bytes("int8", n, 1) == 0   # single-rank ring


def test_mesh_wire_bytes_model():
    assert dev.mesh_wire_bytes("none", 1024, 256) == 4096
    assert dev.mesh_wire_bytes("int8", 1024, 256) == 1024 + 4
    assert dev.mesh_wire_bytes("2bit", 1024, 256) == 256 + 4
    assert dev.mesh_wire_bytes("fp16", 1024, 256) == 2048


# -- telemetry: codec label, WAN exclusion ---------------------------------


def test_count_collective_codec_label_and_wan_exclusion():
    """mesh.bytes carries codec= and stays out of wan_bytes(); the
    counted value is the ring's wire model, not the fp32 payload."""
    from types import SimpleNamespace

    from geomx_tpu.kvstore.mesh_party import KVStorePartyMesh, _ring_bytes

    was = telemetry.enabled()
    try:
        telemetry.reset()
        telemetry.enable(True)
        nbytes = 4096 * 4
        for codec in ("none", "int8"):
            shim = SimpleNamespace(mesh_codec=codec, party_size=4,
                                   mesh_block=256)
            KVStorePartyMesh.count_collective(shim, nbytes)
        snap = telemetry.snapshot()
    finally:
        telemetry.reset()
        telemetry.enable(was)

    by_codec = telemetry.mesh_bytes_by_codec(snap)
    assert by_codec["none"] == _ring_bytes(4, nbytes)
    assert by_codec["int8"] == qc.ring_wire_bytes("int8", 4096, 4, 256)
    assert by_codec["int8"] < by_codec["none"] / 3.5
    assert telemetry.mesh_bytes(snap) == sum(by_codec.values())
    assert telemetry.wan_bytes(snap) == 0.0
    for key in snap["counters"]:
        if key.startswith("mesh."):
            assert "tier=mesh" in key


# -- end-to-end: trainers over the quantized mesh --------------------------


BSC_DIM = 8
ROUNDS = 4
_rng = np.random.RandomState(21)
E2E_DATA = _rng.randint(-8, 9, size=(ROUNDS, 2, 2, BSC_DIM)
                        ).astype(np.float32) * 0.25


def _bsc_master_init(kv):
    kv.init(0, np.zeros(BSC_DIM, np.float32))
    kv.wait()


def _bsc_grad_fn(leaves, X, y):
    import jax.numpy as jnp

    w = leaves[0]
    d = w[None, :] - X
    return 0.5 * jnp.mean(jnp.sum(d * d, axis=-1)), [jnp.mean(d, axis=0)]


def _run_device_trainer(codec):
    from geomx_tpu.simulate import InProcessHiPS
    from geomx_tpu.trainer_device import DeviceResidentTrainer

    sim = InProcessHiPS(num_parties=2, workers_per_party=2,
                        party_mesh_size=2,
                        extra_cfg={"mesh_codec": codec,
                                   "mesh_block": 4}).start()
    out = {}
    try:
        def worker(kv):
            p = sim.workers.index(kv)
            assert kv.mesh_codec == codec
            tr = DeviceResidentTrainer(
                [np.zeros(BSC_DIM, np.float32)], kv, _bsc_grad_fn,
                threshold=1.0, learning_rate=0.25)
            assert tr._mesh_quant == (codec != "none")
            for r in range(ROUNDS):
                tr.step(E2E_DATA[r, p].reshape(2, BSC_DIM), None)
            out[p] = np.array(tr.leaves[0])

        sim.run_workers(worker, include_master=_bsc_master_init,
                        timeout=300)
    finally:
        sim.stop()
    return out


@pytest.mark.mesh
def test_device_trainer_int8_replicas_identical():
    """DeviceResidentTrainer with the int8 ring fused into its jitted
    step: both parties end on the SAME bits (verbatim-relay all-gather
    keeps every rank's dequantized aggregate identical), and the
    quantized run's weights track the unquantized run."""
    mesh = _run_device_trainer("int8")
    np.testing.assert_array_equal(mesh[0], mesh[1])
    assert np.any(mesh[0] != 0)
    none = _run_device_trainer("none")
    np.testing.assert_array_equal(none[0], none[1])
    # block-scaled int8 with error feedback stays close to fp32
    assert float(np.max(np.abs(mesh[0] - none[0]))) < 0.05


@pytest.mark.mesh
def test_hierarchical_trainer_int8_parties_identical():
    """HierarchicalTrainer routes per-key grads through the store's
    ring reducers (kv.ring_reducer) instead of the XLA psum; parties
    stay bit-identical and the loss still falls."""
    import jax
    import jax.numpy as jnp
    import optax

    from geomx_tpu.models import MLP
    from geomx_tpu.optimizer import SGD
    from geomx_tpu.parallel.train_step import (DataParallelTrainer,
                                               HierarchicalTrainer)
    from geomx_tpu.simulate import InProcessHiPS

    def master_init(kv):
        model = MLP(features=(16, 4))
        params = model.init(jax.random.PRNGKey(42),
                            jnp.zeros((1, 8), jnp.float32))
        for i, leaf in enumerate(jax.tree_util.tree_leaves(params)):
            kv.init(i, np.asarray(leaf))
        kv.wait()

    sim = InProcessHiPS(num_parties=2, workers_per_party=2,
                        party_mesh_size=2,
                        extra_cfg={"mesh_codec": "int8",
                                   "mesh_block": 8}).start()
    out = {}
    try:
        sim.master.set_optimizer(SGD(learning_rate=0.1))

        def worker(kv):
            p = sim.workers.index(kv)
            model = MLP(features=(16, 4))
            dp = DataParallelTrainer(model, optax.sgd(0.1), kv.mesh,
                                     jnp.zeros((1, 8), jnp.float32),
                                     num_classes=4)
            ht = HierarchicalTrainer(dp, kv)
            ht.init_on_kvstore()
            rng = np.random.RandomState(0)
            X = rng.randn(8, 8).astype(np.float32)
            y = rng.randint(0, 4, (8,))
            losses = [ht.step(X, y) for _ in range(3)]
            leaves = jax.tree_util.tree_leaves(ht.t.params)
            out[p] = (np.concatenate([np.asarray(l).ravel()
                                      for l in leaves]), losses)

        sim.run_workers(worker, include_master=master_init, timeout=300)
    finally:
        sim.stop()

    w0, l0 = out[0]
    w1, _l1 = out[1]
    np.testing.assert_array_equal(w0, w1)
    assert l0[-1] < l0[0]
