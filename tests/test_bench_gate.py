"""The bench accuracy-parity gate (round-3 verdict item 2).

A throughput headline at broken accuracy must not publish: bench.main()
zeroes the headline, attaches ``parity_failed``, and exits nonzero.
"""

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench"] = mod
    spec.loader.exec_module(mod)
    return mod


def test_round3_regression_would_have_failed():
    bench = _load_bench()
    # the recorded r03 run: nokv 1.0, hips 1.0, bsc 0.9668
    fails = bench.parity_violations(1.0, 1.0, 0.9668)
    assert [f["config"] for f in fails] == ["hips_bsc_cnn"]
    assert fails[0]["tol"] == bench.PARITY_TOL_BSC


def test_within_tolerance_passes():
    bench = _load_bench()
    assert bench.parity_violations(1.0, 0.99, 0.985) == []
    # better-than-baseline never fails
    assert bench.parity_violations(0.9, 1.0, 1.0) == []


def test_fsa_breakage_named():
    bench = _load_bench()
    fails = bench.parity_violations(1.0, 0.5, 1.0)
    assert [f["config"] for f in fails] == ["hips_cnn"]


def test_bsc_compares_iteration_matched_baseline():
    """The BSC probe runs longer than the dense probes; its baseline
    must be the nokv accuracy at the SAME iteration count."""
    bench = _load_bench()
    # nokv@100 = 0.95, nokv@200 = 1.0: bsc 0.975 fails vs the
    # 200-iter baseline even though it beats the 100-iter one
    fails = bench.parity_violations(0.95, 0.95, 0.975, nokv_acc_long=1.0)
    assert [f["config"] for f in fails] == ["hips_bsc_cnn"]
    assert fails[0]["baseline"] == 1.0
    # and passes when within tolerance of the matched baseline
    assert bench.parity_violations(0.95, 0.95, 0.985,
                                   nokv_acc_long=1.0) == []


def test_hfa_below_gate_fails():
    """Round-4 verdict item 6: HFA carries an accuracy gate too."""
    bench = _load_bench()
    fails = bench.parity_violations(1.0, 1.0, 1.0, hfa_acc=0.9)
    assert [f["config"] for f in fails] == ["hips_hfa_cnn"]
    assert fails[0]["tol"] == bench.PARITY_TOL_HFA


def test_hfa_within_gate_passes():
    bench = _load_bench()
    assert bench.parity_violations(1.0, 1.0, 1.0, hfa_acc=0.985) == []
    # absent probe (old capture) does not gate
    assert bench.parity_violations(1.0, 1.0, 1.0, hfa_acc=None) == []


def test_bsc_line_carries_wan_bytes_per_round():
    """The canonical JSON line must surface the WAN-bytes figure when
    the BSC phase measured one (the number ROADMAP item 2 gates on; the
    value itself is cross-checked against the per-verb telemetry
    counters in tests/test_telemetry.py)."""
    bench = _load_bench()
    bsc = {"img_s": 10.0, "acc": 0.99, "threshold": 0.02,
           "trials": [1.0], "wan_bytes_per_round": 12345.6}
    result, _ = bench._assemble({"hips_bsc": bsc})
    assert result["details"]["hips_bsc_cnn"]["wan_bytes_per_round"] \
        == 12345.6
    # an old capture without the figure stays schema-stable
    del bsc["wan_bytes_per_round"]
    result, _ = bench._assemble({"hips_bsc": bsc})
    assert "wan_bytes_per_round" not in result["details"]["hips_bsc_cnn"]
