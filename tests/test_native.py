"""Native (C++) transport core tests.

The native core (native/transport.cc) replaces the Python van's socket
layer the way ZMQVan underlies ps-lite's Van in the reference
(3rdparty/ps-lite/src/zmq_van.h:41-516). Both backends speak the identical
wire format, so a topology may mix native and pure-Python nodes — the
mixed-tier test below proves it.
"""

import threading

import numpy as np
import pytest

from geomx_tpu.ps import base, native
from geomx_tpu.ps.kv_app import KVPairs, KVServer, KVWorker
from geomx_tpu.ps.message import Message, Meta, Node, Role

from test_transport import free_port, make_tier, shutdown

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native transport not buildable")


def test_build_and_bind():
    t = native.NativeTransport("127.0.0.1", 0)
    assert t.port > 0
    t.close()


def test_frame_roundtrip_and_order():
    a = native.NativeTransport("127.0.0.1", 0)
    b = native.NativeTransport("127.0.0.1", 0)
    try:
        a.set_route(7, "127.0.0.1", b.port)
        frames = []
        for i in range(50):
            m = Message(Meta(sender=1, recver=7, timestamp=i))
            m.add_array(np.full((16,), float(i), dtype=np.float32))
            buf = m.pack()
            frames.append(buf)
            a.send(7, buf)
        for i in range(50):
            got = b.recv(timeout_s=5.0)
            assert got == frames[i]  # byte-exact, in order
            m = Message.unpack(got)
            assert m.meta.timestamp == i
            np.testing.assert_allclose(m.get_array(0), float(i))
        assert a.send_bytes == sum(len(f) for f in frames)
        assert b.recv_bytes == a.send_bytes
    finally:
        a.close()
        b.close()


def test_recv_timeout_and_stop():
    t = native.NativeTransport("127.0.0.1", 0)
    assert t.recv(timeout_s=0.05) is None
    t.stop()
    with pytest.raises(ConnectionAbortedError):
        t.recv(timeout_s=1.0)
    t.close()


def test_send_no_route():
    t = native.NativeTransport("127.0.0.1", 0)
    with pytest.raises(OSError, match="no route"):
        t.send(42, b"x")
    t.close()


def test_route_change_evicts_connection():
    """Re-pointing an id at a new address must reach the new peer."""
    a = native.NativeTransport("127.0.0.1", 0)
    b1 = native.NativeTransport("127.0.0.1", 0)
    b2 = native.NativeTransport("127.0.0.1", 0)
    try:
        msg = Message(Meta(recver=5)).pack()
        a.set_route(5, "127.0.0.1", b1.port)
        a.send(5, msg)
        assert b1.recv(timeout_s=5.0) == msg
        # peer "recovers" at a new port
        a.set_route(5, "127.0.0.1", b2.port)
        a.send(5, msg)
        assert b2.recv(timeout_s=5.0) == msg
        assert b1.recv(timeout_s=0.1) is None
    finally:
        a.close()
        b1.close()
        b2.close()


def test_send_to_addr_oneshot():
    a = native.NativeTransport("127.0.0.1", 0)
    b = native.NativeTransport("127.0.0.1", 0)
    try:
        msg = Message(Meta(recver=1, control_cmd=2,
                           nodes=[Node(role=Role.WORKER, port=1234)])).pack()
        a.send_to_addr("127.0.0.1", b.port, msg)
        assert b.recv(timeout_s=5.0) == msg
    finally:
        a.close()
        b.close()


def test_redial_after_peer_restart():
    """A cached connection to a dead peer is evicted and redialed."""
    a = native.NativeTransport("127.0.0.1", 0)
    b = native.NativeTransport("127.0.0.1", 0)
    port = b.port
    msg = Message(Meta(recver=5)).pack()
    try:
        a.set_route(5, "127.0.0.1", port)
        a.send(5, msg)
        assert b.recv(timeout_s=5.0) == msg
        b.close()
        # peer restarts on the same port
        b = native.NativeTransport("127.0.0.1", port)
        # first send may fail (stale fd detected mid-send) — the van layer
        # retries; at most two attempts needed
        for _ in range(3):
            try:
                a.send(5, msg)
                break
            except OSError:
                pass
        assert b.recv(timeout_s=5.0) == msg
    finally:
        a.close()
        b.close()


def test_native_tier_push_pull():
    """Full rendezvous + KV push/pull over the native backend (default-on)."""
    sched, servers, workers = make_tier(num_workers=2, num_servers=1)
    store = {}
    try:
        assert sched.van._native is not None, "native backend not engaged"
        server = KVServer(servers[0])

        def handle(req, kvs, srv):
            if req.push:
                for k, v in zip(kvs.keys, kvs.vals):
                    store[k] = store.get(k, 0) + v
                srv.response(req)
            elif req.pull:
                srv.response(req, KVPairs(
                    keys=kvs.keys, vals=[store[k] for k in kvs.keys]))

        server.set_request_handle(handle)
        w0, w1 = KVWorker(workers[0]), KVWorker(workers[1])
        v = np.ones((4, 3), dtype=np.float32)
        ts0 = w0.push(KVPairs(keys=[7], vals=[v]), server_rank=0)
        ts1 = w1.push(KVPairs(keys=[7], vals=[2 * v]), server_rank=0)
        w0.wait(ts0, 10)
        w1.wait(ts1, 10)
        ts = w0.pull([7], server_rank=0)
        w0.wait(ts, 10)
        (resp,) = w0.take_response(ts)
        np.testing.assert_allclose(resp.vals[0], 3 * v)
    finally:
        shutdown(sched, *servers, *workers)


def test_mixed_backend_tier_interop():
    """Native and pure-Python nodes interoperate in one tier."""
    import geomx_tpu.ps.postoffice as postoffice_mod

    port = free_port()
    kw = dict(is_global=False, root_uri="127.0.0.1", root_port=port,
              num_workers=2, num_servers=1)
    sched = postoffice_mod.Postoffice(my_role=Role.SCHEDULER, **kw)
    server = postoffice_mod.Postoffice(my_role=Role.SERVER, **kw)
    w_native = postoffice_mod.Postoffice(my_role=Role.WORKER, **kw)
    w_python = postoffice_mod.Postoffice(my_role=Role.WORKER, **kw)
    # force one worker (and the server) onto the pure-Python backend
    server.van.use_native = False
    w_python.van.use_native = False
    threads = []
    for po in (sched, server, w_native, w_python):
        t = threading.Thread(target=po.start, daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(20)
    store = {}
    try:
        assert w_native.van._native is not None
        assert w_python.van._native is None and server.van._native is None
        srv = KVServer(server)

        def handle(req, kvs, s):
            if req.push:
                for k, v in zip(kvs.keys, kvs.vals):
                    store[k] = store.get(k, 0) + v
                s.response(req)
            elif req.pull:
                s.response(req, KVPairs(
                    keys=kvs.keys, vals=[store[k] for k in kvs.keys]))

        srv.set_request_handle(handle)
        a, b = KVWorker(w_native), KVWorker(w_python)
        v = np.arange(12, dtype=np.float32).reshape(3, 4)
        ta = a.push(KVPairs(keys=[1], vals=[v]), server_rank=0)
        tb = b.push(KVPairs(keys=[1], vals=[v]), server_rank=0)
        a.wait(ta, 10)
        b.wait(tb, 10)
        ts = b.pull([1], server_rank=0)
        b.wait(ts, 10)
        (resp,) = b.take_response(ts)
        np.testing.assert_allclose(resp.vals[0], 2 * v)
    finally:
        shutdown(sched, server, w_native, w_python)


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
