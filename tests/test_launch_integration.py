"""Launch-path integration test: the REAL multi-process topology.

Spawns the full 12-process, 3-party HiPS demo through the same chain a
user runs — ``scripts/run_vanilla_hips.sh`` → ``hips_env.sh`` env-var
wiring → ``import geomx_tpu`` bootstrap for infra roles →
``examples/cnn.py`` workers — and asserts the observable correctness
signal the reference uses (climbing test accuracy on the foreground
worker, reference: scripts/cpu/run_vanilla_hips.sh:8-148 + cnn.py:129).

This covers exactly the path in-process tests cannot: env-var config
parsing, the import-time server bootstrap (kvstore_server.py), process
isolation, and clean exit cascades. The round-1 startup-deadlock
regression shipped through this path while every in-process test stayed
green.
"""

import os
import re
import signal
import subprocess
import sys
import time

import pytest

from geomx_tpu.simulate import free_port as _free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launch(script: str, extra_args, n_iters: int, timeout: float,
                expect_lines: int = 0):
    env = dict(os.environ)
    env.update({
        "GPORT": str(_free_port()), "CPORT": str(_free_port()),
        "APORT": str(_free_port()), "BPORT": str(_free_port()),
        "JAX_PLATFORMS": "cpu",
        "PYTHON": sys.executable,
        # don't inherit the conftest's 8-device virtual mesh into 12
        # separate processes
        "XLA_FLAGS": "",
    })
    proc = subprocess.Popen(
        ["bash", os.path.join(REPO, "scripts", script),
         "--max-iters", str(n_iters), *extra_args],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, start_new_session=True,
    )
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        out, _ = proc.communicate()
        pytest.fail(f"launch timed out; output:\n{out[-4000:]}")

    assert proc.returncode == 0, f"launch failed:\n{out[-4000:]}"
    accs = [float(m) for m in re.findall(r"Test Acc (\d+\.\d+)", out)]
    expect = expect_lines or n_iters
    assert len(accs) == expect, \
        f"expected {expect} iteration lines, got:\n{out[-4000:]}"

    # clean exits: every background process of the group must terminate
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            os.killpg(proc.pid, 0)
        except ProcessLookupError:
            break  # whole group gone
        time.sleep(0.5)
    else:
        os.killpg(proc.pid, signal.SIGKILL)
        pytest.fail("background topology processes did not exit cleanly")
    return accs


def test_vanilla_hips_subprocess_topology():
    accs = _run_launch("run_vanilla_hips.sh", [], n_iters=15, timeout=240)
    # the correctness signal: training must actually learn (random = 0.1)
    assert max(accs[-5:]) > 0.4, f"accuracy did not climb: {accs}"
    assert max(accs[-5:]) > accs[0], f"accuracy did not improve: {accs}"


def test_bsc_subprocess_topology():
    """The BASELINE headline config through the REAL launch chain:
    cnn_bsc.py (aggregator PS, worker-side Adam, BSC both directions).

    Assertion calibration: sparse-top-k trajectories are chaotically
    run-to-run variable (near-tie index selections flip on float
    summation order), so a fixed-iteration accuracy bar flakes.
    What this test exists to catch is (a) the launch machinery — boot,
    N iterations, clean exit cascade — and (b) the frozen-training
    regression mode where pulls return nothing and accuracy pins at
    chance (~0.097) for the whole run. Measured over 5 calibration
    runs, every healthy run peaked >= 0.20 by iter 40 while the frozen
    mode never left 0.097."""
    accs = _run_launch("run_bsc.sh", ["-cr", "0.2"], n_iters=40,
                       timeout=360)
    # late-window bars so a mid-run freeze is caught too
    assert max(accs[-10:]) > 0.15, \
        f"BSC training frozen at chance: {accs}"
    assert len(set(accs[-20:])) > 3, f"accuracy never moved: {accs}"



def test_mixed_sync_subprocess_topology():
    """MixedSync (dist_async: per-push global updates, no global
    barrier) through the real launch chain. Deterministic across runs
    (two calibration trials produced identical curves)."""
    accs = _run_launch("run_mixed_sync.sh", [], n_iters=15, timeout=240)
    assert max(accs[-5:]) > 0.3, f"MixedSync did not learn: {accs}"
    assert max(accs[-5:]) > accs[0], f"no improvement: {accs}"


def test_hfa_subprocess_topology():
    """HFA (K1 local steps per LAN sync, K2-periodic WAN rounds)
    through the real launch chain; prints every K1=2 iterations.
    Deterministic (two calibration trials identical: 0.7471 @ 20)."""
    accs = _run_launch("run_hfa.sh", [], n_iters=20, timeout=240,
                       expect_lines=10)
    assert max(accs[-4:]) > 0.5, f"HFA did not learn: {accs}"


def test_fp16_subprocess_topology():
    """FP16 wire transmission through the real launch chain
    (deterministic: calibration trials identical, 0.6934 @ 15)."""
    accs = _run_launch("run_fp16.sh", [], n_iters=15, timeout=240)
    assert max(accs[-5:]) > 0.5, f"FP16 did not learn: {accs}"


def test_mpq_subprocess_topology():
    """MPQ (size-threshold fp16/bsc routing) through the real launch
    chain (near-deterministic: 0.775-0.782 @ 25 across trials; the BSC
    component adds slight variance)."""
    accs = _run_launch("run_mpq.sh", [], n_iters=25, timeout=300)
    assert max(accs[-8:]) > 0.5, f"MPQ did not learn: {accs}"


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-x", "-q"]))
