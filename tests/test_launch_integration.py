"""Launch-path integration test: the REAL multi-process topology.

Spawns the full 12-process, 3-party HiPS demo through the same chain a
user runs — ``scripts/run_vanilla_hips.sh`` → ``hips_env.sh`` env-var
wiring → ``import geomx_tpu`` bootstrap for infra roles →
``examples/cnn.py`` workers — and asserts the observable correctness
signal the reference uses (climbing test accuracy on the foreground
worker, reference: scripts/cpu/run_vanilla_hips.sh:8-148 + cnn.py:129).

This covers exactly the path in-process tests cannot: env-var config
parsing, the import-time server bootstrap (kvstore_server.py), process
isolation, and clean exit cascades. The round-1 startup-deadlock
regression shipped through this path while every in-process test stayed
green.
"""

import os
import re
import signal
import subprocess
import sys
import time

import pytest

from geomx_tpu.simulate import free_port as _free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launch(script: str, extra_args, n_iters: int, timeout: float,
                expect_lines: int = 0, env_extra=None,
                pattern: str = r"Test Acc (\d+\.\d+)",
                pass_max_iters: bool = True):
    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    env.update({
        "GPORT": str(_free_port()), "CPORT": str(_free_port()),
        "APORT": str(_free_port()), "BPORT": str(_free_port()),
        "JAX_PLATFORMS": "cpu",
        "PYTHON": sys.executable,
        # don't inherit the conftest's 8-device virtual mesh into 12
        # separate processes
        "XLA_FLAGS": "",
    })
    argv = ["bash", os.path.join(REPO, "scripts", script)]
    if pass_max_iters:
        argv += ["--max-iters", str(n_iters)]
    proc = subprocess.Popen(
        [*argv, *extra_args],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, start_new_session=True,
    )
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        out, _ = proc.communicate()
        pytest.fail(f"launch timed out; output:\n{out[-4000:]}")

    assert proc.returncode == 0, f"launch failed:\n{out[-4000:]}"
    accs = [float(m) for m in re.findall(pattern, out)]
    expect = expect_lines or n_iters
    assert len(accs) == expect, \
        f"expected {expect} iteration lines, got:\n{out[-4000:]}"

    # clean exits: every background process of the group must terminate
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            os.killpg(proc.pid, 0)
        except ProcessLookupError:
            break  # whole group gone
        time.sleep(0.5)
    else:
        os.killpg(proc.pid, signal.SIGKILL)
        pytest.fail("background topology processes did not exit cleanly")
    return accs


@pytest.mark.slow
def test_vanilla_hips_subprocess_topology():
    accs = _run_launch("run_vanilla_hips.sh", [], n_iters=15, timeout=240)
    # the correctness signal: training must actually learn (random = 0.1)
    assert max(accs[-5:]) > 0.4, f"accuracy did not climb: {accs}"
    assert max(accs[-5:]) > accs[0], f"accuracy did not improve: {accs}"


@pytest.mark.slow
def test_bsc_subprocess_topology():
    """The BASELINE headline config through the REAL launch chain:
    cnn_bsc.py (aggregator PS, worker-side Adam, BSC both directions).

    Assertion calibration: sparse-top-k trajectories are chaotically
    run-to-run variable (near-tie index selections flip on float
    summation order), so a fixed-iteration accuracy bar flakes.
    What this test exists to catch is (a) the launch machinery — boot,
    N iterations, clean exit cascade — and (b) the frozen-training
    regression mode where pulls return nothing and accuracy pins at
    chance (~0.097) for the whole run. Measured over 5 calibration
    runs, every healthy run peaked >= 0.20 by iter 40 while the frozen
    mode never left 0.097."""
    accs = _run_launch("run_bsc.sh", ["-cr", "0.2"], n_iters=40,
                       timeout=360)
    # late-window bars so a mid-run freeze is caught too
    assert max(accs[-10:]) > 0.15, \
        f"BSC training frozen at chance: {accs}"
    assert len(set(accs[-20:])) > 3, f"accuracy never moved: {accs}"



@pytest.mark.slow
def test_mixed_sync_subprocess_topology():
    """MixedSync (dist_async: per-push global updates, no global
    barrier) through the real launch chain. Deterministic across runs
    (two calibration trials produced identical curves)."""
    accs = _run_launch("run_mixed_sync.sh", [], n_iters=15, timeout=240)
    assert max(accs[-5:]) > 0.3, f"MixedSync did not learn: {accs}"
    assert max(accs[-5:]) > accs[0], f"no improvement: {accs}"


@pytest.mark.slow
def test_hfa_subprocess_topology():
    """HFA (K1 local steps per LAN sync, K2-periodic WAN rounds)
    through the real launch chain; prints every K1=2 iterations.
    Deterministic (two calibration trials identical: 0.7471 @ 20)."""
    accs = _run_launch("run_hfa.sh", [], n_iters=20, timeout=240,
                       expect_lines=10)
    assert max(accs[-4:]) > 0.5, f"HFA did not learn: {accs}"


@pytest.mark.slow
def test_fp16_subprocess_topology():
    """FP16 wire transmission through the real launch chain
    (deterministic: calibration trials identical, 0.6934 @ 15)."""
    accs = _run_launch("run_fp16.sh", [], n_iters=15, timeout=240)
    assert max(accs[-5:]) > 0.5, f"FP16 did not learn: {accs}"


@pytest.mark.slow
def test_mpq_subprocess_topology():
    """MPQ (size-threshold fp16/bsc routing) through the real launch
    chain (near-deterministic: 0.775-0.782 @ 25 across trials; the BSC
    component adds slight variance)."""
    accs = _run_launch("run_mpq.sh", [], n_iters=25, timeout=300)
    assert max(accs[-8:]) > 0.5, f"MPQ did not learn: {accs}"


# ---------------------------------------------------------------------------
# round-4: the remaining 6 feature scripts (round-3 verdict item 5 —
# DGT, P3, TS pair, MultiGPS, DCASGD had only in-process coverage; the
# round-1 regression shipped through exactly this untested env-var ->
# bootstrap -> subprocess glue). Marked slow: the default CI tier runs
# `pytest -m "not slow"`; these belong to the nightly/full tier.
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_p3_subprocess_topology():
    """P3 priority scheduling (ENABLE_P3=1: bigarray-granularity key
    slicing + priority send queues) through the real launch chain."""
    accs = _run_launch("run_p3.sh", [], n_iters=15, timeout=300)
    assert max(accs[-5:]) > 0.4, f"P3 did not learn: {accs}"
    assert max(accs[-5:]) > accs[0], f"no improvement: {accs}"


@pytest.mark.slow
def test_multi_gps_subprocess_topology():
    """MultiGPS (DMLC_NUM_GLOBAL_SERVER=2): 13 processes, keys shard
    across two global servers by the canonical heuristic."""
    accs = _run_launch("run_multi_gps.sh", [], n_iters=15, timeout=300)
    assert max(accs[-5:]) > 0.4, f"MultiGPS did not learn: {accs}"
    assert max(accs[-5:]) > accs[0], f"no improvement: {accs}"


@pytest.mark.slow
def test_dcasgd_subprocess_topology():
    """DCASGD (dist_async + delay compensation at the global tier)
    through the real launch chain. Async trajectories are noisy —
    the bar is leaving chance decisively, not a fixed curve."""
    accs = _run_launch("run_dcasgd.sh", ["-lr", "0.05"], n_iters=60,
                       timeout=420)
    assert max(accs) > 0.3, f"DCASGD did not learn: {accs}"
    assert len(set(accs[-20:])) > 3, f"accuracy never moved: {accs}"


@pytest.mark.slow
def test_dgt_udp_subprocess_topology():
    """DGT mode 1: unimportant gradient blocks ride lossy UDP channels
    on the inter-DC tier (ENABLE_DGT=1, DMLC_UDP_CHANNEL_NUM=3)."""
    accs = _run_launch("run_dgt.sh", [], n_iters=20, timeout=300,
                       env_extra={"ENABLE_DGT": "1"})
    assert max(accs[-5:]) > 0.3, f"DGT/UDP did not learn: {accs}"


@pytest.mark.slow
def test_dgt_quantized_subprocess_topology():
    """DGT mode 3: unimportant blocks 4-bit quantized over TCP."""
    accs = _run_launch("run_dgt.sh", [], n_iters=20, timeout=300,
                       env_extra={"ENABLE_DGT": "3"})
    assert max(accs[-5:]) > 0.3, f"DGT/quantized did not learn: {accs}"


@pytest.mark.slow
def test_intra_ts_subprocess_topology():
    """Intra-DC TSEngine: worker-to-worker merge overlays built by the
    party scheduler (ENABLE_INTRA_TS=1)."""
    accs = _run_launch("run_intra_ts.sh", [], n_iters=15, timeout=300)
    assert max(accs[-5:]) > 0.3, f"intra-TS did not learn: {accs}"


@pytest.mark.slow
def test_inter_ts_subprocess_topology():
    """Inter-DC TSEngine: party-to-party aggregate merge on the WAN
    tier (ENABLE_INTER_TS=1)."""
    accs = _run_launch("run_inter_ts.sh", [], n_iters=15, timeout=300)
    assert max(accs[-5:]) > 0.3, f"inter-TS did not learn: {accs}"


@pytest.mark.slow
def test_transformer_bsc_subprocess_topology():
    """The round-4 flagship: a transformer through the device-resident
    BSC trainer (element-sparse wire) in the real 12-process topology.
    Small dims keep the 12 jax compiles tractable; the loss lines are
    the learning signal (transformer_bsc_device.py prints Loss, not
    Test Acc)."""
    losses = _run_launch(
        "run_transformer_bsc.sh",
        ["--cpu", "--dim", "64", "--depth", "2", "--heads", "4",
         "--vocab", "256", "--seq-len", "64", "-bs", "4"],
        n_iters=12, timeout=360, pattern=r"Loss (\d+\.\d+)")
    assert min(losses[-6:]) < losses[0], f"no learning: {losses}"


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-x", "-q"]))


@pytest.mark.slow
def test_esync_subprocess_topology():
    """ESync (beyond parity: reference README.md:45 documents it, ships
    no code) through the real launch chain: per-party state server
    assigns local step counts, synchronous model averaging. Uniform
    hosts here, so the signal is boot + learn + clean exit; the
    heterogeneity balancing itself is asserted in tests/test_esync.py."""
    accs = _run_launch("run_esync.sh", ["-r", "25", "-lr", "0.01"],
                       n_iters=0, timeout=240, expect_lines=1,
                       pattern=r"final acc=(\d+\.\d+)",
                       pass_max_iters=False)
    # calibration: the same config in-process reaches 0.73 @ 25 rounds
    assert accs[0] > 0.5, f"ESync did not learn: {accs}"
