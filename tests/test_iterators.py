"""Data iterator family + RecordIO (reference: src/io/, mx.io, mx.recordio)."""

import numpy as np
import pytest

from geomx_tpu.io import (
    CSVIter, ImageRecordIter, IRHeader, LibSVMIter, MXRecordIO,
    NDArrayIter, PrefetchIter, pack, pack_array, unpack, unpack_array)


def _data(n=10):
    return np.arange(n * 4, dtype=np.float32).reshape(n, 4), \
        np.arange(n, dtype=np.int32)


def test_ndarray_iter_pad_wraps_head():
    X, y = _data(10)
    batches = list(NDArrayIter(X, y, batch_size=4, last_batch_handle="pad"))
    assert len(batches) == 3
    assert all(b[0].shape == (4, 4) for b in batches)
    # tail batch = samples 8,9 + head samples 0,1
    np.testing.assert_array_equal(batches[2][1], [8, 9, 0, 1])


def test_ndarray_iter_discard():
    X, y = _data(10)
    it = NDArrayIter(X, y, batch_size=4, last_batch_handle="discard")
    assert len(list(it)) == 2 and len(it) == 2


def test_ndarray_iter_roll_over_carries_tail():
    X, y = _data(10)
    it = NDArrayIter(X, y, batch_size=4, last_batch_handle="roll_over")
    assert len(list(it)) == 2
    # epoch 2 starts with the carried samples 8, 9
    epoch2 = list(it)
    np.testing.assert_array_equal(epoch2[0][1][:2], [8, 9])
    it.reset()
    assert len(list(it)) == 2  # reset drops the carry


def test_ndarray_iter_shuffle_covers_all():
    X, y = _data(8)
    it = NDArrayIter(X, y, batch_size=4, shuffle=True, seed=1)
    seen = np.concatenate([b[1] for b in it])
    assert sorted(seen.tolist()) == list(range(8))


def test_csv_iter(tmp_path):
    X, y = _data(6)
    data_csv = tmp_path / "d.csv"
    label_csv = tmp_path / "l.csv"
    np.savetxt(data_csv, X, delimiter=",")
    np.savetxt(label_csv, y, delimiter=",")
    it = CSVIter(str(data_csv), data_shape=(2, 2), batch_size=3,
                 label_csv=str(label_csv))
    batches = list(it)
    assert batches[0][0].shape == (3, 2, 2)
    np.testing.assert_allclose(
        np.concatenate([b[0] for b in batches]).reshape(6, 4), X)
    with pytest.raises(ValueError, match="row width"):
        CSVIter(str(data_csv), data_shape=(3,), batch_size=2)


def test_libsvm_iter(tmp_path):
    p = tmp_path / "d.svm"
    p.write_text("1 0:1.5 3:2.0\n0 1:7.0\n1 2:1.0 0:4.0\n")
    it = LibSVMIter(str(p), data_shape=(4,), batch_size=2)
    X, y = next(iter(it))
    np.testing.assert_allclose(X, [[1.5, 0, 0, 2.0], [0, 7.0, 0, 0]])
    np.testing.assert_allclose(y, [1, 0])
    bad = tmp_path / "bad.svm"
    bad.write_text("1 9:1.0\n")
    with pytest.raises(ValueError, match="out of range"):
        LibSVMIter(str(bad), data_shape=(4,), batch_size=1)


def test_prefetch_iter_same_sequence_and_errors():
    X, y = _data(12)
    base = NDArrayIter(X, y, batch_size=4)
    pre = PrefetchIter(NDArrayIter(X, y, batch_size=4), prefetch=3)
    for (a, la), (b, lb) in zip(base, pre):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(la, lb)

    class Boom:
        def __iter__(self):
            yield _data(1)
            raise RuntimeError("producer died")

    with pytest.raises(RuntimeError, match="producer died"):
        list(PrefetchIter(Boom()))


def test_recordio_round_trip(tmp_path):
    p = tmp_path / "x.rec"
    payloads = [b"alpha", b"bb", b"", b"0123456789" * 100]
    with MXRecordIO(str(p), "w") as w:
        for b in payloads:
            w.write(b)
    with MXRecordIO(str(p), "r") as r:
        got = []
        while True:
            item = r.read()
            if item is None:
                break
            got.append(item)
    assert got == payloads


def test_recordio_header_pack_scalar_and_vector():
    h = IRHeader(0, 3.0, 7, 0)
    rec = pack(h, b"payload")
    h2, body = unpack(rec)
    assert h2.label == 3.0 and h2.id == 7 and body == b"payload"
    hv = IRHeader(0, np.array([1.0, 2.0, 3.0], np.float32), 9, 0)
    h3, body3 = unpack(pack(hv, b"zz"))
    np.testing.assert_allclose(h3.label, [1.0, 2.0, 3.0])
    assert body3 == b"zz"


def test_image_record_iter(tmp_path):
    p = tmp_path / "imgs.rec"
    shape = (4, 4, 3)
    rng = np.random.RandomState(0)
    imgs = [rng.randint(0, 256, shape, np.uint8) for _ in range(5)]
    with MXRecordIO(str(p), "w") as w:
        for i, img in enumerate(imgs):
            w.write(pack_array(IRHeader(0, float(i % 2), i, 0), img))
    it = ImageRecordIter(str(p), data_shape=shape, batch_size=2)
    batches = list(it)
    assert len(batches) == 3  # 5 samples, tail padded from head
    X0, y0 = batches[0]
    assert X0.shape == (2, 4, 4, 3) and X0.dtype == np.float32
    np.testing.assert_allclose(X0[0], imgs[0].astype(np.float32) / 255.0)
    np.testing.assert_allclose(y0, [0.0, 1.0])


def test_recordio_rejects_corrupt_magic(tmp_path):
    p = tmp_path / "bad.rec"
    p.write_bytes(b"\x00" * 16)
    with MXRecordIO(str(p), "r") as r:
        with pytest.raises(IOError, match="magic"):
            r.read()


def test_prefetch_iter_early_exit_releases_producer():
    """ADVICE r3: breaking out of a PrefetchIter must not strand the
    producer thread on a full queue; a subsequent reset+re-iteration must
    see the full sequence again."""
    import threading

    X, y = _data(64)
    pre = PrefetchIter(NDArrayIter(X, y, batch_size=4), prefetch=1)
    it = iter(pre)
    next(it)  # consume one batch, abandon the rest
    it.close()
    # producer must have exited (close() joins with a 5 s timeout)
    assert not any(t.name == "geomx-prefetch"
                   for t in threading.enumerate())
    pre.reset()
    assert len(list(pre)) == 16


def test_pack_scalar_label_forces_flag_zero():
    """ADVICE r3: a caller-constructed IRHeader with flag>0 and a scalar
    label must not claim extra float32 labels in the written record."""
    body = pack(IRHeader(flag=3, label=1.0, id=7, id2=0), b"payload")
    header, payload = unpack(body)
    assert header.flag == 0
    assert header.label == 1.0
    assert payload == b"payload"
