"""Self-tuning transport controller (kvstore/controller.py): the pure
decision step (bootstrap / noise-floor hysteresis / sustained squeeze /
detector bypass), the live slice-budget source and its clamp edges,
plan plumbing (wire_tag / wan_tag / geomx_top rendering), flight-
recorder replayability, bit-for-bit guards for controller-off, and the
e2e mid-run link squeeze on a shaped 2-party cluster.
"""

import json
import os
import random
import time

import numpy as np
import pytest

from geomx_tpu import telemetry
from geomx_tpu.config import Config
from geomx_tpu.kvstore import controller as ctrl
from geomx_tpu.kvstore.frontier import (auto_slice_bytes,
                                        slice_bytes_from_links)
from geomx_tpu.optimizer import SGD
from geomx_tpu.ps.flightrec import FlightRecorder
from geomx_tpu.ps.shaping import ShapeLink
from geomx_tpu.ps.tsengine import TSScheduler
from geomx_tpu.simulate import InProcessHiPS
from tools import geomx_top

from tests.test_hips import _parallel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SHAPE_PLAN = os.path.join(REPO, "scripts", "shapes",
                          "wan2_50ms_100mbps.json")


@pytest.fixture(autouse=True)
def _clean_registry():
    telemetry.reset()
    yield
    telemetry.reset()


# ---------------------------------------------------------------------------
# step_link: the pure per-round decision
# ---------------------------------------------------------------------------

def test_bootstrap_classifies_immediately():
    """Hysteresis guards CHANGES, not the first classification: a fresh
    link (no learned baseline) commits on its first evidence — thin
    below thin_mbps, fat at/above fat_mbps, and the fp16 floor for a
    measured link in between."""
    k = ctrl.Knobs()
    _, rec = ctrl.step_link(None, 5.0, 150.0, 0, False, k)
    assert (rec["codec"], rec["changed"], rec["reason"]) == \
        (ctrl.THIN_POLICY, True, "thin_bw")
    _, rec = ctrl.step_link(None, 200.0, 10.0, 0, False, k)
    assert (rec["codec"], rec["changed"], rec["reason"]) == \
        (ctrl.FAT_POLICY, True, "fat_bw")
    _, rec = ctrl.step_link(None, 25.0, 50.0, 0, False, k)
    assert (rec["codec"], rec["changed"], rec["reason"]) == \
        (ctrl.FAT_POLICY, True, "fp16_floor")
    # ... but the floor never overrides an existing assignment
    st, _ = ctrl.step_link(None, 5.0, 150.0, 0, False, k)
    _, rec = ctrl.step_link(st, 25.0, 50.0, 0, False, k)
    assert (rec["codec"], rec["changed"], rec["reason"]) == \
        (ctrl.THIN_POLICY, False, "dead_zone")


def test_no_evidence_never_classifies():
    k = ctrl.Knobs()
    st, rec = ctrl.step_link(None, 0.0, 50.0, 0, False, k)
    assert rec["reason"] == "no_evidence"
    assert st["codec"] is None and not rec["changed"]


def test_noisy_healthy_link_never_flaps():
    """The ISSUE bar: a noisy-but-healthy link whose dips stay within
    its own learned noise floor (the PR-13 convention: sigma from the
    link's measured variance) must NEVER trigger a codec change after
    its bootstrap classification."""
    k = ctrl.Knobs(thin_mbps=50.0)  # dips to 47-49 cross the static bar
    st, rec = ctrl.step_link(None, 60.0, 50.0, 0, False, k)
    assert rec["changed"] and rec["reason"] == "fp16_floor"
    for bw in (52, 68, 51, 69, 47, 66, 48, 62, 49, 65, 47):
        st, rec = ctrl.step_link(st, float(bw), 50.0, 0, False, k)
        assert not rec["changed"], rec
    assert st["codec"] == ctrl.FAT_POLICY
    # the dips were recognized as noise, not squeezes
    _, rec = ctrl.step_link(st, 47.0, 50.0, 0, False, k)
    assert rec["reason"] == "noise_dip"


def test_sustained_squeeze_converges_within_3_rounds_and_stays():
    k = ctrl.Knobs()
    st = None
    for _ in range(3):  # healthy fat baseline, codec committed
        st, _ = ctrl.step_link(st, 200.0, 10.0, 0, False, k)
    assert st["codec"] == ctrl.FAT_POLICY
    hist = []
    for _ in range(10):  # sustained squeeze: 200 -> 10 Mbps
        st, rec = ctrl.step_link(st, 10.0, 10.0, 0, False, k)
        hist.append(rec)
    switched = [i for i, r in enumerate(hist) if r["changed"]]
    assert switched and switched[0] < 3, hist  # within 3 rounds
    # ... and stays: exactly one change, thin policy from then on
    assert len(switched) == 1
    assert all(r["codec"] == ctrl.THIN_POLICY
               for r in hist[switched[0]:])
    # baseline froze during the squeeze (the drop must not erode its
    # own reference): still near the healthy 200
    assert st["base"] > 150.0


def test_degraded_latch_and_rtx_burst_bypass_persistence():
    k = ctrl.Knobs()
    st, _ = ctrl.step_link(None, 200.0, 10.0, 0, False, k)
    assert st["codec"] == ctrl.FAT_POLICY
    # a latched link_degraded switches NOW even at healthy bandwidth:
    # the detector already cleared its own noise floor
    _, rec = ctrl.step_link(dict(st), 180.0, 10.0, 0, True, k)
    assert (rec["codec"], rec["changed"], rec["reason"]) == \
        (ctrl.THIN_POLICY, True, "degraded")
    # same for a local retransmit burst
    _, rec = ctrl.step_link(dict(st), 180.0, 10.0, k.rtx_burst, False, k)
    assert (rec["codec"], rec["changed"], rec["reason"]) == \
        (ctrl.THIN_POLICY, True, "rtx_burst")


def test_replay_record_matches_step():
    """Each record embeds its pre-state: replaying any record standalone
    must reproduce the logged action exactly."""
    k = ctrl.Knobs()
    rng = random.Random(5)
    st = None
    for _ in range(60):
        bw = rng.choice((0.0, 10.0, 30.0, 60.0, 100.0, 160.0, 220.0))
        st, rec = ctrl.step_link(st, bw, rng.uniform(1, 200),
                                 rng.choice((0, 0, 0, 7)),
                                 rng.random() < 0.05, k)
        assert ctrl.replay_record(rec, k) == {
            "codec": rec["codec"], "changed": rec["changed"],
            "reason": rec["reason"]}


# ---------------------------------------------------------------------------
# slice budget: live-estimate source + clamp edges
# ---------------------------------------------------------------------------

def test_auto_slice_clamp_edges():
    assert auto_slice_bytes(100.0, 1.0) == 65536          # BDP 12.5KB
    assert auto_slice_bytes(200.0, 1000.0) == 4 << 20     # BDP 25MB
    mid = auto_slice_bytes(50.0, 100.0)                   # BDP 625KB
    assert 65536 < mid < (4 << 20) and mid == 625000


def test_slice_bytes_from_links_precedence_and_floor():
    # empty / unmeasured links contribute nothing: callers keep their
    # configured budget (precedence rule 2 only fires with evidence)
    assert slice_bytes_from_links([]) == 0
    assert slice_bytes_from_links([(50.0, 0.0)]) == 0
    # loopback exclusion: rtt under the floor never drives chunking
    assert slice_bytes_from_links([(0.2, 10000.0)],
                                  rtt_floor_ms=1.0) == 0
    # worst (highest-BDP) qualifying link wins
    assert slice_bytes_from_links(
        [(0.2, 10000.0), (50.0, 100.0), (150.0, 20.0)],
        rtt_floor_ms=1.0) == 625000
    # clamp edges survive the max() composition
    assert slice_bytes_from_links([(100.0, 1.0)]) == 65536
    assert slice_bytes_from_links([(200.0, 1000.0),
                                   (100.0, 1.0)]) == 4 << 20


def test_controller_slice_hold_band():
    est = _FakeEstimator({"8": _row(50.0, 100.0)})
    c = _controller(est)
    p1 = c.plan(1)
    assert p1.slice_bytes == 625000
    # a jittery +10% estimate stays inside the 25% hold band
    est.rows = {"8": _row(50.0, 110.0)}
    assert c.plan(2).slice_bytes == 625000
    # a real move re-publishes
    est.rows = {"8": _row(50.0, 300.0)}
    assert c.plan(3).slice_bytes == 1875000


# ---------------------------------------------------------------------------
# TransportPlan / TransportController plumbing
# ---------------------------------------------------------------------------

def _row(rtt_ms, bw, rtx=0):
    # digest "lk" row layout (ps/linkstate.py): [rtt_ms, bw_mbps,
    # rtt_var, bw_var, goodput, rtx, give_ups, n_small, n_big]
    return [rtt_ms, bw, 0.0, 0.0, bw / 8.0, rtx, 0, 4, 8]


class _FakeEstimator:
    def __init__(self, rows):
        self.rows = rows

    def digest(self):
        return {"lk": self.rows}


def _controller(est, flightrec=None, out_dir="", board_fn=None):
    return ctrl.TransportController(
        Config(), tier="global", node_fn=lambda: 9, estimator=est,
        board_fn=board_fn, flightrec=flightrec, out_dir=out_dir)


def test_plan_wire_tag_resolves_policy_per_chunk():
    plan = ctrl.TransportPlan(3, {10: "mpq", 12: "fp16"}, 0, {},
                              size_lower_bound=200000)
    assert plan.wire_tag(10, "", 300000) == "2bit"   # bulk chunk
    assert plan.wire_tag(10, "", 1000) == "fp16"     # small chunk
    assert plan.wire_tag(12, "", 300000) == "fp16"
    # no decision for this peer: static default rides
    assert plan.wire_tag(99, "2bit", 5) == "2bit"
    assert plan.wire_tag(99, "", 5) == ""


def test_wan_tag_thinnest_class_governs():
    est = _FakeEstimator({"8": _row(50.0, 200.0)})
    c = _controller(est)
    c.plan(1)
    assert c.wan_tag(300000) == "fp16"               # all fat
    est.rows = {"8": _row(50.0, 200.0), "10": _row(150.0, 10.0)}
    for r in (2, 3):
        c.plan(r)
    assert c.wan_tag(300000) == "2bit"               # thin peer governs
    assert c.wan_tag(1000) == "fp16"                 # mpq size rule
    # no decisions at all -> None (static precedence continues)
    c2 = _controller(_FakeEstimator({}))
    c2.plan(1)
    assert c2.wan_tag(300000) is None


def test_plan_is_idempotent_per_round():
    est = _FakeEstimator({"8": _row(50.0, 20.0)})
    c = _controller(est)
    p = c.plan(4)
    est.rows = {"8": _row(50.0, 200.0)}
    assert c.plan(4) is p            # same round: cached
    assert c.plan(3) is p            # stale round: cached
    assert c.plan(5) is not p        # new round: recomputed


def test_degraded_board_input_feeds_decision():
    est = _FakeEstimator({"8": _row(50.0, 200.0)})
    board = {"links": {"9>8": {"degraded": True},
                       "11>8": {"degraded": True}}}
    c = _controller(est, board_fn=lambda: board)
    p = c.plan(1)
    # healthy bandwidth, but the board latched 9>8: thin NOW
    assert p.codecs[8] == ctrl.THIN_POLICY
    assert p.reasons[8] == "degraded"


def test_replay_from_flightrec_dump(tmp_path):
    """Acceptance bar: every decision is reconstructable from a flight-
    recorder dump — each transport_plan record carries inputs + embedded
    pre-state, so a dump replays standalone."""
    rec = FlightRecorder(lambda: "n9", size=256, out_dir=str(tmp_path))
    est = _FakeEstimator({"8": _row(50.0, 200.0)})
    c = _controller(est, flightrec=rec, out_dir=str(tmp_path))
    c.plan(1)
    est.rows = {"8": _row(50.0, 10.0, rtx=0)}        # squeeze
    for r in (2, 3, 4):
        c.plan(r)
    path = rec.dump("test: controller replay")
    events = json.loads(open(path).read())["events"]
    plans = [e for e in events if e["kind"] == "transport_plan"]
    assert len(plans) == 4
    assert any(e["changed"] and e["codec"] == ctrl.THIN_POLICY
               for e in plans)
    for e in plans:
        assert ctrl.replay_record(e, c.knobs) == {
            "codec": e["codec"], "changed": e["changed"],
            "reason": e["reason"]}, e
    # the squeeze decision also hit the telemetry funnel
    # (transport.codec events are counted by the registry)


def test_plan_export_and_geomx_top_render(tmp_path):
    est = _FakeEstimator({"8": _row(50.0, 10.0)})
    c = _controller(est, out_dir=str(tmp_path))
    c.plan(1)
    plans = geomx_top.load_plans(str(tmp_path))
    assert ("global", 9) in plans
    doc = plans[("global", 9)]
    assert doc["links"]["8"]["codec"] == ctrl.THIN_POLICY
    assert doc["slice_bytes"] == auto_slice_bytes(50.0, 10.0)
    board = {"tier": "global", "node": "g8", "max_round": 1,
             "links": {"9>8": {"rtt_ms": 50.0, "bw_mbps": 10.0,
                               "rtx": 0, "give_ups": 0}}}
    text = geomx_top.render_board(board, plans=plans)
    assert "mpq[thin_bw]" in text
    assert "transport plan slice budgets" in text
    # a local-tier plan for the same numeric id must NOT leak onto the
    # global board's rows
    lplans = {("local", 9): doc}
    assert "mpq[" not in geomx_top.render_board(board, plans=lplans)


# ---------------------------------------------------------------------------
# controller off: today's behavior, bit-for-bit
# ---------------------------------------------------------------------------

def test_controller_defaults_off():
    c = Config()
    assert c.transport_controller is False


def test_pick_pair_rng_sequence_unchanged_when_bias_off():
    """GEOMX_TRANSPORT_CONTROLLER=0 must reproduce the PR-12 overlay
    matchmaking bit-for-bit: with no degraded set, _pick_pair consumes
    the RNG in exactly the legacy order (random() gate, then sample or
    shuffle+argmax)."""
    sched = TSScheduler(object(), num_workers=4, greed_rate=0.9)
    ref = random.Random(0x75)
    for _ in range(200):
        pend = {9, 11, 13, 15}
        # replicate list(pend)'s iteration order: the scheduler's RNG
        # draws (sample / shuffle) depend on it
        ids = list(pend)
        pairs = [(s, r) for s in ids for r in ids if s != r]
        got = sched._pick_pair(pend)
        if ref.random() >= sched.greed:
            exp = tuple(ref.sample(ids, 2))
        else:
            ref.shuffle(pairs)
            best, best_t = pairs[0], -1.0
            for s, r in pairs:
                t = sched.A.get((s, r), 0.0)
                if t > best_t:
                    best, best_t = (s, r), t
            exp = best
        assert tuple(got) == exp


def test_pick_pair_avoids_degraded_until_all_degraded():
    sched = TSScheduler(object(), num_workers=4, greed_rate=1.0)
    ids = [9, 11, 13]
    bad = frozenset({(9, 11), (11, 9), (9, 13), (13, 9)})
    for _ in range(50):
        rerouted = []
        s, r = sched._pick_pair(set(ids), bad, rerouted)
        assert (s, r) not in bad
        assert rerouted  # the filter engaged and was logged
    # every pair degraded: fall back to a plain pick (a stalled overlay
    # is worse than a slow hop)
    all_bad = frozenset((s, r) for s in ids for r in ids if s != r)
    s, r = sched._pick_pair(set(ids), all_bad, [])
    assert s != r and s in ids and r in ids


# ---------------------------------------------------------------------------
# e2e: mid-run squeeze absorbed, plan flips, every decision replayable
# ---------------------------------------------------------------------------

def test_e2e_squeeze_flips_plan_without_round_abort(tmp_path):
    """2-party HiPS on the wan2 plan (100 Mbps links: dead zone, so the
    controller starts with NO codec override) with the transport
    controller ON. A mid-run squeeze of 9->8 to 10 Mbps must be
    absorbed without a round abort; the board's link_degraded fires AND
    the party server's exported TransportPlan assigns the thin policy
    to peer 8 within 3 rounds of the detection; every logged decision
    replays from the flight recorder."""
    telemetry.enable(True)
    health_dir = str(tmp_path / "health")
    sim = InProcessHiPS(
        num_parties=2, workers_per_party=1,
        extra_cfg=dict(
            shape_plan="@" + SHAPE_PLAN,
            resend=True, resend_timeout_ms=2000, resend_deadline_s=120.0,
            heartbeat_interval_s=0.2, heartbeat_timeout_s=60,
            health=True, health_dir=health_dir,
            transport_controller=True,
        )).start(sync_global=True)
    try:
        sim.master.set_optimizer(SGD(learning_rate=1.0))
        big = np.zeros(65_536, np.float32)            # 256 KB bw probe

        def init_on(kv):
            kv.init(1, big)
            kv.wait()

        _parallel([lambda kv=kv: init_on(kv)
                   for kv in sim.workers + [sim.master]])

        def step(kv):
            kv.push_pull(1, np.ones(65_536, np.float32),
                         np.zeros(65_536, np.float32))
            kv.wait()

        def wan_plan():
            plans = geomx_top.load_plans(health_dir)
            return plans.get(("global", 9))

        for _ in range(5):  # healthy baseline rounds
            _parallel([lambda kv=kv: step(kv) for kv in sim.workers])
        baseline = wan_plan()
        assert baseline is not None, "controller exported no plan"
        # 100 Mbps sits in the dead zone between thin and fat: a
        # measured-but-unclassified link takes the fp16 floor
        assert baseline["links"].get("8", {}).get("codec", "") == \
            ctrl.FAT_POLICY

        gsrv = sim.servers[0]
        assert gsrv.is_global_server
        gsrv.po_global.van._shaper.plan.links.insert(0, ShapeLink(
            src=9, dst=8, tier="global", rtt_ms=50.0, bw_mbps=10.0))

        def board_degraded():
            got = sim.workers[0].health()
            for b in got["global"]:
                if b.get("tier") != "global":
                    continue
                lk = b.get("links", {}).get("9>8")
                if lk and lk.get("degraded"):
                    return True
            return False

        detect_round = plan_round = None
        for r in range(12):  # squeeze rounds: no abort tolerated
            _parallel([lambda kv=kv: step(kv) for kv in sim.workers])
            time.sleep(0.45)  # heartbeat cadence: digests land
            if detect_round is None and board_degraded():
                detect_round = r
            p = wan_plan()
            if plan_round is None and p is not None \
                    and p["links"].get("8", {}).get("codec") == \
                    ctrl.THIN_POLICY:
                plan_round = r
            if detect_round is not None and plan_round is not None:
                break
        assert detect_round is not None, "link_degraded never fired"
        assert plan_round is not None, "TransportPlan never flipped"
        assert plan_round <= detect_round + 3, (
            f"plan lagged detection: detected r{detect_round}, "
            f"flipped r{plan_round}")

        # every logged decision replays from the party server's ring
        party = next(s for s in sim.servers
                     if getattr(s, "_transport", None) is not None
                     and s.po_global.van.my_id == 9)
        recs = [e for e in party.po_global.van.flightrec.snapshot()
                if e["kind"] == "transport_plan"]
        assert recs, "no transport_plan flight-recorder records"
        assert any(e["changed"] and e["codec"] == ctrl.THIN_POLICY
                   for e in recs)
        for e in recs:
            assert ctrl.replay_record(e, party._transport.knobs) == {
                "codec": e["codec"], "changed": e["changed"],
                "reason": e["reason"]}
        # the codec flip hit the telemetry funnel
        counts = telemetry.snapshot()["counters"]
        assert counts.get("event.transport.codec", 0) >= 1
    finally:
        sim.stop()
