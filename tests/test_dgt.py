"""DGT tests: 4-bit codec, block split/reassembly, loss tolerance, and the
full HiPS topology with ENABLE_DGT (reference: kv_app.h:966-1260 send path,
van.cc:330-370 reassembly, van.cc:707-745 classifier)."""

import numpy as np
import pytest

from geomx_tpu.ps import dgt
from geomx_tpu.ps.kv_app import KVPairs, _pack_kv
from geomx_tpu.ps.message import Message, Meta


def test_quantize4_roundtrip():
    rng = np.random.RandomState(0)
    x = rng.randn(1001).astype(np.float32)
    packed, scale = dgt.quantize4(x)
    assert packed.dtype == np.uint8 and packed.size == 501
    back = dgt.dequantize4(packed, x.size, scale)
    # 4-bit: 15 levels over [-max, max] -> error <= scale/7/2 + rounding
    assert np.max(np.abs(back - x)) <= scale / 7.0
    # zeros stay zeros
    p0, s0 = dgt.quantize4(np.zeros(8, np.float32))
    np.testing.assert_array_equal(dgt.dequantize4(p0, 8, s0), np.zeros(8))


def _push_msg(key=3, n=100, dtype=np.float32, seed=1, ts=7):
    rng = np.random.RandomState(seed)
    val = rng.randn(n).astype(dtype)
    kvs = KVPairs(keys=[key], vals=[val], offsets=[0], totals=[n], lens=[n])
    meta = Meta(recver=8, app_id=0, customer_id=0, timestamp=ts,
                request=True, push=True)
    msg = _pack_kv(meta, kvs)
    msg.meta.sender = 9
    return msg, val


def _mk_sender(mode=2, channels=2, block=16, k=0.5):
    return dgt.DGTSender(mode=mode, num_channels=channels, block_size=block,
                         contri_alpha=0.3, k=k, k_min=0.1, adaptive_k=False)


def test_split_reassemble_exact_tcp_mode():
    sender = _mk_sender(mode=2)
    msg, val = _push_msg(n=100)
    assert sender.applicable(msg)
    blocks = sender.split(msg)
    assert len(blocks) == 7  # ceil(100/16)
    # tail is channel 0 and carries the header parts
    tail = blocks[-1][1]
    assert tail.meta.msg_type == dgt.MSG_TYPE_TAIL
    assert blocks[-1][0] == 0
    assert len(tail.data) == 5
    # reliable fraction: ceil(0.5*7)=4 blocks on channel 0 (+ tail forced)
    assert sum(1 for ch, _ in blocks if ch == 0) >= 4

    reasm = dgt.DGTReassembler()
    out = None
    for _ch, b in blocks:
        # survive a pack/unpack cycle (what the wire does)
        b2 = Message.unpack(b.pack())
        b2.meta.sender = 9
        got = reasm.accept(b2)
        if got is not None:
            out = got
    assert out is not None
    np.testing.assert_array_equal(out.get_array(4), val)
    assert out.meta.push and out.meta.request and out.meta.timestamp == 7
    assert [int(x) for x in out.get_array(0)] == [3]
    assert out.meta.msg_type == 0


def test_reassemble_zero_fills_lost_blocks():
    sender = _mk_sender(mode=1, block=16, k=0.3)
    msg, val = _push_msg(n=100)
    blocks = sender.split(msg)
    reasm = dgt.DGTReassembler()
    lost = [i for i, (ch, _b) in enumerate(blocks) if ch > 0][:2]
    out = None
    for i, (_ch, b) in enumerate(blocks):
        if i in lost:
            continue
        got = reasm.accept(b)
        if got is not None:
            out = got
    assert out is not None
    rebuilt = out.get_array(4)
    stride = 16
    for i in range(len(blocks)):
        lo, hi = i * stride, min((i + 1) * stride, 100)
        if i in lost:
            np.testing.assert_array_equal(rebuilt[lo:hi], 0.0)
        else:
            np.testing.assert_array_equal(rebuilt[lo:hi], val[lo:hi])
    # straggler after completion is dropped, not re-delivered
    assert reasm.accept(blocks[lost[0]][1]) is None
    assert reasm.blocks_dropped_late == 1


def test_split_mode3_quantizes_unimportant():
    sender = _mk_sender(mode=3, block=16, k=0.3)
    msg, val = _push_msg(n=128)
    blocks = sender.split(msg)
    comprs = {b.meta.compr for ch, b in blocks if ch > 0}
    assert comprs == {"dgt4"}
    reasm = dgt.DGTReassembler()
    out = None
    for _ch, b in blocks:
        got = reasm.accept(Message.unpack(b.pack()))
        if got is not None:
            out = got
    rebuilt = out.get_array(4)
    # reliable blocks exact, quantized blocks within 4-bit error
    assert np.max(np.abs(rebuilt - val)) <= np.max(np.abs(val)) / 7.0 + 1e-6
    exact = [ch == 0 for ch, _ in blocks]
    for i, ex in enumerate(exact[:-1]):
        lo, hi = i * 16, (i + 1) * 16
        if ex:
            np.testing.assert_array_equal(rebuilt[lo:hi], val[lo:hi])


def test_contribution_ewma_prefers_hot_blocks():
    sender = _mk_sender(mode=2, block=10, k=0.26)
    key_msg = None
    for _ in range(5):
        # block 2 (elements 20-30) consistently has the largest gradient
        val = np.ones(100, np.float32) * 0.01
        val[20:30] = 5.0
        kvs = KVPairs(keys=[1], vals=[val], offsets=[0], totals=[100],
                      lens=[100])
        meta = Meta(recver=8, timestamp=1, request=True, push=True)
        key_msg = _pack_kv(meta, kvs)
        blocks = sender.split(key_msg)
    chans = [ch for ch, _ in blocks]
    assert chans[2] == 0           # hot block rides the reliable channel
    # ceil(0.26*10)=3 reliable + forced tail
    assert sum(1 for c in chans if c == 0) == 4


def test_not_applicable_cases():
    sender = _mk_sender()
    small, _ = _push_msg(n=8)      # smaller than one block
    assert not sender.applicable(small)
    msg, _ = _push_msg(n=100)
    msg.meta.push = False
    msg.meta.pull = True
    assert not sender.applicable(msg)
    c, _ = _push_msg(n=100)
    c.meta.compr = "bsc"
    assert not sender.applicable(c)


@pytest.mark.parametrize("mode", [1, 2, 3])
def test_hips_training_with_dgt(mode):
    """Full 2-party topology with ENABLE_DGT on the global tier. Modes 1/2
    are lossless on loopback (UDP rarely drops locally; zero-fill would
    only perturb, not break); mode 3 quantizes unimportant blocks, so we
    assert approximate convergence of the stored weights."""
    from tests.test_hips import Topology, _parallel
    from geomx_tpu.optimizer import SGD

    topo = Topology()
    # enable DGT on every node config (only global-tier vans act on it)
    base_common = topo._common

    def common_with_dgt(**kw):
        cfg = base_common(**kw)
        cfg.enable_dgt = mode
        cfg.udp_channel_num = 2
        cfg.dgt_block_size = 8
        cfg.dmlc_k = 0.5
        return cfg

    topo._common = common_with_dgt
    topo.start(sync_global=True)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.arange(64, dtype=np.float32)
        _parallel([lambda kv=kv: kv.init(0, w0)
                   for kv in topo.workers + [topo.master]])

        def train(kv):
            kv.push(0, np.ones(64, np.float32))
            out = np.zeros(64, np.float32)
            kv.pull(0, out=out)
            kv.wait()
            if mode == 3:
                # unimportant blocks 4-bit quantized: small per-element error
                np.testing.assert_allclose(out, w0 - 4.0, atol=0.6)
            else:
                np.testing.assert_allclose(out, w0 - 4.0)

        _parallel([lambda kv=kv: train(kv) for kv in topo.workers])
    finally:
        topo.stop()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
