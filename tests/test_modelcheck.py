"""Gate for the small-scope explorer (tools/modelcheck.py).

Three jobs:

1. Clean protocol: every scenario explores to quiescence with zero
   invariant violations (the big churn scenario is nightly-tier).
2. Mutation teeth: each seeded fence/behavior removal trips EXACTLY its
   documented invariant — proving the invariants actually distinguish
   the real protocol from its broken neighbors.
3. Replay: the offline conformance pass over flight-recorder dumps
   flags seeded epoch regressions and stays silent on clean rings.
"""

import json

import pytest

from tools.modelcheck import (MUTANTS, SCENARIOS, explore, replay_events,
                              replay_paths, run_clean, run_mutants)

FAST = {n: s for n, s in SCENARIOS.items() if n != "churn-3w2s"}


# ---------------------------------------------------------------------------
# clean protocol
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(FAST))
def test_scenario_clean(name):
    res = explore(SCENARIOS[name], frozenset(), scenario=name)
    assert res.violations == [], (
        f"{name}: {[ (v.invariant, v.detail) for v in res.violations ]}")
    assert res.terminals > 0


@pytest.mark.slow
def test_churn_scenario_clean():
    """3 workers / 2 servers with crash + rejoin — the headline scope
    (~270k states, ~1 min)."""
    res = explore(SCENARIOS["churn-3w2s"], frozenset(),
                  scenario="churn-3w2s")
    assert res.violations == []
    assert res.states > 100_000      # the scope actually is that big


# ---------------------------------------------------------------------------
# mutation teeth
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mutant", sorted(MUTANTS))
def test_mutant_trips_exactly_its_invariant(mutant):
    flag, scenario, expected = MUTANTS[mutant]
    res = explore(SCENARIOS[scenario], frozenset([flag]),
                  scenario=scenario)
    assert res.invariants_hit == [expected], (
        f"{mutant} ({flag} under {scenario}): expected exactly "
        f"[{expected}], hit {res.invariants_hit}")


def test_run_mutants_wrapper_agrees():
    for name, (res, expected) in run_mutants().items():
        assert res.invariants_hit == [expected], name


# ---------------------------------------------------------------------------
# partial-order reduction soundness (on the scenarios where full
# exploration is cheap): same verdicts with and without POR
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", ["zombie-rejoin", "double-declare",
                                  "recovery-2r"])
def test_por_equivalence(name):
    with_por = explore(SCENARIOS[name], frozenset(), por=True,
                       scenario=name)
    without = explore(SCENARIOS[name], frozenset(), por=False,
                      scenario=name)
    assert with_por.violations == [] and without.violations == []
    # POR may only SHRINK the explored graph, never change verdicts
    assert with_por.states <= without.states


def test_run_clean_wrapper(capsys=None):
    out = run_clean(only="crash-only")
    assert list(out) == ["crash-only"]
    assert out["crash-only"].violations == []


# ---------------------------------------------------------------------------
# replay
# ---------------------------------------------------------------------------

def _wire(seq, peer, epoch, kind="recv"):
    return {"seq": seq, "kind": kind, "peer": peer, "epoch": epoch}


def test_replay_flags_epoch_regression():
    problems = replay_events([
        _wire(1, 9, 1), _wire(2, 9, 2), _wire(3, 9, 1)])
    assert len(problems) == 1 and "epoch 1 after seeing 2" in problems[0]


def test_replay_flags_non_monotonic_declare():
    problems = replay_events([
        {"seq": 1, "kind": "membership", "event": "declare_dead",
         "epoch": 2, "dead": [11]},
        {"seq": 2, "kind": "membership", "event": "declare_dead",
         "epoch": 2, "dead": [12]}])
    assert len(problems) == 1 and "not above 2" in problems[0]


def test_replay_clean_ring_is_silent():
    assert replay_events([
        _wire(1, 9, 1, "sent"), _wire(2, 9, 1), _wire(3, 9, 2),
        {"seq": 4, "kind": "membership", "event": "declare_dead",
         "epoch": 3, "dead": [11]},
        _wire(5, 9, 3)]) == []


def test_replay_paths_over_dump_files(tmp_path):
    (tmp_path / "flightrec_a.json").write_text(json.dumps({
        "node": "l8", "events": [_wire(1, 9, 2), _wire(2, 9, 1)]}))
    (tmp_path / "flightrec_b.json").write_text(json.dumps({
        "node": "l9", "events": [_wire(1, 8, 1)]}))
    (tmp_path / "unrelated.json").write_text("{}")
    report = replay_paths([tmp_path])
    assert report["violations"] == 1
    assert [f["node"] for f in report["files"]] == ["l8", "l9"]
