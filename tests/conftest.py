"""Test configuration: force an 8-device virtual CPU mesh before jax imports.

Mirrors the reference's "multi-node without a cluster" testing stance
(reference: 3rdparty/ps-lite/tests/local.sh runs schedulers/servers/workers
as localhost processes): unit tests run single-process, state-machine tests
use a fake in-process transport, integration tests spawn real subprocesses.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
