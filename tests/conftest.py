"""Test configuration: force an 8-device virtual CPU mesh before jax imports.

Mirrors the reference's "multi-node without a cluster" testing stance
(reference: 3rdparty/ps-lite/tests/local.sh runs schedulers/servers/workers
as localhost processes): unit tests run single-process, state-machine tests
use a fake in-process transport, integration tests spawn real subprocesses.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon TPU plugin (sitecustomize) force-registers itself regardless of
# JAX_PLATFORMS in the environment; config.update is the reliable override.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # transport-only test runs without jax
    pass
