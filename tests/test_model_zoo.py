"""Vision model zoo: construction, forward shapes, train-mode stats.

Mirrors the reference zoo surface (gluon model_zoo/vision); every
family initializes and produces [B, num_classes] logits in fp32.
Small spatial inputs keep CPU runtime down — each net's stem/pool
stack still exercises every block type.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from geomx_tpu.models import get_model

pytestmark = pytest.mark.slow  # compile-heavy: nightly tier

RNG = jax.random.PRNGKey(0)


def _forward(model, hw, classes=10, train_rngs=False):
    x = jnp.zeros((2, hw, hw, 3), jnp.float32)
    variables = model.init(RNG, x)
    out = model.apply(variables, x)
    assert out.shape == (2, classes) and out.dtype == jnp.float32
    return variables


@pytest.mark.parametrize("name,hw", [
    ("alexnet", 64),
    ("squeezenet1.0", 64),
    ("squeezenet1.1", 64),
])
def test_stateless_zoo_models(name, hw):
    _forward(get_model(name, num_classes=10), hw)


@pytest.mark.parametrize("name,hw", [
    ("vgg11", 32),
    ("vgg13_bn", 32),
    ("mobilenet1.0", 32),
    ("mobilenet0.25", 32),
    ("mobilenetv2_1.0", 32),
    ("mobilenetv2_0.5", 32),
    ("densenet121", 32),
])
def test_batchnorm_zoo_models(name, hw):
    variables = _forward(get_model(name, num_classes=10), hw)
    if "batch_stats" in variables:
        model = get_model(name, num_classes=10)
        x = jnp.ones((2, hw, hw, 3), jnp.float32)
        _, updated = model.apply(
            variables, x, train=True, mutable=["batch_stats"],
            rngs={"dropout": jax.random.PRNGKey(1)})
        # running stats actually move in train mode
        before = jax.tree_util.tree_leaves(variables["batch_stats"])
        after = jax.tree_util.tree_leaves(updated["batch_stats"])
        assert any(not np.allclose(b, a) for b, a in zip(before, after))


def test_inception_v3():
    _forward(get_model("inceptionv3", num_classes=10), 75)


def test_resnet_via_zoo_factory():
    _forward(get_model("resnet18_v1", num_classes=10), 32)


def test_vgg_spec_sizes():
    """vgg16 conv stack is 13 conv layers (reference spec)."""
    model = get_model("vgg16", num_classes=10)
    variables = model.init(RNG, jnp.zeros((1, 32, 32, 3)))
    convs = [k for k in variables["params"] if k.startswith("Conv")]
    assert len(convs) == 13


def test_mobilenet_multiplier_scales_params():
    def nparams(name):
        m = get_model(name, num_classes=10)
        v = m.init(RNG, jnp.zeros((1, 32, 32, 3)))
        return sum(x.size for x in jax.tree_util.tree_leaves(v["params"]))

    assert nparams("mobilenet0.25") < nparams("mobilenet1.0") / 4


def test_unknown_model_rejected():
    with pytest.raises(ValueError, match="unknown model"):
        get_model("resnext50")
