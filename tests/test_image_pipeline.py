"""JPEG/PNG ImageRecordIO + augmentation (round-3 verdict item 7).

Reference: src/io/iter_image_recordio_2.cc (decode-from-record),
image_aug_default.cc (default augmenter), iter_normalize.h
(scale/mean/std), python/mxnet/recordio.py pack_img/unpack_img.
"""

import numpy as np
import pytest

from geomx_tpu.io import (ImageAugmenter, ImageRecordIter, IRHeader,
                          MXRecordIO, PrefetchIter, imdecode, imencode,
                          pack_array, pack_img, unpack_img)


def _imgs(n, h=32, w=32, c=3, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 256, (h, w, c), np.uint8) for _ in range(n)]


def test_png_pack_img_round_trip_exact():
    img = _imgs(1)[0]
    rec = pack_img(IRHeader(0, 3.0, 7, 0), img, img_fmt=".png")
    header, back = unpack_img(rec)
    assert header.label == 3.0 and header.id == 7
    np.testing.assert_array_equal(back, img)


def test_jpeg_pack_img_round_trip_close():
    # smooth gradient: JPEG is lossy but must stay close
    y, x = np.mgrid[0:64, 0:64]
    img = np.stack([x * 4, y * 4, (x + y) * 2], -1).astype(np.uint8)
    header, back = unpack_img(pack_img(IRHeader(0, 1.0, 0, 0), img,
                                       quality=95))
    assert back.shape == img.shape
    assert np.abs(back.astype(int) - img.astype(int)).mean() < 4.0


def test_unpack_img_rejects_raw_payload():
    rec = pack_array(IRHeader(0, 1.0, 0, 0), _imgs(1)[0])
    with pytest.raises(ValueError, match="not a JPEG/PNG"):
        unpack_img(rec)


def test_encoded_iter_matches_raw_iter(tmp_path):
    """Property test vs the raw-array path: the same pixels packed as
    PNG (lossless) and as raw arrays must iterate identically."""
    imgs = _imgs(10)
    p_raw, p_png = str(tmp_path / "raw.rec"), str(tmp_path / "png.rec")
    with MXRecordIO(p_raw, "w") as w_raw, MXRecordIO(p_png, "w") as w_png:
        for i, img in enumerate(imgs):
            hdr = IRHeader(0, float(i % 3), i, 0)
            w_raw.write(pack_array(hdr, img))
            w_png.write(pack_img(hdr, img, img_fmt=".png"))
    it_raw = ImageRecordIter(p_raw, (32, 32, 3), batch_size=4)
    it_png = ImageRecordIter(p_png, (32, 32, 3), batch_size=4)
    assert len(it_raw) == len(it_png) == 3
    for (xr, yr), (xp, yp) in zip(it_raw, it_png):
        np.testing.assert_allclose(xr, xp)
        np.testing.assert_array_equal(yr, yp)


def test_jpeg_iter_decodes_on_the_fly(tmp_path):
    p = str(tmp_path / "jpg.rec")
    imgs = _imgs(6, h=40, w=48)
    with MXRecordIO(p, "w") as w:
        for i, img in enumerate(imgs):
            w.write(pack_img(IRHeader(0, float(i), i, 0), img,
                             img_fmt=".jpg"))
    aug = ImageAugmenter((32, 32, 3), rand_crop=True, rand_mirror=True,
                         seed=3)
    it = ImageRecordIter(p, (32, 32, 3), batch_size=2, aug=aug)
    batches = list(it)
    assert len(batches) == 3
    for X, y in batches:
        assert X.shape == (2, 32, 32, 3) and X.dtype == np.float32
    # epochs re-augment: random crops differ across epochs
    again = list(it)
    assert not all(np.array_equal(a[0], b[0])
                   for a, b in zip(batches, again))


def test_iter_rejects_mixed_payloads(tmp_path):
    p = str(tmp_path / "mixed.rec")
    img = _imgs(1)[0]
    with MXRecordIO(p, "w") as w:
        w.write(pack_array(IRHeader(0, 0.0, 0, 0), img))
        w.write(pack_img(IRHeader(0, 1.0, 1, 0), img, img_fmt=".png"))
    with pytest.raises(ValueError, match="mixes"):
        ImageRecordIter(p, (32, 32, 3), batch_size=1)


def test_augmenter_ops():
    img = _imgs(1, h=64, w=80)[0]
    # center crop, deterministic
    aug = ImageAugmenter((32, 32, 3))
    out = aug(img)
    assert out.shape == (32, 32, 3)
    np.testing.assert_allclose(
        out, img[16:48, 24:56].astype(np.float32) / 255.0)
    # resize path: shorter side to 36 then crop
    out = ImageAugmenter((32, 32, 3), resize=36)(img)
    assert out.shape == (32, 32, 3)
    # mean/std normalization (iter_normalize.h semantics)
    aug = ImageAugmenter((64, 80, 3), mean_rgb=[0.5, 0.5, 0.5],
                         std_rgb=[0.25, 0.25, 0.25])
    out = aug(img)
    expect = (img.astype(np.float32) / 255.0 - 0.5) / 0.25
    np.testing.assert_allclose(out, expect, atol=1e-5)
    # grayscale output
    out = ImageAugmenter((64, 80, 1))(img)
    assert out.shape == (64, 80, 1)
    # color jitter stays in range and changes pixels
    aug = ImageAugmenter((64, 80, 3), brightness=0.5, contrast=0.5,
                         saturation=0.5, seed=1)
    out = aug(img)
    assert out.shape == (64, 80, 3)
    assert not np.allclose(out, img.astype(np.float32) / 255.0)


def test_prefetch_composes(tmp_path):
    p = str(tmp_path / "pf.rec")
    with MXRecordIO(p, "w") as w:
        for i, img in enumerate(_imgs(8)):
            w.write(pack_img(IRHeader(0, float(i), i, 0), img,
                             img_fmt=".png"))
    base = ImageRecordIter(p, (32, 32, 3), batch_size=4)
    direct = list(base)
    pre = list(PrefetchIter(
        ImageRecordIter(p, (32, 32, 3), batch_size=4), prefetch=2))
    assert len(direct) == len(pre)
    for (a, la), (b, lb) in zip(direct, pre):
        np.testing.assert_allclose(a, b)
        np.testing.assert_array_equal(la, lb)


@pytest.mark.slow
def test_cifar_records_train_zoo_model(tmp_path):
    """The verdict's 'done' bar: CIFAR-10-shaped images packed as JPEG
    records train a zoo model through the real decode+augment
    iterator (loss falls over a few steps)."""
    import jax.numpy as jnp

    from examples.utils import build_model_and_step

    # CIFAR-shaped structured data (class = dominant channel) so a few
    # steps show real learning signal
    rng = np.random.RandomState(0)
    p = str(tmp_path / "cifar.rec")
    with MXRecordIO(p, "w") as w:
        for i in range(96):
            cls = i % 3
            img = rng.randint(0, 64, (32, 32, 3), np.uint8)
            img[..., cls] = rng.randint(160, 256, (32, 32), np.uint8)
            w.write(pack_img(IRHeader(0, float(cls), i, 0), img,
                             img_fmt=".jpg"))
    aug = ImageAugmenter((32, 32, 3), rand_crop=True, rand_mirror=True,
                         resize=34, seed=5)
    it = ImageRecordIter(p, (32, 32, 3), batch_size=32, shuffle=True,
                         aug=aug, seed=5)

    leaves, _td, grad_step, _ev = build_model_and_step(
        32, input_shape=(32, 32, 3), model="resnet18", num_classes=3)
    import optax

    opt = optax.adam(1e-3)
    lv = [jnp.asarray(l) for l in leaves]
    st = opt.init(lv)
    losses = []
    for _ in range(4):  # epochs over 3 batches
        for X, y in PrefetchIter(it, prefetch=2):
            loss, grads = grad_step(lv, jnp.asarray(X),
                                    jnp.asarray(y.astype(np.int32)))
            updates, st = opt.update(grads, st)
            lv = [w + u for w, u in zip(lv, updates)]
            losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_raw_pack_applies_augmenter(tmp_path):
    """aug= must run on raw-array packs too (review finding: silently
    un-normalized raw batches would diverge from the same pixels as
    PNG)."""
    imgs = _imgs(4)
    p = str(tmp_path / "rawaug.rec")
    with MXRecordIO(p, "w") as w:
        for i, img in enumerate(imgs):
            w.write(pack_array(IRHeader(0, float(i), i, 0), img))
    aug = ImageAugmenter((32, 32, 3), mean_rgb=[0.5] * 3,
                         std_rgb=[0.25] * 3)
    X, _ = next(iter(ImageRecordIter(p, (32, 32, 3), batch_size=4,
                                     aug=aug)))
    expect = (imgs[0].astype(np.float32) / 255.0 - 0.5) / 0.25
    np.testing.assert_allclose(X[0], expect, atol=1e-5)


def test_grayscale_hw1_arrays(tmp_path):
    """(H, W, 1) arrays encode, augment, and iterate (PIL needs the
    singleton axis squeezed internally)."""
    rng = np.random.RandomState(3)
    img = rng.randint(0, 256, (28, 28, 1), np.uint8)
    back = imdecode(imencode(img, ".png"))
    np.testing.assert_array_equal(back, img[..., 0])
    out = ImageAugmenter((28, 28, 1))(img)
    np.testing.assert_allclose(out, img.astype(np.float32) / 255.0)
