"""Batched multi-key push/pull wire (list forms of push/pull).

One message per server per round instead of one per key: the server
runs its per-key state machines unchanged and a countdown responder
(kvstore.server._BatchResponder) merges their acks/responses into the
single response the transport allows per request. Semantics must equal
the per-key wire exactly, including the push-ack -> pull freshness
ordering.
"""

import numpy as np
import pytest

from geomx_tpu.optimizer import SGD
from geomx_tpu.simulate import InProcessHiPS

KEYS = list(range(6))
SHAPES = [(4,), (2, 3), (8,), (5,), (1,), (7,)]


def _run(batched, sharded: bool = False):
    kw = dict(num_parties=2, workers_per_party=1)
    if sharded:
        kw.update(servers_per_party=2, bigarray_bound=4)
    topo = InProcessHiPS(**kw).start()
    result = {}
    try:
        def master_init(kv):
            kv.set_optimizer(SGD(learning_rate=0.5))
            for k, sh in zip(KEYS, SHAPES):
                kv.init(k, np.zeros(sh, np.float32))
            kv.wait()

        def worker(kv):
            widx = 0 if kv is topo.workers[0] else 1
            outs = [np.zeros(sh, np.float32) for sh in SHAPES]
            for k, o in zip(KEYS, outs):
                kv.init(k, o.copy())
                kv.pull(k, out=o)
            kv.wait()
            rng = np.random.RandomState(17)  # same on both workers
            for step in range(3):
                grads = [rng.uniform(-1, 1, sh).astype(np.float32) / 2
                         for sh in SHAPES]
                if batched == "push_pull":
                    kv.push_pull(KEYS, grads, out=outs)
                elif batched:
                    kv.push(KEYS, grads)
                    kv.pull(KEYS, out=outs)
                else:
                    for k, g, o in zip(KEYS, grads, outs):
                        kv.push(k, g)
                        kv.pull(k, out=o)
                kv.wait()
            result[widx] = [o.copy() for o in outs]

        topo.run_workers(worker, include_master=master_init, timeout=300)
    finally:
        topo.stop()
    np.testing.assert_equal(len(result), 2)
    for a, b in zip(result[0], result[1]):
        np.testing.assert_array_equal(a, b)
    return result[0]


@pytest.mark.parametrize("sharded", [False, True])
def test_batched_wire_matches_per_key_exactly(sharded):
    """Same seeds, same optimizer: the batched rounds must produce
    bit-identical weights to per-key rounds (freshness ordering and
    aggregation semantics preserved)."""
    per_key = _run(batched=False, sharded=sharded)
    batched = _run(batched=True, sharded=sharded)
    for a, b in zip(per_key, batched):
        np.testing.assert_array_equal(a, b)
    # and training actually moved the weights
    assert any(np.abs(a).sum() > 0 for a in batched)


@pytest.mark.parametrize("sharded", [False, True])
def test_push_pull_matches_per_key_exactly(sharded):
    """Combined push+pull (ZPushPull wire: the round's ack carries the
    post-round params) must be bit-identical to the two-op sequence."""
    per_key = _run(batched=False, sharded=sharded)
    combined = _run(batched="push_pull", sharded=sharded)
    for a, b in zip(per_key, combined):
        np.testing.assert_array_equal(a, b)
    assert any(np.abs(a).sum() > 0 for a in combined)


def test_batched_pull_requires_writable_arrays():
    topo = InProcessHiPS(num_parties=2, workers_per_party=1).start()
    try:
        def master_init(kv):
            for k in (0, 1):
                kv.init(k, np.zeros(3, np.float32))
            kv.wait()

        def worker(kv):
            for k in (0, 1):
                kv.init(k, np.zeros(3, np.float32))
            kv.wait()
            with pytest.raises(TypeError, match="writable"):
                kv.pull([0, 1], out=[np.zeros(3), "nope"])

        topo.run_workers(worker, include_master=master_init, timeout=120)
    finally:
        topo.stop()


def test_duplicate_keys_rejected_loudly():
    """Review finding: duplicate keys in one list call would corrupt
    the batched bookkeeping — and even the per-key path double-counts
    the worker's FSA contribution and wedges the round barrier. The
    misuse is rejected with an error, never a hang."""
    topo = InProcessHiPS(num_parties=2, workers_per_party=1).start()
    try:
        def master_init(kv):
            kv.init(0, np.zeros(3, np.float32))
            kv.wait()

        def worker(kv):
            kv.init(0, np.zeros(3, np.float32))
            kv.wait()
            with pytest.raises(ValueError, match="duplicate keys"):
                kv.push([0, 0], [np.ones(3, np.float32),
                                 np.ones(3, np.float32)])
            with pytest.raises(ValueError, match="duplicate keys"):
                kv.pull([0, 0], out=[np.zeros(3, np.float32),
                                     np.zeros(3, np.float32)])

        topo.run_workers(worker, include_master=master_init, timeout=120)
    finally:
        topo.stop()


def test_p3_list_form_fans_out_per_key():
    """Under ENABLE_P3 the list forms fan out to per-key prioritized
    messages (coalescing would defeat the priority send thread); the
    results must still be exact, and the sparse batch paths must fan
    out the same way."""
    topo = InProcessHiPS(num_parties=2, workers_per_party=1,
                         extra_cfg={"enable_p3": True,
                                    "bigarray_bound": 8}).start()
    try:
        def master_init(kv):
            kv.set_optimizer(SGD(learning_rate=1.0))
            for k, n in ((0, 20), (1, 6)):
                kv.init(k, np.zeros(n, np.float32))
            kv.wait()

        def worker(kv):
            assert kv.cfg.enable_p3
            outs = [np.zeros(20, np.float32), np.zeros(6, np.float32)]
            for k, o in zip((0, 1), outs):
                kv.init(k, o.copy())
                kv.pull(k, out=o)
            kv.wait()
            for r in range(1, 3):
                kv.push([0, 1], [np.ones(20, np.float32),
                                 np.ones(6, np.float32)])
                kv.pull([0, 1], out=outs)
                kv.wait()
                for o in outs:
                    np.testing.assert_allclose(o, -2.0 * r)

        topo.run_workers(worker, include_master=master_init, timeout=300)
    finally:
        topo.stop()


def test_p3_sparse_batch_fans_out_per_key():
    """The sparse batch paths under ENABLE_P3 fan out per key like the
    dense list form (aggregator mode: no server optimizer, the
    pull-back is the aggregated selection)."""
    topo = InProcessHiPS(num_parties=2, workers_per_party=1,
                         extra_cfg={"enable_p3": True,
                                    "bigarray_bound": 8}).start()
    try:
        def master_init(kv):
            for k, n in ((0, 20), (1, 6)):
                kv.init(k, np.zeros(n, np.float32))
            kv.wait()

        def worker(kv):
            assert kv.cfg.enable_p3
            for k, n in ((0, 20), (1, 6)):
                kv.init(k, np.zeros(n, np.float32))
                kv.pull(k, out=np.zeros(n, np.float32))
            kv.wait()
            kv.push_bsc_batch([0, 1],
                              [np.array([1.0], np.float32)] * 2,
                              [np.array([3], np.int64)] * 2)
            agg = kv.pull_bsc_batch([0, 1])()
            for k in (0, 1):
                avals, aidx = agg[k]
                dense = np.zeros(20 if k == 0 else 6, np.float32)
                dense[aidx] = avals
                np.testing.assert_allclose(dense[3], 2.0)  # 2 workers

        topo.run_workers(worker, include_master=master_init, timeout=300)
    finally:
        topo.stop()
