"""DeviceResidentTrainer: device-resident params, BSC-compressed link.

Validates the cnn_bsc-style round (aggregator PS, worker-side optimizer)
over a LIVE two-party in-process HiPS topology: exactness at
threshold=1.0 (top-k covers everything -> must equal dense data-parallel
SGD), replica consistency, convergence at sparse thresholds, and the
compact-payload claim.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np

from geomx_tpu.simulate import InProcessHiPS
from geomx_tpu.trainer_device import DeviceResidentTrainer

TARGET = np.arange(1.0, 9.0, dtype=np.float32).reshape(2, 4)


def _grad_fn(leaves, X, y):
    """Quadratic bowl: loss = 0.5*||w - target||^2 (per worker batch
    shift given by X so worker grads differ)."""
    w = leaves[0]
    diff = w - jnp.asarray(TARGET) + X
    return 0.5 * jnp.sum(diff * diff), [diff]


def _run_two_workers(threshold, rounds=30, lr=0.2, momentum=0.0):
    topo = InProcessHiPS(num_parties=2, workers_per_party=1).start()
    results = {}
    try:
        def worker(kv):
            widx = 0 if kv is topo.workers[0] else 1
            tr = DeviceResidentTrainer(
                [np.zeros((2, 4), np.float32)], kv, _grad_fn,
                threshold=threshold, learning_rate=lr, momentum=momentum)
            # worker batches pull in opposite directions; the MEAN grad
            # points at TARGET exactly
            shift = jnp.asarray(0.5 if widx == 0 else -0.5)
            for _ in range(rounds):
                tr.step(shift, None)
            results[widx] = tr.leaves[0]

        def master_init(kv):
            kv.init(0, np.zeros((2, 4), np.float32))
            kv.wait()

        t = threading.Thread(target=lambda: topo.run_workers(
            worker, include_master=master_init, timeout=300))
        t.start()
        t.join(300)
        assert not t.is_alive(), "workers hung"
    finally:
        topo.stop()
    return results


def test_dense_threshold_matches_plain_sgd():
    """threshold=1.0 selects every coordinate -> the distributed run
    must track plain full-gradient SGD on the mean gradient exactly
    (BSC with k=n is lossless)."""
    res = _run_two_workers(threshold=1.0, rounds=25, lr=0.2)
    w = np.zeros((2, 4), np.float32)
    for _ in range(25):
        w = w - 0.2 * (w - TARGET)  # mean of the two shifted grads
    np.testing.assert_allclose(res[0], w, rtol=1e-5, atol=1e-5)


def test_replicas_stay_identical():
    res = _run_two_workers(threshold=0.5, rounds=20)
    np.testing.assert_array_equal(res[0], res[1])


def test_sparse_threshold_converges():
    """With k=2 of 8 coords per round, the iterate lands in a bounded
    neighborhood of the optimum (BSC residual feedback batches deferred
    coordinates, so persistent worker dissent -> bounded oscillation,
    not exact convergence — reference behavior)."""
    res = _run_two_workers(threshold=0.25, rounds=150, lr=0.15)
    err = np.abs(res[0] - TARGET)
    assert float(err.mean()) < 0.25 and float(err.max()) < 0.6, res[0]


def test_momentum_variant_matches_heavyball():
    """threshold=1.0 makes the wire lossless, so the local momentum
    update must equal plain heavyball SGD on the mean gradient."""
    res = _run_two_workers(threshold=1.0, rounds=30, lr=0.05, momentum=0.9)
    w = np.zeros((2, 4), np.float32)
    mom = np.zeros_like(w)
    for _ in range(30):
        mom = 0.9 * mom + (w - TARGET)
        w = w - 0.05 * mom
    np.testing.assert_allclose(res[0], w, rtol=1e-5, atol=1e-5)


def test_payload_is_compact():
    """The device->host payload is k = ceil(total*threshold) pairs."""
    from geomx_tpu.kvstore import create as kv_create

    kv = kv_create("local")
    tr = DeviceResidentTrainer(
        [np.zeros((100,), np.float32)], kv, _grad_fn_100,
        threshold=0.02, learning_rate=0.1)
    assert tr.k == 2
    # and a local round still works end to end
    tr.step(jnp.asarray(0.0), None)
    assert tr.leaves[0].shape == (100,)


def _grad_fn_100(leaves, X, y):
    w = leaves[0]
    return 0.5 * jnp.sum(w * w), [w + 1.0]


def test_warmup_compiles_without_state_change():
    from geomx_tpu.kvstore import create as kv_create

    kv = kv_create("local")
    tr = DeviceResidentTrainer(
        [np.zeros((16,), np.float32)], kv, _grad_fn_16,
        threshold=0.5, learning_rate=0.1)
    before = tr.leaves[0].copy()
    tr.warmup(jnp.asarray(0.0), None)
    np.testing.assert_array_equal(tr.leaves[0], before)
    tr.step(jnp.asarray(0.0), None)  # and a real round still works
    assert not np.array_equal(tr.leaves[0], before)


def _grad_fn_16(leaves, X, y):
    w = leaves[0]
    return 0.5 * jnp.sum(w * w), [w + 1.0]


def test_packed_wire_is_int32_and_index_exact():
    """Round-4 chip regression: the packed device<->host payload must be
    an INT32 array (floats bitcast int-wards), never float32 with
    indices bitcast float-wards. Indices < 2^23 bitcast to float32 are
    denormals, and TPU float data movement inside jit flushes denormals
    to zero — on the r04 capture every index collapsed to 0 and headline
    accuracy fell to chance (BENCH_r04.json hips_bsc_cnn 0.0967).
    CPU can't reproduce the flush, so this asserts the wire CONTRACT:
    dtype int32 end-to-end and bit-exact recovery of small indices."""
    from geomx_tpu.kvstore import create as kv_create

    rng = np.random.default_rng(3)
    w = rng.standard_normal(500).astype(np.float32)

    def gfn(leaves, X, y):
        return jnp.sum(leaves[0]), [jnp.asarray(w)]

    kv = kv_create("local")
    tr = DeviceResidentTrainer([np.zeros(500, np.float32)], kv, gfn,
                               threshold=0.01, learning_rate=1.0)
    packed, _u, _v = tr._fwd_compress(tr._flat, tr._u, tr._v,
                                      jnp.asarray(0.0), None)
    assert np.asarray(packed).dtype == np.int32
    k = tr.k
    p = np.asarray(packed)
    idx = p[1 + k:]
    vals = p[1:1 + k].view(np.float32)
    # exact top-k of the rigged gradient: u=g, v=g -> top-|g| coords
    expect = np.argsort(-np.abs(w), kind="stable")[:k]
    assert set(idx.tolist()) == set(expect.tolist())
    np.testing.assert_array_equal(np.sort(np.abs(vals)),
                                  np.sort(np.abs(w[expect])))
