"""Optimizer family + LR schedulers.

Covers the reference optimizer library surface
(python/mxnet/optimizer/optimizer.py) and lr_scheduler.py: update-rule
math spot-checks, convergence on a convex problem for every registry
entry, pickling (the command-channel transport requirement), and the
scheduler/num_update contract.
"""

import pickle

import numpy as np
import pytest

from geomx_tpu import lr_scheduler as lrs
from geomx_tpu import optimizer as opt_mod
from geomx_tpu.optimizer import (
    SGD, NAG, Signum, SGLD, Adam, Adamax, Nadam, FTML, AdaGrad, RMSProp,
    AdaDelta, Ftrl, DCASGD, create,
)


ALL_NAMES = sorted(opt_mod._REGISTRY)


# ---------------------------------------------------------------------------
# convergence: every optimizer shrinks ||w|| on grad = w (quadratic bowl)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", ALL_NAMES)
def test_converges_on_quadratic(name):
    # per-family pacing: adagrad's effective lr decays 1/sqrt(t),
    # adadelta self-scales from eps, ftrl is proximal, sgld is a
    # SAMPLER (stationary std ~ 1, so only the mean contracts)
    kw, iters, bound = {"learning_rate": 0.05}, 400, 0.5
    if name in ("adadelta", "adagrad", "ftrl"):
        kw, iters, bound = {"learning_rate": 0.5}, 2000, 0.05
    elif name == "sgld":
        kw, iters, bound = {"learning_rate": 0.002, "seed": 3}, 2000, 1.5
    opt = create(name, **kw)
    w = np.full(64, 5.0, np.float32)
    for _ in range(iters):
        w = np.asarray(opt.update(0, w, w.copy()), np.float32)
    end = float(np.mean(np.abs(w)))
    assert end < bound, f"{name}: mean|w| only reached {end} from 5.0"


# ---------------------------------------------------------------------------
# update-rule math (one or two steps, hand-computed)
# ---------------------------------------------------------------------------

def test_nag_matches_reference_formula():
    opt = NAG(learning_rate=0.1, momentum=0.9)
    w = np.array([1.0], np.float32)
    g = np.array([0.5], np.float32)
    # step 1: state = g; w -= lr*(g + mom*state)
    w1 = opt.update(0, w, g)
    np.testing.assert_allclose(w1, 1.0 - 0.1 * (0.5 + 0.9 * 0.5))
    # step 2 with g2: state = mom*state + g2; w -= lr*(g2 + mom*state)
    g2 = np.array([0.2], np.float32)
    state = 0.9 * 0.5 + 0.2
    w2 = opt.update(0, w1, g2)
    np.testing.assert_allclose(
        w2, np.asarray(w1) - 0.1 * (0.2 + 0.9 * state), rtol=1e-6)


def test_signum_takes_sign_and_decoupled_wd():
    opt = Signum(learning_rate=0.1, momentum=0.0, wd_lh=0.1)
    w = np.array([2.0, -2.0], np.float32)
    g = np.array([0.003, -7.0], np.float32)
    out = opt.update(0, w, g)
    np.testing.assert_allclose(
        out, (1 - 0.1 * 0.1) * w - 0.1 * np.array([1.0, -1.0]), rtol=1e-6)


def test_adagrad_accumulates_history():
    opt = AdaGrad(learning_rate=0.5, eps=1e-7)
    w = np.array([1.0], np.float32)
    g = np.array([2.0], np.float32)
    w1 = opt.update(0, w, g)
    np.testing.assert_allclose(w1, 1.0 - 0.5 * 2.0 / np.sqrt(4 + 1e-7),
                               rtol=1e-6)
    w2 = opt.update(0, w1, g)
    np.testing.assert_allclose(
        w2, np.asarray(w1) - 0.5 * 2.0 / np.sqrt(8 + 1e-7), rtol=1e-6)


def test_rmsprop_plain_and_centered():
    g = np.array([1.0], np.float32)
    w = np.array([1.0], np.float32)
    plain = RMSProp(learning_rate=0.1, gamma1=0.9, epsilon=1e-8)
    w1 = plain.update(0, w, g)
    n = 0.1 * 1.0
    np.testing.assert_allclose(w1, 1.0 - 0.1 * 1.0 / np.sqrt(n + 1e-8),
                               rtol=1e-6)
    cent = RMSProp(learning_rate=0.1, gamma1=0.9, gamma2=0.9,
                   centered=True, epsilon=1e-8)
    w1c = cent.update(0, w, g)
    gbar = 0.1 * 1.0
    delta = -0.1 * 1.0 / np.sqrt(n - gbar ** 2 + 1e-8)
    np.testing.assert_allclose(w1c, 1.0 + delta, rtol=1e-6)


def test_adadelta_reference_formula():
    opt = AdaDelta(rho=0.9, epsilon=1e-5)
    w = np.array([1.0], np.float32)
    g = np.array([2.0], np.float32)
    out = opt.update(0, w, g)
    acc_g = 0.1 * 4.0
    delta = np.sqrt(1e-5) / np.sqrt(acc_g + 1e-5) * 2.0
    np.testing.assert_allclose(out, 1.0 - delta, rtol=1e-5)


def test_ftrl_sparsifies_small_weights():
    """|z| <= lamda1 coordinates snap to exactly zero (the FTRL
    proximal property the reference update encodes)."""
    opt = Ftrl(lamda1=1.0, learning_rate=0.1, beta=1.0)
    w = np.zeros(2, np.float32)
    out = opt.update(0, w, np.array([0.01, 50.0], np.float32))
    assert out[0] == 0.0 and out[1] != 0.0


def test_adamax_infinity_norm():
    opt = Adamax(learning_rate=0.002, beta1=0.9, beta2=0.999)
    w = np.array([1.0], np.float32)
    g = np.array([4.0], np.float32)
    out = opt.update(0, w, g)
    m = 0.1 * 4.0
    u = 4.0  # max(0.999*0, |g|)
    np.testing.assert_allclose(
        out, 1.0 - 0.002 / (1 - 0.9) * m / u, rtol=1e-6)


def test_nadam_first_step():
    opt = Nadam(learning_rate=0.1, beta1=0.9, beta2=0.999, epsilon=1e-8)
    w = np.array([1.0], np.float32)
    g = np.array([1.0], np.float32)
    out = opt.update(0, w, g)
    mt = 0.9 * (1 - 0.5 * 0.96 ** 0.004)
    mt1 = 0.9 * (1 - 0.5 * 0.96 ** 0.008)
    msched = mt
    gp = 1.0 / (1 - msched)
    mp = (0.1 * 1.0) / (1 - msched * mt1)
    vp = (0.001 * 1.0) / (1 - 0.999)
    mbar = (1 - mt) * gp + mt1 * mp
    np.testing.assert_allclose(
        out, 1.0 - 0.1 * mbar / (np.sqrt(vp) + 1e-8), rtol=1e-5)


def test_ftml_first_step():
    opt = FTML(learning_rate=0.1, beta1=0.6, beta2=0.999, epsilon=1e-8)
    w = np.array([1.0], np.float32)
    g = np.array([2.0], np.float32)
    out = opt.update(0, w, g)
    v = 0.001 * 4.0
    d_t = (1 - 0.6) / 0.1 * (np.sqrt(v / 0.001) + 1e-8)
    z = 0.4 * 2.0 - d_t * 1.0
    np.testing.assert_allclose(out, -z / d_t, rtol=1e-5)


def test_sgld_adds_noise_with_lr_scale():
    a = SGLD(learning_rate=0.01, seed=7)
    b = SGLD(learning_rate=0.01, seed=7)
    w = np.zeros(1000, np.float32)
    g = np.zeros(1000, np.float32)
    oa, ob = a.update(0, w, g), b.update(0, w, g)
    np.testing.assert_array_equal(oa, ob)  # seeded determinism
    assert 0.05 < float(np.std(oa)) < 0.2  # ~ sqrt(lr) = 0.1


# ---------------------------------------------------------------------------
# pickling (command-channel transport) and state round-trip
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", ALL_NAMES)
def test_pickle_round_trip_continues_identically(name):
    opt = create(name, learning_rate=0.05)
    w = np.full(4, 3.0, np.float32)
    rng = np.random.default_rng(0)
    for _ in range(5):
        w = np.asarray(opt.update(0, w, rng.normal(
            size=4).astype(np.float32)))
    clone = pickle.loads(pickle.dumps(opt))
    g = np.ones(4, np.float32)
    np.testing.assert_allclose(np.asarray(opt.update(0, w.copy(), g)),
                               np.asarray(clone.update(0, w.copy(), g)),
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# LR schedulers
# ---------------------------------------------------------------------------

def test_factor_scheduler_decay_and_floor():
    s = lrs.FactorScheduler(step=10, factor=0.1, base_lr=1.0,
                            stop_factor_lr=1e-3)
    assert s(1) == 1.0
    assert abs(s(11) - 0.1) < 1e-12
    assert abs(s(21) - 0.01) < 1e-12
    for nu in (31, 41, 51):
        s(nu)
    assert s(99) == 1e-3  # floored


def test_multifactor_milestones():
    s = lrs.MultiFactorScheduler(step=[5, 8], factor=0.5, base_lr=1.0)
    assert s(5) == 1.0
    assert s(6) == 0.5
    assert s(8) == 0.5
    assert s(9) == 0.25
    assert s(100) == 0.25


def test_poly_and_cosine_endpoints():
    p = lrs.PolyScheduler(max_update=100, base_lr=1.0, pwr=2,
                          final_lr=0.1)
    assert abs(p(0) - 1.0) < 1e-12
    assert abs(p(100) - 0.1) < 1e-12
    c = lrs.CosineScheduler(max_update=100, base_lr=1.0, final_lr=0.0)
    assert abs(c(0) - 1.0) < 1e-12
    assert abs(c(50) - 0.5) < 1e-9
    assert abs(c(100) - 0.0) < 1e-12


def test_warmup_linear_then_decay():
    s = lrs.CosineScheduler(max_update=20, base_lr=1.0,
                            warmup_steps=10, warmup_begin_lr=0.0)
    assert s(0) == 0.0
    assert abs(s(5) - 0.5) < 1e-12
    assert abs(s(10) - 1.0) < 1e-12  # decay starts at base_lr


def test_scheduler_factory_and_validation():
    assert isinstance(lrs.create("cosine", max_update=10),
                      lrs.CosineScheduler)
    with pytest.raises(ValueError):
        lrs.create("nope")
    with pytest.raises(ValueError):
        lrs.FactorScheduler(step=0)
    with pytest.raises(ValueError):
        lrs.MultiFactorScheduler(step=[5, 3])


def test_optimizer_uses_scheduler_with_max_key_count():
    """num_update is the MAX per-key count (reference lr_scheduler
    contract) and the effective lr follows the scheduler."""
    sched = lrs.MultiFactorScheduler(step=[2], factor=0.1, base_lr=0.5)
    opt = SGD(learning_rate=0.5, lr_scheduler=sched)
    w = np.zeros(1, np.float32)
    g = np.ones(1, np.float32)
    # key 0 updated 3x -> num_update 3 > milestone 2 -> lr 0.05
    opt.update(0, w, g)
    opt.update(0, w, g)
    opt.update(0, w, g)
    out = opt.update(1, w.copy(), g)  # key 1 first update, lr already 0.05
    np.testing.assert_allclose(out, -0.05, rtol=1e-6)


def test_scheduler_travels_in_pickle():
    sched = lrs.FactorScheduler(step=1, factor=0.5, base_lr=1.0)
    opt = SGD(learning_rate=1.0, lr_scheduler=sched)
    w, g = np.zeros(1, np.float32), np.ones(1, np.float32)
    for _ in range(3):
        opt.update(0, w, g)
    clone = pickle.loads(pickle.dumps(opt))
    np.testing.assert_allclose(
        np.asarray(opt.update(0, w.copy(), g)),
        np.asarray(clone.update(0, w.copy(), g)))


def test_dcasgd_prev_is_pre_update_weight():
    """ADVICE r3 (medium): state['prev'] must snapshot the PRE-update
    weight (reference optimizer.py:924) so the compensation term
    lamda*g*g*(w - prev) is nonzero on the next stale gradient."""
    opt = DCASGD(learning_rate=0.1, lamda=0.04)
    w0 = np.array([1.0, -2.0], np.float32)
    g = np.array([0.5, 0.5], np.float32)
    state = opt.create_state(0, w0)
    w1 = opt.step(0, w0, g, state, 0.1)
    # prev now holds w0 (pre-update), not w1
    np.testing.assert_allclose(state["prev"], w0)
    # second step: compensation term must fire (w1 != prev)
    comp = g + opt.lamda * g * g * (w1 - w0)
    expected = w1 - 0.1 * comp
    w2 = opt.step(0, w1, g, state, 0.1)
    np.testing.assert_allclose(w2, expected, rtol=1e-6)


def test_lbsgd_cumulates_to_macro_batches():
    """batch_scale micro-grads accumulate; the macro step applies SGD
    on their mean (reference: optimizer.py:826-839)."""
    from geomx_tpu.optimizer import LBSGD

    opt = LBSGD(learning_rate=0.1, batch_scale=3, warmup_epochs=0)
    w = np.array([1.0, 2.0], np.float32)
    st = opt.create_state(0, w)
    g1 = np.array([0.3, 0.6], np.float32)
    g2 = np.array([0.6, 0.9], np.float32)
    g3 = np.array([0.0, 0.3], np.float32)
    # mid-macro-batch: weight untouched
    assert opt.step(0, w, g1, st, 0.1) is w
    assert opt.step(0, w, g2, st, 0.1) is w
    w2 = opt.step(0, w, g3, st, 0.1)
    # warmup done (warmup_epochs=0) -> mult = batch_scale = 3
    mean_g = (g1 + g2 + g3) / 3
    np.testing.assert_allclose(w2, w - 0.1 * 3 * mean_g, rtol=1e-6)
    assert st["cum"] is None  # reset for the next macro batch


def test_lbsgd_warmup_ramps_linearly():
    from geomx_tpu.optimizer import LBSGD

    opt = LBSGD(learning_rate=1.0, batch_scale=8, warmup_epochs=1,
                updates_per_epoch=16)
    # nup halfway through warmup: mult = 1 + 7 * 8/16
    assert opt._lbmult(8) == 1.0 + 7 * 0.5
    assert opt._lbmult(16) == 8.0   # warmup done
    assert opt._lbmult(999) == 8.0


def test_lbsgd_lars_trust_ratio():
    from geomx_tpu.optimizer import LBSGD

    opt = LBSGD(learning_rate=0.1, warmup_strategy="lars", wd=0.0)
    w = np.array([3.0, 4.0], np.float32)       # ||w|| = 5
    g = np.array([0.6, 0.8], np.float32)       # ||g|| = 1
    assert abs(opt._lars(w, g) - 5.0) < 1e-5
    # clipping
    assert opt._lars(w, np.zeros(2, np.float32) + 1e-12) == 100.0
    assert opt._lars(np.zeros(2, np.float32) + 1e-12, g) == 0.01


def test_lbsgd_begin_epoch_keeps_macro_alignment():
    """Review finding: seeding the cumulation counter with
    begin_epoch*updates_per_epoch fired the first macro update early on
    an under-scaled mean; the boundary counter must start at zero."""
    from geomx_tpu.optimizer import LBSGD

    opt = LBSGD(learning_rate=0.1, batch_scale=3, updates_per_epoch=32,
                begin_epoch=1, warmup_epochs=0)
    w = np.array([1.0], np.float32)
    st = opt.create_state(0, w)
    g = np.array([0.3], np.float32)
    # first two micro-grads must NOT update
    assert opt.step(0, w, g, st, 0.1) is w
    assert opt.step(0, w, g, st, 0.1) is w
    w2 = opt.step(0, w, g, st, 0.1)
    assert not np.array_equal(w2, w)
