"""Pytest gate for geomx-lint (tools/analyze).

Two jobs:

1. Prove every rule fires — each rule id is exercised against the
   seeded-violation fixtures in tests/fixtures_analyze/ (which also
   carry clean counterparts that must stay clean).
2. Gate the real tree — ``run_all`` over geomx_tpu/ must produce zero
   findings beyond the committed baseline, and the baseline must carry
   no stale entries (every accepted fingerprint still corresponds to a
   live finding).

Pure AST analysis: none of this imports jax or spawns processes beyond
the one CLI smoke test.
"""

import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from tools.analyze import (DEFAULT_BASELINE, load_baseline, load_sources,
                           run_all, run_concurrency, run_config_drift,
                           run_lockmodel, run_metrics, run_protocol,
                           run_traced, save_baseline, split_by_baseline,
                           write_binmeta_lock, write_lock_model)
from tools.analyze.config_drift import _expand_doc_shorthand
from tools.analyze.lockmodel import (extract_lock_model, lockmodel_lock_path,
                                     model_fingerprint)
from tools.analyze.protocol import (binmeta_lock_path, extract_meta_schema,
                                    meta_schema_fingerprint)

REPO = Path(__file__).resolve().parents[1]
FIXTURES = Path(__file__).resolve().parent / "fixtures_analyze"


def _rules(findings):
    return {f.rule for f in findings}


def _by_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


# ---------------------------------------------------------------------------
# concurrency pass (GX-L001..L004)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def lock_findings():
    sources = load_sources([FIXTURES / "locks_bad.py"], FIXTURES)
    return run_concurrency(sources)


def test_lock_order_inversion_fires(lock_findings):
    hits = _by_rule(lock_findings, "GX-L001")
    assert len(hits) == 1
    assert hits[0].symbol == "locks_bad.Inverted"
    assert hits[0].detail == "a:b"


def test_mixed_guarded_unguarded_write_fires(lock_findings):
    hits = _by_rule(lock_findings, "GX-L002")
    assert [h.symbol for h in hits] == ["locks_bad.Inverted.counter"]
    assert "unguarded" in hits[0].message


def test_blocking_under_lock_fires(lock_findings):
    hits = _by_rule(lock_findings, "GX-L003")
    by_detail = {h.detail: h for h in hits}
    assert "time.sleep" in by_detail            # sleep under self.a
    assert "self.t.join" in by_detail           # thread join under self.a
    # Condition.wait while holding ANOTHER lock is flagged ...
    assert by_detail["self.cv.wait"].symbol == "bad_wait"
    # ... but the canonical with-cv: cv.wait() pattern is not
    assert all(h.symbol != "ok_wait" for h in hits)


def test_reentrant_lock_fires(lock_findings):
    hits = _by_rule(lock_findings, "GX-L004")
    symbols = {h.symbol for h in hits}
    assert "reenter_lexical" in symbols         # with a: with a:
    assert "reenter_via_call" in symbols        # helper retakes b
    # RLock re-entry is legal and must stay clean
    assert all(h.detail != "r" for h in hits)


# ---------------------------------------------------------------------------
# traced pass (GX-J101..J103)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def traced_findings():
    sources = load_sources([FIXTURES / "traced_bad.py"], FIXTURES)
    return run_traced(sources)


def test_host_sync_fires(traced_findings):
    hits = _by_rule(traced_findings, "GX-J101")
    names = {h.detail.split(":")[0] for h in hits}
    assert {"float", "y.item"} <= names          # directly in hot()
    # np.asarray is reached transitively: hot() -> helper()
    assert any(h.symbol == "helper" and "np.asarray" in h.detail
               for h in hits)
    # shape arithmetic is static under tracing — never flagged
    assert all(h.symbol != "static_ok" for h in hits)


def test_retrace_hazard_fires(traced_findings):
    hits = _by_rule(traced_findings, "GX-J102")
    details = {h.detail for h in hits}
    assert "inline-call" in details              # jax.jit(f)(x)
    assert any(d.startswith("loop:") for d in details)
    assert all(h.symbol == "looped" for h in hits)


def test_missing_donate_fires(traced_findings):
    hits = _by_rule(traced_findings, "GX-J103")
    assert [h.symbol for h in hits] == ["train_step"]
    # donated, non-state-returning, and static functions all stay clean


def test_mesh_host_transfer_fires():
    """GX-J104: unguarded host transfers on round-shaped methods of
    Mesh-named classes fire — directly, transitively, and for
    .addressable_data — while is_global_worker-guarded forms, fenced
    early exits, non-round methods, and non-Mesh classes stay clean."""
    sources = load_sources([FIXTURES / "mesh_bad.py"], FIXTURES)
    hits = _by_rule(run_traced(sources), "GX-J104")
    syms = {h.symbol for h in hits}
    assert "PartyMeshStore.push_round" in syms
    assert "PartyMeshStore.step" in syms
    # transitive: pull_results -> _fetch -> jax.device_get
    assert any(h.symbol == "PartyMeshStore._fetch"
               and "jax.device_get" in h.detail for h in hits)
    # guarded / fenced / out-of-scope symbols never fire
    assert all(not h.symbol.startswith("CleanMeshStore") for h in hits)
    assert all(not h.symbol.startswith("PlainWireStore") for h in hits)
    assert all(h.symbol != "PartyMeshStore.close" for h in hits)
    assert all(h.severity == "error" for h in hits)


def test_mesh_codec_host_transfer_fires():
    """GX-J105: unguarded host transfers inside codec-shaped methods of
    Ring-named classes fire — directly, transitively, and for
    .addressable_data — while guarded/fenced forms, host-zero
    constructors, non-codec methods, and the van WireCodec (whose host
    arrays are the product) stay clean."""
    sources = load_sources([FIXTURES / "codec_bad.py"], FIXTURES)
    hits = _by_rule(run_traced(sources), "GX-J105")
    syms = {h.symbol for h in hits}
    assert "PartyRingReducer.reduce" in syms
    assert "PartyRingReducer.reset" in syms
    # transitive: quantize_hop -> _drain -> jax.device_get
    assert any(h.symbol == "PartyRingReducer._drain"
               and "jax.device_get" in h.detail for h in hits)
    # guarded / fenced / out-of-scope symbols never fire
    assert all(not h.symbol.startswith("CleanRingReducer") for h in hits)
    assert all(not h.symbol.startswith("WireCodec") for h in hits)
    assert all(h.symbol != "PartyRingReducer.wire_bytes" for h in hits)
    assert all(h.severity == "error" for h in hits)


# ---------------------------------------------------------------------------
# config-drift pass (GX-C201..C204)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def drift_findings():
    root = FIXTURES / "driftproj"
    sources = load_sources([root / "geomx_tpu"], root)
    return run_config_drift(sources, root)


def test_undocumented_knob_fires(drift_findings):
    hits = _by_rule(drift_findings, "GX-C201")
    names = {h.symbol for h in hits}
    assert names == {"PS_UNDOCUMENTED", "PS_RAW_FLAG"}
    assert "PS_DOCUMENTED" not in names          # registered + documented


def test_stale_doc_row_fires(drift_findings):
    hits = _by_rule(drift_findings, "GX-C202")
    assert [h.symbol for h in hits] == ["PS_STALE"]
    assert hits[0].path == "docs/env-var-summary.md"


def test_raw_env_read_fires(drift_findings):
    hits = _by_rule(drift_findings, "GX-C203")
    assert [h.symbol for h in hits] == ["PS_RAW_FLAG"]
    assert hits[0].path == "geomx_tpu/other.py"


def test_dead_script_knob_fires(drift_findings):
    hits = _by_rule(drift_findings, "GX-C204")
    assert [h.symbol for h in hits] == ["DMLC_DEAD_KNOB"]
    # PS_DOCUMENTED is exported by the same script but IS read — clean


def test_doc_shorthand_expansion():
    assert _expand_doc_shorthand(
        ["DMLC_PS_GLOBAL_ROOT_URI", "_PORT"]) == \
        ["DMLC_PS_GLOBAL_ROOT_URI", "DMLC_PS_GLOBAL_ROOT_PORT"]
    assert _expand_doc_shorthand(["DMLC_K", "_K_MIN"]) == \
        ["DMLC_K", "DMLC_K_MIN"]


# ---------------------------------------------------------------------------
# protocol pass (GX-P301..P307)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def proto_findings():
    root = FIXTURES / "protoproj"
    sources = load_sources([root / "geomx_tpu"], root)
    return run_protocol(sources, root)


def test_control_verb_drift_fires(proto_findings):
    hits = _by_rule(proto_findings, "GX-P301")
    got = {(h.symbol, h.detail) for h in hits}
    assert got == {("Control.ORPHAN", "sent-unhandled"),
                   ("Control.GHOST", "dispatched-unsent"),
                   ("Control.UNUSED", "unused")}
    # PING (sent + dispatched) and EMPTY (exempt marker) stay clean


def test_droppable_request_fires(proto_findings):
    hits = _by_rule(proto_findings, "GX-P302")
    assert [h.symbol for h in hits] == ["BadServer.handle_push"]
    assert hits[0].detail.startswith("return@")
    # the fenced drop, the `return False` decline and the post-ack
    # return in GoodServer all stay clean


def test_bare_key_routing_fires(proto_findings):
    hits = _by_rule(proto_findings, "GX-P303")
    assert [h.symbol for h in hits] == ["BadServer.handle_pull"]
    # GoodServer.handle_pull consults offset_of — clean


def test_unfenced_mutation_fires(proto_findings):
    hits = _by_rule(proto_findings, "GX-P304")
    assert [h.symbol for h in hits] == ["BadServer.handle_push"]
    assert hits[0].detail == "unfenced-mutation"
    # GoodServer.handle_push mutates behind its is_stale fence — clean


def test_static_count_fires(proto_findings):
    hits = _by_rule(proto_findings, "GX-P305")
    got = {(h.symbol, h.detail) for h in hits}
    assert got == {("BadServer.check_round", "compare:num_workers"),
                   ("BadServer.start_round", "kwarg:tgt:num_workers")}
    # GoodServer.check_round uses num_live_workers() — clean


def test_compr_without_aux_fires(proto_findings):
    hits = _by_rule(proto_findings, "GX-P307")
    assert [h.symbol for h in hits] == ["send_quantized"]
    assert hits[0].detail == "van.push:2bit"
    # the aux-carrying 2bit/rsp sites, the self-describing fp16 tag and
    # the dynamic compr=tag form all stay clean


def test_binmeta_schema_drift_fires(proto_findings):
    hits = _by_rule(proto_findings, "GX-P306")
    assert [h.detail for h in hits] == ["schema-changed"]
    assert hits[0].symbol == "_META_FIELDS"


def test_binmeta_lock_missing_and_version_change(tmp_path):
    src = FIXTURES / "protoproj" / "geomx_tpu" / "proto_bad.py"
    (tmp_path / "geomx_tpu").mkdir()
    fx = tmp_path / "geomx_tpu" / "proto_bad.py"
    fx.write_text(src.read_text(encoding="utf-8"), encoding="utf-8")
    sources = load_sources([tmp_path / "geomx_tpu"], tmp_path)

    # no lock at all -> lock-missing
    hits = _by_rule(run_protocol(sources, tmp_path), "GX-P306")
    assert [h.detail for h in hits] == ["lock-missing"]

    # a fresh lock makes the pass clean
    write_binmeta_lock(sources, tmp_path)
    assert _by_rule(run_protocol(sources, tmp_path), "GX-P306") == []

    # bump BINMETA_VERSION without refreshing the lock -> version-changed
    fx.write_text(fx.read_text(encoding="utf-8").replace(
        "BINMETA_VERSION = 3", "BINMETA_VERSION = 4"), encoding="utf-8")
    sources = load_sources([tmp_path / "geomx_tpu"], tmp_path)
    hits = _by_rule(run_protocol(sources, tmp_path), "GX-P306")
    assert [h.detail for h in hits] == ["version-changed"]


def test_committed_binmeta_lock_matches_tree():
    """The real lock is in sync with geomx_tpu/ps/message.py — the
    schema-drift gate holds on the committed tree."""
    import json
    sources = load_sources([REPO / "geomx_tpu" / "ps" / "message.py"], REPO)
    schema = extract_meta_schema(sources)
    assert schema is not None
    _src, _line, version, fields = schema
    lock = json.loads(binmeta_lock_path(REPO).read_text(encoding="utf-8"))
    assert lock["version"] == version
    assert lock["fingerprint"] == meta_schema_fingerprint(fields)


# ---------------------------------------------------------------------------
# lockmodel pass (GX-L005..L007)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def lockmodel_findings():
    root = FIXTURES / "lockproj"
    return run_lockmodel(load_sources([root / "geomx_tpu"], root), root)


def test_unguarded_multiroot_write_fires(lockmodel_findings):
    hits = _by_rule(lockmodel_findings, "GX-L005")
    assert [h.symbol for h in hits] == ["lockmodel_bad.Bad005.count"]
    # both racing roots are named: the spawned loop and the external
    # caller; the @guarded_by-declared and lock-holding counterparts
    # stay clean
    assert "_loop" in hits[0].detail and "<caller>" in hits[0].detail


def test_wait_outside_while_fires(lockmodel_findings):
    hits = _by_rule(lockmodel_findings, "GX-L006")
    assert [h.symbol for h in hits] == ["lockmodel_bad.Bad006.take"]
    assert hits[0].detail == "_cv"
    # the while-predicate loop and wait_for() shapes stay clean


def test_lock_model_missing_and_drift(tmp_path):
    src = FIXTURES / "lockproj" / "geomx_tpu" / "lockmodel_bad.py"
    (tmp_path / "geomx_tpu").mkdir()
    fx = tmp_path / "geomx_tpu" / "lockmodel_bad.py"
    fx.write_text(src.read_text(encoding="utf-8"), encoding="utf-8")
    sources = load_sources([tmp_path / "geomx_tpu"], tmp_path)

    # no lock file at all -> lock-missing
    hits = _by_rule(run_lockmodel(sources, tmp_path), "GX-L007")
    assert [h.detail for h in hits] == ["lock-missing"]

    # freezing the model makes the pass clean
    write_lock_model(sources, tmp_path)
    assert _by_rule(run_lockmodel(sources, tmp_path), "GX-L007") == []

    # moving a @guarded_by declaration to another lock without
    # refreshing the frozen model -> model-changed
    fx.write_text(fx.read_text(encoding="utf-8").replace(
        'locks.guarded_by("_lock", "count")',
        'locks.guarded_by("_cv", "count")'), encoding="utf-8")
    sources = load_sources([tmp_path / "geomx_tpu"], tmp_path)
    hits = _by_rule(run_lockmodel(sources, tmp_path), "GX-L007")
    assert [h.detail for h in hits] == ["model-changed"]
    assert hits[0].symbol == "geomx_tpu/lockmodel_bad.py"


def test_committed_lock_model_matches_tree():
    """The real lock model is in sync with the tree: the runtime
    witness and GX-L007 read the same frozen declarations."""
    import json
    model = extract_lock_model(load_sources([REPO / "geomx_tpu"], REPO))
    doc = json.loads(
        lockmodel_lock_path(REPO).read_text(encoding="utf-8"))
    files = doc["files"]
    assert sorted(files) == sorted(model)
    for rel, entry in model.items():
        assert files[rel]["fingerprint"] == model_fingerprint(entry), rel


# ---------------------------------------------------------------------------
# metrics pass (GX-M401)
# ---------------------------------------------------------------------------

def test_raw_profiler_event_fires():
    root = FIXTURES / "metricsproj"
    sources = load_sources([root / "geomx_tpu"], root)
    hits = _by_rule(run_metrics(sources), "GX-M401")
    got = {(h.symbol, h.detail) for h in hits}
    # pre-suppression: the disable-commented site is still found here
    assert got == {
        ("Thing.flag", "profiler.instant:thing.flagged"),
        ("Thing.count", "profiler.counter:thing.count"),
        ("Thing.suppressed", "profiler.instant:thing.quiet"),
        ("module_level", "profiler.instant:module.marker"),
    }
    # the funnel file itself and telemetry.event/sample callers, plus
    # profiler.scope spans, all stay clean
    assert all(h.path.endswith("other.py") for h in hits)


def test_metrics_suppression_and_funnel_exemption():
    root = FIXTURES / "metricsproj"
    hits = _by_rule(run_all([root / "geomx_tpu"], root,
                            passes=["metrics"]), "GX-M401")
    assert {h.symbol for h in hits} == \
        {"Thing.flag", "Thing.count", "module_level"}


# ---------------------------------------------------------------------------
# metrics pass (GX-M402: link.* outside the linkstate funnel)
# ---------------------------------------------------------------------------

def test_link_metric_outside_linkstate_fires():
    root = FIXTURES / "linkstateproj"
    sources = load_sources([root / "geomx_tpu"], root)
    hits = _by_rule(run_metrics(sources), "GX-M402")
    got = {(h.symbol, h.detail) for h in hits}
    # pre-suppression: the disable-commented site is still found here
    assert got == {
        ("Shaper.hold", "telemetry.gauge_set:link.shaped_delay_ms"),
        ("Shaper.carried", "telemetry.counter_inc:link.shaped_bytes"),
        ("Shaper.suppressed", "telemetry.gauge_set:link.goodput_mb_s"),
        ("module_level", "telemetry.gauge_set:link.bw_mbps"),
    }
    # the funnel file itself, linkstate-routed callers and non-link
    # metric names all stay clean
    assert all(h.path.endswith("other.py") for h in hits)


def test_link_metric_suppression_and_funnel_exemption():
    root = FIXTURES / "linkstateproj"
    hits = _by_rule(run_all([root / "geomx_tpu"], root,
                            passes=["metrics"]), "GX-M402")
    assert {h.symbol for h in hits} == \
        {"Shaper.hold", "Shaper.carried", "module_level"}


def test_repo_tree_has_no_link_metric_leaks():
    """Zero new baseline entries: the real tree's only link.* emitter
    is ps/linkstate.py (tsengine and shaping route through it)."""
    sources = load_sources([REPO / "geomx_tpu"], REPO)
    assert _by_rule(run_metrics(sources), "GX-M402") == []


# ---------------------------------------------------------------------------
# plumbing: syntax errors, suppression, baseline
# ---------------------------------------------------------------------------

def test_syntax_error_becomes_finding(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def broken(:\n", encoding="utf-8")
    findings = run_all([bad], tmp_path, passes=["concurrency"])
    assert _rules(findings) == {"GX-E000"}


_SLEEPER = textwrap.dedent("""\
    import threading, time

    class C:
        def __init__(self):
            self.l = threading.Lock()

        def m(self):
            with self.l:
                time.sleep(1){comment}
    """)


def test_suppression_comment_drops_finding(tmp_path):
    noisy = tmp_path / "noisy.py"
    noisy.write_text(_SLEEPER.format(comment=""), encoding="utf-8")
    assert "GX-L003" in _rules(run_all([noisy], tmp_path,
                                       passes=["concurrency"]))

    noisy.write_text(
        _SLEEPER.format(comment="  # geomx-lint: disable=GX-L003"),
        encoding="utf-8")
    assert run_all([noisy], tmp_path, passes=["concurrency"]) == []

    # disable=all works too, and an unrelated rule id does not suppress
    noisy.write_text(
        _SLEEPER.format(comment="  # geomx-lint: disable=all"),
        encoding="utf-8")
    assert run_all([noisy], tmp_path, passes=["concurrency"]) == []
    noisy.write_text(
        _SLEEPER.format(comment="  # geomx-lint: disable=GX-L001"),
        encoding="utf-8")
    assert "GX-L003" in _rules(run_all([noisy], tmp_path,
                                       passes=["concurrency"]))


_MULTILINE = textwrap.dedent("""\
    class Counter:
        def __init__(self, po):
            self.po = po

        def arm(self, received):
            {before}self.check(
                received,{inline}
                tgt=self.po.num_workers,
            )

        def check(self, received, tgt):
            return received >= tgt
    """)


def test_suppression_spans_multiline_statement(tmp_path):
    """A disable comment anywhere on a multi-line statement — or on the
    line above it — suppresses a finding anchored inside it."""
    f = tmp_path / "span.py"

    f.write_text(_MULTILINE.format(before="", inline=""), encoding="utf-8")
    assert "GX-P305" in _rules(run_all([f], tmp_path, passes=["protocol"]))

    # comment on a DIFFERENT line of the same statement than the finding
    f.write_text(
        _MULTILINE.format(
            before="", inline="  # geomx-lint: disable=GX-P305"),
        encoding="utf-8")
    assert _by_rule(run_all([f], tmp_path, passes=["protocol"]),
                    "GX-P305") == []

    # comment on the line above the statement's first line
    f.write_text(
        _MULTILINE.format(
            before="# geomx-lint: disable=GX-P305\n        ", inline=""),
        encoding="utf-8")
    assert _by_rule(run_all([f], tmp_path, passes=["protocol"]),
                    "GX-P305") == []


_DECORATED = textwrap.dedent("""\
    import functools

    class S:
        def __init__(self, po):
            self.po = po
            self.nm = 0

        {comment}@functools.lru_cache(None)
        def handle_push(self, req):
            self.nm += 1
            self.po.respond(req)
    """)


def test_suppression_spans_decorated_def(tmp_path):
    """A disable comment above the decorator suppresses a finding
    anchored at the def line; a body comment must NOT (header-only
    span)."""
    f = tmp_path / "deco.py"

    f.write_text(_DECORATED.format(comment=""), encoding="utf-8")
    assert "GX-P304" in _rules(run_all([f], tmp_path, passes=["protocol"]))

    f.write_text(
        _DECORATED.format(comment="# geomx-lint: disable=GX-P304\n    "),
        encoding="utf-8")
    assert _by_rule(run_all([f], tmp_path, passes=["protocol"]),
                    "GX-P304") == []

    # a comment in the BODY is outside the header span — still fires
    body = _DECORATED.format(comment="").replace(
        "self.nm += 1", "self.nm += 1  # geomx-lint: disable=GX-P304")
    f.write_text(body, encoding="utf-8")
    assert "GX-P304" in _rules(run_all([f], tmp_path, passes=["protocol"]))


def test_baseline_roundtrip_and_split(tmp_path, lock_findings):
    bl = tmp_path / "baseline.json"
    save_baseline(bl, lock_findings)
    baseline = load_baseline(bl)
    new, accepted = split_by_baseline(lock_findings, baseline)
    assert new == []
    assert len(accepted) == len(lock_findings)
    # fingerprints are line-free: a renumbered finding still matches
    moved = accepted[0].__class__(**{**vars(accepted[0]),
                                     "line": accepted[0].line + 40})
    assert moved.fingerprint in baseline


def test_prune_baseline_drops_only_stale(tmp_path, lock_findings):
    """`--prune-baseline` removes fingerprints no finding produces and
    keeps the live ones."""
    import json
    bl = tmp_path / "baseline.json"
    save_baseline(bl, lock_findings)
    live = sorted(load_baseline(bl))
    stale = ["GX-L999:gone.py:nowhere:", "GX-L998:gone.py:also:"]
    bl.write_text(json.dumps({"version": 1,
                              "findings": sorted(live + stale)}) + "\n",
                  encoding="utf-8")

    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--prune-baseline",
         "--root", str(FIXTURES), "--baseline", str(bl),
         str(FIXTURES / "locks_bad.py")],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "2 stale entrie(s) dropped" in proc.stdout
    assert sorted(load_baseline(bl)) == live


def test_prune_committed_baseline_is_noop(tmp_path):
    """Pruning a copy of the committed baseline changes nothing — the
    repo baseline carries no stale entries."""
    bl = tmp_path / "baseline.json"
    bl.write_text(DEFAULT_BASELINE.read_text(encoding="utf-8"),
                  encoding="utf-8")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--prune-baseline",
         "--baseline", str(bl)],
        cwd=REPO, capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 stale entrie(s) dropped" in proc.stdout
    assert load_baseline(bl) == load_baseline(DEFAULT_BASELINE)


# ---------------------------------------------------------------------------
# the gate: the real tree is clean against the committed baseline
# ---------------------------------------------------------------------------

def test_repo_clean_against_committed_baseline():
    findings = run_all([REPO / "geomx_tpu"], REPO)
    baseline = load_baseline(DEFAULT_BASELINE)
    new, accepted = split_by_baseline(findings, baseline)
    assert new == [], "new findings beyond baseline:\n" + "\n".join(
        f"  {f.render()}  (fingerprint {f.fingerprint})" for f in new)
    # no stale baseline entries either: every accepted fingerprint is live
    assert {f.fingerprint for f in accepted} == baseline


def test_cli_smoke():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyze"], cwd=REPO,
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert proc.stdout.startswith("OK: 0 new finding(s)"), proc.stdout

    # seeded violations must fail the gate when the baseline is bypassed
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--no-baseline",
         str(FIXTURES / "locks_bad.py")], cwd=REPO,
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "FAIL:" in proc.stdout
