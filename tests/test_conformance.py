"""State-model conformance sanitizer (geomx_tpu/ps/conformance.py).

Unit half: a StubVan drives StateSanitizer's hooks directly and proves
each divergence class latches (and that faithful transition reports
stay silent).

Van half: a real (unstarted) member Van processes DEAD_NODE / ADD_NODE
control messages with the sanitizer on — the live handlers and the
model must agree transition by transition. This also regression-tests
the table-adoption fix: a revival learned through the ADD_NODE table
broadcast must fire ``_membership_side_effects`` (countdown re-checks),
exactly like a DEAD_NODE adoption.

Recovery half: regression for the version-aware restore merge — a stale
snapshot must LOSE to a fresher peer replica (and win when it is the
fresher one).

Integration half: a real in-process tier runs a kill + zombie-fence
scenario with ``state_sanitizer=True`` on every van; the run must end
with zero violations on every statecheck, and the flight-recorder dumps
it leaves behind must replay clean through tools/modelcheck.py.
"""

import json
import os
import threading
import time
import types

import numpy as np
import pytest

from geomx_tpu import checkpoint
from geomx_tpu.ps.conformance import MARKER, StateSanitizer

assert MARKER  # the grep target scripts/run_chaos_matrix.sh fails on


class StubVan:
    def __init__(self, scheduler=False):
        self.is_scheduler = scheduler
        self.my_id = 1 if scheduler else 8
        self.flightrec = None


# ---------------------------------------------------------------------------
# unit: hook-level latching
# ---------------------------------------------------------------------------

def test_faithful_member_transitions_stay_silent():
    san = StateSanitizer(StubVan())
    san.on_dead_node(1, {11}, "adopt", (1, frozenset({11})))
    san.on_dead_node(1, {11}, "duplicate", (1, frozenset({11})))
    san.on_dead_node(0, set(), "stale", (1, frozenset({11})))
    san.on_fence(11, 0, True)            # dead -> stale: model agrees
    san.on_table(2, [11], (2, frozenset()))   # revival via table
    san.on_fence(11, 1, True)            # old-epoch zombie stays fenced
    san.on_fence(11, 2, False)           # rejoined incarnation passes
    assert san.report() == []


def test_outcome_divergence_latches(caplog):
    san = StateSanitizer(StubVan())
    san.on_dead_node(1, {11}, "adopt", (1, frozenset({11})))
    with caplog.at_level("ERROR", logger="geomx.conformance"):
        # a re-delivered broadcast the model calls "duplicate"
        san.on_dead_node(1, {11}, "adopt", (1, frozenset({11, 13})))
    assert any("outcome diverged" in v for v in san.violations)
    assert MARKER in caplog.text


def test_post_state_divergence_latches():
    san = StateSanitizer(StubVan())
    san.on_dead_node(1, {11}, "adopt", (1, frozenset({11, 12})))
    assert any("post-state diverged" in v for v in san.violations)


def test_declare_divergence_latches():
    san = StateSanitizer(StubVan(scheduler=True))
    san.on_declare([11], 1, frozenset({11}))       # faithful
    san.on_declare([12], 5, frozenset({11, 12}))   # epoch jumped to 5
    assert len(san.violations) == 1
    assert "declare_dead diverged" in san.violations[0]


def test_revive_divergence_latches():
    san = StateSanitizer(StubVan(scheduler=True))
    san.on_declare([11], 1, frozenset({11}))
    san.on_revive(11, 2)                 # faithful
    san.on_declare([12], 3, frozenset({12}))
    assert san.violations == []
    san.on_revive(12, 99)                # wrong epoch (model: 4)
    assert any("revive(12) diverged" in v for v in san.violations)


def test_fence_divergence_latches():
    san = StateSanitizer(StubVan())
    san.on_fence(9, 0, True)             # van fences a live sender
    assert any("is_stale(9, epoch=0) diverged" in v
               for v in san.violations)


def test_release_requires_fence_pass():
    san = StateSanitizer(StubVan())
    san.on_fence(9, 0, False)
    san.on_release(0, {(9, 0)})          # passed the fence: fine
    assert san.violations == []
    san.on_release(0, {(10, 0)})         # never fence-checked
    assert any("never passed the is_stale fence" in v
               for v in san.violations)


def test_restore_after_serving_latches():
    san = StateSanitizer(StubVan())
    san.on_restore("snapshot", served=False)
    assert san.violations == []
    san.on_restore("replica", served=True)
    assert any("AFTER the server started serving" in v
               for v in san.violations)


def test_report_is_idempotent(caplog):
    san = StateSanitizer(StubVan())
    san.on_fence(9, 0, True)
    assert len(san.report()) == 1
    assert len(san.on_shutdown()) == 1   # second report: no re-log
    assert len(san.violations) == 1


# ---------------------------------------------------------------------------
# van-level: real handlers against the mirror
# ---------------------------------------------------------------------------

def _member_van():
    from geomx_tpu.ps.message import Role
    from geomx_tpu.ps.van import Van

    van = Van(my_role=Role.WORKER, is_global=False,
              root_uri="127.0.0.1", root_port=1, num_workers=2,
              num_servers=1, state_sanitizer=True)
    van.my_id = 9
    van.my_port = 0      # normally assigned at bind time
    return van


def _msg(epoch, nodes):
    from geomx_tpu.ps.message import Message, Meta

    return Message(Meta(epoch=epoch, nodes=nodes))


def test_member_van_conforms_and_table_adoption_fires_side_effects():
    from geomx_tpu.ps.message import Node

    van = _member_van()
    events = []
    van.on_membership = lambda epoch, dead: events.append(
        (epoch, frozenset(dead)))

    # DEAD_NODE adoption
    van._process_dead_node(_msg(1, [Node(id=11)]))
    assert van.membership_epoch == 1
    assert events == [(1, frozenset({11}))]
    # duplicate and stale broadcasts: no re-fire, still conformant
    van._process_dead_node(_msg(1, [Node(id=11)]))
    van._process_dead_node(_msg(0, []))
    assert events == [(1, frozenset({11}))]

    # the regression: a revival learned ONLY via the ADD_NODE table
    # broadcast must fire the membership side effects (countdown
    # re-checks) — before the fix this hook never fired here
    van._process_add_node(_msg(2, [Node(id=11, hostname="127.0.0.1",
                                        port=5, is_recovery=True)]))
    assert van.membership_epoch == 2
    assert van._rejoin_epoch[11] == 2
    assert events == [(1, frozenset({11})), (2, frozenset())]

    # an initial (unchanged) table broadcast must NOT fire side effects
    van._process_add_node(_msg(2, [Node(id=11, hostname="127.0.0.1",
                                        port=5, is_recovery=True)]))
    assert events == [(1, frozenset({11})), (2, frozenset())]

    # fences agree with the model throughout
    assert van.is_stale(11, 1) and not van.is_stale(11, 2)
    assert van.statecheck.report() == []


def test_out_of_band_mutation_is_caught():
    """The runtime dual of GX-S502: membership state mutated outside a
    modeled transition desynchronizes the mirror — the next faithful
    transition exposes it."""
    from geomx_tpu.ps.message import Node

    van = _member_van()
    van._process_dead_node(_msg(1, [Node(id=11)]))
    assert van.statecheck.violations == []

    van._declared_dead.add(13)           # rogue out-of-band mutation

    # the same broadcast again: the van sees a CHANGED set and adopts;
    # the model knows it is a duplicate
    van._process_dead_node(_msg(1, [Node(id=11)]))
    assert any("diverged" in v for v in van.statecheck.violations)


# ---------------------------------------------------------------------------
# recovery: version-aware snapshot-vs-replica merge
# ---------------------------------------------------------------------------

def _image(version, value):
    entries = {(0, 0): {"v": np.full(4, value, np.float32),
                        "total": 4, "version": version,
                        "rounds": version}}
    return checkpoint.serialize_blob({
        "entries": checkpoint.serialize_states(entries),
        "updater": b"", "updater_states": b"", "flags": {}})


def _stub_replication(tmp_path, snapshot_version, replica_version):
    from geomx_tpu.kvstore.replication import ReplicationManager

    def mkstate():
        return types.SimpleNamespace(
            lock=threading.Lock(), stored=None, length=0, total=0,
            dtype=np.float32, version=0, rounds=0, initialized=False)

    states = {}
    server = types.SimpleNamespace(
        is_global_server=False,
        po_global=None,
        po_local=types.SimpleNamespace(
            my_rank=0, num_servers=2,
            van=types.SimpleNamespace(statecheck=None)),
        _ready=threading.Event(),
        _lock=threading.Lock(),
        _key_total={},
        _state=lambda key, off: states.setdefault((key, off), mkstate()),
        updater=None,
    )
    cfg = types.SimpleNamespace(snapshot_dir=str(tmp_path),
                                snapshot_interval_s=1.0, replicate=True)
    rep = ReplicationManager(server, cfg)
    with open(rep.path(), "wb") as f:
        f.write(_image(snapshot_version, 1.0))
    rep._fetch_from_peer = lambda timeout=60.0: _image(replica_version, 2.0)
    return rep, states


def test_restore_prefers_fresher_replica(tmp_path):
    """The fix: a snapshot written a tick ago must lose to the peer's
    replica when the replica carries more released rounds."""
    rep, states = _stub_replication(tmp_path, snapshot_version=1,
                                    replica_version=3)
    assert rep.restore() == "replica"
    assert rep.restored_from == "replica"
    st = states[(0, 0)]
    assert st.version == 3
    np.testing.assert_allclose(st.stored, np.full(4, 2.0, np.float32))


def test_restore_keeps_snapshot_when_fresher_or_tied(tmp_path):
    rep, states = _stub_replication(tmp_path, snapshot_version=3,
                                    replica_version=3)
    assert rep.restore() == "snapshot"   # tie: local snapshot wins
    assert states[(0, 0)].version == 3
    np.testing.assert_allclose(states[(0, 0)].stored,
                               np.full(4, 1.0, np.float32))


# ---------------------------------------------------------------------------
# integration: kill + zombie fence under the sanitizer
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_membership_churn_with_state_sanitizer_clean(tmp_path, caplog):
    """A worker dies (declared by fiat — the partition case), keeps
    pushing as a zombie, the survivor finishes its round sized to the
    live view. Every van runs the conformance sanitizer; the run must
    end with zero violations, and the flight-recorder dumps must replay
    clean through the offline checker."""
    from tests.test_hips import _parallel
    from tests.test_membership import _kill, _wait_declared
    from tests.test_recovery import SingleTier, _round
    from geomx_tpu.optimizer import SGD
    from tools.modelcheck import replay_paths

    topo = SingleTier(extra={"state_sanitizer": True,
                             "flightrec_dir": str(tmp_path)}).start()
    w0 = np.full(8, 10.0, np.float32)
    vans = []
    try:
        rank0 = next(kv for kv in topo.workers if kv.rank == 0)
        zombie = next(kv for kv in topo.workers if kv.rank == 1)
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: kv.init(0, w0) for kv in topo.workers])
        _parallel([lambda kv=kv: _round(kv, 0, w0, w0 - 2.0)
                   for kv in topo.workers])

        vans = [topo.sched_po.van, topo.server.po_local.van,
                rank0.po.van, zombie.po.van]
        assert all(v.statecheck is not None for v in vans)

        zid = zombie.po.my_id
        topo.sched_po.van.declare_dead([zid])
        _wait_declared([rank0.po.van, topo.server.po_local.van], zid)

        # fenced zombie push (never acked; we don't wait on it)
        zombie.push(0, np.full_like(w0, 100.0))
        time.sleep(0.5)

        # survivor's round releases against the live view
        _round(rank0, 0, w0, w0 - 3.0)

        # force a dump from every van so the replay half has real rings
        for v in vans:
            v.flightrec.dump("test-conformance")

        topo.workers = [rank0]
        _kill(zombie)
    finally:
        _parallel([kv.close for kv in topo.workers])
        for t in topo.threads:
            t.join(30)
        if topo.errors:
            raise topo.errors[0]

    for v in vans:
        assert v.statecheck.violations == [], (
            f"van {v.my_id}: {v.statecheck.violations}")
    assert MARKER not in caplog.text

    # offline replay over the rings this run left behind
    from pathlib import Path

    report = replay_paths([Path(tmp_path)])
    assert report["files"], "no flightrec dumps were written"
    assert report["violations"] == 0, json.dumps(report, indent=1)


def test_crashed_van_barrier_fails_fast():
    """A stopped (crashed) van can neither deliver a barrier request nor
    receive the release: barrier() must refuse immediately instead of
    parking the caller for the full timeout — a chaos-crashed worker's
    atexit path would otherwise bleed out serially through it."""
    van = _member_van()
    van.stop()
    t0 = time.monotonic()
    with pytest.raises(OSError):
        van.barrier(group=7, timeout=60.0)
    assert time.monotonic() - t0 < 1.0
