"""ShapePlan parsing + deterministic link shaping primitives.

Mirrors test_faults.py: every shaping primitive (fixed delay, token
bucket queueing, jitter, shared access pipes, control exemption) runs
against a stub van — twice where determinism is the contract — and the
two shapers' ``decision_log`` audit trails must match exactly: same
plan + same seed + same traffic => the identical delivery schedule.
That is the acceptance bar the shaped captures (PERF.md) and the chaos
matrix's shaped cases lean on, and it is what makes a shaped run a
reproducible experiment instead of a noisy one.
"""

import json
import threading
import time
import types

import numpy as np
import pytest

from geomx_tpu.config import Config
from geomx_tpu.kvstore import frontier, sharding
from geomx_tpu.ps import shaping
from geomx_tpu.ps.shaping import LinkShaper, ShapeLink, ShapePlan
from geomx_tpu.ps.van import Van

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------------------
# parsing / validation


def test_link_rejects_unknown_keys():
    with pytest.raises(ValueError, match="unknown keys"):
        ShapeLink.from_dict({"rtt_ms": 10, "bandwidth": 100})


def test_link_rejects_bad_tier():
    with pytest.raises(ValueError, match="bad tier"):
        ShapeLink.from_dict({"tier": "wan"})


def test_link_rejects_negative_values():
    with pytest.raises(ValueError, match=">= 0"):
        ShapeLink.from_dict({"rtt_ms": -1})
    with pytest.raises(ValueError, match=">= 0"):
        ShapeLink.from_dict({"bw_mbps": -5})


def test_parse_dict_with_embedded_seed():
    plan = ShapePlan.parse(
        '{"seed": 42, "links": [{"rtt_ms": 10}]}', seed=7)
    assert plan.seed == 42            # embedded seed wins
    assert len(plan.links) == 1 and plan.default is None


def test_parse_bare_list_and_default():
    plan = ShapePlan.parse('[{"rtt_ms": 10}]', seed=7)
    assert plan.seed == 7 and plan.default is None
    plan = ShapePlan.parse(
        '{"default": {"rtt_ms": 50, "bw_mbps": 100}, "links": []}')
    assert plan.default.rtt_ms == 50


def test_parse_at_file(tmp_path):
    p = tmp_path / "shape.json"
    p.write_text(json.dumps({"seed": 3, "links": [{"bw_mbps": 20}]}))
    plan = ShapePlan.parse("@" + str(p))
    assert plan.seed == 3
    assert plan.links[0].bw_mbps == 20


def test_plan_from_config_seed_precedence():
    assert shaping.plan_from_config(Config()) is None
    # GEOMX_SHAPE_SEED beats PS_SEED
    plan = shaping.plan_from_config(
        Config(shape_plan='[{"rtt_ms": 1}]', shape_seed=5, ps_seed=11))
    assert plan.seed == 5
    # PS_SEED is the fallback
    plan = shaping.plan_from_config(
        Config(shape_plan='[{"rtt_ms": 1}]', ps_seed=11))
    assert plan.seed == 11
    # plan-embedded seed beats both
    plan = shaping.plan_from_config(
        Config(shape_plan='{"seed": 2, "links": [{"rtt_ms": 1}]}',
               shape_seed=5, ps_seed=11))
    assert plan.seed == 2


def test_link_for_first_match_wins_and_tier_scoping():
    plan = ShapePlan.parse(json.dumps({"links": [
        {"src": 9, "dst": 8, "rtt_ms": 150},
        {"dst": 8, "rtt_ms": 50},
        {"tier": "local", "rtt_ms": 1},
    ], "default": {"rtt_ms": 99}}))
    assert plan.link_for(9, 8, True).rtt_ms == 150    # first match wins
    assert plan.link_for(11, 8, True).rtt_ms == 50
    assert plan.link_for(11, 9, False).rtt_ms == 1    # local-tier rule
    assert plan.link_for(11, 9, True).rtt_ms == 99    # default
    plan = ShapePlan.parse('[{"tier": "local", "rtt_ms": 1}]')
    assert plan.link_for(11, 9, True) is None         # unmatched: unshaped


def test_worst_link_picks_highest_bdp():
    plan = ShapePlan.parse(json.dumps({"links": [
        {"rtt_ms": 10, "bw_mbps": 1000},   # BDP 1.25 MB
        {"rtt_ms": 150, "bw_mbps": 20},    # BDP 375 KB
        {"rtt_ms": 200, "bw_mbps": 100},   # BDP 2.5 MB <- worst
    ]}))
    assert plan.worst_link(is_global=True) == (200, 100)
    assert ShapePlan.parse("[]").worst_link() is None


# ---------------------------------------------------------------------------
# shaping primitives against a stub van


class StubVan:
    """Just enough van surface for LinkShaper + deliver_later: identity,
    a stopped event, and a _process sink recording held frames as they
    re-enter dispatch."""

    def __init__(self, my_id=8, is_global=True):
        self.my_id = my_id
        self.is_global = is_global
        self.stopped = threading.Event()
        self.delivered = []

    def _process(self, msg):
        self.delivered.append(msg)


def msg(sender=9, nbytes=0, control=False):
    m = types.SimpleNamespace()
    m.meta = types.SimpleNamespace(sender=sender)
    m.is_control = control
    m.data = [b"\0" * nbytes] if nbytes else []
    return m


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_fixed_delay_holds_then_redelivers():
    plan = ShapePlan.parse('[{"rtt_ms": 20}]', seed=1)
    van = StubVan()
    sh = plan.bind(van)
    m = msg(nbytes=10)
    assert sh.on_inbound(m) is False      # held for rtt/2
    deadline = time.monotonic() + 5
    while not van.delivered and time.monotonic() < deadline:
        time.sleep(0.005)
    assert van.delivered == [m]
    (src, dst, seq, nb, delay_ms) = sh.decision_log[0]
    assert (src, dst, seq, nb) == (9, 8, 1, 10)
    assert delay_ms == pytest.approx(10.0)


def test_zero_delay_link_delivers_inline():
    # a 0-rtt infinite-bw rule matches but never holds the frame
    plan = ShapePlan.parse('[{"rtt_ms": 0, "bw_mbps": 0}]', seed=1)
    sh = plan.bind(StubVan())
    assert sh.on_inbound(msg(nbytes=100)) is True
    assert len(sh.decision_log) == 1


def test_control_frames_exempt_unless_opted_in():
    plan = ShapePlan.parse('[{"rtt_ms": 100}]', seed=1)
    sh = plan.bind(StubVan())
    assert sh.on_inbound(msg(control=True)) is True
    assert sh.decision_log == []          # exempt frames leave no trace
    plan = ShapePlan.parse('[{"rtt_ms": 100, "control": true}]', seed=1)
    sh = plan.bind(StubVan())
    assert sh.on_inbound(msg(control=True)) is False


def test_token_bucket_queues_back_to_back_frames():
    # 1 MB at 8 Mbps = 1.0 s serialization per frame; with a fake clock
    # the horizons stack exactly: 1 s, 2 s, 3 s (+ rtt/2 each)
    plan = ShapePlan.parse('[{"src": 9, "rtt_ms": 20, "bw_mbps": 8}]',
                           seed=1)
    van = StubVan()
    sh = LinkShaper(plan, van, clock=FakeClock())
    for _ in range(3):
        sh.on_inbound(msg(sender=9, nbytes=1_000_000))
    delays = [e[4] for e in sh.decision_log]
    assert delays == pytest.approx([1010.0, 2010.0, 3010.0])
    # an unmatched src is unshaped: delivered inline, no bucket, no log
    assert sh.on_inbound(msg(sender=11, nbytes=1_000_000)) is True
    assert len(sh.decision_log) == 3


def test_per_link_fifo_under_jitter():
    # folding jitter into the horizon keeps per-link delivery FIFO:
    # absolute delivery times (clock fixed => delay order) never invert
    plan = ShapePlan.parse(
        '[{"rtt_ms": 10, "bw_mbps": 100, "jitter_ms": 5}]', seed=9)
    sh = LinkShaper(plan, StubVan(), clock=FakeClock())
    for _ in range(20):
        sh.on_inbound(msg(nbytes=10_000))
    delays = [e[4] for e in sh.decision_log]
    assert delays == sorted(delays)
    assert len(set(delays)) == len(delays)   # jitter actually spreads


def test_schedule_deterministic_same_seed_differs_across_seeds():
    plan_json = ('[{"rtt_ms": 30, "bw_mbps": 50, "jitter_ms": 4}]')

    def run(seed):
        plan = ShapePlan.parse(plan_json, seed=seed)
        sh = LinkShaper(plan, StubVan(), clock=FakeClock())
        for i in range(30):
            sh.on_inbound(msg(sender=9 + 2 * (i % 3), nbytes=50_000 + i))
        return sh.decision_log

    assert run(7) == run(7)               # identical delivery schedule
    assert run(7) != run(8)               # seed actually reaches jitter


def test_shared_ingress_pipe_contends_across_senders():
    # private per-pair buckets would give both senders 1 s each; the
    # shared rule makes the second sender queue behind the first
    plan = ShapePlan.parse(
        '{"links": [{"dst": 8, "shared": true, "rtt_ms": 0,'
        ' "bw_mbps": 8}]}', seed=1)
    sh = LinkShaper(plan, StubVan(my_id=8), clock=FakeClock())
    sh.on_inbound(msg(sender=9, nbytes=1_000_000))
    sh.on_inbound(msg(sender=11, nbytes=1_000_000))
    delays = [e[4] for e in sh.decision_log]
    assert delays == pytest.approx([1000.0, 2000.0])


def test_shared_egress_pipe_contends_across_shapers():
    # frames fanning out from one src to two receivers hit two different
    # receiver-side shapers; the process-global registry still
    # serializes them on the src's one egress pipe
    shaping.reset_shared_buckets()
    try:
        plan = ShapePlan.parse(
            '{"links": [{"src": 8, "shared": true, "rtt_ms": 0,'
            ' "bw_mbps": 40}]}', seed=1)
        sh_a = plan.bind(StubVan(my_id=9))
        sh_b = plan.bind(StubVan(my_id=11))
        sh_a.on_inbound(msg(sender=8, nbytes=1_000_000))   # 0.2 s ser
        sh_b.on_inbound(msg(sender=8, nbytes=1_000_000))
        d_a = sh_a.decision_log[0][4]
        d_b = sh_b.decision_log[0][4]
        assert d_a == pytest.approx(200.0, rel=0.05)
        assert d_b == pytest.approx(400.0, rel=0.05)       # queued behind a
    finally:
        shaping.reset_shared_buckets()


def test_fake_clock_shared_buckets_stay_instance_private():
    # determinism tests rely on fake-clock shapers NOT touching the
    # process-global registry (wall-clock horizons would wedge them)
    shaping.reset_shared_buckets()
    plan = ShapePlan.parse(
        '{"links": [{"dst": 8, "shared": true, "bw_mbps": 8}]}', seed=1)
    sh = LinkShaper(plan, StubVan(my_id=8), clock=FakeClock())
    sh.on_inbound(msg(sender=9, nbytes=1_000_000))
    assert shaping._shared_horizons == {}


# ---------------------------------------------------------------------------
# composition with the fault plan (Van._inbound_gate ordering)


def _gate_stub(shaper=None, injector=None):
    """A bare object carrying exactly the attributes _inbound_gate
    reads, so the REAL gate method runs against scripted frames."""
    stub = types.SimpleNamespace()
    stub._faults = injector
    stub._shaper = shaper
    stub.drop_rate = 0.0
    stub._rng = None
    stub.verbose = False
    stub.num_data_recv = 0
    stub._stats_lock = threading.Lock()
    return stub


def test_gate_runs_faults_before_shaping():
    from geomx_tpu.ps.faults import FaultPlan

    fplan = FaultPlan.parse('[{"type": "drop", "p": 1.0}]', seed=1)
    splan = ShapePlan.parse('[{"rtt_ms": 100}]', seed=1)
    van = StubVan()
    inj = fplan.bind(van)
    sh = LinkShaper(splan, van, clock=FakeClock())
    stub = _gate_stub(shaper=sh, injector=inj)
    assert Van._inbound_gate(stub, msg(nbytes=10)) is False
    # the dropped frame never reached the shaper — no bucket occupancy,
    # no decision, and it was never counted as received either
    assert sh.decision_log == []
    assert stub.num_data_recv == 0


def test_gate_counts_frame_before_shaping_hold():
    splan = ShapePlan.parse('[{"rtt_ms": 100}]', seed=1)
    van = StubVan()
    sh = LinkShaper(splan, van, clock=FakeClock())
    stub = _gate_stub(shaper=sh)
    assert Van._inbound_gate(stub, msg(nbytes=10)) is False  # held
    # a held frame is on the (emulated) wire: crash-at-message-N fault
    # points must land identically shaped or not
    assert stub.num_data_recv == 1
    assert len(sh.decision_log) == 1


# ---------------------------------------------------------------------------
# slice sizing from the topology (frontier + sharding plumbing)


def test_auto_slice_bytes_tracks_bdp():
    assert frontier.auto_slice_bytes(0, 100) == 0        # unshaped
    assert frontier.auto_slice_bytes(50, 0) == 4 << 20   # latency-only
    # 50 ms * 100 Mbps = 625 KB BDP
    assert frontier.auto_slice_bytes(50, 100) == 625_000
    assert frontier.auto_slice_bytes(1, 1) == 65536      # clamps to min


def test_slice_bytes_from_shape_uses_worst_global_link():
    cfg = Config(shape_plan=json.dumps({"links": [
        {"rtt_ms": 10, "bw_mbps": 100, "tier": "global"},
        {"rtt_ms": 200, "bw_mbps": 100, "tier": "global"},
        {"rtt_ms": 500, "bw_mbps": 100, "tier": "local"},
    ]}))
    assert frontier.slice_bytes_from_shape(cfg) == \
        frontier.auto_slice_bytes(200, 100)
    assert frontier.slice_bytes_from_shape(Config()) == 0


def test_split_slices_refines_without_moving_boundaries():
    shards = sharding.assign(0, 1000, 2, bigarray_bound=100)
    fine = sharding.split_slices(shards, 128)
    assert sharding.split_slices(shards, 0) == shards    # 0 = no refine
    assert sum(s.length for s in fine) == 1000
    assert all(s.length <= 128 for s in fine)
    # placement and outer boundaries untouched: a peer addressing the
    # coarse ranges overlaps a contiguous run of the fine ones
    for coarse in shards:
        sub = [s for s in fine if s.server_rank == coarse.server_rank
               and coarse.offset <= s.offset < coarse.offset + coarse.length]
        assert sub[0].offset == coarse.offset
        assert sub[-1].offset + sub[-1].length == \
            coarse.offset + coarse.length
