"""Pipelined round: async chunked push_pull (P3 slicing).

The async frontier (kvstore.frontier) splits a round into
priority-ordered chunks and completes keys as their responses land;
the acceptance bar is BIT-exactness against the serial wire — same
FSA rounds, same aggregation, same post-round bytes — with only the
blocking moved. Covers the planning/future primitives, the dense and
BSC async wire against their blocking twins (including multi-shard
keys under P3_SLICE_BYTES sharding), the pipelined device trainer,
and out-of-order completion under a seeded FaultPlan.
"""

import json
import threading
import time

import numpy as np
import pytest

from geomx_tpu.kvstore.frontier import (RoundFuture, give_up_exc,
                                        plan_chunks)
from geomx_tpu.optimizer import SGD
from geomx_tpu.simulate import InProcessHiPS

KEYS = list(range(6))
SHAPES = [(4,), (2, 3), (8,), (5,), (1,), (7,)]


# ---------------------------------------------------------------------------
# chunk planning
# ---------------------------------------------------------------------------

def test_plan_chunks_groups_in_layer_order():
    chunks = plan_chunks(["a", "b", "c", "d"], [4, 4, 4, 4], 8)
    assert [c.items for c in chunks] == [["a", "b"], ["c", "d"]]
    assert [c.cid for c in chunks] == [0, 1]
    # chunk index descends into priority: layer order = priority
    assert [c.priority for c in chunks] == [0, -1]


def test_plan_chunks_zero_budget_is_one_chunk():
    chunks = plan_chunks([1, 2, 3], [100, 200, 300], 0, base_priority=5)
    assert len(chunks) == 1
    assert chunks[0].items == [1, 2, 3]
    assert chunks[0].priority == 5


def test_plan_chunks_oversized_item_gets_own_chunk():
    # an item above the budget is NOT split (BSC keys stay whole for
    # the server FSA's per-(key, shard) push counting)
    chunks = plan_chunks(["small", "huge", "small2"], [2, 99, 2], 8)
    assert [c.items for c in chunks] == [["small", "huge"], ["small2"]] \
        or [c.items for c in chunks] == [["small"], ["huge"], ["small2"]]
    # greedy close: "huge" may close the first chunk or own one, but
    # never merges with items AFTER it beyond the budget
    assert all(sum({"small": 2, "huge": 99, "small2": 2}[i]
                   for i in c.items) <= 101 for c in chunks)


def test_plan_chunks_empty():
    assert plan_chunks([], [], 8) == []


def test_plan_chunks_base_priority_offsets_every_chunk():
    chunks = plan_chunks([0, 1, 2], [8, 8, 8], 8, base_priority=-3)
    assert [c.priority for c in chunks] == [-3, -4, -5]


# ---------------------------------------------------------------------------
# RoundFuture
# ---------------------------------------------------------------------------

def test_round_future_completes_per_key():
    fut = RoundFuture([1, 2])
    assert not fut.done()
    fut.complete_key(1, "r1")
    assert fut.done([1]) and not fut.done()
    assert fut.result(1, timeout=1) == "r1"
    fut.complete_key(2, "r2")
    assert fut.results(timeout=1) == {1: "r1", 2: "r2"}
    # idempotent: a duplicate completion does not clobber the result
    fut.complete_key(1, "other")
    assert fut.result(1) == "r1"


def test_round_future_wait_timeout_lists_pending():
    fut = RoundFuture([3, 4])
    fut.complete_key(3)
    with pytest.raises(TimeoutError, match=r"\[4\]"):
        fut.wait(timeout=0.05)


def test_round_future_on_key_fires_now_and_later():
    fut = RoundFuture([1, 2])
    seen = []
    fut.on_key(1, seen.append)
    fut.complete_key(1)
    fut.on_key(1, seen.append)    # already done: fires immediately
    assert seen == [1, 1]


def test_round_future_rejects_duplicate_keys():
    with pytest.raises(AssertionError, match="duplicate"):
        RoundFuture([1, 1])


def test_round_future_error_mapping_and_consume():
    # a blown resend deadline maps to TimeoutError; other give-ups stay
    # RuntimeError — same classes KVStoreDist.wait() raises
    assert give_up_exc(["delivery deadline exceeded"]) is TimeoutError
    assert give_up_exc(["retry cap"]) is RuntimeError

    consumed = []
    fut = RoundFuture([1], consume=consumed.extend)
    fut.add_error(1, "push key 1: delivery deadline exceeded")
    fut.complete_key(1)
    with pytest.raises(TimeoutError, match="delivery deadline"):
        fut.wait(timeout=1)
    assert consumed == ["push key 1: delivery deadline exceeded"]

    fut2 = RoundFuture([7])
    fut2.add_error(7, "gave up after 5 retries")
    fut2.complete_key(7)
    with pytest.raises(RuntimeError, match="retries"):
        fut2.wait(timeout=1)


def test_round_future_completion_from_other_thread():
    fut = RoundFuture([9])
    t = threading.Timer(0.05, fut.complete_key, args=(9, "late"))
    t.start()
    assert fut.result(9, timeout=5) == "late"


# ---------------------------------------------------------------------------
# OpFuture (kv_app-level handle)
# ---------------------------------------------------------------------------

class _FakeWorker:
    def __init__(self, failure=None, resp=()):
        self._failure = failure
        self._resp = list(resp)

    def take_failure(self, ts):
        return self._failure

    def take_response(self, ts):
        return self._resp


def test_op_future_completes_and_serves_response():
    from geomx_tpu.ps.kv_app import OpFuture

    fut = OpFuture(_FakeWorker(resp=["kvs"]), 3)
    assert not fut.done()
    fut._fire(3)
    fut.wait(timeout=1)
    assert fut.done() and fut.failure() is None
    assert fut.responses() == ["kvs"]


def test_op_future_raises_give_up_with_class_mapping():
    from geomx_tpu.ps.kv_app import OpFuture

    fut = OpFuture(_FakeWorker(failure="delivery deadline exceeded"), 5)
    fut._fire(5)
    with pytest.raises(TimeoutError, match="delivery deadline"):
        fut.wait(timeout=1)

    fut2 = OpFuture(_FakeWorker(failure="gave up after retries"), 6)
    fut2._fire(6)
    with pytest.raises(RuntimeError, match="gave up"):
        fut2.wait(timeout=1)

    fut3 = OpFuture(_FakeWorker(), 7)
    with pytest.raises(TimeoutError, match="still pending"):
        fut3.wait(timeout=0.05)


# ---------------------------------------------------------------------------
# dense async wire == serial wire, bit for bit
# ---------------------------------------------------------------------------

def _run_dense(mode, slice_bytes=0, sharded=False, extra_cfg=None):
    kw = dict(num_parties=2, workers_per_party=1)
    if sharded:
        kw.update(servers_per_party=2, bigarray_bound=4)
    if extra_cfg:
        kw["extra_cfg"] = extra_cfg
    topo = InProcessHiPS(**kw).start()
    result = {}
    try:
        def master_init(kv):
            kv.set_optimizer(SGD(learning_rate=0.5))
            for k, sh in zip(KEYS, SHAPES):
                kv.init(k, np.zeros(sh, np.float32))
            kv.wait()

        def worker(kv):
            widx = 0 if kv is topo.workers[0] else 1
            outs = [np.zeros(sh, np.float32) for sh in SHAPES]
            for k, o in zip(KEYS, outs):
                kv.init(k, o.copy())
                kv.pull(k, out=o)
            kv.wait()
            rng = np.random.RandomState(17)  # same on both workers
            for step in range(3):
                grads = [rng.uniform(-1, 1, sh).astype(np.float32) / 2
                         for sh in SHAPES]
                if mode == "async":
                    fut = kv.push_pull_async(KEYS, grads, outs,
                                             slice_bytes=slice_bytes)
                    fut.wait(timeout=120)
                else:
                    kv.push_pull(KEYS, grads, out=outs)
                    kv.wait()
            result[widx] = [o.copy() for o in outs]

        topo.run_workers(worker, include_master=master_init, timeout=300)
    finally:
        topo.stop()
    np.testing.assert_equal(len(result), 2)
    for a, b in zip(result[0], result[1]):
        np.testing.assert_array_equal(a, b)
    return result[0]


@pytest.mark.parametrize("slice_bytes", [0, 16, 10 ** 6])
def test_push_pull_async_matches_serial_exactly(slice_bytes):
    """Chunked async rounds must be bit-identical to the blocking
    combined wire at every chunk budget (one chunk, many chunks, one
    chunk again via a huge budget)."""
    serial = _run_dense("serial")
    piped = _run_dense("async", slice_bytes=slice_bytes)
    for a, b in zip(serial, piped):
        np.testing.assert_array_equal(a, b)
    assert any(np.abs(a).sum() > 0 for a in piped)


def test_push_pull_async_matches_serial_sharded():
    """Chunks at _shards() granularity across 2 servers per party."""
    serial = _run_dense("serial", sharded=True)
    piped = _run_dense("async", slice_bytes=16, sharded=True)
    for a, b in zip(serial, piped):
        np.testing.assert_array_equal(a, b)


def test_push_pull_async_p3_slice_bytes_sharding():
    """P3_SLICE_BYTES > 0 slices keys into priority shards at init
    (sharding.assign_p3); the async round and the serial round must
    still agree bit for bit — this is the multi-(key, off)-per-message
    path through the server's batched WAN forward."""
    cfg = {"p3_slice_bytes": 16}
    serial = _run_dense("serial", extra_cfg=cfg)
    piped = _run_dense("async", slice_bytes=16, extra_cfg=cfg)
    for a, b in zip(serial, piped):
        np.testing.assert_array_equal(a, b)


def test_push_pull_async_rejects_bad_inputs():
    topo = InProcessHiPS(num_parties=2, workers_per_party=1).start()
    try:
        def master_init(kv):
            kv.init(0, np.zeros(3, np.float32))
            kv.wait()

        def worker(kv):
            kv.init(0, np.zeros(3, np.float32))
            kv.wait()
            g = np.ones(3, np.float32)
            with pytest.raises(ValueError, match="duplicate"):
                kv.push_pull_async([0, 0], [g, g],
                                   [np.zeros(3, np.float32),
                                    np.zeros(3, np.float32)])
            with pytest.raises(TypeError, match="writable"):
                kv.push_pull_async([0], [g], ["nope"])

        topo.run_workers(worker, include_master=master_init, timeout=120)
    finally:
        topo.stop()


# ---------------------------------------------------------------------------
# BSC async wire == blocking BSC join, element for element
# ---------------------------------------------------------------------------

def _run_bsc(mode, slice_bytes=0, extra_cfg=None):
    sizes = [8, 5, 12, 6]
    keys = list(range(len(sizes)))
    kw = dict(num_parties=2, workers_per_party=1)
    if extra_cfg:
        kw["extra_cfg"] = extra_cfg
    topo = InProcessHiPS(**kw).start()
    result = {}
    try:
        def master_init(kv):
            for k, n in zip(keys, sizes):
                kv.init(k, np.zeros(n, np.float32))
            kv.wait()

        def worker(kv):
            widx = 0 if kv is topo.workers[0] else 1
            for k, n in zip(keys, sizes):
                kv.init(k, np.zeros(n, np.float32))
            kv.wait()
            rng = np.random.RandomState(5 + widx)
            vals = [rng.rand(3).astype(np.float32) + 1.0 for _ in keys]
            idxs = [np.sort(rng.choice(n, 3, replace=False))
                    for n in sizes]
            if mode == "async":
                fut = kv.push_pull_bsc_batch_async(
                    keys, vals, idxs, slice_bytes=slice_bytes)
                agg = fut.results(timeout=120)
            else:
                agg = kv.push_pull_bsc_batch(keys, vals, idxs)()
            # compare as dense scatters: part ORDER may differ between
            # the chunked and monolithic responses, the bytes must not
            dense = {}
            for k, n in zip(keys, sizes):
                buf = np.zeros(n, np.float32)
                avals, aidx = agg[k]
                np.add.at(buf, aidx, avals)
                dense[k] = buf
            result[widx] = dense

        topo.run_workers(worker, include_master=master_init, timeout=300)
    finally:
        topo.stop()
    np.testing.assert_equal(len(result), 2)
    for k in keys:
        np.testing.assert_array_equal(result[0][k], result[1][k])
    return result[0]


@pytest.mark.parametrize("slice_bytes", [0, 48])
def test_bsc_async_matches_blocking_join(slice_bytes):
    blocking = _run_bsc("sync")
    piped = _run_bsc("async", slice_bytes=slice_bytes)
    for k in blocking:
        np.testing.assert_array_equal(blocking[k], piped[k])
    assert any(np.abs(v).sum() > 0 for v in piped.values())


def test_bsc_async_under_p3_slice_sharding():
    """Keys sliced into multiple tiny shards per server (the
    P3_SLICE_BYTES _shards branch): the combined BSC round must still
    aggregate exactly — covers >1 entry of the SAME key per message on
    both tiers, and the batched global forward's overlap routing."""
    cfg = {"p3_slice_bytes": 8}
    blocking = _run_bsc("sync", extra_cfg=cfg)
    piped = _run_bsc("async", slice_bytes=24, extra_cfg=cfg)
    for k in blocking:
        np.testing.assert_array_equal(blocking[k], piped[k])


# ---------------------------------------------------------------------------
# out-of-order completion under faults (chaos tier)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_async_frontier_exact_under_faultplan():
    """Drop + reorder + dup on every link (seeded), resend on: chunk
    responses land out of order and some messages retransmit, yet the
    per-key async results are bit-identical to a clean serial round.
    Also asserts the frontier completes every key exactly once."""
    plan = json.dumps({"rules": [
        {"type": "drop", "p": 0.15},
        {"type": "dup", "p": 0.15},
        {"type": "reorder", "window": 4},
    ]})
    chaos_cfg = {"fault_plan": plan, "ps_seed": 7, "resend": True,
                 "resend_timeout_ms": 1000}

    clean = _run_bsc("sync")
    completions = []

    sizes = [8, 5, 12, 6]
    keys = list(range(len(sizes)))
    topo = InProcessHiPS(num_parties=2, workers_per_party=1,
                         extra_cfg=chaos_cfg).start()
    result = {}
    try:
        def master_init(kv):
            for k, n in zip(keys, sizes):
                kv.init(k, np.zeros(n, np.float32))
            kv.wait()

        def worker(kv):
            widx = 0 if kv is topo.workers[0] else 1
            for k, n in zip(keys, sizes):
                kv.init(k, np.zeros(n, np.float32))
            kv.wait()
            rng = np.random.RandomState(5 + widx)
            vals = [rng.rand(3).astype(np.float32) + 1.0 for _ in keys]
            idxs = [np.sort(rng.choice(n, 3, replace=False))
                    for n in sizes]
            fut = kv.push_pull_bsc_batch_async(keys, vals, idxs,
                                               slice_bytes=24)
            for k in keys:
                fut.on_key(k, lambda kk: completions.append(kk))
            agg = fut.results(timeout=120)
            dense = {}
            for k, n in zip(keys, sizes):
                buf = np.zeros(n, np.float32)
                avals, aidx = agg[k]
                np.add.at(buf, aidx, avals)
                dense[k] = buf
            result[widx] = dense

        topo.run_workers(worker, include_master=master_init, timeout=300)
    finally:
        topo.stop()

    for k in keys:
        np.testing.assert_array_equal(result[0][k], result[1][k])
        np.testing.assert_array_equal(result[0][k], clean[k])
    # every key completed on both workers, each exactly once
    assert sorted(completions) == sorted(keys * 2)


# ---------------------------------------------------------------------------
# pipelined device trainer == serial trainer, bit for bit
# ---------------------------------------------------------------------------

def _run_trainer(extra_cfg, rounds=8):
    import jax.numpy as jnp

    from geomx_tpu.trainer_device import DeviceResidentTrainer

    target = np.arange(1.0, 9.0, dtype=np.float32).reshape(2, 4)

    def loss_fn(leaves, X, y):
        diff = leaves[0] - jnp.asarray(target) + X
        return (0.5 * jnp.sum(diff * diff) + jnp.sum(leaves[1] ** 2),
                [diff, 2.0 * leaves[1] + 1.0])

    topo = InProcessHiPS(num_parties=2, workers_per_party=1,
                         extra_cfg=extra_cfg).start()
    results = {}
    try:
        def master_init(kv):
            kv.init(0, np.zeros((2, 4), np.float32))
            kv.init(1, np.zeros((5,), np.float32))
            kv.wait()

        def worker(kv):
            widx = 0 if kv is topo.workers[0] else 1
            tr = DeviceResidentTrainer(
                [np.zeros((2, 4), np.float32),
                 np.zeros((5,), np.float32)],
                kv, loss_fn, threshold=0.5, learning_rate=0.2,
                momentum=0.9)
            shift = jnp.asarray(0.5 if widx == 0 else -0.5)
            for _ in range(rounds):
                tr.step(shift, None)
            results[widx] = ([np.asarray(l).copy() for l in tr.leaves],
                             tr._pipeline,
                             len(getattr(tr, "_chunks", [])))

        topo.run_workers(worker, include_master=master_init,
                         timeout=300)
    finally:
        topo.stop()
    (l0, pipe0, nch0), (l1, pipe1, _) = results[0], results[1]
    assert pipe0 == pipe1
    for a, b in zip(l0, l1):
        np.testing.assert_array_equal(a, b)
    return l0, pipe0, nch0


def test_pipelined_trainer_bit_identical_to_serial():
    """GEOMX_OVERLAP + P3_SLICE_BYTES route DeviceResidentTrainer
    through per-chunk fetch/dispatch/apply; the post-training leaves
    must equal the monolithic round's bit for bit (chunk flat ranges
    partition the parameter vector; per-coordinate arithmetic is
    unchanged)."""
    serial, pipe_s, _ = _run_trainer({"overlap": False})
    assert not pipe_s
    piped, pipe_p, nchunks = _run_trainer(
        {"overlap": True, "p3_slice_bytes": 8})
    assert pipe_p and nchunks == 2
    for a, b in zip(serial, piped):
        np.testing.assert_array_equal(a, b)
    assert any(np.abs(a).sum() > 0 for a in piped)


# ---------------------------------------------------------------------------
# host-trainer overlap (deferred barrier)
# ---------------------------------------------------------------------------

def test_trainer_overlap_defers_barrier_same_results():
    """Trainer(overlap=True) returns from step() with the round in
    flight; the next leaves access joins it. Weights after N steps
    must equal the blocking trainer's exactly."""
    from geomx_tpu.trainer import Trainer

    def run(overlap):
        topo = InProcessHiPS(num_parties=2,
                             workers_per_party=1).start()
        result = {}
        try:
            def master_init(kv):
                kv.set_optimizer(SGD(learning_rate=0.5))
                kv.init(0, np.ones(6, np.float32))
                kv.wait()

            def worker(kv):
                widx = 0 if kv is topo.workers[0] else 1
                tr = Trainer([np.ones(6, np.float32)], kv,
                             overlap=overlap)
                rng = np.random.RandomState(23)
                for _ in range(4):
                    g = rng.uniform(-1, 1, 6).astype(np.float32)
                    tr.step([g])
                    # leaves joins the in-flight round before reading
                    assert tr.leaves[0].shape == (6,)
                result[widx] = tr.leaves[0].copy()

            topo.run_workers(worker, include_master=master_init,
                             timeout=300)
        finally:
            topo.stop()
        np.testing.assert_array_equal(result[0], result[1])
        return result[0]

    blocking = run(False)
    overlapped = run(True)
    np.testing.assert_array_equal(blocking, overlapped)
