"""FlashAttention Pallas kernels vs the dense reference.

Runs in Pallas interpret mode on the CPU mesh (conftest pins
JAX_PLATFORMS=cpu); the same code path compiles for TPU. Checks
forward values and all three gradients against
``models.transformer.dense_attention`` (reference for the math:
FlashAttention-2; the GeoMX reference has no attention op, SURVEY §5.7).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from geomx_tpu.models.transformer import dense_attention
from geomx_tpu.ops.flash_attention import flash_attention


def _rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype=dtype)


def _check(B, T, H, D, causal, block=32, dtype=jnp.float32,
           tol=2e-5):
    q = _rand((B, T, H, D), 0, dtype)
    k = _rand((B, T, H, D), 1, dtype)
    v = _rand((B, T, H, D), 2, dtype)

    def f_flash(q, k, v):
        return flash_attention(q, k, v, causal=causal, block_q=block,
                               block_k=block)

    def f_dense(q, k, v):
        return dense_attention(q, k, v, causal=causal)

    out_f = f_flash(q, k, v)
    out_d = f_dense(q, k, v)
    np.testing.assert_allclose(np.asarray(out_f, np.float32),
                               np.asarray(out_d, np.float32),
                               atol=tol, rtol=tol)

    cot = _rand(out_d.shape, 3, out_d.dtype)
    gf = jax.vjp(f_flash, q, k, v)[1](cot)
    gd = jax.vjp(f_dense, q, k, v)[1](cot)
    for name, a, b in zip("qkv", gf, gd):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=10 * tol, rtol=10 * tol,
            err_msg=f"d{name} mismatch (causal={causal}, T={T})")


def test_forward_backward_causal():
    _check(B=2, T=64, H=2, D=16, causal=True)


def test_forward_backward_full():
    _check(B=2, T=64, H=2, D=16, causal=False)


def test_ragged_seq_len_pads_correctly():
    # T=50 is not a multiple of the 32-block: exercises padding+masking
    _check(B=1, T=50, H=2, D=8, causal=True)
    _check(B=1, T=50, H=2, D=8, causal=False)


def test_multi_block_causal_boundary():
    # several k-blocks per q-block, exercising the causal skip logic
    _check(B=1, T=96, H=1, D=8, causal=True, block=16)


def test_bfloat16_inputs():
    _check(B=1, T=32, H=2, D=16, causal=True, dtype=jnp.bfloat16,
           tol=2e-2)


def test_jit_and_grad_compose():
    q = _rand((1, 32, 2, 8), 0)
    k = _rand((1, 32, 2, 8), 1)
    v = _rand((1, 32, 2, 8), 2)

    @jax.jit
    def loss(q, k, v):
        return flash_attention(q, k, v, block_q=16, block_k=16).sum()

    g = jax.grad(loss)(q, k, v)
    assert g.shape == q.shape and bool(jnp.all(jnp.isfinite(g)))


def test_matches_transformer_plug_in():
    """flash_attention slots into the Transformer attn_fn hook."""
    from geomx_tpu.models.transformer import Transformer

    tok = jax.random.randint(jax.random.PRNGKey(0), (2, 24), 0, 64)
    m_dense = Transformer(vocab=64, dim=32, depth=1, heads=2, max_len=64)
    m_flash = Transformer(vocab=64, dim=32, depth=1, heads=2, max_len=64,
                          attn_fn=lambda q, k, v: flash_attention(
                              q, k, v, block_q=8, block_k=8))
    p = m_dense.init(jax.random.PRNGKey(1), tok)
    np.testing.assert_allclose(
        np.asarray(m_flash.apply(p, tok)),
        np.asarray(m_dense.apply(p, tok)), atol=1e-4, rtol=1e-4)


def test_shard_mapped_flash_on_mesh():
    """make_attention(mesh=...) runs the kernel per dp/tp shard (the
    Pallas call has no SPMD rule; shard_map supplies the partitioning)."""
    from geomx_tpu.models.transformer import make_attention
    from geomx_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(jax.devices(), tp=2, sp=1)  # dp=4 x tp=2 on 8 cpus
    attn = make_attention("flash", mesh=mesh, block_q=8, block_k=8)
    q = _rand((4, 16, 2, 8), 0)
    k = _rand((4, 16, 2, 8), 1)
    v = _rand((4, 16, 2, 8), 2)
    out = jax.jit(attn)(q, k, v)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_make_attention_rejects_sp_sharding():
    from geomx_tpu.models.transformer import make_attention
    from geomx_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(jax.devices(), tp=1, sp=2)
    with pytest.raises(ValueError, match="ring"):
        make_attention("flash", mesh=mesh)


def test_cross_attention_unequal_lengths():
    """Tq != Tk, non-causal (cross-attention)."""
    q = _rand((1, 24, 2, 8), 0)
    k = _rand((1, 40, 2, 8), 1)
    v = _rand((1, 40, 2, 8), 2)
    out = flash_attention(q, k, v, causal=False, block_q=8, block_k=8)
    # dense reference built by hand (dense_attention assumes Tq == Tk)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(8.0)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_causal_decode_offset():
    """Causal with Tq < Tk: queries are the LAST Tq positions of the key
    sequence (kv-cache decode convention) — a single query must attend
    to the whole prefix, not just key 0."""
    Tq, Tk = 8, 32
    q = _rand((1, Tq, 1, 8), 0)
    k = _rand((1, Tk, 1, 8), 1)
    v = _rand((1, Tk, 1, 8), 2)
    out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(8.0)
    qpos = jnp.arange(Tq)[:, None] + (Tk - Tq)
    mask = qpos >= jnp.arange(Tk)[None, :]
    s = jnp.where(mask[None, None], s, -1e30)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # and the gradient path composes for the decode shape
    g = jax.grad(lambda q: flash_attention(
        q, k, v, causal=True, block_q=8, block_k=8).sum())(q)
    assert bool(jnp.all(jnp.isfinite(g)))


def test_causal_rejects_more_queries_than_keys():
    q = _rand((1, 16, 1, 8), 0)
    k = _rand((1, 12, 1, 8), 1)
    with pytest.raises(ValueError, match="Tq <= Tk"):
        flash_attention(q, k, k, causal=True, block_q=8, block_k=8)
