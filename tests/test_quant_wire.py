"""Quantized combined wire: numpy-oracle bit-exactness.

The wire codec (compression.device.WireCodec, GEOMX_WIRE_CODEC) narrows
every leg of a combined round — worker push, party WAN forward, global
response, party response — to fp16 or residual-feedback 2-bit codes.
These tests replay the EXACT four-leg protocol chain in numpy (same
kernels, same residual streams, same aggregation order) and require the
live multi-node topology to reproduce it bit for bit, across >= 3
rounds so error-feedback residual carry is covered, on both the plain
van tier (``dist_sync``) and the mesh-party tier (``dist_sync_mesh``).
Aggregator mode throughout (no optimizer): the store holds the round's
aggregated gradient, so responses quantize too — both directions of
the WAN narrow, which is where the >= 4x byte drop comes from.
"""

import numpy as np
import pytest

from geomx_tpu import telemetry
from geomx_tpu.compression import two_bit_dequantize, two_bit_quantize
from geomx_tpu.compression.device import (WireCodec, codec_requires_aux,
                                          decode_wire)
from geomx_tpu.kvstore.frontier import plan_chunks
from geomx_tpu.simulate import InProcessHiPS

THR = 0.5          # wire_2bit_threshold (the config default)
KEYS = [0, 1, 2]
SIZES = [6, 9, 4]  # 6 and 9 exercise the 2-bit pad (not divisible by 4)
ROUNDS = 3


def _f16(x):
    return np.asarray(x, np.float32).astype(np.float16).astype(np.float32)


def _qd(x, res):
    """One quantize->dequantize hop: what the receiving node sees after
    a 2-bit leg, with ``res`` the sender's error-feedback residual
    (mutated in place, exactly like the wire's encode)."""
    packed = two_bit_quantize(np.asarray(x, np.float32).copy(), res, THR)
    return two_bit_dequantize(packed, x.size, THR)


def _g(widx, rnd, key, n):
    """Deterministic per-(worker, round, key) gradient."""
    rng = np.random.RandomState(1000 + 97 * widx + 13 * rnd + key)
    return rng.uniform(-1, 1, n).astype(np.float32)


# ---------------------------------------------------------------------------
# policy / planner units
# ---------------------------------------------------------------------------

def test_wire_codec_rejects_unknown_policy():
    with pytest.raises(ValueError, match="GEOMX_WIRE_CODEC"):
        WireCodec("zstd")


def test_codec_requires_aux():
    assert codec_requires_aux("2bit")
    assert codec_requires_aux("bsc16")
    assert codec_requires_aux("rsp")
    assert not codec_requires_aux("")
    assert not codec_requires_aux("fp16")
    assert not codec_requires_aux("bsc")


def test_chunk_codec_routing():
    assert WireCodec("").chunk_codec(0, 4, 10 ** 6) == ""
    assert WireCodec("fp16").chunk_codec(3, 4, 10 ** 6) == "fp16"
    assert WireCodec("2bit").chunk_codec(0, 4, 1) == "2bit"
    # MPQ: the size rule at chunk granularity — boundary is inclusive
    mpq = WireCodec("mpq", size_lower_bound=100)
    assert mpq.chunk_codec(0, 3, 99) == "fp16"
    assert mpq.chunk_codec(0, 3, 100) == "2bit"
    assert mpq.chunk_codec(0, 3, 101) == "2bit"
    # P3: the head chunk keeps fp16 no matter its size; tails route mpq
    p3 = WireCodec("p3", size_lower_bound=100)
    assert p3.chunk_codec(0, 3, 10 ** 6) == "fp16"
    assert p3.chunk_codec(1, 3, 100) == "2bit"
    assert p3.chunk_codec(2, 3, 99) == "fp16"


def test_plan_chunks_stamps_codec():
    mpq = WireCodec("mpq", size_lower_bound=8)
    # 4-elem chunk (16 bytes) then 16-elem chunk (64 bytes)
    chunks = plan_chunks(["a", "b"], [16, 64], 16,
                         codec_for=mpq.chunk_codec)
    assert [c.codec for c in chunks] == ["fp16", "2bit"]
    # zero budget: one chunk, codec from the round's total element count
    chunks = plan_chunks(["a", "b"], [16, 64], 0,
                         codec_for=mpq.chunk_codec)
    assert len(chunks) == 1 and chunks[0].codec == "2bit"
    # no codec_for: codec stays raw
    assert plan_chunks(["a"], [16], 0)[0].codec == ""


def test_encode_decode_2bit_residual_carry_vs_oracle():
    """Host encode path == the raw kernels, including residual carry
    across rounds and the non-divisible-by-4 pad."""
    wc = WireCodec("2bit", threshold=THR)
    res = np.zeros(7, np.float32)
    rng = np.random.RandomState(3)
    for _ in range(4):
        g = rng.uniform(-1, 1, 7).astype(np.float32)
        wv, aux, tag = wc.encode("2bit", g, ("k", 0))
        assert tag == "2bit" and wv.dtype == np.uint8 and wv.size == 2
        np.testing.assert_array_equal(aux, np.asarray([THR], np.float32))
        expect = _qd(g, res)
        np.testing.assert_array_equal(
            decode_wire("2bit", wv, aux, 7), expect)
    wc.reset(("k", 0))
    wv, aux, _ = wc.encode("2bit", np.ones(7, np.float32), ("k", 0))
    np.testing.assert_array_equal(
        decode_wire("2bit", wv, aux, 7),
        _qd(np.ones(7, np.float32), np.zeros(7, np.float32)))


def test_encode_fp16_and_raw():
    wc = WireCodec("fp16")
    g = np.asarray([1.0001, -2.5, 3e-5], np.float32)
    wv, aux, tag = wc.encode("fp16", g)
    assert tag == "fp16" and wv.dtype == np.float16 and aux is None
    np.testing.assert_array_equal(decode_wire("fp16", wv, None, 3), _f16(g))
    wv, aux, tag = wc.encode("", g)
    assert tag == "" and aux is None
    np.testing.assert_array_equal(decode_wire("", wv, None, 3), g)


# ---------------------------------------------------------------------------
# dense combined rounds vs the four-leg numpy oracle
# ---------------------------------------------------------------------------

def _run_dense_wire(policy, party_mesh_size=0, rounds=ROUNDS):
    """3 dense combined rounds at 2 parties x 1 van worker; returns
    {party: [per-round list of per-key outs]}. Multi-key rounds so the
    party server's batched WAN forward (the pull=True combined hop)
    carries the codec on every leg."""
    kw = dict(num_parties=2, workers_per_party=1,
              extra_cfg={"wire_codec": policy,
                         "wire_2bit_threshold": THR})
    if party_mesh_size:
        kw["party_mesh_size"] = party_mesh_size
    topo = InProcessHiPS(**kw).start()
    result = {}
    try:
        def master_init(kv):
            for k, n in zip(KEYS, SIZES):
                kv.init(k, np.zeros(n, np.float32))
            kv.wait()

        def worker(kv):
            widx = 0 if kv is topo.workers[0] else 1
            outs = [np.zeros(n, np.float32) for n in SIZES]
            for k, o in zip(KEYS, outs):
                kv.init(k, o.copy())
                kv.pull(k, out=o)
            kv.wait()
            per_round = []
            for rnd in range(rounds):
                grads = [_g(widx, rnd, k, n)
                         for k, n in zip(KEYS, SIZES)]
                fut = kv.push_pull_async(KEYS, grads, outs)
                fut.wait(timeout=120)
                per_round.append([o.copy() for o in outs])
            result[widx] = per_round

        topo.run_workers(worker, include_master=master_init, timeout=300)
    finally:
        topo.stop()
    assert len(result) == 2
    return result


def _oracle_dense(policy, rounds=ROUNDS):
    """Replay the protocol in numpy: per party p (1 worker each)
    agg_p = decode(encode(g_p)); WAN forward wan_p = decode(encode(agg_p));
    global store S = sum_p wan_p; global response rsp = decode(encode(S))
    (ONE encode per round — both parties get identical bytes); party
    response out_p = decode(encode(rsp)). 2-bit legs each have their own
    persistent residual stream, keyed like the wire's
    ((key, off) / ("fwd", ...) / ("rsp", ...) state keys)."""
    zeros = lambda n: np.zeros(n, np.float32)
    r_push = {(p, k): zeros(n) for p in (0, 1)
              for k, n in zip(KEYS, SIZES)}
    r_fwd = {(p, k): zeros(n) for p in (0, 1)
             for k, n in zip(KEYS, SIZES)}
    r_grsp = {k: zeros(n) for k, n in zip(KEYS, SIZES)}
    r_prsp = {(p, k): zeros(n) for p in (0, 1)
              for k, n in zip(KEYS, SIZES)}
    out = {0: [], 1: []}
    for rnd in range(rounds):
        ko = {0: [], 1: []}
        for k, n in zip(KEYS, SIZES):
            if policy == "fp16":
                agg = [_f16(_g(p, rnd, k, n)) for p in (0, 1)]
                S = _f16(agg[0]) + _f16(agg[1])
                rsp = _f16(S)
                outs = [_f16(rsp), _f16(rsp)]
            else:
                agg = [_qd(_g(p, rnd, k, n), r_push[(p, k)])
                       for p in (0, 1)]
                wan = [_qd(agg[p], r_fwd[(p, k)]) for p in (0, 1)]
                S = wan[0] + wan[1]
                rsp = _qd(S, r_grsp[k])
                outs = [_qd(rsp, r_prsp[(p, k)]) for p in (0, 1)]
            ko[0].append(outs[0])
            ko[1].append(outs[1])
        out[0].append(ko[0])
        out[1].append(ko[1])
    return out


@pytest.mark.parametrize("mesh", [0, 2],
                         ids=["dist_sync", "dist_sync_mesh"])
@pytest.mark.parametrize("policy", ["fp16", "2bit"])
def test_dense_wire_matches_numpy_oracle(policy, mesh):
    got = _run_dense_wire(policy, party_mesh_size=mesh)
    want = _oracle_dense(policy)
    for p in (0, 1):
        for rnd in range(ROUNDS):
            for ki in range(len(KEYS)):
                np.testing.assert_array_equal(
                    got[p][rnd][ki], want[p][rnd][ki],
                    err_msg=f"party {p} round {rnd} key {KEYS[ki]} "
                            f"policy {policy}")
    # the rounds did real work (quantized gradients flowed end to end)
    assert any(np.abs(a).sum() > 0
               for rnd in want[0] for a in rnd)


# ---------------------------------------------------------------------------
# BSC combined rounds: the "bsc16" sparse wire vs its oracle
# ---------------------------------------------------------------------------

BSC_SIZES = [8, 5, 12, 6]
BSC_KEYS = list(range(len(BSC_SIZES)))


def _run_bsc_wire(party_mesh_size=0):
    kw = dict(num_parties=2, workers_per_party=1,
              extra_cfg={"wire_codec": "fp16"})
    if party_mesh_size:
        kw["party_mesh_size"] = party_mesh_size
    topo = InProcessHiPS(**kw).start()
    result = {}
    try:
        def master_init(kv):
            for k, n in zip(BSC_KEYS, BSC_SIZES):
                kv.init(k, np.zeros(n, np.float32))
            kv.wait()

        def worker(kv):
            widx = 0 if kv is topo.workers[0] else 1
            for k, n in zip(BSC_KEYS, BSC_SIZES):
                kv.init(k, np.zeros(n, np.float32))
            kv.wait()
            vals, idxs = _bsc_inputs(widx)
            fut = kv.push_pull_bsc_batch_async(BSC_KEYS, vals, idxs)
            agg = fut.results(timeout=120)
            dense = {}
            for k, n in zip(BSC_KEYS, BSC_SIZES):
                buf = np.zeros(n, np.float32)
                avals, aidx = agg[k]
                np.add.at(buf, aidx, avals)
                dense[k] = buf
            result[widx] = dense

        topo.run_workers(worker, include_master=master_init, timeout=300)
    finally:
        topo.stop()
    assert len(result) == 2
    return result


def _bsc_inputs(widx):
    rng = np.random.RandomState(5 + widx)
    vals = [rng.rand(3).astype(np.float32) + 1.0 for _ in BSC_KEYS]
    idxs = [np.sort(rng.choice(n, 3, replace=False)) for n in BSC_SIZES]
    return vals, idxs


def _oracle_bsc():
    """bsc16 narrows the sparse VALUES to fp16 on every leg; indices
    are exact. Per party: dense_p = scatter(f16(vals_p)); WAN forward
    is the dense fp16 downgrade (a party server has no sparse selection
    of its own); global S = sum_p f16(dense_p); both response legs are
    exact-nonzero f16 — dense result f16(S)."""
    out = {}
    for k, n in zip(BSC_KEYS, BSC_SIZES):
        S = np.zeros(n, np.float32)
        for p in (0, 1):
            vals, idxs = _bsc_inputs(p)
            dense = np.zeros(n, np.float32)
            np.add.at(dense, idxs[k], _f16(vals[k]))
            S += _f16(dense)
        out[k] = _f16(S)
    return out


@pytest.mark.parametrize("mesh", [0, 2],
                         ids=["dist_sync", "dist_sync_mesh"])
def test_bsc_wire_matches_numpy_oracle(mesh):
    got = _run_bsc_wire(party_mesh_size=mesh)
    want = _oracle_bsc()
    for k in BSC_KEYS:
        np.testing.assert_array_equal(got[0][k], want[k])
        np.testing.assert_array_equal(got[1][k], want[k])
    assert any(np.abs(v).sum() > 0 for v in want.values())


# ---------------------------------------------------------------------------
# MPQ chunk routing + per-codec WAN telemetry
# ---------------------------------------------------------------------------

def test_mpq_routes_per_chunk_and_telemetry_breaks_out_codecs():
    """Two keys straddling size_lower_bound, sliced one chunk each: the
    head chunk goes fp16, the bulk chunk 2-bit, and
    telemetry.wan_bytes_by_codec sees BOTH codec families on the WAN
    (forwards inherit each chunk's codec; responses echo it)."""
    sizes = [4, 16]
    keys = [0, 1]
    topo = InProcessHiPS(
        num_parties=2, workers_per_party=1,
        extra_cfg={"wire_codec": "mpq", "size_lower_bound": 8,
                   "wire_2bit_threshold": THR}).start()
    try:
        def master_init(kv):
            for k, n in zip(keys, sizes):
                kv.init(k, np.zeros(n, np.float32))
            kv.wait()

        def init_worker(kv):
            for k, n in zip(keys, sizes):
                kv.init(k, np.zeros(n, np.float32))
            kv.wait()

        topo.run_workers(init_worker, include_master=master_init,
                         timeout=120)
        telemetry.reset()
        telemetry.enable(True)

        def train(kv):
            widx = 0 if kv is topo.workers[0] else 1
            outs = [np.zeros(n, np.float32) for n in sizes]
            grads = [_g(widx, 0, k, n) for k, n in zip(keys, sizes)]
            # 16-byte budget: key 0 (4 floats) and key 1 (16 floats)
            # land in separate chunks -> separate codecs
            fut = kv.push_pull_async(keys, grads, outs, slice_bytes=16)
            fut.wait(timeout=120)

        topo.run_workers(train, timeout=120)
        snap = telemetry.snapshot()
    finally:
        telemetry.reset()
        topo.stop()
    by_codec = telemetry.wan_bytes_by_codec(snap)
    assert by_codec.get("fp16", 0) > 0, by_codec
    assert by_codec.get("2bit", 0) > 0, by_codec
    # the breakdown partitions wan_bytes exactly
    assert sum(by_codec.values()) == telemetry.wan_bytes(snap)


# ---------------------------------------------------------------------------
# the acceptance number: >= 4x WAN byte drop with the 2-bit wire
# ---------------------------------------------------------------------------

def _wan_bytes_for(policy, n=4096, rounds=2):
    topo = InProcessHiPS(
        num_parties=2, workers_per_party=1,
        extra_cfg={"wire_codec": policy,
                   "wire_2bit_threshold": THR}).start()
    try:
        def master_init(kv):
            kv.init(0, np.zeros(n, np.float32))
            kv.init(1, np.zeros(n, np.float32))
            kv.wait()

        def init_worker(kv):
            kv.init(0, np.zeros(n, np.float32))
            kv.init(1, np.zeros(n, np.float32))
            kv.wait()

        topo.run_workers(init_worker, include_master=master_init,
                         timeout=120)
        telemetry.reset()
        telemetry.enable(True)   # count the training rounds only

        def train(kv):
            widx = 0 if kv is topo.workers[0] else 1
            outs = [np.zeros(n, np.float32) for _ in (0, 1)]
            for rnd in range(rounds):
                grads = [_g(widx, rnd, k, n) for k in (0, 1)]
                fut = kv.push_pull_async([0, 1], grads, outs)
                fut.wait(timeout=120)

        topo.run_workers(train, timeout=240)
        wb = telemetry.wan_bytes()
    finally:
        telemetry.reset()
        topo.stop()
    assert wb > 0
    return wb


def test_wan_bytes_drop_at_least_4x_with_2bit_wire():
    """Aggregator mode quantizes BOTH WAN directions (2-bit forward,
    2-bit response): at 16 KiB keys the bytes/round must drop >= 4x vs
    the raw wire (the ISSUE's acceptance floor; the actual pack ratio
    is ~16x, headroom covers message framing)."""
    raw = _wan_bytes_for("")
    quant = _wan_bytes_for("2bit")
    if quant * 4 > raw:
        # the registry is process-global: a prior topology's teardown
        # can land a few late frames inside this measurement window
        # (seen as ~3 raw-size frames inflating the 2-bit figure).
        # One remeasure shakes the stragglers out; a real codec
        # regression fails both times.
        raw = _wan_bytes_for("")
        quant = _wan_bytes_for("2bit")
    assert quant * 4 <= raw, (raw, quant)
