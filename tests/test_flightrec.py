"""Crash flight recorder: ring semantics, dump discipline, and the
acceptance scenario — a FaultPlan crash leaves a dump whose last events
are the in-flight round's wire frames.
"""

import glob
import json
import os
import signal

import numpy as np
import pytest

from geomx_tpu.optimizer import SGD
from geomx_tpu.ps import base as psbase
from geomx_tpu.ps import flightrec
from geomx_tpu.ps.flightrec import FlightRecorder, default_dir
from tools import flight_report

from tests.test_hips import _parallel
from tests.test_recovery import SingleTier


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------

def test_ring_bounds_and_seq_ordering():
    rec = FlightRecorder(lambda: "n1", size=4)
    assert rec.enabled
    for i in range(10):
        rec.record("sent", peer=i)
    evs = rec.snapshot()
    assert len(evs) == 4
    # the ring keeps the LAST events; seq keeps counting across drops
    assert [e["peer"] for e in evs] == [6, 7, 8, 9]
    assert [e["seq"] for e in evs] == [7, 8, 9, 10]
    assert all(e["kind"] == "sent" and "t" in e for e in evs)


def test_size_zero_disables(tmp_path):
    rec = FlightRecorder(lambda: "n1", size=0, out_dir=str(tmp_path))
    assert not rec.enabled
    rec.record("sent", peer=1)
    assert rec.snapshot() == []
    assert rec.dump("crash:off") == ""
    assert list(tmp_path.iterdir()) == []


def test_dump_writes_atomic_json(tmp_path):
    rec = FlightRecorder(lambda: "g8p9000", size=8, out_dir=str(tmp_path))
    rec.record("sent", peer=10, verb="push", bytes=64, round=3)
    path = rec.dump("violation:unanswered-request")
    assert os.path.basename(path) == f"flightrec_g8p9000_pid{os.getpid()}.json"
    doc = json.loads(open(path).read())
    assert doc["node"] == "g8p9000"
    assert doc["reason"] == "violation:unanswered-request"
    assert doc["events"][0]["round"] == 3
    assert all(".tmp." not in p.name for p in tmp_path.iterdir())


def test_dump_dedups_by_reason_class(tmp_path):
    rec = FlightRecorder(lambda: "n1", size=8, out_dir=str(tmp_path))
    rec.record("crash", reason="x")
    first = rec.dump("crash:rule #0")
    assert first
    # a cascade within the class must not rewrite the first dump
    assert rec.dump("crash:rule #1") == ""
    # a different class still dumps (explicit path: don't collide on name)
    other = rec.dump("round_abort", path=str(tmp_path / "abort.json"))
    assert other and other != first


def test_dump_never_raises(tmp_path, monkeypatch):
    rec = FlightRecorder(lambda: "n1", size=8,
                         out_dir=str(tmp_path / "sub"))
    rec.record("sent", peer=1)

    real_open = open

    def failing_open(path, *a, **kw):
        if ".tmp." in str(path):
            raise OSError("disk full")
        return real_open(path, *a, **kw)

    monkeypatch.setattr("builtins.open", failing_open)
    assert rec.dump("crash:boom") == ""     # swallowed, logged
    monkeypatch.undo()
    # the failed attempt must not burn the reason class
    assert rec.dump("crash:boom") != ""


def test_node_fn_failure_falls_back_to_unknown(tmp_path):
    def exploding():
        raise RuntimeError("no rendezvous yet")

    rec = FlightRecorder(exploding, size=4, out_dir=str(tmp_path))
    rec.record("note", event="early")
    path = rec.dump("crash:pre-start")
    assert "flightrec_unknown_pid" in path


def test_default_dir_under_tmp():
    assert default_dir().endswith("geomx_flightrec")


# ---------------------------------------------------------------------------
# shutdown dumps (reason class "shutdown": SIGTERM / atexit)
# ---------------------------------------------------------------------------

def test_shutdown_dump_all_writes_own_file(tmp_path):
    rec = FlightRecorder(lambda: "n1", size=8, out_dir=str(tmp_path))
    rec.record("sent", peer=8)
    paths = flightrec.dump_all("shutdown:atexit")
    mine = [p for p in paths if str(tmp_path) in p]
    assert len(mine) == 1
    assert mine[0].endswith("_shutdown.json")
    doc = json.loads(open(mine[0]).read())
    assert doc["reason"] == "shutdown:atexit"
    assert doc["events"][0]["peer"] == 8
    # the shutdown class is latched like any other: a second pass (the
    # atexit hook after a SIGTERM dump) must not re-dump
    assert [p for p in flightrec.dump_all("shutdown:atexit")
            if str(tmp_path) in p] == []


def test_shutdown_skips_empty_rings_and_default_dir(tmp_path):
    # empty ring: enrolled but nothing worth a post-mortem
    FlightRecorder(lambda: "empty", size=8, out_dir=str(tmp_path))
    # default out_dir: NOT enrolled (ordinary runs must not litter $TMPDIR)
    implicit = FlightRecorder(lambda: "implicit", size=8)
    implicit.record("sent", peer=1)
    assert implicit not in flightrec._shutdown_registry
    assert [p for p in flightrec.dump_all("shutdown:atexit")
            if str(tmp_path) in p] == []


def test_shutdown_dump_does_not_clobber_crash_dump(tmp_path):
    rec = FlightRecorder(lambda: "n2", size=8, out_dir=str(tmp_path))
    rec.record("crash", reason="x")
    crash = rec.dump("crash:rule #0")
    shut = rec.dump("shutdown:sigterm")
    assert crash and shut and shut != crash
    assert json.loads(open(crash).read())["reason"] == "crash:rule #0"


def test_sigterm_dumps_and_preserves_kill_status(tmp_path):
    """A SIGTERM'd process leaves a shutdown dump AND still dies by
    SIGTERM (the handler re-delivers the default disposition)."""
    code = (
        "import os, signal, sys, time\n"
        "from geomx_tpu.ps.flightrec import FlightRecorder\n"
        "rec = FlightRecorder(lambda: 'victim', size=8,"
        f" out_dir={str(tmp_path)!r})\n"
        "rec.record('sent', peer=8, verb='push')\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
        "time.sleep(5)\n"
        "sys.exit(3)  # unreachable unless the re-kill was swallowed\n"
    )
    import subprocess
    import sys as _sys
    proc = subprocess.run([_sys.executable, "-c", code], timeout=60,
                          capture_output=True)
    assert proc.returncode == -signal.SIGTERM, proc.stderr.decode()
    dumps = glob.glob(str(tmp_path / "*_shutdown.json"))
    assert len(dumps) == 1
    doc = json.loads(open(dumps[0]).read())
    assert doc["reason"] == "shutdown:sigterm"
    assert doc["events"][0]["peer"] == 8


# ---------------------------------------------------------------------------
# flight_report rendering
# ---------------------------------------------------------------------------

def test_flight_report_renders_narrative(tmp_path, capsys):
    rec = FlightRecorder(lambda: "l9p5001", size=8, out_dir=str(tmp_path))
    rec.record("sent", peer=8, verb="push", bytes=4096, req=True,
               ts=12, round=5, chunk=-1, origin=9, epoch=0)
    rec.record("recv", peer=8, verb="push", bytes=16, req=False,
               ts=12, round=5, chunk=-1, origin=9, epoch=0)
    rec.record("crash", reason="crash rule #0")
    path = rec.dump("crash:rule #0")

    text = flight_report.report(json.loads(open(path).read()))
    assert "node l9p5001" in text
    assert "crash:rule #0" in text
    assert "rounds in flight: [5]" in text
    assert "push" in text and "round=5" in text

    # CLI over a directory finds the dump; --tail trims events
    rc = flight_report.main([str(tmp_path), "--tail", "2"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "showing last 2" in out and "crash" in out


def test_flight_report_cli_errors_on_missing(tmp_path, capsys):
    assert flight_report.main([str(tmp_path)]) == 1  # empty dir
    bad = tmp_path / "flightrec_x_pid1.json"
    bad.write_text("{not json")
    assert flight_report.main([str(bad)]) == 1
    assert "unreadable" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# acceptance: a FaultPlan crash dumps the in-flight round's frames
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_faultplan_crash_dumps_in_flight_round(tmp_path):
    """Kill a worker with an ``at_round`` crash rule after a full traced
    round: its van must leave a flight-recorder dump whose tail is the
    round's wire frames (with the trace round id) ending in the crash."""
    victim_id = psbase.worker_rank_to_id(1)
    plan = json.dumps({"rules": [{
        "type": "crash", "node": victim_id, "at_round": 2,
        "tier": "local"}]})
    topo = SingleTier(extra={"fault_plan": plan, "ps_seed": 11,
                             "flightrec_dir": str(tmp_path)}).start()
    w0 = np.zeros(8, np.float32)
    try:
        workers = sorted(topo.workers, key=lambda kv: kv.rank)
        rank0, victim = workers
        rank0.set_optimizer(SGD(learning_rate=1.0))
        _parallel([lambda kv=kv: kv.init(0, w0) for kv in workers])

        # round 1: a traced push_pull from every worker puts round-
        # stamped frames in the victim's ring
        def step(kv):
            kv.push_pull(0, np.ones_like(w0), np.zeros_like(w0))
            kv.wait(timeout=60.0)

        _parallel([lambda kv=kv: step(kv) for kv in workers])

        victim._closed = True            # disarm its atexit close
        victim.notify_round(2)           # at_round rule fires here
        assert victim.po.van.stopped.wait(10), "crash rule did not fire"

        dumps = glob.glob(str(tmp_path / "flightrec_*.json"))
        docs = [json.loads(open(p).read()) for p in dumps]
        crash = [d for d in docs if d["reason"].startswith("crash")]
        assert len(crash) == 1, f"expected one crash dump, got {dumps}"
        doc = crash[0]
        events = doc["events"]
        assert events[-1]["kind"] == "crash"
        # the tail is the in-flight round: the victim's own sends,
        # carrying the trace round id the worker stamped
        sends = [e for e in events if e["kind"] == "sent"
                 and e.get("round", -1) >= 1]
        assert sends, "no round-stamped sends in the crash dump"
        assert any(e["verb"] in ("push", "pull") for e in sends)
        topo.workers = [rank0]
    finally:
        _parallel([kv.close for kv in topo.workers])
        for t in topo.threads:
            t.join(30)
        if topo.errors:
            raise topo.errors[0]


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
