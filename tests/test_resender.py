"""Resender (ACK/retransmit) tests under deterministic fault injection.

Mirrors the reference pairing of ``PS_DROP_MSG`` random message drops
(van.cc:498-499, 871-877) with the ACK resender (resender.h:15-141): a
lossy transport with resend enabled must still complete every push/pull,
and retransmits must not double-apply server-side aggregation.

Loss is injected through the declarative FaultPlan layer (a seeded
``drop`` rule) rather than the legacy uniform ``drop_rate``, so every
run sees the same drop schedule and failures reproduce byte-for-byte.
"""

import json
import threading

import numpy as np
import pytest

from geomx_tpu.config import Config
from geomx_tpu.ps import base
from geomx_tpu.ps.kv_app import KVPairs, KVServer, KVWorker
from geomx_tpu.ps.message import Role
from geomx_tpu.ps.postoffice import Postoffice

from test_transport import free_port, shutdown


def make_lossy_tier(drop_rate, num_workers=2, num_servers=1,
                    resend_timeout_ms=100, seed=1234):
    port = free_port()
    kw_cfg = dict(resend=True, resend_timeout_ms=resend_timeout_ms,
                  ps_seed=seed)
    if drop_rate:
        # seeded drop rule: same schedule on every run (control frames
        # are exempt by default, so rendezvous always completes)
        kw_cfg["fault_plan"] = json.dumps(
            {"rules": [{"type": "drop", "p": drop_rate}]})
    cfg = Config(**kw_cfg)
    kw = dict(is_global=False, root_uri="127.0.0.1", root_port=port,
              num_workers=num_workers, num_servers=num_servers, cfg=cfg)
    sched = Postoffice(my_role=Role.SCHEDULER, **kw)
    servers = [Postoffice(my_role=Role.SERVER, **kw)
               for _ in range(num_servers)]
    workers = [Postoffice(my_role=Role.WORKER, **kw)
               for _ in range(num_workers)]
    threads = []
    for po in [sched] + servers + workers:
        t = threading.Thread(target=po.start, daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(30)
    for po in [sched] + servers + workers:
        assert po.van.ready.is_set(), "rendezvous failed under loss"
    return sched, servers, workers


def test_sig_assignment_and_ack_clears_pending():
    sched, servers, workers = make_lossy_tier(drop_rate=0.0)
    try:
        store = {}
        server = KVServer(servers[0])

        def handle(req, kvs, srv):
            if req.push:
                for k, v in zip(kvs.keys, kvs.vals):
                    store[k] = store.get(k, 0) + v
                srv.response(req)

        server.set_request_handle(handle)
        w = KVWorker(workers[0])
        ts = w.push(KVPairs(keys=[1], vals=[np.ones(4, np.float32)]),
                    server_rank=0)
        w.wait(ts, 10)
        # all ACKs should drain the outgoing tables on both sides
        for po in [*workers, *servers]:
            r = po.van._resender
            assert r is not None
            for _ in range(100):
                if r.pending() == 0:
                    break
                threading.Event().wait(0.05)
            assert r.pending() == 0
    finally:
        shutdown(sched, *servers, *workers)


def test_lossy_push_pull_completes_exactly_once():
    """30% data-frame loss: pushes still aggregate exactly once each."""
    sched, servers, workers = make_lossy_tier(drop_rate=0.3)
    try:
        store = {}
        applied = []
        lock = threading.Lock()
        server = KVServer(servers[0])

        def handle(req, kvs, srv):
            if req.push:
                with lock:
                    applied.append(req.sender)
                    for k, v in zip(kvs.keys, kvs.vals):
                        store[k] = store.get(k, 0) + v
                srv.response(req)
            elif req.pull:
                srv.response(req, KVPairs(
                    keys=kvs.keys, vals=[store[k] for k in kvs.keys]))

        server.set_request_handle(handle)
        w0, w1 = KVWorker(workers[0]), KVWorker(workers[1])
        v = np.ones((8,), dtype=np.float32)
        n_rounds = 5
        for _ in range(n_rounds):
            ts0 = w0.push(KVPairs(keys=[7], vals=[v]), server_rank=0)
            ts1 = w1.push(KVPairs(keys=[7], vals=[v]), server_rank=0)
            w0.wait(ts0, 60)
            w1.wait(ts1, 60)
        ts = w0.pull([7], server_rank=0)
        w0.wait(ts, 60)
        (resp,) = w0.take_response(ts)
        # exactly 2 workers x n_rounds pushes applied, despite drops+resends
        assert len(applied) == 2 * n_rounds
        np.testing.assert_allclose(resp.vals[0], 2 * n_rounds * v)
        total_resends = sum(po.van._resender.num_resends
                            for po in [*workers, *servers])
        assert total_resends > 0, "drop_rate=0.3 but nothing was resent?"
    finally:
        shutdown(sched, *servers, *workers)


def test_duplicate_suppression():
    """Exact duplicate frames (same signature — i.e. a retransmit whose
    original actually arrived) must be suppressed: server-side effects
    stay exactly-once."""
    sched, servers, workers = make_lossy_tier(drop_rate=0.0)
    try:
        count = [0]
        server = KVServer(servers[0])

        def handle(req, kvs, srv):
            if req.push:
                count[0] += 1
                srv.response(req)

        server.set_request_handle(handle)
        # transport-level duplicate injection: every data frame is sent
        # twice with the same already-assigned signature, exactly what a
        # retransmit after a lost ACK looks like on the wire
        van = workers[0].van
        orig = van._send_one_inner

        def dup_send(target, msg):
            n = orig(target, msg)
            if not msg.is_control:
                orig(target, msg)
            return n

        van._send_one_inner = dup_send
        w = KVWorker(workers[0])
        ts = w.push(KVPairs(keys=[3], vals=[np.ones(4, np.float32)]),
                    server_rank=0)
        w.wait(ts, 30)
        threading.Event().wait(0.3)  # let the duplicate arrive and settle
        assert count[0] == 1
        dups = servers[0].van._resender.num_duplicates
        assert dups >= 1, "expected at least one suppressed duplicate"
    finally:
        shutdown(sched, *servers, *workers)


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
