"""Profiler tests: chrome-trace recording + the remote command channel.

Reference behaviors covered: Profiler SetState/DumpProfile emitting
chrome-tracing JSON (src/profiler/profiler.h:270,304) and worker-driven
server profiler control with rank-prefixed dump files
(KVStoreServerProfilerCommand, include/mxnet/kvstore.h:49;
kvstore_dist_server.h:383-430).
"""

import json
import threading

import numpy as np
import pytest

from geomx_tpu import profiler
from geomx_tpu.config import Config
from geomx_tpu.kvstore.dist import KVStoreDist
from geomx_tpu.kvstore.server import KVStoreDistServer
from geomx_tpu.optimizer import SGD
from geomx_tpu.ps import base as psbase
from geomx_tpu.ps.message import Role
from geomx_tpu.ps.postoffice import Postoffice

from test_hips import _parallel, free_port


@pytest.fixture(autouse=True)
def _clean_profiler():
    profiler.reset()
    yield
    profiler.reset()


def test_scope_records_chrome_trace_events(tmp_path):
    profiler.set_config(filename=str(tmp_path / "trace.json"),
                        aggregate_stats=True)
    profiler.set_state("run")
    with profiler.scope("work", cat="test"):
        pass
    profiler.counter("queue_depth", 3)
    profiler.set_state("stop")
    path = profiler.dump()
    doc = json.loads(open(path).read())
    names = [e["name"] for e in doc["traceEvents"]]
    assert "work" in names and "queue_depth" in names
    ev = next(e for e in doc["traceEvents"] if e["name"] == "work")
    assert ev["ph"] == "X" and ev["dur"] >= 0 and ev["cat"] == "test"
    assert profiler.aggregate_stats().get("work", 0) >= 0


def test_paused_and_stopped_record_nothing():
    profiler.set_state("run")
    profiler.pause()
    with profiler.scope("hidden"):
        pass
    profiler.resume()
    profiler.set_state("stop")
    with profiler.scope("hidden2"):
        pass
    assert json.loads(profiler.dumps())["traceEvents"] == []


def test_dump_clears_when_finished(tmp_path):
    profiler.set_config(filename=str(tmp_path / "t.json"))
    profiler.set_state("run")
    with profiler.scope("once"):
        pass
    profiler.dump(finished=True)
    assert json.loads(profiler.dumps())["traceEvents"] == []


def test_remote_command_rank_prefixes_dump(tmp_path):
    body = json.dumps({"cmd": profiler.CMD_SET_CONFIG,
                       "params": {"filename": str(tmp_path / "p.json")}})
    profiler.apply_remote_command(body, rank=2)
    profiler.apply_remote_command(
        json.dumps({"cmd": profiler.CMD_STATE, "params": {"state": "run"}}), 2)
    with profiler.scope("server_work"):
        pass
    profiler.apply_remote_command(
        json.dumps({"cmd": profiler.CMD_DUMP, "params": {}}), 2)
    out = tmp_path / "rank2_p.json"
    assert out.exists()
    doc = json.loads(out.read_text())
    assert any(e["name"] == "server_work" for e in doc["traceEvents"])


def test_remote_command_malformed_json_is_ignored():
    profiler.set_state("run")
    profiler.apply_remote_command("{not json", rank=0)
    profiler.apply_remote_command("", rank=0)
    # state untouched: still running, scopes record
    with profiler.scope("alive"):
        pass
    names = [e["name"] for e in json.loads(profiler.dumps())["traceEvents"]]
    assert names == ["alive"]


def test_remote_command_unknown_cmd_is_noop():
    profiler.set_state("run")
    profiler.apply_remote_command(json.dumps({"cmd": 99, "params": {}}), 0)
    profiler.apply_remote_command(json.dumps({"params": {}}), 0)  # no cmd
    assert profiler.is_running()


def test_remote_state_defaults_to_stop():
    profiler.set_state("run")
    profiler.apply_remote_command(json.dumps({"cmd": profiler.CMD_STATE}), 0)
    assert not profiler.is_running()


def test_remote_pause_defaults_true_and_roundtrips():
    profiler.set_state("run")
    profiler.apply_remote_command(json.dumps({"cmd": profiler.CMD_PAUSE}), 0)
    with profiler.scope("while_paused"):
        pass
    profiler.apply_remote_command(
        json.dumps({"cmd": profiler.CMD_PAUSE,
                    "params": {"paused": False}}), 0)
    with profiler.scope("after_resume"):
        pass
    names = [e["name"] for e in json.loads(profiler.dumps())["traceEvents"]]
    assert "while_paused" not in names and "after_resume" in names


def test_remote_set_config_without_filename_keeps_default():
    profiler.apply_remote_command(
        json.dumps({"cmd": profiler.CMD_SET_CONFIG,
                    "params": {"aggregate_stats": True}}), rank=5)
    # no filename param -> nothing to rank-prefix, default stays
    assert profiler._config["filename"] == "profile.json"
    assert profiler._config["aggregate_stats"] is True


def test_dump_is_atomic_leaves_no_tmp(tmp_path):
    profiler.set_config(filename=str(tmp_path / "t.json"))
    profiler.set_state("run")
    with profiler.scope("w"):
        pass
    profiler.dump()
    assert [p.name for p in tmp_path.iterdir()] == ["t.json"]


def test_interrupted_dump_preserves_previous_trace(tmp_path, monkeypatch):
    """A dump that dies mid-write must not clobber an earlier good trace
    — the crash flight path dumps into files other tools then read."""
    target = tmp_path / "t.json"
    profiler.set_config(filename=str(target))
    profiler.set_state("run")
    with profiler.scope("good"):
        pass
    profiler.dump(finished=False)
    before = target.read_text()

    real_open = open

    def failing_open(path, *a, **kw):
        if ".tmp." in str(path):
            raise OSError("disk full")
        return real_open(path, *a, **kw)

    monkeypatch.setattr("builtins.open", failing_open)
    with pytest.raises(OSError):
        profiler.dump()
    monkeypatch.undo()
    assert target.read_text() == before
    doc = json.loads(target.read_text())
    assert any(e["name"] == "good" for e in doc["traceEvents"])


def test_worker_drives_server_profiler_end_to_end(tmp_path):
    """A worker remotely configures, runs, and dumps the server's
    profiler; the dump lands rank-prefixed and contains server.push
    scopes from real request handling."""
    port = free_port()
    threads, errors = [], []

    def run(fn):
        def w():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
        t = threading.Thread(target=w, daemon=True)
        t.start()
        threads.append(t)

    def sched():
        po = Postoffice(my_role=Role.SCHEDULER, is_global=False,
                        root_uri="127.0.0.1", root_port=port,
                        num_workers=1, num_servers=1, cfg=Config())
        po.start(60)
        po.barrier(psbase.ALL_GROUP, timeout=60)
        po.barrier(psbase.ALL_GROUP, timeout=120)
        po.van.stop()

    run(sched)
    scfg = Config(role="server", ps_root_uri="127.0.0.1", ps_root_port=port,
                  num_workers=1, num_servers=1)
    srv = KVStoreDistServer(scfg)
    run(srv.run)
    box = []
    wcfg = Config(role="worker", ps_root_uri="127.0.0.1", ps_root_port=port,
                  num_workers=1, num_servers=1)
    run(lambda: box.append(KVStoreDist(cfg=wcfg)))
    for _ in range(300):
        if errors:
            raise errors[0]
        if box:
            break
        threading.Event().wait(0.1)
    kv = box[0]
    try:
        kv.set_optimizer(SGD(learning_rate=1.0))
        kv.set_profiler_params(profiler.CMD_SET_CONFIG,
                               filename=str(tmp_path / "srv.json"))
        kv.set_profiler_params(profiler.CMD_STATE, state="run")
        kv.init(0, np.ones(4, np.float32))
        kv.push(0, np.ones(4, np.float32))
        out = kv.pull(0)
        kv.wait()
        np.testing.assert_allclose(out, np.zeros(4))
        kv.set_profiler_params(profiler.CMD_STATE, state="stop")
        kv.set_profiler_params(profiler.CMD_DUMP)
        dump = tmp_path / "rank0_srv.json"
        assert dump.exists()
        doc = json.loads(dump.read_text())
        names = {e["name"] for e in doc["traceEvents"]}
        assert "server.push" in names
        # per-operator engine tags (reference op tagging at
        # kvstore_dist_server.h:570): key-level spans + the updater span
        assert "push:key0" in names
        assert "pull:key0" in names
        assert "update:key0" in names
    finally:
        kv.close()
        for t in threads:
            t.join(30)
        if errors:
            raise errors[0]


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
