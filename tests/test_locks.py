"""geomx-racecheck runtime sanitizer (geomx_tpu/ps/locks.py) tests.

Harness half: real two-thread seeded inversions, blocking-call probes,
Condition.wait semantics and the Eraser-style @guarded_by lockset, all
against a fresh process-global witness per test.

Off-path half: with the sanitizer disabled the factories must hand back
the *raw* threading primitives (same class, not a wrapper), and an
acquire/release loop through a factory-built lock must cost within 5%
of a hand-built ``threading.Lock`` (the ISSUE acceptance bar).
"""

import logging
import threading
import time
import timeit

import pytest

from geomx_tpu import config as cfg_mod
from geomx_tpu.ps import locks

assert locks.MARKER  # the grep target scripts/run_chaos_matrix.sh fails on


@pytest.fixture(autouse=True)
def _restore_sanitizer_state():
    """Every test flips the process-global witness/enable flag; restore
    the environment-derived default afterwards so no state leaks into
    the rest of the tier-1 run."""
    yield
    locks.reset_for_tests(on=cfg_mod.env_bool("GEOMX_LOCK_SANITIZER"))


def _run_in_thread(fn):
    errs = []

    def runner():
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — surfaced via assert
            errs.append(e)

    t = threading.Thread(target=runner)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive(), "harness thread wedged"
    assert not errs, errs
    return t


# ---------------------------------------------------------------------------
# acquisition-order graph
# ---------------------------------------------------------------------------

def test_seeded_inversion_latches_exactly_once(caplog):
    w = locks.reset_for_tests(on=True)
    a = locks.make_lock("inv.A")
    b = locks.make_lock("inv.B")

    def forward():
        with a:
            with b:
                pass

    def inverted():
        with b:
            with a:
                pass

    with caplog.at_level(logging.ERROR, logger="geomx.locks"):
        _run_in_thread(forward)
        _run_in_thread(inverted)
        # re-seeding the same pair must NOT re-fire: latched per pair
        _run_in_thread(inverted)

    assert len(w.violations) == 1
    desc = w.violations[0]
    assert "lock-order inversion" in desc
    assert "inv.A" in desc and "inv.B" in desc
    # both acquisition stacks are named, one per direction
    assert "this thread:" in desc and "seen before:" in desc
    assert desc.count("test_locks.py") >= 2
    assert any(locks.MARKER in r.getMessage() for r in caplog.records)


def test_lock_ordered_control_is_clean():
    w = locks.reset_for_tests(on=True)
    a = locks.make_lock("ctl.A")
    b = locks.make_lock("ctl.B")

    def worker():
        for _ in range(200):
            with a:
                with b:
                    pass

    ts = [threading.Thread(target=worker) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)
        assert not t.is_alive()

    assert w.violations == []
    assert w.report() == []


def test_three_lock_cycle_is_flagged():
    w = locks.reset_for_tests(on=True)
    a = locks.make_lock("cyc.A")
    b = locks.make_lock("cyc.B")
    c = locks.make_lock("cyc.C")

    def edge(first, second):
        def body():
            with first:
                with second:
                    pass
        return body

    _run_in_thread(edge(a, b))
    _run_in_thread(edge(b, c))
    assert w.violations == []  # A->B->C alone is a fine total order
    _run_in_thread(edge(c, a))
    assert len(w.violations) == 1
    assert "lock-order cycle" in w.violations[0]
    for name in ("cyc.A", "cyc.B", "cyc.C"):
        assert name in w.violations[0]


def test_rlock_reentrancy_is_silent():
    w = locks.reset_for_tests(on=True)
    r = locks.make_rlock("re.R")
    with r:
        with r:
            assert r.held_by_me()
    assert not r.held_by_me()
    assert w.violations == []


# ---------------------------------------------------------------------------
# blocking-call probes
# ---------------------------------------------------------------------------

def test_blocking_call_under_lock_fires_and_latches():
    w = locks.reset_for_tests(on=True)
    lk = locks.make_lock("blk.L")

    time.sleep(0)  # no traced lock held: probe is inert
    assert w.violations == []

    with lk:
        time.sleep(0)
        time.sleep(0)  # same fingerprint: latched

    assert len(w.violations) == 1
    assert "time.sleep" in w.violations[0]
    assert "blk.L" in w.violations[0]


def test_queue_get_under_lock_fires():
    import queue

    w = locks.reset_for_tests(on=True)
    lk = locks.make_lock("blk.Q")
    q = queue.Queue()
    q.put("x")  # put with nothing held: clean
    assert w.violations == []
    with lk:
        q.get()
    assert len(w.violations) == 1
    assert "Queue.get" in w.violations[0]


# ---------------------------------------------------------------------------
# Condition.wait
# ---------------------------------------------------------------------------

def test_condition_wait_on_own_lock_is_exempt():
    w = locks.reset_for_tests(on=True)
    cv = locks.make_condition(name="cv.solo")
    with cv:
        cv.wait(timeout=0.01)  # releases its own lock: sanctioned
    assert w.violations == []


def test_condition_wait_holding_other_lock_fires():
    w = locks.reset_for_tests(on=True)
    other = locks.make_lock("cv.other")
    cv = locks.make_condition(name="cv.pair")
    with other:
        with cv:
            cv.wait(timeout=0.01)  # sleeps with cv.other still held
    assert len(w.violations) == 1
    assert "Condition.wait" in w.violations[0]
    assert "cv.other" in w.violations[0]


def test_condition_notify_wakes_waiter_through_traced_lock():
    """The traced condition must still BE a condition: a waiter parked
    through the wrapper wakes on notify and reacquires the traced lock
    (held stacks stay balanced across the wait)."""
    locks.reset_for_tests(on=True)
    cv = locks.make_condition(name="cv.live")
    ready = threading.Event()
    state = {"woke": False}

    def waiter():
        with cv:
            ready.set()
            got = cv.wait(timeout=5)
            assert got
            assert cv.held_by_me()  # reacquired after the wait
            state["woke"] = True

    t = threading.Thread(target=waiter)
    t.start()
    assert ready.wait(timeout=5)
    # lock is only released once the waiter is parked inside wait()
    with cv:
        cv.notify_all()
    t.join(timeout=5)
    assert not t.is_alive()
    assert state["woke"]
    assert locks.witness().violations == []


# ---------------------------------------------------------------------------
# @guarded_by lockset
# ---------------------------------------------------------------------------

def test_lockset_unlocked_write_after_publication_fires():
    w = locks.reset_for_tests(on=True)

    @locks.guarded_by("_lock", "val")
    class Box:
        def __init__(self):
            self._lock = locks.make_lock("Box._lock")
            self.val = 0  # construction phase: thread-confined

    box = Box()
    box.val = 1  # same thread, never published: still legal
    assert w.violations == []
    with box._lock:
        box.val = 2  # published under its declared lock
    box.val = 3  # unguarded write after publication
    assert len(w.violations) == 1
    assert "Box.val" in w.violations[0]
    assert "published" in w.violations[0]


def test_lockset_second_thread_unlocked_write_fires():
    w = locks.reset_for_tests(on=True)

    @locks.guarded_by("_lock", "val")
    class Box2:
        def __init__(self):
            self._lock = locks.make_lock("Box2._lock")
            self.val = 0

    box = Box2()
    _run_in_thread(lambda: setattr(box, "val", 5))
    assert len(w.violations) == 1
    assert "Box2.val" in w.violations[0]
    assert "second thread" in w.violations[0]


def test_lockset_guarded_writes_from_any_thread_are_clean():
    w = locks.reset_for_tests(on=True)

    @locks.guarded_by("_lock", "val")
    class Box3:
        def __init__(self):
            self._lock = locks.make_lock("Box3._lock")
            self.val = 0

    box = Box3()

    def mutate():
        with box._lock:
            box.val += 1

    _run_in_thread(mutate)
    mutate()
    assert box.val == 2
    assert w.violations == []


# ---------------------------------------------------------------------------
# off path: raw primitives, zero per-acquisition overhead
# ---------------------------------------------------------------------------

def test_factories_return_raw_primitives_when_off():
    locks.reset_for_tests(on=False)
    assert type(locks.make_lock("x")) is type(threading.Lock())
    assert isinstance(locks.make_rlock("x"), type(threading.RLock()))
    assert isinstance(locks.make_condition(name="x"), threading.Condition)

    @locks.guarded_by("_lock", "val")
    class Cold:
        pass

    # metadata recorded for the static lockmodel pass, but no
    # __setattr__ hook installed
    assert Cold.__guarded_by__ == {"val": "_lock"}
    assert "__lockset_hooked__" not in Cold.__dict__


def test_raw_lock_into_condition_factory_stays_functional():
    # a raw lock built before enable() slipping into make_condition
    # afterwards must degrade to an untraced threading.Condition, not
    # crash the interop
    raw = threading.Lock()
    locks.reset_for_tests(on=True)
    cv = locks.make_condition(raw, name="late")
    assert isinstance(cv, threading.Condition)
    with cv:
        cv.wait(timeout=0.001)


def test_off_path_overhead_under_five_percent():
    locks.reset_for_tests(on=False)
    lk = locks.make_lock("perf.L")
    raw = threading.Lock()
    # the structural guarantee behind the number: off path, the factory
    # hands back the raw class itself — not a delegating wrapper
    assert type(lk) is type(raw)

    n, reps = 50_000, 9
    t_factory = min(timeit.repeat("lk.acquire(); lk.release()",
                                  globals={"lk": lk},
                                  number=n, repeat=reps))
    t_raw = min(timeit.repeat("lk.acquire(); lk.release()",
                              globals={"lk": raw},
                              number=n, repeat=reps))
    assert t_factory <= t_raw * 1.05, (
        f"off-path factory lock {t_factory:.4f}s vs raw {t_raw:.4f}s "
        f"(> 5% overhead)")
