"""Transport-layer tests: rendezvous, barriers, push/pull round-trips.

Because each tier is an independent Postoffice instance (no process-global
singletons, unlike the reference's ps::Postoffice), an entire scheduler +
server + worker topology can run inside one test process on ephemeral ports.
"""

import socket
import threading

import numpy as np
import pytest

from geomx_tpu.ps import base
from geomx_tpu.ps.kv_app import KVPairs, KVServer, KVWorker
from geomx_tpu.ps.message import Message, Meta, Node, Role
from geomx_tpu.ps.postoffice import Postoffice


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def make_tier(num_workers=2, num_servers=1, is_global=False):
    """Boot a full tier in-process; returns (scheduler, servers, workers)."""
    port = free_port()
    kw = dict(
        is_global=is_global,
        root_uri="127.0.0.1",
        root_port=port,
        num_workers=num_workers,
        num_servers=num_servers,
    )
    sched = Postoffice(my_role=Role.SCHEDULER, **kw)
    servers = [Postoffice(my_role=Role.SERVER, **kw) for _ in range(num_servers)]
    workers = [Postoffice(my_role=Role.WORKER, **kw) for _ in range(num_workers)]
    threads = []
    sched_t = threading.Thread(target=sched.start, daemon=True)
    sched_t.start()
    for po in servers + workers:
        t = threading.Thread(target=po.start, daemon=True)
        t.start()
        threads.append(t)
    sched_t.join(20)
    for t in threads:
        t.join(20)
    for po in [sched] + servers + workers:
        assert po.van.ready.is_set(), "rendezvous failed"
    return sched, servers, workers


def shutdown(*pos):
    for po in pos:
        po.finalize(do_barrier=False)


def test_message_roundtrip():
    m = Message(
        Meta(
            sender=9,
            recver=8,
            app_id=0,
            timestamp=42,
            request=True,
            push=True,
            priority=-3,
            is_global=True,
            nodes=[Node(role=Role.WORKER, id=9, hostname="127.0.0.1", port=1234)],
        )
    )
    m.add_array(np.arange(6, dtype=np.float32).reshape(2, 3))
    m.add_array(np.array([1, 2, 3], dtype=np.int64))
    buf = m.pack()
    m2 = Message.unpack(buf)
    assert m2.meta.sender == 9 and m2.meta.recver == 8
    assert m2.meta.timestamp == 42 and m2.meta.push and m2.meta.request
    assert m2.meta.priority == -3 and m2.meta.is_global
    assert m2.meta.nodes[0].port == 1234
    np.testing.assert_array_equal(m2.get_array(0), m.get_array(0))
    np.testing.assert_array_equal(m2.get_array(1), np.array([1, 2, 3]))


def test_rendezvous_assigns_ids():
    sched, servers, workers = make_tier(num_workers=2, num_servers=2)
    try:
        assert sched.my_id == base.SCHEDULER
        assert sorted(s.my_id for s in servers) == [8, 10]
        assert sorted(w.my_id for w in workers) == [9, 11]
        # every node has the full table
        for po in servers + workers:
            assert set(po.van.node_table) == {1, 8, 9, 10, 11}
    finally:
        shutdown(sched, *servers, *workers)


def test_barrier_releases_all_members():
    sched, servers, workers = make_tier(num_workers=2, num_servers=1)
    try:
        done = []

        def do_barrier(po):
            po.barrier(base.WORKER_SERVER_GROUP, timeout=20)
            done.append(po.my_id)

        ts = [
            threading.Thread(target=do_barrier, args=(po,), daemon=True)
            for po in servers + workers
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(20)
        assert sorted(done) == sorted([8, 9, 11])
    finally:
        shutdown(sched, *servers, *workers)


def test_push_pull_roundtrip():
    sched, servers, workers = make_tier(num_workers=2, num_servers=1)
    store = {}
    try:
        server = KVServer(servers[0])

        def handle(req, kvs, srv):
            if req.push:
                for k, v in zip(kvs.keys, kvs.vals):
                    store[k] = store.get(k, 0) + v
                srv.response(req)
            elif req.pull:
                out = KVPairs(
                    keys=kvs.keys, vals=[store[k] for k in kvs.keys]
                )
                srv.response(req, out)

        server.set_request_handle(handle)

        w0 = KVWorker(workers[0])
        w1 = KVWorker(workers[1])
        v = np.ones((4, 3), dtype=np.float32)
        ts0 = w0.push(KVPairs(keys=[7], vals=[v]), server_rank=0)
        ts1 = w1.push(KVPairs(keys=[7], vals=[2 * v]), server_rank=0)
        w0.wait(ts0, 10)
        w1.wait(ts1, 10)

        ts = w0.pull([7], server_rank=0)
        w0.wait(ts, 10)
        (resp,) = w0.take_response(ts)
        np.testing.assert_allclose(resp.vals[0], 3 * v)
    finally:
        shutdown(sched, *servers, *workers)


def test_simple_app_command():
    sched, servers, workers = make_tier(num_workers=1, num_servers=1)
    got = {}
    try:
        server = KVServer(servers[0])

        def handle(req, kvs, srv):
            if req.simple_app:
                got["head"] = req.head
                got["body"] = req.body
                srv.response(req)

        server.set_request_handle(handle)
        w = KVWorker(workers[0])
        ts = w.request(head=5, body="sync_mode", recver=base.server_rank_to_id(0))
        w.wait(ts, 10)
        assert got == {"head": 5, "body": "sync_mode"}
    finally:
        shutdown(sched, *servers, *workers)


def test_two_tiers_coexist():
    """A process can be a local-tier server and a global-tier worker at once."""
    sched_l, servers_l, workers_l = make_tier(num_workers=1, num_servers=1)
    sched_g, servers_g, workers_g = make_tier(
        num_workers=1, num_servers=1, is_global=True
    )
    try:
        # the "intra-DC server" owns both: its local KVServer and a global KVWorker
        local_server = KVServer(servers_l[0])
        global_store = {}
        gserver = KVServer(servers_g[0])

        def ghandle(req, kvs, srv):
            if req.push:
                for k, v in zip(kvs.keys, kvs.vals):
                    global_store[k] = v
                srv.response(req)

        gserver.set_request_handle(ghandle)
        gworker = KVWorker(workers_g[0])

        def lhandle(req, kvs, srv):
            if req.push:
                # forward aggregated grad up to the global tier
                ts = gworker.push(kvs, server_rank=0)
                gworker.wait(ts, 10)
                srv.response(req)

        local_server.set_request_handle(lhandle)

        w = KVWorker(workers_l[0])
        v = np.full((2, 2), 5.0, dtype=np.float32)
        ts = w.push(KVPairs(keys=[3], vals=[v]), server_rank=0)
        w.wait(ts, 10)
        np.testing.assert_allclose(global_store[3], v)
    finally:
        shutdown(sched_l, *servers_l, *workers_l, sched_g, *servers_g, *workers_g)


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))


def test_binary_meta_roundtrip_all_field_kinds():
    """FLAG_BINMETA TLV codec: every field kind survives pack/unpack
    bit-exactly, and node-table messages stay JSON (round-4 verdict
    item 5: JSON meta was the hot path's largest per-message CPU)."""
    from geomx_tpu.ps.message import (FLAG_BINMETA, _PREHDR, Message,
                                      Meta, Node)

    m = Meta(sender=5, recver=9, app_id=0, customer_id=1, timestamp=42,
             request=True, push=True, pull=True, head=3, body="cmd",
             dtypes=["<f4", "<i8"], shapes=[[2, 3], [7]], priority=-2,
             version=11, key=123, iters=6, compr="bsc", first_key=1,
             seq=2, seq_begin=0, seq_end=4, msg_type=1, val_bytes=99,
             total_bytes=400, channel=1, tos=32, val_dtype="<f2",
             dgt_scale=0.125, dgt_n=77, lossy=True, num_merge=3,
             party_nsrv=2, aux_mask=0b101, aux_len=3, is_global=True)
    msg = Message(meta=m)
    msg.add_array(np.arange(6, dtype=np.float32).reshape(2, 3))
    wire = msg.pack()
    flags = _PREHDR.unpack_from(wire, 0)[2]
    assert flags & FLAG_BINMETA, "data-plane meta must ride the binary codec"
    back = Message.unpack(wire)
    for f in ("sender", "recver", "timestamp", "request", "push", "pull",
              "head", "body", "priority", "version", "key", "iters",
              "compr", "seq_end", "val_dtype", "dgt_scale", "dgt_n",
              "lossy", "num_merge", "party_nsrv", "aux_mask", "aux_len",
              "is_global"):
        assert getattr(back.meta, f) == getattr(m, f), f
    # add_array appended a third entry to dtypes/shapes
    assert back.meta.dtypes == ["<f4", "<i8", "<f4"]
    assert back.meta.shapes == [[2, 3], [7], [2, 3]]
    np.testing.assert_array_equal(back.get_array(0),
                                  np.arange(6, dtype=np.float32).reshape(2, 3))

    # control message with a node table falls back to JSON
    ctrl = Message(meta=Meta(control_cmd=2, nodes=[Node(id=8, port=99,
                                                        hostname="h")]))
    wire2 = ctrl.pack()
    assert not _PREHDR.unpack_from(wire2, 0)[2] & FLAG_BINMETA
    back2 = Message.unpack(wire2)
    assert back2.meta.nodes[0].port == 99


def test_binary_meta_large_fields():
    """Regressions from review: aux_mask with >=64 keys (bigint), body
    >64 KiB (optimizer-state relays), and malformed binary meta raising
    ValueError (the reader loop's drop-connection contract)."""
    import pytest

    from geomx_tpu.ps.message import (FLAG_BINMETA, _PREHDR, Message,
                                      Meta, _decode_meta)

    mask = int("1" * 200, 2)                  # 200-key batched aux mask
    big_body = "ab" * 40000                   # 80 KB command payload
    m = Meta(sender=1, recver=2, timestamp=3, aux_mask=mask,
             aux_len=200, body=big_body, simple_app=True)
    back = Message.unpack(Message(meta=m).pack())
    assert back.meta.aux_mask == mask
    assert back.meta.aux_len == 200
    assert back.meta.body == big_body

    wire = bytearray(Message(meta=m).pack())
    flags = _PREHDR.unpack_from(wire, 0)[2]
    assert flags & FLAG_BINMETA
    with pytest.raises(ValueError):
        _decode_meta(b"\xff\x01\x02", FLAG_BINMETA)   # unknown field id
    with pytest.raises(ValueError):
        _decode_meta(b"\x00\x01", FLAG_BINMETA)       # truncated i64
