"""Parallelism tests on the 8-device virtual CPU mesh (conftest forces
JAX_PLATFORMS=cpu + xla_force_host_platform_device_count=8)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from geomx_tpu.models import create_cnn
from geomx_tpu.models.transformer import (
    Transformer,
    dense_attention,
    transformer_param_sharding,
)
from geomx_tpu.parallel.mesh import make_mesh
from geomx_tpu.parallel.ring_attention import make_ring_attention
from geomx_tpu.parallel.train_step import DataParallelTrainer


@pytest.fixture(scope="module")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, "tests need the 8-device virtual CPU mesh"
    return devs[:8]


def test_ring_attention_matches_dense(devices):
    mesh = make_mesh(devices, tp=2, sp=2)
    B, T, H, D = 4, 32, 4, 16
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
               for _ in range(3))
    for causal in (False, True):
        ra = make_ring_attention(mesh, causal=causal)
        out = ra(q, k, v)
        ref = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_ring_attention_gradients_match_dense(devices):
    """Ring attention gradients must EQUAL dense attention gradients — the
    streaming-softmax max bookkeeping must contribute no gradient (a
    stop_gradient imbalance here once produced ~70%-wrong q/k grads while
    the forward still matched to 2e-7)."""
    mesh = make_mesh(devices, tp=1, sp=4)
    B, T, H, D = 2, 16, 2, 8
    rng = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
               for _ in range(3))
    for causal in (False, True):
        ra = make_ring_attention(mesh, causal=causal)

        def loss(fn, q, k, v):
            out = fn(q, k, v)
            return jnp.sum(out * jnp.cos(out))  # non-trivial cotangent

        g_ring = jax.grad(lambda *a: loss(ra, *a), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(
            lambda *a: loss(
                lambda q, k, v: dense_attention(q, k, v, causal=causal), *a),
            argnums=(0, 1, 2))(q, k, v)
        for gr, gd in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                       atol=2e-5, rtol=2e-5)
            assert float(jnp.max(jnp.abs(gr))) > 0


def test_data_parallel_trainer_learns(devices):
    mesh = make_mesh(devices)  # dp=8
    model = create_cnn()
    trainer = DataParallelTrainer(
        model, optax.adam(3e-3), mesh,
        jnp.zeros((1, 28, 28, 1), jnp.float32))
    from geomx_tpu.io import load_data
    train_iter, _, _, _ = load_data(64, num_workers=1)
    losses = []
    for i, (X, y) in enumerate(train_iter):
        losses.append(trainer.step(X, y))
        if i >= 15:
            break
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_transformer_tp_sharded_step(devices):
    mesh = make_mesh(devices, tp=2, sp=2)
    attn = make_ring_attention(mesh, causal=True)
    model = Transformer(vocab=32, dim=32, depth=1, heads=4, max_len=16,
                        attn_fn=attn)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 32, (4, 16)),
                       jnp.int32)
    with mesh:
        params = model.init(jax.random.PRNGKey(0), toks)
        params = transformer_param_sharding(mesh)(params)
        from jax.sharding import NamedSharding, PartitionSpec as P
        toks = jax.device_put(toks, NamedSharding(mesh, P("dp", "sp")))
        logits = jax.jit(model.apply)(params, toks)
    assert logits.shape == (4, 16, 32)
    assert np.isfinite(np.asarray(logits)).all()
    # qkv kernels really are tp-sharded
    qkv = params["params"]["block0"]["qkv"]["kernel"]
    assert "tp" in str(qkv.sharding.spec)


def test_graft_entry_dryrun():
    import __graft_entry__ as g

    g.dryrun_multichip(8)


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))


def test_hierarchical_trainer_geo_dp(devices):
    """The flagship geo-DP composition: each 'data center' is a DP mesh
    (4 virtual devices), and the HiPS tiers carry ONE aggregated
    gradient per key across the WAN (reference replaces the per-worker
    push/pull loop, examples/cnn.py:121-124). Both workers must see
    identical post-round parameters."""
    import threading

    from geomx_tpu.models import MLP
    from geomx_tpu.optimizer import SGD
    from geomx_tpu.parallel.train_step import HierarchicalTrainer
    from tests.test_hips import Topology, _parallel

    topo = Topology(num_parties=2, workers_per_party=1).start(
        sync_global=True)
    try:
        topo.master.set_optimizer(SGD(learning_rate=0.1))
        meshes = [make_mesh(devices[:4]), make_mesh(devices[4:8])]
        results = {}
        lock = threading.Lock()

        def run(kv, mesh):
            model = MLP(features=(16, 4))
            dp = DataParallelTrainer(model, optax.sgd(0.1), mesh,
                                     jnp.zeros((1, 8), jnp.float32),
                                     num_classes=4)
            ht = HierarchicalTrainer(dp, kv)
            # master init path: rank-0 worker of each party pushes
            ht.init_on_kvstore()
            rng = np.random.RandomState(0)  # same data on both DCs
            X = rng.randn(8, 8).astype(np.float32)
            y = rng.randint(0, 4, 8)
            losses = [ht.step(X, y) for _ in range(3)]
            leaves = jax.tree_util.tree_leaves(ht.t.params)
            with lock:
                results[id(kv)] = ([np.asarray(l) for l in leaves], losses)

        def master(kv):
            model = MLP(features=(16, 4))
            dp = DataParallelTrainer(model, optax.sgd(0.1),
                                     make_mesh(devices[:1]),
                                     jnp.zeros((1, 8), jnp.float32),
                                     num_classes=4)
            HierarchicalTrainer(dp, kv).init_on_kvstore()

        _parallel([lambda kv=kv, m=m: run(kv, m)
                   for kv, m in zip(topo.workers, meshes)]
                  + [lambda: master(topo.master)])

        (l0, losses0), (l1, losses1) = results.values()
        for a, b in zip(l0, l1):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
        assert all(np.isfinite(losses0))
    finally:
        topo.stop()


def test_fsdp_trainer_shards_and_matches_replicated(devices):
    """FSDP (ZeRO-style) sharding: params/opt-state split ~1/dp per
    device, and the loss trajectory matches the replicated DP trainer
    on identical data (GSPMD collectives are exact, not approximate)."""
    from geomx_tpu.parallel.fsdp import FSDPTrainer

    mesh = make_mesh(devices)  # dp=8
    model = create_cnn()
    ex = jnp.zeros((1, 28, 28, 1), jnp.float32)
    fsdp = FSDPTrainer(model, optax.adam(3e-3), mesh, ex)
    repl = DataParallelTrainer(model, optax.adam(3e-3), mesh, ex)
    # memory evidence: the big leaves are split (mean shard fraction
    # well under 1; conv kernels whose axes don't divide stay whole)
    assert fsdp.param_shard_fraction() < 0.6
    from geomx_tpu.io import load_data
    train_iter, _, _, _ = load_data(64, num_workers=1)
    l_f, l_r = [], []
    for i, (X, y) in enumerate(train_iter):
        l_f.append(fsdp.step(X, y))
        l_r.append(repl.step(X, y))
        if i >= 10:
            break
    np.testing.assert_allclose(l_f, l_r, rtol=2e-4, atol=2e-4)
    assert l_f[-1] < l_f[0]


def test_fsdp_spec_rules(devices):
    from jax.sharding import PartitionSpec as P

    from geomx_tpu.parallel.fsdp import fsdp_spec

    mesh = make_mesh(devices)  # dp=8
    assert fsdp_spec((16, 3), mesh) == P("dp", None)
    assert fsdp_spec((3, 24), mesh) == P(None, "dp")
    assert fsdp_spec((5, 3), mesh) == P()     # nothing divides -> whole
    assert fsdp_spec((), mesh) == P()         # scalar
    # largest divisible axis wins
    assert fsdp_spec((8, 800), mesh) == P(None, "dp")
