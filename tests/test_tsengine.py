"""TSEngine tests: scheduler matchmaking unit tests + overlay integration.

Covers the reference behaviors of ProcessAskPush/PullCommand (reference:
3rdparty/ps-lite/src/van.cc:1197-1458), the worker merge relay
(WorkersMerge, src/kvstore/kvstore_dist.h:91-121) and AutoPull model
dissemination (include/ps/kv_app.h:549-659,1694) — re-implemented in
geomx_tpu/ps/tsengine.py.
"""

import json
import threading
import types

import numpy as np
import pytest

from geomx_tpu.config import Config
from geomx_tpu.kvstore.dist import KVStoreDist
from geomx_tpu.kvstore.server import KVStoreDistServer
from geomx_tpu.optimizer import SGD
from geomx_tpu.ps import base as psbase
from geomx_tpu.ps.message import Control, Message, Meta, Role
from geomx_tpu.ps.postoffice import Postoffice
from geomx_tpu.ps.tsengine import DONE_DEST, SERVER_DEST, TSScheduler

from test_hips import Topology, _parallel, free_port


class FakeVan:
    is_global = False

    def __init__(self, dead=()):
        self.sent = []
        self.dead = set(dead)

    def send(self, msg):
        self.sent.append(msg)

    def declared_dead_ids(self):
        return frozenset(self.dead)


def _ask(sched, cmd, sender, **body):
    msg = Message(Meta(control_cmd=cmd,
                       body=json.dumps(body)))
    msg.meta.sender = sender
    sched.handle(msg)


def _replies(van):
    out = []
    for m in van.sent:
        d = json.loads(m.meta.body)
        out.append((m.meta.recver, d))
    van.sent.clear()
    return out


def test_scheduler_push_pairing_reduces_to_server():
    """4 workers ask with nm=1: the scheduler pairs them into a reduction
    tree; the final holder (nm=4) is told to push to the server."""
    van = FakeVan()
    sched = TSScheduler(van, num_workers=4, greed_rate=1.0)
    w = [psbase.worker_rank_to_id(r) for r in range(4)]

    for wid in w:
        _ask(sched, Control.ASKPUSH, wid, key=0, off=0, ver=1, nm=1, tgt=4)
    rep = _replies(van)
    assert len(rep) == 2  # two pairs formed
    senders = {to for to, _ in rep}
    receivers = {d["dest"] for _, d in rep}
    assert senders.isdisjoint(receivers)
    assert all(d["kind"] == "push" for _, d in rep)

    # the two receivers merged -> re-ask with nm=2
    for r in receivers:
        _ask(sched, Control.ASKPUSH, r, key=0, off=0, ver=1, nm=2, tgt=4)
    rep = _replies(van)
    assert len(rep) == 1
    final_recv = rep[0][1]["dest"]

    # final holder has everything -> push to server
    _ask(sched, Control.ASKPUSH, final_recv, key=0, off=0, ver=1, nm=4, tgt=4)
    rep = _replies(van)
    assert rep == [(final_recv, {"kind": "push", "key": 0, "off": 0,
                                 "ver": 1, "dest": SERVER_DEST})]


def test_scheduler_pull_relay_serves_every_worker_once():
    van = FakeVan()
    sched = TSScheduler(van, num_workers=3, greed_rate=0.0)
    server = psbase.server_rank_to_id(0)
    served = set()

    # the server keeps asking; each reply hands out a fresh worker
    for _ in range(3):
        _ask(sched, Control.ASKPULL, server, key=5, off=0, ver=2)
        [(_, d)] = _replies(van)
        assert d["dest"] not in served and d["dest"] != DONE_DEST
        served.add(d["dest"])
    assert len(served) == 3

    _ask(sched, Control.ASKPULL, server, key=5, off=0, ver=2)
    [(_, d)] = _replies(van)
    assert d["dest"] == DONE_DEST


def test_scheduler_pull_excludes_holder():
    """A worker that already holds the model is never chosen to receive."""
    van = FakeVan()
    sched = TSScheduler(van, num_workers=2, greed_rate=1.0)
    holder = psbase.worker_rank_to_id(0)
    _ask(sched, Control.ASKPULL, holder, key=1, off=0, ver=1)
    [(_, d)] = _replies(van)
    assert d["dest"] == psbase.worker_rank_to_id(1)


def test_scheduler_pull_skips_declared_dead():
    """Dissemination never targets a declared-dead worker: the model hop
    would park in the resender against a corpse (GX-P3xx fix)."""
    dead = psbase.worker_rank_to_id(1)
    van = FakeVan(dead={dead})
    sched = TSScheduler(van, num_workers=2, greed_rate=0.0)
    server = psbase.server_rank_to_id(0)
    _ask(sched, Control.ASKPULL, server, key=2, off=0, ver=1)
    [(_, d)] = _replies(van)
    assert d["dest"] == psbase.worker_rank_to_id(0)
    # the only live worker is served: the round is done, not stalled
    _ask(sched, Control.ASKPULL, server, key=2, off=0, ver=1)
    [(_, d)] = _replies(van)
    assert d["dest"] == DONE_DEST


def _make_tsnode(tgt_merge, stale=False):
    from geomx_tpu.ps.tsengine import TSNode

    po = types.SimpleNamespace(
        attach_ts=lambda node: None, is_global=False,
        van=types.SimpleNamespace(is_stale=lambda s, e: stale))
    return TSNode(po, kvw=None, tgt_merge=tgt_merge)


def test_tsnode_tgt_accepts_callable_live_view():
    """tgt re-evaluates a callable target per ask — a static int frozen
    at construction can never be satisfied after a death (GX-P305)."""
    live = [3]
    node = _make_tsnode(lambda: live[0])
    assert node.tgt == 3
    live[0] = 2          # a contributor died; the live view shrank
    assert node.tgt == 2
    live[0] = 0
    assert node.tgt == 1  # floor: a round needs at least one party
    assert _make_tsnode(4).tgt == 4  # plain ints still work


def test_tsnode_drops_stale_relay_without_ack():
    """A zombie peer's DATA_TS_RELAY hop is fence-dropped: no merge into
    the slot countdown and no ack (same fence as _handle_data)."""
    from geomx_tpu.ps.tsengine import DATA_TS_RELAY

    node = _make_tsnode(2, stale=True)
    app = types.SimpleNamespace(responses=[])
    app.response = lambda req, kvs=None, body="": app.responses.append(req)
    req = types.SimpleNamespace(simple_app=False, push=True,
                                head=DATA_TS_RELAY, sender=9, epoch=1,
                                version=1, num_merge=1)
    assert node.handle_request(req, None, app) is True  # consumed
    assert app.responses == []                          # ... silently
    assert node._slots == {}                            # ... untouched


def test_scheduler_push_greedy_prefers_fat_links():
    """Under a heterogeneous throughput matrix the greedy matchmaking
    measurably prefers the fat link: with four askers pending and one
    pair's measured throughput far above the rest, that pair is formed
    (in the reported direction)."""
    van = FakeVan()
    sched = TSScheduler(van, num_workers=4, greed_rate=1.0)
    w = [psbase.worker_rank_to_id(r) for r in range(4)]
    # sender-side reports ride the asks: w0->w1 is the fat metro link,
    # everything else measured thin
    _ask(sched, Control.ASKPUSH, w[0], key=0, off=0, ver=1, nm=1, tgt=4,
         rep=[[w[1], 500.0], [w[2], 2.0], [w[3], 2.0]])
    _ask(sched, Control.ASKPUSH, w[1], key=0, off=0, ver=1, nm=1, tgt=4,
         rep=[[w[0], 3.0], [w[2], 2.0]])
    rep = _replies(van)
    # two askers pending -> one pair; greedy must pick the fat direction
    assert rep == [(w[0], {"kind": "push", "key": 0, "off": 0, "ver": 1,
                           "dest": w[1]})]


def test_scheduler_degraded_link_triggers_reroute():
    """A link whose measured throughput collapses (EWMA decays on every
    fresh report) stops being chosen: the scheduler re-routes the pair
    through the now-fastest link."""
    van = FakeVan()
    sched = TSScheduler(van, num_workers=4, greed_rate=1.0)
    w = [psbase.worker_rank_to_id(r) for r in range(4)]
    sched._update_tput(w[0], w[1], 1000.0)   # initially fat
    sched._update_tput(w[2], w[3], 100.0)    # steady mid link
    assert sched._pick_pair({w[0], w[1], w[2], w[3]}) == (w[0], w[1])
    # the fat link degrades: repeated slow measurements pull the EWMA
    # under the mid link
    for _ in range(8):
        sched._update_tput(w[0], w[1], 1.0)
    assert sched.A[(w[0], w[1])] < sched.A[(w[2], w[3])]
    assert sched._pick_pair({w[0], w[1], w[2], w[3]}) == (w[2], w[3])


def test_scheduler_greedy_prefers_measured_throughput():
    van = FakeVan()
    sched = TSScheduler(van, num_workers=3, greed_rate=1.0)
    server = psbase.server_rank_to_id(0)
    w = [psbase.worker_rank_to_id(r) for r in range(3)]
    # report: server->w2 is the fast link
    _ask(sched, Control.ASKPULL, server, key=9, off=0, ver=1,
         rep=[[w[2], 1000.0], [w[0], 1.0]])
    [(_, d)] = _replies(van)
    assert d["dest"] == w[2]


def _single_tier(enable_ts, num_workers=3):
    """1 scheduler + 1 server + N workers on localhost threads."""
    port = free_port()
    threads, errors = [], []
    extra = dict(enable_intra_ts=enable_ts)

    def run(fn):
        def wrapped():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
        t = threading.Thread(target=wrapped, daemon=True)
        t.start()
        threads.append(t)

    def sched():
        po = Postoffice(my_role=Role.SCHEDULER, is_global=False,
                        root_uri="127.0.0.1", root_port=port,
                        num_workers=num_workers, num_servers=1,
                        cfg=Config(**extra))
        po.start(60)
        po.barrier(psbase.ALL_GROUP, timeout=60)
        po.barrier(psbase.ALL_GROUP, timeout=300)
        po.van.stop()

    run(sched)
    scfg = Config(role="server", ps_root_uri="127.0.0.1", ps_root_port=port,
                  num_workers=num_workers, num_servers=1, **extra)
    srv = KVStoreDistServer(scfg)
    run(srv.run)
    boxes = [[] for _ in range(num_workers)]
    for i in range(num_workers):
        wcfg = Config(role="worker", ps_root_uri="127.0.0.1",
                      ps_root_port=port, num_workers=num_workers,
                      num_servers=1, **extra)
        run(lambda b=boxes[i], c=wcfg: b.append(KVStoreDist(cfg=c)))
    for _ in range(300):
        if errors:
            raise errors[0]
        if all(len(b) == 1 for b in boxes):
            break
        threading.Event().wait(0.1)
    assert all(len(b) == 1 for b in boxes), "workers failed to start"
    return [b[0] for b in boxes], threads, errors


def test_intra_ts_single_tier_end_to_end():
    """3 workers under ENABLE_INTRA_TS: gradients merge worker-to-worker,
    one merged push hits the server, the model relays back; results match
    the direct-push semantics exactly."""
    kvs, threads, errors = _single_tier(enable_ts=True)
    try:
        rank0 = next(kv for kv in kvs if kv.rank == 0)
        rank0.set_optimizer(SGD(learning_rate=0.5))
        w0 = np.arange(12, dtype=np.float32)
        _parallel([lambda kv=kv: kv.init(7, w0) for kv in kvs])

        def step(kv, expect):
            kv.push(7, np.ones(12, np.float32))
            out = kv.pull(7)
            kv.wait()
            np.testing.assert_allclose(out, expect, rtol=1e-6)

        # each round: w -= 0.5 * sum(3 x ones) = w - 1.5
        _parallel([lambda kv=kv: step(kv, w0 - 1.5) for kv in kvs])
        _parallel([lambda kv=kv: step(kv, w0 - 3.0) for kv in kvs])
        _parallel([lambda kv=kv: step(kv, w0 - 4.5) for kv in kvs])
    finally:
        _parallel([kv.close for kv in kvs])
        for t in threads:
            t.join(30)
        if errors:
            raise errors[0]


def test_intra_ts_hips_two_tier():
    """Full HiPS topology with intra-DC TSEngine: parity with the vanilla
    FSA result (test_hips_fsa_vanilla)."""
    topo = Topology(extra_cfg=dict(enable_intra_ts=True)).start(
        sync_global=True)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.arange(24, dtype=np.float32)
        _parallel([lambda kv=kv: kv.init(0, w0)
                   for kv in topo.workers + [topo.master]])

        def step(kv, expect):
            kv.push(0, np.ones(24, np.float32))
            out = kv.pull(0)
            kv.wait()
            np.testing.assert_allclose(out, expect)

        _parallel([lambda kv=kv: step(kv, w0 - 4.0) for kv in topo.workers])
        _parallel([lambda kv=kv: step(kv, w0 - 8.0) for kv in topo.workers])
    finally:
        topo.stop()


def test_inter_ts_hips_two_tier():
    """HiPS with inter-DC TSEngine: party aggregates merge party-to-party
    before one merged push reaches the global server; the fresh model
    relays back through the party servers."""
    topo = Topology(extra_cfg=dict(enable_inter_ts=True)).start(
        sync_global=True)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.arange(16, dtype=np.float32)
        _parallel([lambda kv=kv: kv.init(0, w0)
                   for kv in topo.workers + [topo.master]])

        def step(kv, expect):
            kv.push(0, np.ones(16, np.float32))
            out = kv.pull(0)
            kv.wait()
            np.testing.assert_allclose(out, expect)

        _parallel([lambda kv=kv: step(kv, w0 - 4.0) for kv in topo.workers])
        _parallel([lambda kv=kv: step(kv, w0 - 8.0) for kv in topo.workers])
    finally:
        topo.stop()


def test_intra_and_inter_ts_combined():
    topo = Topology(extra_cfg=dict(enable_intra_ts=True,
                                   enable_inter_ts=True)).start(
        sync_global=True)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.zeros(10, np.float32)
        _parallel([lambda kv=kv: kv.init(0, w0)
                   for kv in topo.workers + [topo.master]])

        def step(kv, expect):
            kv.push(0, np.ones(10, np.float32))
            out = kv.pull(0)
            kv.wait()
            np.testing.assert_allclose(out, np.full(10, expect))

        _parallel([lambda kv=kv: step(kv, -4.0) for kv in topo.workers])
        _parallel([lambda kv=kv: step(kv, -8.0) for kv in topo.workers])
    finally:
        topo.stop()


def _shaped_direct_vs_overlay(parties, size, rounds, shape_plan):
    """Run identical integer-gradient training on a SHAPED in-process
    HiPS cluster twice — direct global wire, then the inter-DC TSEngine
    overlay — and return (direct, overlay) final models. Gradients are
    integer-valued, so float32 summation is exact in ANY merge order:
    the two wires must agree bit for bit, not just within tolerance."""
    from geomx_tpu.optimizer import SGD
    from geomx_tpu.simulate import InProcessHiPS

    w0 = np.arange(size, dtype=np.float32)
    finals = {}
    for inter_ts in (False, True):
        topo = InProcessHiPS(
            num_parties=parties, workers_per_party=1,
            extra_cfg=dict(shape_plan=shape_plan,
                           enable_inter_ts=inter_ts)).start()
        outs = []
        try:
            def master_init(kv):
                kv.set_optimizer(SGD(learning_rate=1.0))
                kv.init(0, w0.copy())
                kv.wait()

            def worker(kv):
                out = w0.copy()
                kv.init(0, w0.copy())
                for r in range(rounds):
                    kv.push(0, np.full(size, float(r + 1), np.float32))
                    kv.pull(0, out=out)
                    kv.wait()
                outs.append(out.copy())

            topo.run_workers(worker, include_master=master_init,
                             timeout=600)
        finally:
            topo.stop()
        for o in outs[1:]:
            np.testing.assert_array_equal(outs[0], o)
        finals[inter_ts] = outs[0]
    return finals[False], finals[True]


# every global link shaped, with the global server's access pipe as a
# SHARED bucket — the small-delay twin of scripts/shapes/hetero16.json
_SHAPED_4P = json.dumps({
    "seed": 3,
    "links": [
        {"dst": 8, "tier": "global", "shared": True,
         "rtt_ms": 4.0, "bw_mbps": 400.0},
        {"src": 8, "tier": "global", "shared": True,
         "rtt_ms": 4.0, "bw_mbps": 400.0},
    ],
    "default": {"tier": "global", "rtt_ms": 4.0, "bw_mbps": 400.0},
})


def test_shaped_overlay_round_bit_exact_vs_direct():
    """A shaped global round through the TSEngine overlay produces the
    SAME bits as the direct wire (4 parties, shared server access pipe
    + per-pair shaped links)."""
    parties, rounds = 4, 2
    direct, overlay = _shaped_direct_vs_overlay(
        parties, size=64, rounds=rounds, shape_plan=_SHAPED_4P)
    np.testing.assert_array_equal(direct, overlay)
    # and both equal the analytic result: w -= sum_p grad_r each round
    expect = np.arange(64, dtype=np.float32) - sum(
        parties * (r + 1) for r in range(rounds))
    np.testing.assert_array_equal(direct, expect)


@pytest.mark.slow
def test_shaped_hetero16_round_bit_exact_vs_direct():
    """The full 16-party heterogeneous plan (fat metro / mid / thin
    transoceanic links, shared server pipe): overlay == direct wire,
    bit for bit. Slow: two 16-party clusters with 150 ms thin links."""
    import os

    plan = "@" + os.path.join(os.path.dirname(__file__), "..",
                              "scripts", "shapes", "hetero16.json")
    direct, overlay = _shaped_direct_vs_overlay(
        16, size=64, rounds=2, shape_plan=plan)
    np.testing.assert_array_equal(direct, overlay)
    expect = np.arange(64, dtype=np.float32) - sum(
        16 * (r + 1) for r in range(2))
    np.testing.assert_array_equal(direct, expect)


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
