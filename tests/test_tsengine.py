"""TSEngine tests: scheduler matchmaking unit tests + overlay integration.

Covers the reference behaviors of ProcessAskPush/PullCommand (reference:
3rdparty/ps-lite/src/van.cc:1197-1458), the worker merge relay
(WorkersMerge, src/kvstore/kvstore_dist.h:91-121) and AutoPull model
dissemination (include/ps/kv_app.h:549-659,1694) — re-implemented in
geomx_tpu/ps/tsengine.py.
"""

import json
import threading
import types

import numpy as np
import pytest

from geomx_tpu.config import Config
from geomx_tpu.kvstore.dist import KVStoreDist
from geomx_tpu.kvstore.server import KVStoreDistServer
from geomx_tpu.optimizer import SGD
from geomx_tpu.ps import base as psbase
from geomx_tpu.ps.message import Control, Message, Meta, Role
from geomx_tpu.ps.postoffice import Postoffice
from geomx_tpu.ps.tsengine import DONE_DEST, SERVER_DEST, TSScheduler

from test_hips import Topology, _parallel, free_port


class FakeVan:
    is_global = False

    def __init__(self, dead=()):
        self.sent = []
        self.dead = set(dead)

    def send(self, msg):
        self.sent.append(msg)

    def declared_dead_ids(self):
        return frozenset(self.dead)


def _ask(sched, cmd, sender, **body):
    msg = Message(Meta(control_cmd=cmd,
                       body=json.dumps(body)))
    msg.meta.sender = sender
    sched.handle(msg)


def _replies(van):
    out = []
    for m in van.sent:
        d = json.loads(m.meta.body)
        out.append((m.meta.recver, d))
    van.sent.clear()
    return out


def test_scheduler_push_pairing_reduces_to_server():
    """4 workers ask with nm=1: the scheduler pairs them into a reduction
    tree; the final holder (nm=4) is told to push to the server."""
    van = FakeVan()
    sched = TSScheduler(van, num_workers=4, greed_rate=1.0)
    w = [psbase.worker_rank_to_id(r) for r in range(4)]

    for wid in w:
        _ask(sched, Control.ASKPUSH, wid, key=0, off=0, ver=1, nm=1, tgt=4)
    rep = _replies(van)
    assert len(rep) == 2  # two pairs formed
    senders = {to for to, _ in rep}
    receivers = {d["dest"] for _, d in rep}
    assert senders.isdisjoint(receivers)
    assert all(d["kind"] == "push" for _, d in rep)

    # the two receivers merged -> re-ask with nm=2
    for r in receivers:
        _ask(sched, Control.ASKPUSH, r, key=0, off=0, ver=1, nm=2, tgt=4)
    rep = _replies(van)
    assert len(rep) == 1
    final_recv = rep[0][1]["dest"]

    # final holder has everything -> push to server
    _ask(sched, Control.ASKPUSH, final_recv, key=0, off=0, ver=1, nm=4, tgt=4)
    rep = _replies(van)
    assert rep == [(final_recv, {"kind": "push", "key": 0, "off": 0,
                                 "ver": 1, "dest": SERVER_DEST})]


def test_scheduler_pull_relay_serves_every_worker_once():
    van = FakeVan()
    sched = TSScheduler(van, num_workers=3, greed_rate=0.0)
    server = psbase.server_rank_to_id(0)
    served = set()

    # the server keeps asking; each reply hands out a fresh worker
    for _ in range(3):
        _ask(sched, Control.ASKPULL, server, key=5, off=0, ver=2)
        [(_, d)] = _replies(van)
        assert d["dest"] not in served and d["dest"] != DONE_DEST
        served.add(d["dest"])
    assert len(served) == 3

    _ask(sched, Control.ASKPULL, server, key=5, off=0, ver=2)
    [(_, d)] = _replies(van)
    assert d["dest"] == DONE_DEST


def test_scheduler_pull_excludes_holder():
    """A worker that already holds the model is never chosen to receive."""
    van = FakeVan()
    sched = TSScheduler(van, num_workers=2, greed_rate=1.0)
    holder = psbase.worker_rank_to_id(0)
    _ask(sched, Control.ASKPULL, holder, key=1, off=0, ver=1)
    [(_, d)] = _replies(van)
    assert d["dest"] == psbase.worker_rank_to_id(1)


def test_scheduler_pull_skips_declared_dead():
    """Dissemination never targets a declared-dead worker: the model hop
    would park in the resender against a corpse (GX-P3xx fix)."""
    dead = psbase.worker_rank_to_id(1)
    van = FakeVan(dead={dead})
    sched = TSScheduler(van, num_workers=2, greed_rate=0.0)
    server = psbase.server_rank_to_id(0)
    _ask(sched, Control.ASKPULL, server, key=2, off=0, ver=1)
    [(_, d)] = _replies(van)
    assert d["dest"] == psbase.worker_rank_to_id(0)
    # the only live worker is served: the round is done, not stalled
    _ask(sched, Control.ASKPULL, server, key=2, off=0, ver=1)
    [(_, d)] = _replies(van)
    assert d["dest"] == DONE_DEST


def _make_tsnode(tgt_merge, stale=False):
    from geomx_tpu.ps.tsengine import TSNode

    po = types.SimpleNamespace(
        attach_ts=lambda node: None, is_global=False,
        van=types.SimpleNamespace(is_stale=lambda s, e: stale))
    return TSNode(po, kvw=None, tgt_merge=tgt_merge)


def test_tsnode_tgt_accepts_callable_live_view():
    """tgt re-evaluates a callable target per ask — a static int frozen
    at construction can never be satisfied after a death (GX-P305)."""
    live = [3]
    node = _make_tsnode(lambda: live[0])
    assert node.tgt == 3
    live[0] = 2          # a contributor died; the live view shrank
    assert node.tgt == 2
    live[0] = 0
    assert node.tgt == 1  # floor: a round needs at least one party
    assert _make_tsnode(4).tgt == 4  # plain ints still work


def test_tsnode_drops_stale_relay_without_ack():
    """A zombie peer's DATA_TS_RELAY hop is fence-dropped: no merge into
    the slot countdown and no ack (same fence as _handle_data)."""
    from geomx_tpu.ps.tsengine import DATA_TS_RELAY

    node = _make_tsnode(2, stale=True)
    app = types.SimpleNamespace(responses=[])
    app.response = lambda req, kvs=None, body="": app.responses.append(req)
    req = types.SimpleNamespace(simple_app=False, push=True,
                                head=DATA_TS_RELAY, sender=9, epoch=1,
                                version=1, num_merge=1)
    assert node.handle_request(req, None, app) is True  # consumed
    assert app.responses == []                          # ... silently
    assert node._slots == {}                            # ... untouched


def test_scheduler_greedy_prefers_measured_throughput():
    van = FakeVan()
    sched = TSScheduler(van, num_workers=3, greed_rate=1.0)
    server = psbase.server_rank_to_id(0)
    w = [psbase.worker_rank_to_id(r) for r in range(3)]
    # report: server->w2 is the fast link
    _ask(sched, Control.ASKPULL, server, key=9, off=0, ver=1,
         rep=[[w[2], 1000.0], [w[0], 1.0]])
    [(_, d)] = _replies(van)
    assert d["dest"] == w[2]


def _single_tier(enable_ts, num_workers=3):
    """1 scheduler + 1 server + N workers on localhost threads."""
    port = free_port()
    threads, errors = [], []
    extra = dict(enable_intra_ts=enable_ts)

    def run(fn):
        def wrapped():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
        t = threading.Thread(target=wrapped, daemon=True)
        t.start()
        threads.append(t)

    def sched():
        po = Postoffice(my_role=Role.SCHEDULER, is_global=False,
                        root_uri="127.0.0.1", root_port=port,
                        num_workers=num_workers, num_servers=1,
                        cfg=Config(**extra))
        po.start(60)
        po.barrier(psbase.ALL_GROUP, timeout=60)
        po.barrier(psbase.ALL_GROUP, timeout=300)
        po.van.stop()

    run(sched)
    scfg = Config(role="server", ps_root_uri="127.0.0.1", ps_root_port=port,
                  num_workers=num_workers, num_servers=1, **extra)
    srv = KVStoreDistServer(scfg)
    run(srv.run)
    boxes = [[] for _ in range(num_workers)]
    for i in range(num_workers):
        wcfg = Config(role="worker", ps_root_uri="127.0.0.1",
                      ps_root_port=port, num_workers=num_workers,
                      num_servers=1, **extra)
        run(lambda b=boxes[i], c=wcfg: b.append(KVStoreDist(cfg=c)))
    for _ in range(300):
        if errors:
            raise errors[0]
        if all(len(b) == 1 for b in boxes):
            break
        threading.Event().wait(0.1)
    assert all(len(b) == 1 for b in boxes), "workers failed to start"
    return [b[0] for b in boxes], threads, errors


def test_intra_ts_single_tier_end_to_end():
    """3 workers under ENABLE_INTRA_TS: gradients merge worker-to-worker,
    one merged push hits the server, the model relays back; results match
    the direct-push semantics exactly."""
    kvs, threads, errors = _single_tier(enable_ts=True)
    try:
        rank0 = next(kv for kv in kvs if kv.rank == 0)
        rank0.set_optimizer(SGD(learning_rate=0.5))
        w0 = np.arange(12, dtype=np.float32)
        _parallel([lambda kv=kv: kv.init(7, w0) for kv in kvs])

        def step(kv, expect):
            kv.push(7, np.ones(12, np.float32))
            out = kv.pull(7)
            kv.wait()
            np.testing.assert_allclose(out, expect, rtol=1e-6)

        # each round: w -= 0.5 * sum(3 x ones) = w - 1.5
        _parallel([lambda kv=kv: step(kv, w0 - 1.5) for kv in kvs])
        _parallel([lambda kv=kv: step(kv, w0 - 3.0) for kv in kvs])
        _parallel([lambda kv=kv: step(kv, w0 - 4.5) for kv in kvs])
    finally:
        _parallel([kv.close for kv in kvs])
        for t in threads:
            t.join(30)
        if errors:
            raise errors[0]


def test_intra_ts_hips_two_tier():
    """Full HiPS topology with intra-DC TSEngine: parity with the vanilla
    FSA result (test_hips_fsa_vanilla)."""
    topo = Topology(extra_cfg=dict(enable_intra_ts=True)).start(
        sync_global=True)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.arange(24, dtype=np.float32)
        _parallel([lambda kv=kv: kv.init(0, w0)
                   for kv in topo.workers + [topo.master]])

        def step(kv, expect):
            kv.push(0, np.ones(24, np.float32))
            out = kv.pull(0)
            kv.wait()
            np.testing.assert_allclose(out, expect)

        _parallel([lambda kv=kv: step(kv, w0 - 4.0) for kv in topo.workers])
        _parallel([lambda kv=kv: step(kv, w0 - 8.0) for kv in topo.workers])
    finally:
        topo.stop()


def test_inter_ts_hips_two_tier():
    """HiPS with inter-DC TSEngine: party aggregates merge party-to-party
    before one merged push reaches the global server; the fresh model
    relays back through the party servers."""
    topo = Topology(extra_cfg=dict(enable_inter_ts=True)).start(
        sync_global=True)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.arange(16, dtype=np.float32)
        _parallel([lambda kv=kv: kv.init(0, w0)
                   for kv in topo.workers + [topo.master]])

        def step(kv, expect):
            kv.push(0, np.ones(16, np.float32))
            out = kv.pull(0)
            kv.wait()
            np.testing.assert_allclose(out, expect)

        _parallel([lambda kv=kv: step(kv, w0 - 4.0) for kv in topo.workers])
        _parallel([lambda kv=kv: step(kv, w0 - 8.0) for kv in topo.workers])
    finally:
        topo.stop()


def test_intra_and_inter_ts_combined():
    topo = Topology(extra_cfg=dict(enable_intra_ts=True,
                                   enable_inter_ts=True)).start(
        sync_global=True)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.zeros(10, np.float32)
        _parallel([lambda kv=kv: kv.init(0, w0)
                   for kv in topo.workers + [topo.master]])

        def step(kv, expect):
            kv.push(0, np.ones(10, np.float32))
            out = kv.pull(0)
            kv.wait()
            np.testing.assert_allclose(out, np.full(10, expect))

        _parallel([lambda kv=kv: step(kv, -4.0) for kv in topo.workers])
        _parallel([lambda kv=kv: step(kv, -8.0) for kv in topo.workers])
    finally:
        topo.stop()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
