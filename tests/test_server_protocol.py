"""Regression tests for the GX-P3xx protocol fixes in kvstore/server.py.

These pin the genuine findings the protocol pass (tools/analyze/
protocol.py) surfaced and this PR fixed:

- a stale (zombie/pre-rejoin) command must be fence-dropped before it
  ticks the STOP_SERVER countdown or enrolls in the global barrier
  (GX-P304 on `_handle_command` / `_handle_global_barrier`);
- the global-server stop countdown completes against the LIVE worker
  count, not the static topology (GX-P305);
- `_pull_global_store` acks a pull that overlaps no canonical range
  instead of silently dropping it, and merges a multi-range overlap
  into ONE wire response (the zero-iteration / double-ack holes GX-P302
  documents as its lexical blind spot).

All units: the server object is built via ``__new__`` with only the
state each path touches, so no sockets or jax are involved.
"""

import threading
import types

import numpy as np
import pytest

from geomx_tpu.kvstore.base import Command
from geomx_tpu.kvstore.server import KVStoreDistServer
from geomx_tpu.kvstore.sharding import Shard


class StubVan:
    def __init__(self, stale=False):
        self.stale = stale

    def is_stale(self, sender, epoch):
        return self.stale


class RecordingApp:
    def __init__(self):
        self.responses = []

    def response(self, req, kvs=None, body=""):
        self.responses.append((req, kvs, body))


def command_req(head, sender=9, epoch=1, body=""):
    return types.SimpleNamespace(head=head, sender=sender, epoch=epoch,
                                 body=body)


def make_server(*, stale=False, live_workers=2):
    s = KVStoreDistServer.__new__(KVStoreDistServer)
    s.po_local = types.SimpleNamespace(van=StubVan(stale))
    s.po_global = types.SimpleNamespace(
        van=StubVan(stale), num_live_workers=lambda: live_workers)
    s.is_global_server = True
    s._lock = threading.Lock()
    s._stops_received = 0
    s._stop = threading.Event()
    return s


def test_stale_stop_server_does_not_tick_countdown():
    s = make_server(stale=True)
    app = RecordingApp()
    s._handle_command(command_req(Command.STOP_SERVER), app, True)
    # fence-dropped: no ack, no countdown tick, no stop
    assert app.responses == []
    assert s._stops_received == 0
    assert not s._stop.is_set()


def test_stale_global_barrier_not_enrolled():
    s = make_server(stale=True)
    app = RecordingApp()
    s._handle_command(command_req(Command.GLOBAL_BARRIER), app, True)
    assert app.responses == []
    assert not hasattr(s, "_gb_reqs")


def test_stop_countdown_sized_from_live_view():
    """3 static global workers, 1 dead: the stop gate must close after
    the 2 LIVE stops (the static count would park forever)."""
    s = make_server(stale=False, live_workers=2)
    app = RecordingApp()
    s._handle_command(command_req(Command.STOP_SERVER, sender=9), app, True)
    assert not s._stop.is_set()
    s._handle_command(command_req(Command.STOP_SERVER, sender=11), app, True)
    assert s._stop.is_set()
    assert len(app.responses) == 2  # every live stop is acked


def make_pull_server():
    s = KVStoreDistServer.__new__(KVStoreDistServer)
    s._lock = threading.Lock()
    s._key_total = {}
    s._states = {}
    s.po_local = None
    s.po_global = types.SimpleNamespace(my_rank=0, num_servers=1)
    s.cfg = types.SimpleNamespace(bigarray_bound=1 << 20)
    return s


def test_pull_missed_range_acks():
    """A pull overlapping no canonical range still acks (empty) — the
    requester must not park until its op timeout."""
    s = make_pull_server()
    app = RecordingApp()
    req = types.SimpleNamespace(push=False, pull=True)
    acts = s._pull_global_store(req, app, 3, 100, 4, 8, "")
    assert len(acts) == 1
    acts[0]()
    assert len(app.responses) == 1
    got_req, kvs, _ = app.responses[0]
    assert got_req is req and kvs is None  # bare empty ack


def test_pull_multi_range_merges_to_one_response():
    """Two canonical ranges overlapped by one pull produce ONE merged
    wire response (a second response to the same timestamp is lost by
    the tracker and flagged by the wire sanitizer)."""
    s = make_pull_server()
    # force the defensive multi-range shape (assign() itself gives one
    # shard per rank): rank 0 owns both halves of key 3
    s._canonical_ranges = lambda key, total: [Shard(0, 0, 4, 8),
                                              Shard(0, 4, 4, 8)]
    for off in (0, 4):
        st = s._state(3, off)
        st.initialized = True
        st.offset = off
        st.length = 4
        st.total = 8
        st.stored = np.arange(off, off + 4, dtype=np.float32)
    app = RecordingApp()
    req = types.SimpleNamespace(push=False, pull=True)
    acts = s._pull_global_store(req, app, 3, 0, 8, 8, "")
    assert len(acts) == 2
    for a in acts:
        a()
    assert len(app.responses) == 1  # ONE merged response, not two
    _, kvs, _ = app.responses[0]
    assert list(kvs.keys) == [3, 3]
    assert [kvs.offset_of(i) for i in range(2)] == [0, 4]
    np.testing.assert_allclose(np.concatenate(kvs.vals),
                               np.arange(8, dtype=np.float32))


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
