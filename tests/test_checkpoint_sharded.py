"""Sharded mesh checkpointing (orbax wrapper).

Round-trips dp/tp-sharded training state on the 8-device CPU mesh,
including restore onto a DIFFERENT mesh shape (the re-layout case a
real pod resize hits).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from geomx_tpu.checkpoint_sharded import (
    latest_step, restore_sharded, save_sharded)
from geomx_tpu.parallel.mesh import make_mesh


def _sharded_tree(mesh, seed=0):
    rng = np.random.RandomState(seed)
    w = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(16,)), jnp.float32)
    return {
        "w": jax.device_put(w, NamedSharding(mesh, P("tp", None))),
        "b": jax.device_put(b, NamedSharding(mesh, P())),
        "step_count": jnp.asarray(7, jnp.int32),
    }


def test_save_restore_same_mesh(tmp_path):
    mesh = make_mesh(jax.devices(), tp=2)
    tree = _sharded_tree(mesh)
    save_sharded(str(tmp_path / "ck"), 3, tree)
    assert latest_step(str(tmp_path / "ck")) == 3
    template = jax.tree_util.tree_map(jnp.zeros_like, tree)
    template = {
        "w": jax.device_put(template["w"],
                            NamedSharding(mesh, P("tp", None))),
        "b": jax.device_put(template["b"], NamedSharding(mesh, P())),
        "step_count": template["step_count"],
    }
    out = restore_sharded(str(tmp_path / "ck"), None, template)
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.asarray(tree["w"]))
    np.testing.assert_array_equal(np.asarray(out["b"]),
                                  np.asarray(tree["b"]))
    assert int(out["step_count"]) == 7
    assert "tp" in str(out["w"].sharding.spec)


def test_restore_onto_different_mesh_shape(tmp_path):
    mesh_a = make_mesh(jax.devices(), tp=2)       # dp=4 x tp=2
    tree = _sharded_tree(mesh_a, seed=1)
    save_sharded(str(tmp_path / "ck"), 0, tree)
    mesh_b = make_mesh(jax.devices(), tp=4)       # dp=2 x tp=4
    template = {
        "w": jax.device_put(jnp.zeros((16, 8), jnp.float32),
                            NamedSharding(mesh_b, P("tp", None))),
        "b": jax.device_put(jnp.zeros((16,), jnp.float32),
                            NamedSharding(mesh_b, P())),
        "step_count": jnp.asarray(0, jnp.int32),
    }
    out = restore_sharded(str(tmp_path / "ck"), 0, template)
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.asarray(tree["w"]))
    # restored array actually lives on the NEW mesh layout
    assert out["w"].sharding.mesh.shape["tp"] == 4


def test_latest_step_empty_and_missing(tmp_path):
    assert latest_step(str(tmp_path / "nope")) is None


def test_corrupt_step_fails_loudly_valid_step_survives(tmp_path):
    """Restoring a torn/corrupt step dir raises (never silent garbage);
    the intact checkpoint next to it still restores."""
    import pytest

    mesh = make_mesh(jax.devices(), tp=2)
    tree = _sharded_tree(mesh)
    path = tmp_path / "ck"
    save_sharded(str(path), 1, tree)
    (path / "5").mkdir()
    (path / "5" / "junk").write_text("partial")
    template = jax.tree_util.tree_map(jnp.zeros_like, tree)
    with pytest.raises(Exception):
        restore_sharded(str(path), 5, template)
    out = restore_sharded(str(path), 1, template)
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.asarray(tree["w"]))
