"""Unit tests for the WAN compression kernels (BSC / FP16 / 2-bit / MPQ).

Mirrors the reference's compression semantics (gradient_compression.cc):
momentum-corrected top-k with residual reset for BSC, residual-feedback
2-bit quantization, size-threshold routing for MPQ.
"""

import numpy as np
import pytest

from geomx_tpu.compression import (
    BSCCompressor,
    FP16Compressor,
    MPQCompressor,
    TwoBitCompressor,
    bsc_compress,
    bsc_decompress,
    bsc_pull_compress,
    make_compressor,
    two_bit_dequantize,
    two_bit_quantize,
)


def test_bsc_full_threshold_is_lossless_for_uniform_magnitudes():
    n = 1000
    grad = np.full(n, 0.5, dtype=np.float32)
    u = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    values, indices = bsc_compress(grad, u, v, threshold=1.0)
    assert values.size == n
    out = bsc_decompress(values, indices, n)
    np.testing.assert_allclose(out, grad, rtol=1e-6)
    # residual reset: transmitted coordinates zeroed
    assert np.all(v[indices] == 0) and np.all(u[indices] == 0)


def test_bsc_sparsifies_and_accumulates_residual():
    rng = np.random.default_rng(0)
    n = 10000
    grad = rng.normal(size=n).astype(np.float32)
    u = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    values, indices = bsc_compress(grad.copy(), u, v, threshold=0.01)
    # at most threshold * n entries transmitted (reference zipped_size cap)
    assert values.size <= int(n * 0.01)
    # untransmitted residual survives in v for the next round
    untouched = np.setdiff1d(np.arange(n), indices)
    assert np.count_nonzero(v[untouched]) > 0
    # transmitted values are the momentum-corrected v, largest magnitudes
    assert np.min(np.abs(values)) > 0


def test_bsc_momentum_correction_matches_reference_recurrence():
    # u = 0.9u + g ; v = v + u (reference: gradient_compression.cc:219-222)
    n = 100
    u = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    g1 = np.ones(n, np.float32)
    bsc_compress(g1, u, v, threshold=1.0)  # round 1: v = 1 -> all sent, reset
    assert np.all(v == 0)
    g2 = np.ones(n, np.float32)
    values, _ = bsc_compress(g2, u, v, threshold=1.0)
    # after reset u==0: u = 0*0.9+1 = 1, v = 0+1 = 1
    np.testing.assert_allclose(values, np.ones(n), rtol=1e-6)


def test_bsc_pull_compress_keeps_nonzeros():
    arr = np.zeros(1000, np.float32)
    idx = np.array([3, 500, 999])
    arr[idx] = [1.5, -2.0, 0.25]
    values, indices = bsc_pull_compress(arr, threshold=0.01, multiplier=2)
    np.testing.assert_array_equal(np.sort(indices), idx)
    out = bsc_decompress(values, indices, 1000)
    np.testing.assert_allclose(out, arr)


def test_two_bit_roundtrip_with_residual():
    thr = 0.5
    grad = np.array([0.7, -0.6, 0.2, 0.0, 1.4], np.float32)
    residual = np.zeros(5, np.float32)
    packed = two_bit_quantize(grad.copy(), residual, thr)
    out = two_bit_dequantize(packed, 5, thr)
    np.testing.assert_allclose(out, [thr, -thr, 0, 0, thr])
    # residual carries the quantization error
    np.testing.assert_allclose(residual, [0.2, -0.1, 0.2, 0.0, 0.9], atol=1e-6)
    # second round drains the residual
    packed2 = two_bit_quantize(np.zeros(5, np.float32), residual, thr)
    out2 = two_bit_dequantize(packed2, 5, thr)
    np.testing.assert_allclose(out2, [0, 0, 0, 0, thr])


@pytest.mark.parametrize("n", [1, 2, 3, 5, 6, 7, 9])
def test_two_bit_roundtrip_length_not_divisible_by_4(n):
    """The pack pads to a whole byte (4 codes each); dequantize must
    honor original_size exactly — no truncation, no phantom tail codes."""
    thr = 0.25
    rng = np.random.default_rng(n)
    grad = rng.normal(scale=1.0, size=n).astype(np.float32)
    residual = np.zeros(n, np.float32)
    res_oracle = residual.copy()
    packed = two_bit_quantize(grad.copy(), residual, thr)
    assert packed.size == (n + 3) // 4 and packed.dtype == np.uint8
    out = two_bit_dequantize(packed, n, thr)
    assert out.size == n
    # element-wise oracle: code from the residual-fed value
    res_oracle += grad
    expect = np.where(res_oracle > thr, thr,
                      np.where(res_oracle < -thr, -thr, 0.0)
                      ).astype(np.float32)
    np.testing.assert_array_equal(out, expect)
    np.testing.assert_allclose(residual, res_oracle - expect, atol=1e-7)
    # pad codes beyond n must decode to nothing: a second dequantize at
    # the padded length shows zeros past the original size
    padded = two_bit_dequantize(packed, packed.size * 4, thr)
    np.testing.assert_array_equal(padded[n:], 0.0)


def test_mpq_size_lower_bound_boundary():
    """Routing at the MXNET_KVSTORE_SIZE_LOWER_BOUND boundary: exactly
    at the bound takes the large-tensor (BSC) route — the same
    inclusive convention the wire codec's chunk router uses."""
    bound = 100
    c = MPQCompressor(threshold=1.0, size_lower_bound=bound)
    for n, want in ((bound - 1, "fp16"), (bound, "bsc"),
                    (bound + 1, "bsc")):
        _, _, tag = c.compress_push(np.ones(n, np.float32), ("k", n))
        assert tag == want, (n, tag)
        assert c.push_tag(n) == want
    # pull side mirrors the route
    assert c.pull_compr_tag(bound - 1) == "fp16"
    assert c.pull_compr_tag(bound) == "bsc"


def test_fp16_wire_cast():
    c = FP16Compressor()
    arr = np.linspace(-3, 3, 77, dtype=np.float32)
    wire, aux, tag = c.compress_push(arr)
    assert wire.dtype == np.float16 and tag == "fp16"
    out = c.decompress_push(tag, wire, aux, arr.size)
    np.testing.assert_allclose(out, arr, atol=2e-3)


def test_mpq_routes_by_size():
    c = MPQCompressor(threshold=0.5, size_lower_bound=100)
    small = np.ones(10, np.float32)
    large = np.ones(1000, np.float32)
    _, _, tag_small = c.compress_push(small, ("k", 0))
    _, _, tag_large = c.compress_push(large, ("k2", 0))
    assert tag_small == "fp16"
    assert tag_large == "bsc"


def test_compressor_server_roundtrip_via_tags():
    """The exact pipeline the HiPS server runs on the WAN hop."""
    gc = BSCCompressor(threshold=1.0)
    grad = np.full(500, 0.25, np.float32)
    wire, aux, tag = gc.compress_push(grad, state_key=(0, 0))
    dense = gc.decompress_push(tag, wire, aux, 500)
    np.testing.assert_allclose(dense, grad)
    # pull side: aggregated (sparse) array, factor = num global workers
    payload, p_aux = gc.compress_pull("bsc", dense * 2, factor=2)
    back = gc.decompress_pull("bsc", payload, p_aux, 500, 2)
    np.testing.assert_allclose(back, grad * 2)


def test_make_compressor_factory():
    assert make_compressor(None).type_name == "none"
    assert make_compressor({"type": "bsc", "threshold": 0.02}).threshold == 0.02
    assert make_compressor({"type": "fp16"}).type_name == "fp16"
    assert make_compressor({"type": "mpq"}).type_name == "mpq"
    with pytest.raises(ValueError):
        make_compressor({"type": "wavelet"})


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
