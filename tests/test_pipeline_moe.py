"""Pipeline parallelism ("pp") and expert-parallel MoE ("ep").

Both run on the virtual 8-device CPU mesh (conftest) and are checked
for EXACTNESS against single-device references — pipeline output must
equal sequentially applying the stages; the sharded MoE must equal its
unsharded evaluation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from geomx_tpu.models.moe import MoEBlock, moe_param_sharding
from geomx_tpu.parallel.mesh import make_mesh
from geomx_tpu.parallel.pipeline import make_pipeline_fn


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _stacked_params(S, D, seed=0):
    rng = np.random.RandomState(seed)
    w = jnp.asarray(rng.normal(0, 0.5, (S, D, D)), jnp.float32)
    b = jnp.asarray(rng.normal(0, 0.1, (S, D)), jnp.float32)
    return (w, b)


def _seq_reference(params, x_mb):
    w, b = params
    out = []
    for m in range(x_mb.shape[0]):
        x = x_mb[m]
        for s in range(w.shape[0]):
            x = _stage_fn((w[s], b[s]), x)
        out.append(x)
    return jnp.stack(out)


@pytest.mark.parametrize("pp,M", [(2, 4), (4, 6)])
def test_pipeline_matches_sequential(pp, M):
    mesh = make_mesh(jax.devices(), pp=pp)
    D, mb = 8, 4
    params = _stacked_params(pp, D)
    x_mb = jnp.asarray(np.random.RandomState(1).normal(
        size=(M, mb, D)), jnp.float32)
    fn = make_pipeline_fn(mesh, _stage_fn)
    out = jax.jit(fn)(params, x_mb)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_seq_reference(params, x_mb)),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match_sequential():
    mesh = make_mesh(jax.devices(), pp=2)
    D, M, mb = 8, 3, 2
    params = _stacked_params(2, D)
    x_mb = jnp.asarray(np.random.RandomState(2).normal(
        size=(M, mb, D)), jnp.float32)
    fn = make_pipeline_fn(mesh, _stage_fn)

    def loss_pipe(p):
        return jnp.sum(fn(p, x_mb) ** 2)

    def loss_seq(p):
        return jnp.sum(_seq_reference(p, x_mb) ** 2)

    gp = jax.grad(loss_pipe)(params)
    gs = jax.grad(loss_seq)(params)
    for a, b in zip(gp, gs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_trains_end_to_end():
    """A 2-stage pipeline regresses a fixed target: loss decreases."""
    mesh = make_mesh(jax.devices(), pp=2)
    D, M, mb = 8, 4, 4
    params = _stacked_params(2, D, seed=3)
    x_mb = jnp.asarray(np.random.RandomState(4).normal(
        size=(M, mb, D)), jnp.float32)
    target = jnp.asarray(np.random.RandomState(5).uniform(
        -0.5, 0.5, (M, mb, D)), jnp.float32)
    fn = make_pipeline_fn(mesh, _stage_fn)

    @jax.jit
    def step(p):
        def loss_fn(p):
            return jnp.mean((fn(p, x_mb) - target) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(p)
        return loss, tuple(pi - 0.3 * gi for pi, gi in zip(p, g))

    losses = []
    for _ in range(25):
        loss, params = step(params)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses


def _moe_apply(model, variables, x):
    out, _ = model.apply(variables, x, mutable=["losses"])
    return out


def test_moe_sharded_matches_unsharded():
    mesh = make_mesh(jax.devices(), ep=4)
    model = MoEBlock(dim=16, num_experts=4)
    x = jnp.asarray(np.random.RandomState(0).normal(
        size=(4, 6, 16)), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    ref = _moe_apply(model, variables, x)
    with mesh:
        sharded = {"params": moe_param_sharding(mesh)(variables["params"])}
        out = jax.jit(lambda v, x: _moe_apply(model, v, x))(sharded, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_moe_routes_to_multiple_experts_and_aux_loss():
    model = MoEBlock(dim=16, num_experts=4)
    x = jnp.asarray(np.random.RandomState(1).normal(
        size=(8, 32, 16)), jnp.float32)
    variables = model.init(jax.random.PRNGKey(1), x)
    _, state = model.apply(variables, x, mutable=["losses"])
    aux = float(state["losses"]["moe_aux"][0])
    # aux loss is >= 1 (perfect balance = 1, all-one-expert = E)
    assert 1.0 <= aux < 4.0


def test_moe_gradients_flow_to_experts():
    mesh = make_mesh(jax.devices(), ep=2)
    model = MoEBlock(dim=8, num_experts=2)
    x = jnp.asarray(np.random.RandomState(2).normal(
        size=(2, 8, 8)), jnp.float32)
    variables = model.init(jax.random.PRNGKey(2), x)
    with mesh:
        sharded = {"params": moe_param_sharding(mesh)(variables["params"])}

        def loss(v):
            return jnp.sum(_moe_apply(model, v, x) ** 2)

        g = jax.jit(jax.grad(loss))(sharded)
    gw = g["params"]["w_up"]
    assert float(jnp.max(jnp.abs(gw))) > 0.0


def test_moe_transformer_trains_on_dp_ep_mesh():
    """Transformer(moe_experts=N) + transformer_param_sharding over a
    dp x ep mesh: one jitted grad step runs and the MoE expert grads
    are sharded over ep."""
    from geomx_tpu.models.transformer import (
        Transformer, transformer_param_sharding)

    mesh = make_mesh(jax.devices(), ep=2)
    model = Transformer(vocab=64, dim=16, depth=1, heads=2, max_len=16,
                        moe_experts=2)
    tok = jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, 64)
    with mesh:
        params = model.init(jax.random.PRNGKey(1), tok)["params"]
        params = transformer_param_sharding(mesh)(params)

        def loss_fn(p):
            logits, _ = model.apply({"params": p}, tok,
                                    mutable=["losses"])
            return jnp.mean(logits ** 2)

        loss, g = jax.jit(jax.value_and_grad(loss_fn))(params)
    assert np.isfinite(float(loss))
    w_up = params["block0"]["moe"]["w_up"]
    assert "ep" in str(w_up.sharding.spec)
    g_up = g["block0"]["moe"]["w_up"]
    assert g_up.shape == w_up.shape
    assert "ep" in str(g_up.sharding.spec)
