"""Server-side multi-precision (round-3 verdict item 8 / missing #2).

Reference: kSetMultiPrecision (kvstore_dist_server.h:50, handled
:324) — fp16-stored keys keep an fp32 master copy server-side so
updates below the fp16 ulp of the weight still accumulate.
"""

import numpy as np

from geomx_tpu.optimizer import SGD
from geomx_tpu.simulate import InProcessHiPS

# at weight 1.0 the fp16 ulp is ~9.8e-4: each lr*g = 1e-4 update is
# swallowed by the fp16 round-trip unless a fp32 master accumulates
LR = 1e-3
GRAD = 0.1
ROUNDS = 8


def _train_fp16(multi_precision: bool) -> np.ndarray:
    topo = InProcessHiPS(num_parties=2, workers_per_party=1).start()
    out = {}
    try:
        def master_init(kv):
            kv.set_optimizer(SGD(learning_rate=LR))
            if multi_precision:
                kv.set_multi_precision()
            kv.init(0, np.ones(4, np.float16))
            kv.wait()

        def worker(kv):
            w = np.ones(4, np.float16)
            kv.init(0, w)
            kv.pull(0, out=w)
            kv.wait()
            for _ in range(ROUNDS):
                kv.push(0, np.full(4, GRAD / 2, np.float16))  # 2 workers
                kv.pull(0, out=w)
                kv.wait()
            out[id(kv)] = w.copy()

        topo.run_workers(worker, include_master=master_init, timeout=300)
    finally:
        topo.stop()
    return next(iter(out.values()))


def test_fp32_master_accumulates_sub_ulp_updates():
    w = _train_fp16(multi_precision=True)
    # master: 1.0 - 8 * 1e-3 * 0.1 = 0.9992 -> fp16 ~0.999
    expect = 1.0 - ROUNDS * LR * GRAD
    np.testing.assert_allclose(w.astype(np.float32), expect, atol=3e-4)


def test_without_flag_fp16_swallows_updates():
    """The failure mode multi-precision exists for: each sub-ulp update
    rounds back to 1.0 in fp16, pinning the weight forever."""
    w = _train_fp16(multi_precision=False)
    np.testing.assert_array_equal(w.astype(np.float32), 1.0)
