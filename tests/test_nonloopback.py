"""Non-loopback address-table validation (round-3 verdict item 6).

Everything previously ran on a single 127.0.0.1: bind and advertised
addresses were conflated, and DMLC_NODE_HOST / DMLC_INTERFACE were
parsed but never exercised. These tests pin the reference semantics
(van.cc:427-477, docs/source/multi-host-deployment.rst): a van binds
0.0.0.0 and ADVERTISES its DMLC_NODE_HOST; DMLC_INTERFACE names a NIC
whose resolved IP is both bound and advertised; and a full 12-process
HiPS topology runs with each party on a DISTINCT address
(127.0.0.2/3/4 — Linux routes all of 127/8 to loopback, giving three
genuinely different addresses in the node tables without root).
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from geomx_tpu.config import Config, resolve_interface_ip  # noqa: E402


def test_interface_resolution_lo():
    assert resolve_interface_ip("lo") == "127.0.0.1"


def test_interface_resolution_unknown_raises():
    with pytest.raises(ValueError, match="DMLC_INTERFACE"):
        resolve_interface_ip("no-such-nic0")


def test_node_addr_rules():
    # DMLC_NODE_HOST not locally bindable (NAT/VIP): bind everything,
    # advertise the named address
    assert Config(node_host="10.1.2.3").node_addr() == \
        ("0.0.0.0", "10.1.2.3")
    # locally bindable DMLC_NODE_HOST: bind it directly (no wildcard
    # listener on shared hosts)
    assert Config(node_host="127.0.0.2").node_addr() == \
        ("127.0.0.2", "127.0.0.2")
    # DMLC_INTERFACE: resolved IP both ways
    assert Config(interface="lo").node_addr() == \
        ("127.0.0.1", "127.0.0.1")
    # node_host wins over interface (most specific)
    assert Config(node_host="10.1.2.3", interface="lo").node_addr() == \
        ("0.0.0.0", "10.1.2.3")
    # neither: loopback
    assert Config().node_addr() == ("127.0.0.1", "127.0.0.1")


def test_van_refuses_unadvertisable_bind():
    from geomx_tpu.ps.message import Role
    from geomx_tpu.ps.van import Van

    with pytest.raises(ValueError, match="advertise"):
        Van(my_role=Role.WORKER, is_global=False, root_uri="127.0.0.1",
            root_port=1, num_workers=1, num_servers=1,
            bind_host="0.0.0.0")


def test_two_party_topology_across_distinct_addresses():
    """In-process 2-node rendezvous across two DIFFERENT addresses: the
    scheduler advertises 127.0.0.2 (bound 0.0.0.0), the worker
    advertises 127.0.0.3 — the broadcast node table must carry the
    advertised addresses and messages must flow both ways."""
    import threading

    from geomx_tpu.ps import base as psbase
    from geomx_tpu.ps.message import Role
    from geomx_tpu.ps.postoffice import Postoffice
    from geomx_tpu.simulate import free_port

    port = free_port()
    boxes = {}

    def node(role, node_host, nw):
        cfg = Config(node_host=node_host)
        po = Postoffice(my_role=role, is_global=False,
                        root_uri="127.0.0.2", root_port=port,
                        num_workers=nw, num_servers=0, cfg=cfg)
        po.start(60.0)
        boxes[role] = po
        po.barrier(psbase.ALL_GROUP, timeout=60.0)

    ts = [threading.Thread(target=node, args=(Role.SCHEDULER, "127.0.0.2", 1),
                           daemon=True),
          threading.Thread(target=node, args=(Role.WORKER, "127.0.0.3", 1),
                           daemon=True)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(90)
    assert not any(t.is_alive() for t in ts), "rendezvous hung"
    try:
        wtable = boxes[Role.WORKER].van.node_table
        hosts = {h for h, _ in wtable.values()}
        assert hosts == {"127.0.0.2", "127.0.0.3"}, wtable
    finally:
        for po in boxes.values():
            po.van.stop()


@pytest.mark.slow
def test_hips_launch_across_three_addresses():
    """The full 12-process HiPS demo with every party on its own
    address (central 127.0.0.2, parties 127.0.0.3/4): nodes bind
    0.0.0.0, advertise DMLC_NODE_HOST, cross-address WAN + LAN tiers
    train and exit clean."""
    from tests.test_launch_integration import _run_launch

    accs = _run_launch(
        "run_vanilla_hips.sh", [], n_iters=15, timeout=300,
        env_extra={"HOST_CENTRAL": "127.0.0.2", "HOST_A": "127.0.0.3",
                   "HOST_B": "127.0.0.4"})
    assert max(accs[-5:]) > 0.4, f"multi-address run did not learn: {accs}"


