"""MultiGPS: multiple global servers (reference: scripts/cpu/run_multi_gps.sh,
DMLC_NUM_GLOBAL_SERVER=2, README.md "MultiGPS" load-balancing feature).

Keys are sharded across global servers by the deterministic heuristic
(small keys hash to one server via (key*9973)%n, big keys split across
all of them — reference EncodeDefaultKey, kvstore_dist.h:725-762); every
global server owns its canonical ranges and the round must complete with
exact values on every path."""

import numpy as np
import pytest

from tests.test_hips import Topology, _parallel
from geomx_tpu.kvstore import sharding
from geomx_tpu.optimizer import SGD


def test_sharding_spreads_keys_across_global_servers():
    # with 2 servers, small keys land on both (hash), big keys split
    ranks = {sharding.assign(k, 10, 2, 1000)[0].server_rank
             for k in range(8)}
    assert ranks == {0, 1}
    shards = sharding.assign(3, 5000, 2, 1000)
    assert {s.server_rank for s in shards} == {0, 1}
    assert sum(s.length for s in shards) == 5000


@pytest.mark.parametrize("spp", [1, 2])
def test_multi_gps_training_exact(spp):
    """2 global servers x (1 or 2) servers per party: small keys hash to
    one global server, the big key splits across both; after each round
    every worker sees exactly w0 - 4r."""
    topo = Topology(num_global_servers=2, servers_per_party=spp,
                    bigarray_bound=16).start(sync_global=True)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        # key 1: big -> split across both global servers; keys 2,3: small
        # -> hashed ((2*9973)%2=0, (3*9973)%2=1) one per global server
        w0 = {1: np.arange(48, dtype=np.float32),
              2: np.full(8, 5.0, np.float32),
              3: np.linspace(0, 1, 12).astype(np.float32)}

        def init_on(kv):
            for k, v in w0.items():
                kv.init(k, v)

        _parallel([lambda kv=kv: init_on(kv)
                   for kv in topo.workers + [topo.master]])

        def train(kv):
            for r in range(1, 4):
                for k in w0:
                    kv.push(k, np.ones_like(w0[k]))
                outs = {k: np.zeros_like(w0[k]) for k in w0}
                for k in w0:
                    kv.pull(k, out=outs[k])
                kv.wait()
                for k in w0:
                    np.testing.assert_allclose(
                        outs[k], w0[k] - 4.0 * r,
                        err_msg=f"key {k} round {r} (spp={spp})")

        _parallel([lambda kv=kv: train(kv) for kv in topo.workers])
    finally:
        topo.stop()


def test_multi_gps_mixed_sync():
    """MixedSync with 2 global servers: per-push updates still land on
    the right canonical shard; final state has all parties applied."""
    topo = Topology(num_global_servers=2, bigarray_bound=16).start(
        sync_global=False)
    try:
        topo.master.set_optimizer(SGD(learning_rate=1.0))
        w0 = np.zeros(40, np.float32)
        _parallel([lambda kv=kv: kv.init(0, w0)
                   for kv in topo.workers + [topo.master]])

        def train(kv):
            kv.push(0, np.ones(40, np.float32))
            out = np.zeros(40, np.float32)
            kv.pull(0, out=out)
            kv.wait()
            assert out[0] in (-2.0, -4.0), out

        _parallel([lambda kv=kv: train(kv) for kv in topo.workers])
        final = topo.master.pull(0)
        np.testing.assert_allclose(final, np.full(40, -4.0))
    finally:
        topo.stop()


def test_multi_gps_optimizer_states_cover_both_servers(tmp_path):
    """Each global server owns states for ITS canonical shards; a save
    must merge both (keyed by global rank)."""
    import json

    from geomx_tpu import checkpoint as ck
    from geomx_tpu.optimizer import Adam

    topo = Topology(num_global_servers=2, bigarray_bound=16).start(
        sync_global=True)
    fname = str(tmp_path / "mgps.states")
    try:
        topo.master.set_optimizer(Adam(learning_rate=0.01))
        w0 = np.ones(48, np.float32)   # big: split across both
        _parallel([lambda kv=kv: kv.init(0, w0)
                   for kv in topo.workers + [topo.master]])

        def push_pull(kv):
            kv.push(0, np.ones(48, np.float32))
            kv.pull(0)
            kv.wait()

        _parallel([lambda kv=kv: push_pull(kv) for kv in topo.workers])
        topo.workers[0].save_optimizer_states(fname)
        with open(fname) as f:
            per_server = json.load(f)
        assert set(per_server) == {"0", "1"}, per_server.keys()
        shard_offsets = set()
        for hexs in per_server.values():
            states = ck.deserialize_states(bytes.fromhex(hexs))
            for (key, off), s in states.items():
                assert key == 0 and s["t"] == 1
                shard_offsets.add(off)
        assert shard_offsets == {0, 24}, shard_offsets
    finally:
        topo.stop()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
