"""Wire sanitizer (geomx_tpu/ps/sanitizer.py) tests.

Unit half: a StubVan drives WireSanitizer's ledgers directly and proves
each violation class fires (and that the legal patterns — fenced stale
drops, give-ups, clean request/response pairs — stay silent).

Integration half: a real in-process tier runs push/pull rounds under a
seeded drop+dup+reorder FaultPlan with the sanitizer enabled on every
van; the run must complete with zero violations (the ISSUE acceptance
bar: chaos + sanitizer = clean).
"""

import json
import threading
import types

import numpy as np
import pytest

from geomx_tpu.ps.sanitizer import MARKER, WireSanitizer

assert MARKER  # the grep target scripts/run_chaos_matrix.sh fails on


class StubVan:
    def __init__(self, my_id=8, dead=(), stale=()):
        self.my_id = my_id
        self._dead = set(dead)
        # (sender, epoch) pairs considered stale
        self._stale = set(stale)

    def declared_dead_ids(self):
        return frozenset(self._dead)

    def is_stale(self, sender, epoch):
        return (sender, epoch) in self._stale


def msg(*, sender=9, recver=8, ts=1, request=True, push=False, pull=False,
        epoch=0, control=False):
    m = types.SimpleNamespace()
    m.meta = types.SimpleNamespace(
        sender=sender, recver=recver, app_id=0, customer_id=0,
        timestamp=ts, request=request, push=push, pull=pull,
        simple_app=False, head=0, epoch=epoch, msg_type=0)
    m.is_control = control
    return m


def test_clean_request_response_cycle():
    san = WireSanitizer(StubVan())
    san.on_inbound(msg(sender=9, ts=5, request=True, push=True))
    san.on_send(9, msg(recver=9, ts=5, request=False))
    assert san.report() == []


def test_double_response_is_unmatched(caplog):
    san = WireSanitizer(StubVan())
    san.on_inbound(msg(sender=9, ts=5, request=True, push=True))
    san.on_send(9, msg(recver=9, ts=5, request=False))
    with caplog.at_level("ERROR", logger="geomx.sanitizer"):
        san.on_send(9, msg(recver=9, ts=5, request=False))  # double ack
    assert any("unmatched-response" in v for v in san.violations)
    assert MARKER in caplog.text


def test_send_to_declared_dead_node():
    san = WireSanitizer(StubVan(dead={11}))
    san.on_send(11, msg(recver=11, ts=3, request=True, push=True))
    assert any("send-to-dead" in v for v in san.violations)


def test_epoch_regression():
    san = WireSanitizer(StubVan())
    san.on_inbound(msg(sender=9, ts=1, push=True, epoch=2))
    san.on_send(9, msg(recver=9, ts=1, request=False))
    san.on_inbound(msg(sender=9, ts=2, push=True, epoch=1))  # regression
    assert any("epoch-regression" in v for v in san.violations)


def test_duplicate_request_delivery():
    san = WireSanitizer(StubVan())
    san.on_inbound(msg(sender=9, ts=5, push=True))
    san.on_inbound(msg(sender=9, ts=5, push=True))  # past the dedup
    assert any("duplicate-request" in v for v in san.violations)


def test_unacked_request_leaks_at_report():
    san = WireSanitizer(StubVan())
    san.on_inbound(msg(sender=9, ts=5, push=True))
    report = san.report()
    assert any("countdown leak" in v for v in report)
    # idempotent: a second report (van.stop after a manual one) does not
    # double-count
    assert san.report() == report


def test_unanswered_request_leaks_at_report():
    san = WireSanitizer(StubVan())
    san.on_send(8, msg(sender=9, recver=8, ts=7, request=True, pull=True))
    assert any("unanswered-request" in v for v in san.report())


def test_give_up_resolves_outbound_and_forgives_late_reply():
    san = WireSanitizer(StubVan())
    m = msg(sender=9, recver=8, ts=7, request=True, pull=True)
    san.on_send(8, m)
    san.on_give_up(m)
    # the late response arriving after the give-up is not a violation
    san.on_inbound(msg(sender=8, ts=7, request=False))
    assert san.report() == []


def test_shutdown_forgives_inflight_request():
    """van.stop() is the give-up for anything still awaiting a response
    (the final teardown ack can always be lost — two generals): where a
    manual report() flags the unanswered request, on_shutdown forgives
    it, and a response landing even later is still not a double-ack."""
    san = WireSanitizer(StubVan())
    san.on_send(8, msg(sender=9, recver=8, ts=7, request=True, pull=True))
    assert san.on_shutdown() == []
    san.on_inbound(msg(sender=8, ts=7, request=False))
    assert san.violations == []


def test_fenced_stale_push_drop_is_legal():
    """A push the server fence-drops via is_stale owes no ack."""
    san = WireSanitizer(StubVan(stale={(9, 1)}))
    san.on_inbound(msg(sender=9, ts=5, push=True, epoch=1))
    assert san.report() == []


def test_control_frames_are_ignored():
    san = WireSanitizer(StubVan(dead={11}))
    san.on_send(11, msg(recver=11, ts=3, control=True))
    san.on_inbound(msg(sender=9, ts=4, control=True))
    assert san.report() == []


# ---------------------------------------------------------------------------
# integration: chaos round-trip with the sanitizer on every van
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_push_pull_with_sanitizer_clean():
    """Drop + dup + reorder faults, resend on, sanitizer on: training
    traffic completes and EVERY van closes with zero violations."""
    from geomx_tpu.config import Config
    from geomx_tpu.ps.kv_app import KVPairs, KVServer, KVWorker
    from geomx_tpu.ps.message import Role
    from geomx_tpu.ps.postoffice import Postoffice

    from test_transport import free_port, shutdown

    port = free_port()
    cfg = Config(
        resend=True, resend_timeout_ms=100, ps_seed=77,
        wire_sanitizer=True,
        fault_plan=json.dumps({"rules": [
            {"type": "drop", "p": 0.15},
            {"type": "reorder", "window": 4},
            {"type": "dup", "p": 0.1},
        ]}))
    kw = dict(is_global=False, root_uri="127.0.0.1", root_port=port,
              num_workers=2, num_servers=1, cfg=cfg)
    sched = Postoffice(my_role=Role.SCHEDULER, **kw)
    servers = [Postoffice(my_role=Role.SERVER, **kw)]
    workers = [Postoffice(my_role=Role.WORKER, **kw) for _ in range(2)]
    pos = [sched] + servers + workers
    threads = [threading.Thread(target=po.start, daemon=True) for po in pos]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    try:
        for po in pos:
            assert po.van.ready.is_set(), "rendezvous failed under faults"
            assert po.van.sanitizer is not None
        store = {}
        lock = threading.Lock()
        server = KVServer(servers[0])

        def handle(req, kvs, srv):
            if req.push:
                with lock:
                    for k, v in zip(kvs.keys, kvs.vals):
                        store[k] = store.get(k, 0) + v
                srv.response(req)
            elif req.pull:
                srv.response(req, KVPairs(
                    keys=kvs.keys, vals=[store[k] for k in kvs.keys]))

        server.set_request_handle(handle)
        w0, w1 = KVWorker(workers[0]), KVWorker(workers[1])
        v = np.ones((16,), dtype=np.float32)
        n_rounds = 4
        for _ in range(n_rounds):
            ts0 = w0.push(KVPairs(keys=[7], vals=[v]), server_rank=0)
            ts1 = w1.push(KVPairs(keys=[7], vals=[v]), server_rank=0)
            w0.wait(ts0, 60)
            w1.wait(ts1, 60)
        ts = w0.pull([7], server_rank=0)
        w0.wait(ts, 60)
        (resp,) = w0.take_response(ts)
        np.testing.assert_allclose(resp.vals[0], 2 * n_rounds * v)
    finally:
        shutdown(sched, *servers, *workers)
    for po in pos:
        assert po.van.sanitizer.report() == [], (
            f"van {po.van.my_id}: {po.van.sanitizer.violations}")


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
