"""Element-sparse push/pull wire (KVStoreDist.push_bsc / pull_bsc).

The TPU-native BSC LAN hop (round-3 verdict item 3): a worker ships its
on-chip top-k selection as (values, indices) — O(k) bytes — the server
scatters to dense for aggregation, and a "bsc"-tagged pull returns the
aggregated gradient's exact nonzero set. Semantics must equal a dense
push of the scattered selection.
"""

import numpy as np
import pytest

from geomx_tpu.simulate import InProcessHiPS


def _run_workers(topo, worker_fn, master_init, timeout=300):
    # run_workers joins with a timeout, surfaces worker errors, and
    # raises on hang — no wrapper thread needed
    topo.run_workers(worker_fn, include_master=master_init,
                     timeout=timeout)


@pytest.mark.parametrize("sharded", [False, True])
def test_push_bsc_aggregates_and_pull_bsc_is_exact(sharded):
    """Two workers push overlapping sparse selections; the aggregated
    pull-back (sparse wire) must equal the dense pull exactly —
    overlapping indices sum, disjoint ones pass through."""
    n = 40
    # sharded=True: two local servers + a bigarray bound below the key
    # size forces the selection to be partitioned across server shards
    kw = dict(num_parties=2, workers_per_party=1)
    if sharded:
        kw.update(servers_per_party=2, bigarray_bound=16)
    topo = InProcessHiPS(**kw).start()
    results = {}
    try:
        def master_init(kv):
            kv.init(7, np.zeros(n, np.float32))
            kv.wait()

        def worker(kv):
            widx = 0 if kv is topo.workers[0] else 1
            kv.init(7, np.zeros(n, np.float32))
            kv.pull(7, out=np.zeros(n, np.float32))
            kv.wait()
            if widx == 0:
                idx = np.array([0, 5, 17, 33], np.int64)
                vals = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
            else:
                idx = np.array([5, 20, 39], np.int64)
                vals = np.array([10.0, 20.0, 30.0], np.float32)
            kv.push_bsc(7, vals, idx)
            join = kv.pull_bsc(7)
            avals, aidx = join()
            dense = np.zeros(n, np.float32)
            dense[aidx] = avals
            results[widx] = dense

        _run_workers(topo, worker, master_init)
    finally:
        topo.stop()

    expect = np.zeros(n, np.float32)
    expect[[0, 5, 17, 33]] += [1.0, 2.0, 3.0, 4.0]
    expect[[5, 20, 39]] += [10.0, 20.0, 30.0]
    np.testing.assert_allclose(results[0], expect)
    np.testing.assert_array_equal(results[0], results[1])


def test_push_bsc_range_check():
    topo = InProcessHiPS(num_parties=2, workers_per_party=1).start()
    try:
        def master_init(kv):
            kv.init(3, np.zeros(8, np.float32))
            kv.wait()

        def worker(kv):
            kv.init(3, np.zeros(8, np.float32))
            kv.wait()
            with pytest.raises(IndexError):
                kv.push_bsc(3, np.ones(1, np.float32),
                            np.array([8], np.int64))
            # the failed push must not poison the round: a clean
            # round still completes
            kv.push_bsc(3, np.ones(1, np.float32),
                        np.array([2], np.int64))
            avals, aidx = kv.pull_bsc(3)()
            dense = np.zeros(8, np.float32)
            dense[aidx] = avals
            np.testing.assert_allclose(dense[2], 2.0)

        _run_workers(topo, worker, master_init)
    finally:
        topo.stop()


def test_trainer_indices_beyond_2p24():
    """Round-3 verdict item 3: the float32-mantissa index packing capped
    the trainer at 2^24 params. Indices now travel as bitcast int32 —
    verify exactness of a selection ABOVE 2^24 on a 17M-element leaf."""
    import jax.numpy as jnp

    from geomx_tpu.kvstore import create as kv_create
    from geomx_tpu.trainer_device import DeviceResidentTrainer

    n = (1 << 24) + 64          # would have raised pre-fix
    spike = (1 << 24) + 37      # not representable in a f32 mantissa +1

    def grad_fn(leaves, X, y):
        w = leaves[0]
        g = jnp.zeros_like(w).at[spike].set(100.0).at[3].set(-50.0)
        return jnp.sum(w * 0.0), [g]

    kv = kv_create("local")
    tr = DeviceResidentTrainer(
        [np.zeros(n, np.float32)], kv, grad_fn,
        threshold=2 / n, learning_rate=0.1)
    tr.step(jnp.zeros(()), None)
    w = tr.leaves[0]
    nz = np.nonzero(w)[0]
    np.testing.assert_array_equal(nz, [3, spike])
    np.testing.assert_allclose(w[spike], -10.0)   # -lr * 100
    np.testing.assert_allclose(w[3], 5.0)         # -lr * -50


def test_push_bsc_duplicate_indices_sum():
    """A payload carrying the same index twice aggregates by SUM (the
    documented contract; fancy-index assignment would silently drop
    the first value)."""
    from geomx_tpu.compression import _generic_decompress

    out = _generic_decompress(
        "bsc", np.array([1.0, 2.0, 5.0], np.float32),
        np.array([5, 5, 0], np.int32), 8)
    np.testing.assert_allclose(out[[0, 5]], [5.0, 3.0])
    assert out.sum() == 8.0


@pytest.mark.parametrize("sharded", [False, True])
def test_push_pull_bsc_batch_matches_two_op(sharded):
    """The COMBINED sparse round must aggregate exactly like
    push_bsc_batch + pull_bsc_batch — including keys partitioned
    across server shards (per-rank slices of one batch, multi-rank
    ack/data accounting)."""
    n0, n1 = 40, 24
    kw = dict(num_parties=2, workers_per_party=1)
    if sharded:
        kw.update(servers_per_party=2, bigarray_bound=16)
    topo = InProcessHiPS(**kw).start()
    results = {}
    try:
        def master_init(kv):
            kv.init(0, np.zeros(n0, np.float32))
            kv.init(1, np.zeros(n1, np.float32))
            kv.wait()

        def worker(kv):
            widx = 0 if kv is topo.workers[0] else 1
            for k, n in ((0, n0), (1, n1)):
                kv.init(k, np.zeros(n, np.float32))
                kv.pull(k, out=np.zeros(n, np.float32))
            kv.wait()
            if widx == 0:
                sels = {0: (np.array([1.0, 2.0], np.float32),
                            np.array([0, 33], np.int64)),
                        1: (np.array([5.0], np.float32),
                            np.array([17], np.int64))}
            else:
                sels = {0: (np.array([10.0, 20.0], np.float32),
                            np.array([33, 39], np.int64)),
                        1: (np.array([7.0, 8.0], np.float32),
                            np.array([17, 3], np.int64))}
            agg = kv.push_pull_bsc_batch(
                [0, 1], [sels[0][0], sels[1][0]],
                [sels[0][1], sels[1][1]])()
            dense = {}
            for k, n in ((0, n0), (1, n1)):
                d = np.zeros(n, np.float32)
                avals, aidx = agg[k]
                d[aidx] = avals
                dense[k] = d
            results[widx] = dense

        _run_workers(topo, worker, master_init)
    finally:
        topo.stop()

    e0 = np.zeros(n0, np.float32)
    e0[[0, 33]] += [1.0, 2.0]
    e0[[33, 39]] += [10.0, 20.0]
    e1 = np.zeros(n1, np.float32)
    e1[[17]] += [5.0]
    e1[[17, 3]] += [7.0, 8.0]
    for w in (0, 1):
        np.testing.assert_allclose(results[w][0], e0)
        np.testing.assert_allclose(results[w][1], e1)
