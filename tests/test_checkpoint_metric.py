"""Checkpoint/resume, metric library, and Trainer tests."""

import numpy as np
import pytest

from geomx_tpu import checkpoint, metric
from geomx_tpu.optimizer import Adam, SGD
from geomx_tpu.trainer import Trainer
from geomx_tpu.kvstore.local import KVStoreLocal


# -- checkpoint ----------------------------------------------------------

def test_checkpoint_roundtrip(tmp_path):
    prefix = str(tmp_path / "model")
    params = {"dense": {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
                        "b": np.zeros(3, np.float32)}}
    meta = {"iter": 42, "lr": 0.1}
    path = checkpoint.save_checkpoint(prefix, 3, params, metadata=meta)
    assert path.endswith("model-0003.ckpt")
    got, opt, got_meta = checkpoint.load_checkpoint(prefix, 3)
    np.testing.assert_array_equal(got["dense"]["w"], params["dense"]["w"])
    np.testing.assert_array_equal(got["dense"]["b"], params["dense"]["b"])
    assert opt is None
    assert got_meta["iter"] == 42 and abs(got_meta["lr"] - 0.1) < 1e-9


def test_latest_checkpoint(tmp_path):
    prefix = str(tmp_path / "ck")
    assert checkpoint.latest_checkpoint(prefix) is None
    for e in (1, 4, 2):
        checkpoint.save_checkpoint(prefix, e, [np.zeros(2, np.float32)])
    assert checkpoint.latest_checkpoint(prefix) == 4


def test_optimizer_state_roundtrip(tmp_path):
    fname = str(tmp_path / "opt.states")
    opt = Adam(learning_rate=0.01)
    w = np.ones(4, np.float32)
    for _ in range(3):
        w = opt.update(0, w, np.full(4, 0.5, np.float32))
    checkpoint.save_optimizer_states(fname, opt)

    opt2 = Adam(learning_rate=0.01)
    checkpoint.load_optimizer_states(fname, opt2)
    s1, s2 = opt.get_states()[0], opt2.get_states()[0]
    assert s2["t"] == s1["t"] == 3
    np.testing.assert_allclose(s2["m"], s1["m"])
    np.testing.assert_allclose(s2["v"], s1["v"])
    # both must produce identical continued trajectories
    w1 = opt.update(0, w.copy(), np.full(4, 0.5, np.float32))
    w2 = opt2.update(0, w.copy(), np.full(4, 0.5, np.float32))
    np.testing.assert_allclose(w1, w2)


def test_kvstore_optimizer_state_save_load(tmp_path):
    kv = KVStoreLocal()
    kv.set_optimizer(SGD(learning_rate=0.1, momentum=0.9))
    kv.init(0, np.zeros(4, np.float32))
    kv.push(0, np.ones(4, np.float32))
    fname = str(tmp_path / "kv.states")
    kv.save_optimizer_states(fname)

    kv2 = KVStoreLocal()
    kv2.set_optimizer(SGD(learning_rate=0.1, momentum=0.9))
    kv2.load_optimizer_states(fname)
    np.testing.assert_allclose(kv2._optimizer.get_states()[0],
                               kv._optimizer.get_states()[0])


# -- metric --------------------------------------------------------------

def test_accuracy_and_topk():
    acc = metric.create("acc")
    scores = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    labels = np.array([1, 0, 0])
    acc.update(labels, scores)
    assert acc.get() == ("accuracy", pytest.approx(2 / 3))

    topk = metric.TopKAccuracy(top_k=2)
    s = np.array([[0.5, 0.3, 0.2], [0.1, 0.2, 0.7]])
    topk.update(np.array([1, 0]), s)  # label1 in top2 of row0; label0 not
    assert topk.get()[1] == pytest.approx(0.5)


def test_f1_and_regression_metrics():
    f1 = metric.F1()
    f1.update(np.array([1, 1, 0, 0]), np.array([1, 0, 1, 0]))
    # tp=1 fp=1 fn=1 -> prec=rec=0.5 -> f1=0.5
    assert f1.get()[1] == pytest.approx(0.5)

    mae = metric.create("mae")
    mae.update(np.array([1.0, 2.0]), np.array([2.0, 4.0]))
    assert mae.get()[1] == pytest.approx(1.5)

    rmse = metric.create("rmse")
    rmse.update(np.array([0.0, 0.0]), np.array([3.0, 4.0]))
    assert rmse.get()[1] == pytest.approx(np.sqrt(12.5))


def test_cross_entropy_perplexity_composite():
    ce = metric.CrossEntropy()
    probs = np.array([[0.5, 0.5], [0.9, 0.1]])
    ce.update(np.array([0, 0]), probs)
    expect = -(np.log(0.5) + np.log(0.9)) / 2
    assert ce.get()[1] == pytest.approx(expect)

    comp = metric.create(["acc", "mae"])
    comp.update(np.array([1]), np.array([[0.2, 0.8]]))
    names, values = comp.get()
    assert names == ["accuracy", "mae"]

    with pytest.raises(ValueError):
        metric.create("nope")


# -- trainer -------------------------------------------------------------

def test_trainer_local_sgd_step(tmp_path):
    kv = KVStoreLocal()
    kv.set_optimizer(SGD(learning_rate=0.5))
    w = [np.ones((2, 2), np.float32), np.zeros(3, np.float32)]
    tr = Trainer([l.copy() for l in w], kv)
    tr.step([np.ones((2, 2), np.float32), np.ones(3, np.float32)])
    np.testing.assert_allclose(tr.leaves[0], 0.5 * np.ones((2, 2)))
    np.testing.assert_allclose(tr.leaves[1], -0.5 * np.ones(3))

    # checkpoint + resume restores parameters
    prefix = str(tmp_path / "tr")
    tr.save(prefix, 1, metadata={"it": 7})
    kv2 = KVStoreLocal()
    kv2.set_optimizer(SGD(learning_rate=0.5))
    tr2 = Trainer.load(prefix, 1, kv2)
    np.testing.assert_allclose(tr2.leaves[0], tr.leaves[0])
    np.testing.assert_allclose(tr2.leaves[1], tr.leaves[1])


def test_dist_optimizer_states_roundtrip(tmp_path):
    """In HiPS the live optimizer states sit on the global server; the
    master worker's save must fetch them over the command channel."""
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_hips import Topology, _parallel

    topo = Topology().start(sync_global=True)
    fname = str(tmp_path / "dist.states")
    try:
        topo.master.set_optimizer(Adam(learning_rate=0.01))
        w0 = np.ones((4, 4), np.float32)

        def init_on(kv):
            kv.init(0, w0)
            if not kv.is_master_worker:
                kv.pull(0)

        _parallel([lambda kv=kv: init_on(kv)
                   for kv in topo.workers + [topo.master]])

        def push_pull(kv):
            kv.push(0, np.ones((4, 4), np.float32))
            kv.pull(0)
            kv.wait()

        for _ in range(2):
            _parallel([lambda kv=kv: push_pull(kv) for kv in topo.workers])

        topo.master.save_optimizer_states(fname)
        import json
        with open(fname) as f:
            per_server = json.load(f)
        from geomx_tpu import checkpoint as ck
        states = ck.deserialize_states(
            bytes.fromhex(next(iter(per_server.values()))))
        # server updater is keyed by (key, shard_offset); Adam ran 2
        # rounds on key 0 -> t == 2 with nonzero moments
        assert states[(0, 0)]["t"] == 2
        assert np.abs(states[(0, 0)]["m"]).max() > 0

        # restore must be accepted by the server without error
        topo.master.load_optimizer_states(fname)
    finally:
        topo.stop()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
