#!/usr/bin/env python
"""Geo-distributed transformer through HiPS + Bi-Sparse, device-resident.

The round-4 flagship config: the 59M-param decoder-only transformer
(the bench model) trains through ``DeviceResidentTrainer`` — parameters
never leave the chip; the host<->device link and the LAN hop carry only
the per-tensor BSC top-k selection down and the aggregated nonzeros up
(KVStoreDist.push_bsc / pull_bsc element-sparse wire).

Reference lineage: examples/cnn_bsc.py's aggregator-PS + worker-side
optimizer semantics (reference: examples/cnn_bsc.py:37-60), applied to
the model family the reference never had. Run it like the other
examples — one process per DMLC_ROLE, or --local for single-process:

  python examples/transformer_bsc_device.py --local --cpu --max-iters 20

Synthetic LM task: next token = (3*t + 7) mod vocab, a deterministic
pattern every worker slices differently, so the loss curve is a real
learning signal (random tokens would pin loss at log(vocab))."""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def synth_batch(rng, batch, seq_len, vocab):
    """Deterministic-pattern LM batch: x[t+1] = (3*x[t] + 7) % vocab."""
    import numpy as np

    start = rng.integers(0, vocab, size=(batch, 1))
    toks = [start]
    for _ in range(seq_len - 1):
        toks.append((3 * toks[-1] + 7) % vocab)
    return np.concatenate(toks, axis=1).astype(np.int32)


def build_transformer_grad_step(dim, depth, heads, vocab, seq_len,
                                compute_dtype=None):
    """(leaves, grad_step) with the leaf-list contract grad_step(leaves,
    tokens, None) -> (loss, grad_leaves) the trainers expect."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from geomx_tpu.models.transformer import Transformer

    model = Transformer(vocab=vocab, dim=dim, depth=depth, heads=heads,
                        max_len=seq_len,
                        compute_dtype=compute_dtype or jnp.bfloat16)
    rng = jax.random.PRNGKey(42)  # same init on every worker
    params = model.init(rng, jnp.zeros((1, seq_len), jnp.int32))
    leaves, treedef = jax.tree_util.tree_flatten(params)

    def loss_fn(leaf_list, toks):
        p = jax.tree_util.tree_unflatten(treedef, leaf_list)
        logits = model.apply(p, toks[:, :-1])
        tgt = toks[:, 1:]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(
            jnp.take_along_axis(logp, tgt[..., None], axis=-1))

    def grad_step(leaf_list, toks, _y):
        return jax.value_and_grad(loss_fn)(leaf_list, toks)

    return [np.array(l, copy=True) for l in leaves], grad_step


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--depth", type=int, default=8)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("-bs", "--batch-size", type=int, default=8)
    ap.add_argument("-lr", "--learning-rate", type=float, default=0.05)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("-cr", "--compression-ratio", type=float, default=0.01,
                    help="BSC threshold: per-tensor top-k keeps this "
                         "fraction of coordinates")
    ap.add_argument("-ds", "--data-slice-idx", type=int, default=None,
                    help="worker slice id (set by the launch scripts); "
                         "seeds this worker's disjoint data stream; "
                         "defaults to the kv rank when not given")
    ap.add_argument("--max-iters", type=int, default=50)
    ap.add_argument("--local", action="store_true",
                    help="single-process local kvstore (no topology)")
    ap.add_argument("-c", "--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import geomx_tpu as gx
    from geomx_tpu.trainer_device import DeviceResidentTrainer

    kv = gx.kv.create("local" if args.local else "dist_sync")
    num_all_workers = getattr(kv, "num_all_workers", 1) or 1
    my_rank = getattr(kv, "rank", 0)
    time.sleep(0 if args.local else 1)

    leaves, grad_step = build_transformer_grad_step(
        args.dim, args.depth, args.heads, args.vocab, args.seq_len)
    n_params = sum(l.size for l in leaves)

    if getattr(kv, "is_master_worker", False):
        for idx, leaf in enumerate(leaves):
            kv.init(idx, leaf)
        kv.wait()
        return

    tr = DeviceResidentTrainer(
        leaves, kv, grad_step, threshold=args.compression_ratio,
        learning_rate=args.learning_rate, momentum=args.momentum)
    print(f"[worker {my_rank}] {n_params / 1e6:.1f}M params, "
          f"per-round selection {tr.k} of {tr.total} "
          f"({100.0 * tr.k / tr.total:.2f}%)", flush=True)

    slice_idx = (my_rank if args.data_slice_idx is None
                 else args.data_slice_idx)
    rng = np.random.default_rng(1234 + slice_idx)  # disjoint data slices
    import jax.numpy as jnp

    begin = time.time()
    for it in range(1, args.max_iters + 1):
        toks = jnp.asarray(synth_batch(rng, args.batch_size,
                                       args.seq_len, args.vocab))
        loss = tr.step(toks, None)
        tokens_s = (it * args.batch_size * args.seq_len * num_all_workers
                    / (time.time() - begin))
        print(f"[Time {time.time() - begin:.3f}][Iteration {it}] "
              f"Loss {loss:.4f} ({tokens_s:.0f} tok/s)", flush=True)


if __name__ == "__main__":
    main()
