#!/usr/bin/env python
"""ESync: heterogeneity-balanced synchronous training (beyond parity).

The reference documents this algorithm but ships no code ("to be
integrated", reference README.md:45; Li et al., IEEE TSC 2020). Each
sync round a worker runs M_i local optimizer steps — assigned by the
state server on the party's rank-0 PS so every worker's reach-server
time balances against the slowest — then joins a synchronous model
average. Fast nodes stop idling at the barrier; no stale gradients are
admitted (geomx_tpu/esync.py).

Run like the other examples — one process per DMLC_ROLE, or --local for
a single process. ``--slowdown S`` injects an artificial per-step sleep
so heterogeneity is observable on a uniform host.
"""

import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import geomx_tpu as gx
from geomx_tpu import optimizer as gx_opt
from examples.utils import build_model_and_step, eval_acc, load_data


def main():
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser()
    parser.add_argument("-lr", "--learning-rate", type=float, default=0.001)
    parser.add_argument("-bs", "--batch-size", type=int, default=32)
    parser.add_argument("-ds", "--data-slice-idx", type=int, default=None)
    parser.add_argument("-r", "--rounds", type=int, default=30,
                        help="sync rounds to run")
    parser.add_argument("--slowdown", type=float, default=0.0,
                        help="artificial seconds of extra compute per "
                             "local step (heterogeneity injection)")
    parser.add_argument("--local", action="store_true")
    parser.add_argument("-c", "--cpu", action="store_true")
    args = parser.parse_args()
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from geomx_tpu.esync import ESyncTrainer

    kv = gx.kv.create("local" if args.local else "dist_sync")
    my_rank = getattr(kv, "rank", 0)
    time.sleep(0 if args.local else 1)

    leaves, _td, grad_step, eval_step = build_model_and_step(
        args.batch_size)

    if getattr(kv, "is_master_worker", False):
        for idx, leaf in enumerate(leaves):
            kv.init(idx, leaf)
        kv.wait()
        return

    def grad_fn(leaf_list, X, y):
        if args.slowdown:
            time.sleep(args.slowdown)
        loss, grads = grad_step(leaf_list, X, y)
        return float(loss), [np.asarray(g) for g in grads]

    opt = gx_opt.Adam(learning_rate=args.learning_rate)
    tr = ESyncTrainer(leaves, kv, grad_fn, opt)

    slice_idx = args.data_slice_idx if args.data_slice_idx is not None \
        else my_rank
    nslices = max(getattr(kv, "num_all_workers", 1), 1)
    train_iter, test_iter, _, _ = load_data(args.batch_size, nslices,
                                            slice_idx)
    import itertools

    batches = [(jnp.asarray(X), jnp.asarray(y))
               for X, y in itertools.islice(train_iter, 8)]
    for r in range(args.rounds):
        loss = tr.round(batches)
        if r % 5 == 0 or r == args.rounds - 1:
            print(f"[esync rank {my_rank}] round {r} steps={tr.steps} "
                  f"local_steps_total={tr.local_steps_run} "
                  f"loss={loss:.4f}", flush=True)
    acc = eval_acc(test_iter, tr.leaves, eval_step)
    print(f"[esync rank {my_rank}] final acc={acc:.4f} "
          f"local_steps_total={tr.local_steps_run}", flush=True)


if __name__ == "__main__":
    main()
