#!/usr/bin/env python
"""Long-context transformer training over a dp x tp x sp device mesh.

The TPU-first flagship beyond the reference's CNN-era model layer
(SURVEY.md §5.7 — the reference has no attention model at all): batch
shards over "dp", sequence over "sp" (ring attention via
shard_map+ppermute), attention heads and MLP hidden over "tp"
(Megatron-style parameter shardings; GSPMD inserts the collectives).

Single process, all local devices. Try it without hardware:

  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
  python examples/train_transformer.py --tp 2 --sp 2 --max-iters 10

For geo-distributed training, wrap the aggregated gradients with a
``dist_sync`` KVStore exactly as examples/cnn.py does (the mesh is the
data center; see geomx_tpu.parallel.HierarchicalTrainer).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--depth", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=1024)
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("-lr", "--learning-rate", type=float, default=3e-4)
    ap.add_argument("--max-iters", type=int, default=20)
    ap.add_argument("--ckpt-dir", type=str, default="",
                    help="sharded-checkpoint dir; resumes from the "
                         "latest step when one exists")
    ap.add_argument("--ckpt-every", type=int, default=10)
    ap.add_argument("--microbatches", type=int, default=1,
                    help="gradient-accumulation microbatches per step")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize transformer blocks")
    ap.add_argument("-c", "--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from geomx_tpu.models.transformer import (
        Transformer, make_attention, transformer_param_sharding)
    from geomx_tpu.parallel.mesh import make_mesh
    from geomx_tpu.parallel.ring_attention import make_ring_attention

    mesh = make_mesh(jax.devices(), tp=args.tp, sp=args.sp)
    dp = mesh.devices.shape[0]
    print(f"mesh: dp={dp} tp={args.tp} sp={args.sp} "
          f"({len(jax.devices())} x {jax.devices()[0].device_kind})")

    # sp>1: ring attention (sequence sharded over the mesh); otherwise the
    # per-device pick — Pallas flash kernels on TPU (shard_mapped over
    # dp/tp when the mesh is multi-device), XLA dense elsewhere
    attn = (make_ring_attention(mesh, causal=True) if args.sp > 1
            else make_attention("auto", mesh=mesh))
    model = Transformer(vocab=args.vocab, dim=args.dim, depth=args.depth,
                        heads=args.heads, max_len=args.seq_len,
                        attn_fn=attn, remat=args.remat,
                        compute_dtype=jnp.bfloat16)

    rng = np.random.RandomState(0)
    # synthetic copy-task-ish stream: next token = current + 1 mod vocab,
    # learnable so the loss visibly drops
    base = rng.randint(0, args.vocab, (args.batch_size, 1))
    tokens_np = (base + np.arange(args.seq_len)[None, :]) % args.vocab
    tokens = jnp.asarray(tokens_np, jnp.int32)

    with mesh:
        # init with the FULL batch: ring attention runs under shard_map,
        # whose specs require every axis divisible by its mesh axis
        params = model.init(jax.random.PRNGKey(0), tokens)
        params = transformer_param_sharding(mesh)(params)
        opt = optax.adamw(args.learning_rate)
        opt_state = opt.init(params)
        tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp", "sp")))

        def loss_fn(p, toks):
            logits = model.apply(p, toks)
            tgt = jnp.roll(toks, -1, axis=1)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt).mean()

        grad_fn = lambda p, toks: jax.value_and_grad(  # noqa: E731
            loss_fn)(p, toks)
        if args.microbatches != 1:
            from geomx_tpu.parallel.grad_accum import accumulate_gradients

            grad_fn = accumulate_gradients(grad_fn, args.microbatches)

        @jax.jit
        def step(p, s, toks):
            loss, grads = grad_fn(p, toks)
            updates, s = opt.update(grads, s, p)
            return optax.apply_updates(p, updates), s, loss

        start_it = 1
        if args.ckpt_dir:
            from geomx_tpu.checkpoint_sharded import (
                latest_step, restore_sharded, save_sharded)

            last = latest_step(args.ckpt_dir)
            if last is not None:
                state = restore_sharded(
                    args.ckpt_dir, last,
                    {"params": params, "opt_state": opt_state})
                params, opt_state = state["params"], state["opt_state"]
                start_it = last + 1
                print(f"resumed from step {last}", flush=True)

        t0 = time.time()
        for it in range(start_it, args.max_iters + 1):
            params, opt_state, loss = step(params, opt_state, tokens)
            print(f"[Time {time.time() - t0:.3f}][Iteration {it}] "
                  f"Loss {float(loss):.4f}", flush=True)
            if args.ckpt_dir and it % args.ckpt_every == 0:
                save_sharded(args.ckpt_dir, it,
                             {"params": params, "opt_state": opt_state})
                print(f"checkpointed step {it}", flush=True)


if __name__ == "__main__":
    main()
