#!/usr/bin/env python
"""Vanilla HiPS training: dist_sync (FSA) / dist_async (MixedSync/DCASGD).

Mirror of the reference entrypoint (reference: examples/cnn.py): same CLI
flags, same roles (master worker sets the optimizer on the global server
and exits after init), same per-iteration accuracy print — the observable
correctness signal. Compute is JAX: a jitted value_and_grad step feeds
kv.push/kv.pull over the HiPS tiers.
"""

import argparse
import logging
import os
import sys
import time

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import geomx_tpu as gx
from geomx_tpu import optimizer as gx_opt
from examples.utils import Measure, build_model_and_step, eval_acc, load_data


def main():
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser()
    # the reference defaults to 0.01 (examples/cnn.py:32) but Adam at 0.01
    # plateaus at chance on this CNN; 0.001 learns to >0.95 within an epoch
    parser.add_argument("-lr", "--learning-rate", type=float, default=0.001)
    parser.add_argument("-bs", "--batch-size", type=int, default=32)
    parser.add_argument("-ds", "--data-slice-idx", type=int, default=0)
    parser.add_argument("-dt", "--data-type", type=str, default="mnist",
                        choices=["mnist", "fashion-mnist", "cifar10"])
    parser.add_argument("-m", "--model", type=str, default="cnn",
                        help="cnn | resnet18 | resnet34 | resnet50 | ...")
    parser.add_argument("-ep", "--epoch", type=int, default=5)
    parser.add_argument("-ms", "--mixed-sync", action="store_true")
    parser.add_argument("-dc", "--dcasgd", action="store_true")
    parser.add_argument("-sc", "--split-by-class", action="store_true")
    parser.add_argument("-c", "--cpu", action="store_true")
    parser.add_argument("--max-iters", type=int, default=0,
                        help="stop after N iterations (0 = full epochs)")
    parser.add_argument("--checkpoint-prefix", type=str, default="",
                        help="save params each epoch; resume from the "
                             "latest epoch if one exists")
    args = parser.parse_args()

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")

    if args.mixed_sync:
        kv = gx.kv.create("dist_async")
        if kv.is_master_worker:
            kv.set_optimizer(gx_opt.Adam(learning_rate=args.learning_rate))
    elif args.dcasgd:
        kv = gx.kv.create("dist_async")
        if kv.is_master_worker:
            kv.set_optimizer(gx_opt.DCASGD(learning_rate=args.learning_rate))
    else:
        # GEOMX_PARTY_MESH=1 resolves this to the mesh-party tier
        # (kvstore "dist_sync_mesh", docs/mesh-party.md): the launch is
        # unchanged, intra-party aggregation moves into the jitted
        # step's psum, and only this process's van speaks to the party
        # server. The factory does the resolution so scripts/run_*.sh
        # stay identical either way.
        kv = gx.kv.create("dist_sync")
        if kv.is_master_worker:
            kv.set_optimizer(gx_opt.Adam(learning_rate=args.learning_rate))
    num_all_workers = kv.num_all_workers
    my_rank = kv.rank
    time.sleep(1)  # let configuration commands land (reference: cnn.py:86)

    input_shape = (32, 32, 3) if args.data_type == "cifar10" else (28, 28, 1)
    leaves, _treedef, grad_step, eval_step = build_model_and_step(
        args.batch_size, input_shape=input_shape, model=args.model)

    if (getattr(kv, "type", "") == "dist_sync_mesh"
            and getattr(kv, "mesh_codec", "none") != "none"
            and args.model == "cnn"
            and not getattr(kv, "is_master_worker", False)):
        # GEOMX_MESH_CODEC: intra-party gradients ride the quantized
        # ppermute ring instead of the fused psum (the zoo path's
        # stateful grad_step cannot be wrapped — see utils)
        from examples.utils import build_mesh_ring_step

        grad_step = build_mesh_ring_step(kv, grad_step)

    start_epoch = 0
    resume_iters = 0
    if args.checkpoint_prefix:
        from geomx_tpu import checkpoint as gx_ckpt

        latest = gx_ckpt.latest_checkpoint(args.checkpoint_prefix)
        if latest is not None:
            saved, _, meta = gx_ckpt.load_checkpoint(
                args.checkpoint_prefix, latest)
            leaves = [np.asarray(l) for l in saved]
            start_epoch = latest
            resume_iters = int(meta.get("iters", 0))
            print(f"Resumed from {args.checkpoint_prefix}-{latest:04d}.ckpt "
                  f"(epoch {latest}, iter {resume_iters}).")

    for idx, leaf in enumerate(leaves):
        kv.init(idx, leaf)
        if kv.is_master_worker:
            continue
        kv.pull(idx, out=leaves[idx])
    kv.wait()

    if kv.is_master_worker:
        return

    train_iter, test_iter, _, _ = load_data(
        args.batch_size, num_all_workers, args.data_slice_idx,
        data_type=args.data_type, split_by_class=args.split_by_class)

    begin_time = time.time()
    global_iters = resume_iters + 1 if args.checkpoint_prefix else 1
    measure = Measure(sub_dir=f"cnn_rank{my_rank}")
    print(f"Start training on {num_all_workers} workers, my rank is {my_rank}.")
    for epoch in range(start_epoch, args.epoch):
        for X, y in train_iter:
            if hasattr(kv, "notify_round"):
                # FaultPlan "crash at_round N" rules key off this
                # counter (chaos matrix worker-kill case)
                kv.notify_round(global_iters)
            loss, grads = grad_step([jnp.asarray(l) for l in leaves],
                                    jnp.asarray(X), jnp.asarray(y))
            # combined push_pull: ONE message per server per round (the
            # ack carries the post-round params — bit-identical to
            # push-then-pull, tests/test_batch_wire.py); falls back to
            # the two-op sequence under P3/TSEngine/local stores
            keylist = list(range(len(grads)))
            if hasattr(kv, "push_pull"):
                kv.push_pull(keylist, [np.asarray(g) for g in grads],
                             out=leaves)
            else:
                kv.push(keylist, [np.asarray(g) for g in grads])
                kv.pull(keylist, out=leaves)
            kv.wait()

            test_acc = eval_acc(test_iter, leaves, eval_step)
            print("[Time %.3f][Epoch %d][Iteration %d] Test Acc %.4f"
                  % (time.time() - begin_time, epoch, global_iters, test_acc))
            measure.add(global_iters, epoch, test_acc, len(X), loss)
            if args.max_iters and global_iters >= args.max_iters:
                measure.dump()
                return
            global_iters += 1
        if args.checkpoint_prefix and my_rank == 0:
            from geomx_tpu import checkpoint as gx_ckpt

            gx_ckpt.save_checkpoint(args.checkpoint_prefix, epoch + 1,
                                    [np.asarray(l) for l in leaves],
                                    metadata={"iters": global_iters - 1})
    measure.dump()


if __name__ == "__main__":
    main()
