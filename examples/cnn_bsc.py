#!/usr/bin/env python
"""Bi-Sparse Compression (reference: examples/cnn_bsc.py).

BSC mode = gradient-aggregation-only: the global server holds the summed
gradient (no server optimizer), the WAN hop is sparsified both directions
(push: momentum-corrected top-k; pull: non-zero filter x num parties), and
every worker applies the optimizer LOCALLY on the pulled global gradient
(reference: Trainer(update_on_kvstore=False) + pull into param.grad(),
examples/cnn_bsc.py:77-121).
"""

import argparse
import logging
import os
import sys
import time

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import geomx_tpu as gx
from geomx_tpu import optimizer as gx_opt
from examples.utils import Measure, build_model_and_step, eval_acc, load_data


def main():
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser()
    # reference default: cnn_bsc.py:33 uses lr 0.01 (10x the vanilla
    # example's 0.001 — sparse top-k gradients need the hotter rate)
    parser.add_argument("-lr", "--learning-rate", type=float, default=0.01)
    parser.add_argument("-bs", "--batch-size", type=int, default=32)
    parser.add_argument("-ds", "--data-slice-idx", type=int, default=0)
    parser.add_argument("-ep", "--epoch", type=int, default=5)
    parser.add_argument("-cr", "--compression-ratio", type=float, default=0.01)
    parser.add_argument("-sc", "--split-by-class", action="store_true")
    parser.add_argument("-c", "--cpu", action="store_true")
    parser.add_argument("--max-iters", type=int, default=0)
    args = parser.parse_args()
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")

    kv = gx.kv.create("dist_sync")
    if kv.is_master_worker:
        kv.set_gradient_compression(
            {"type": "bsc", "threshold": args.compression_ratio})
    num_all_workers = kv.num_all_workers
    my_rank = kv.rank
    time.sleep(1)

    leaves, _treedef, grad_step, eval_step = build_model_and_step(
        args.batch_size)
    # local optimizer per worker (reference: Trainer update_on_kvstore=False)
    local_opt = gx_opt.Adam(learning_rate=args.learning_rate)

    for idx, leaf in enumerate(leaves):
        kv.init(idx, leaf)
        if kv.is_master_worker:
            continue
        kv.pull(idx, out=leaves[idx])
    kv.wait()
    if kv.is_master_worker:
        return

    train_iter, test_iter, _, _ = load_data(
        args.batch_size, num_all_workers, args.data_slice_idx,
        split_by_class=args.split_by_class)

    begin_time = time.time()
    global_iters = 1
    measure = Measure(sub_dir=f"cnn_bsc_rank{my_rank}")
    grad_bufs = [np.zeros_like(l) for l in leaves]
    print(f"Start training on {num_all_workers} workers, my rank is {my_rank}.")
    for epoch in range(args.epoch):
        for X, y in train_iter:
            loss, grads = grad_step([jnp.asarray(l) for l in leaves],
                                    jnp.asarray(X), jnp.asarray(y))
            # one batched message per server each way; the pull-back
            # is the globally-aggregated (sparsified) gradient
            keylist = list(range(len(grads)))
            kv.push(keylist, [np.asarray(g) for g in grads])
            kv.pull(keylist, out=grad_bufs)
            kv.wait()
            for idx in range(len(leaves)):
                leaves[idx] = np.asarray(
                    local_opt.update(idx, leaves[idx], grad_bufs[idx])
                ).reshape(leaves[idx].shape)

            test_acc = eval_acc(test_iter, leaves, eval_step)
            print("[Time %.3f][Epoch %d][Iteration %d] Test Acc %.4f"
                  % (time.time() - begin_time, epoch, global_iters, test_acc))
            measure.add(global_iters, epoch, test_acc, len(X), loss)
            if args.max_iters and global_iters >= args.max_iters:
                measure.dump()
                return
            global_iters += 1
    measure.dump()


if __name__ == "__main__":
    main()
