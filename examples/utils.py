"""Shared example harness (reference: examples/utils.py).

Provides the data loaders (via geomx_tpu.io), a jitted train/eval step pair
for the demo CNN, flat parameter<->pytree plumbing for the KVStore integer
key space, and the Measure JSON reporter (reference: examples/utils.py:120).
"""

from __future__ import annotations

import json
import os
import time
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from geomx_tpu.io import load_data  # noqa: F401  (re-export)
from geomx_tpu.models import create_cnn


def build_model_and_step(batch_size: int, compute_dtype=jnp.float32,
                         num_classes: int = 10,
                         input_shape=(28, 28, 1), model: str = "cnn"):
    """Returns (param_leaves, treedef, grad_step, eval_step).

    grad_step(leaves, X, y) -> (loss, grad_leaves); mean-normalized grads
    (the reference pushes grad/num_samples, examples/cnn.py:123 — MXNet
    grads are per-batch sums; JAX mean-loss grads are already normalized).

    ``model``: "cnn" (the reference demo net) or any
    ``geomx_tpu.models.get_model`` zoo name ("resnet18", "mobilenet1.0",
    "vgg11", "densenet121", ...). BatchNorm running stats stay
    WORKER-LOCAL (not pushed through the kvstore) — the reference's
    kvstore flow treats BN aux states the same way: only optimizer-
    updated parameters travel.

    Contract note: the zoo-path grad_step/eval_step close over a
    mutable batch_stats box, so unlike the cnn path they are STATEFUL —
    do not wrap them in an outer jax.jit and do not share one instance
    across concurrent workers; call build_model_and_step per worker.
    """
    rng = jax.random.PRNGKey(42)  # same init on every worker process
    if model == "cnn":
        net = create_cnn(num_classes=num_classes,
                         compute_dtype=compute_dtype)
        params = net.init(rng, jnp.zeros((1, *input_shape), jnp.float32))
        leaves, treedef = jax.tree_util.tree_flatten(params)

        def loss_fn(leaf_list, X, y):
            p = jax.tree_util.tree_unflatten(treedef, leaf_list)
            logits = net.apply(p, X)
            one_hot = jax.nn.one_hot(y, num_classes)
            return -jnp.mean(
                jnp.sum(jax.nn.log_softmax(logits) * one_hot, axis=-1))

        @jax.jit
        def grad_step(leaf_list, X, y):
            loss, grads = jax.value_and_grad(loss_fn)(leaf_list, X, y)
            return loss, grads

        @jax.jit
        def eval_step(leaf_list, X, y):
            p = jax.tree_util.tree_unflatten(treedef, leaf_list)
            pred = jnp.argmax(net.apply(p, X), axis=-1)
            return jnp.mean((pred == y).astype(jnp.float32))

    else:
        from geomx_tpu.models import get_model

        # small_images: cifar/mnist-sized stem for the resnet family
        # (forwarded through the zoo factory; other families size by
        # their conv/pool stacks alone)
        extra = {"small_images": True} if model.startswith("resnet") \
            else {}
        net = get_model(model, num_classes=num_classes,
                        compute_dtype=compute_dtype, **extra)
        variables = net.init(rng, jnp.zeros((1, *input_shape), jnp.float32))
        leaves, treedef = jax.tree_util.tree_flatten(variables["params"])
        has_bn = "batch_stats" in variables
        state_box = {"batch_stats": variables.get("batch_stats", {}),
                     "step": 0}

        def loss_fn(leaf_list, bstats, step, X, y):
            p = jax.tree_util.tree_unflatten(treedef, leaf_list)
            vs = {"params": p, **({"batch_stats": bstats} if has_bn
                                  else {})}
            # fresh dropout mask per step: fold the step counter into
            # the key (a closed-over key would bake ONE mask into the
            # jitted trace and train a fixed subnetwork)
            rngs = {"dropout": jax.random.fold_in(
                jax.random.PRNGKey(7), step)}
            if has_bn:
                logits, updates = net.apply(vs, X, train=True,
                                            mutable=["batch_stats"],
                                            rngs=rngs)
                new_bs = updates["batch_stats"]
            else:
                logits = net.apply(vs, X, train=True, rngs=rngs)
                new_bs = bstats
            one_hot = jax.nn.one_hot(y, num_classes)
            loss = -jnp.mean(
                jnp.sum(jax.nn.log_softmax(logits) * one_hot, axis=-1))
            return loss, new_bs

        @jax.jit
        def _grad_step(leaf_list, bstats, step, X, y):
            (loss, new_bs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(leaf_list, bstats, step, X, y)
            return loss, grads, new_bs

        def grad_step(leaf_list, X, y):
            step = state_box["step"]
            state_box["step"] = step + 1
            loss, grads, state_box["batch_stats"] = _grad_step(
                leaf_list, state_box["batch_stats"],
                jnp.asarray(step, jnp.int32), X, y)
            return loss, grads

        @jax.jit
        def _eval_step(leaf_list, bstats, X, y):
            p = jax.tree_util.tree_unflatten(treedef, leaf_list)
            vs = {"params": p, **({"batch_stats": bstats} if has_bn
                                  else {})}
            logits = net.apply(vs, X)
            pred = jnp.argmax(logits, axis=-1)
            return jnp.mean((pred == y).astype(jnp.float32))

        def eval_step(leaf_list, X, y):
            return _eval_step(leaf_list, state_box["batch_stats"], X, y)

    # writable host copies (np.asarray of a jax array is a read-only view)
    return ([np.array(l, copy=True) for l in leaves], treedef, grad_step,
            eval_step)


def build_mesh_ring_step(kv, grad_step):
    """Quantized mesh tier (GEOMX_MESH_CODEC != "none"): wrap the demo
    grad_step so the batch shards over the party mesh's "dp" axis, each
    rank computes LOCAL grads (no XLA-inserted psum), and every leaf is
    party-mean-reduced through the store's quantized ppermute ring
    (``kv.ring_reducer`` — error-feedback residual streams live in the
    store, keyed, so round aborts zero them in one place). Returns a
    drop-in ``(lv, X, y) -> (loss, grads)`` whose outputs are replicated
    and bit-identical on every mesh rank.

    Only valid for STATELESS grad_steps (the "cnn" path of
    build_model_and_step); the zoo path mutates a host-side
    batch_stats box per call and cannot be re-traced under shard_map.
    """
    from jax.sharding import PartitionSpec as P

    from geomx_tpu.compat import shard_map

    mesh = kv.mesh

    def _local(lv, X, y):
        loss, grads = grad_step(lv, X, y)
        return loss[None], [g[None] for g in grads]

    local_step = jax.jit(shard_map(
        _local, mesh=mesh, in_specs=(P(), P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp")), check_vma=False))

    def ring_step(lv, X, y):
        X, y = kv.shard_batch(jnp.asarray(X), jnp.asarray(y))
        losses, grads = local_step([jnp.asarray(l) for l in lv], X, y)
        out = []
        for idx, g in enumerate(grads):
            shape = g.shape[1:]
            n = int(np.prod(shape)) if shape else 1
            red = kv.ring_reducer(idx, n, mean=True)
            out.append(red.reduce(g.reshape(g.shape[0], -1))
                       .reshape(shape))
        kv.record_round_collectives(out, op="ring")
        return jnp.mean(losses), out

    return ring_step


def build_flat_step(leaves: List[np.ndarray], grad_step):
    """Fuse the per-leaf param/grad transfers into ONE array each way.

    Returns ``(flat_grad_step, pack, unpack)`` where
    ``flat_grad_step(flat_params, X, y) -> (loss, flat_grads)`` is jitted
    (split/reshape/concat happen ON DEVICE and fuse away), ``pack`` maps
    a leaf list to one flat fp32 vector and ``unpack`` maps a flat
    vector back to per-key leaves.

    Why: each host->device transfer pays one round-trip of link latency;
    when the chip hangs off a network tunnel that is ~13 ms per leaf.
    A per-leaf device_put of the demo CNN costs ~8 RTTs (~106 ms) per
    training round; packed, the whole round is 2 RTTs. On a TPU-local
    host the same trick still batches PCIe DMAs. (The reference's
    engine hides this with per-key async ops, kvstore_dist.h:567 — in
    JAX the equivalent is one fused transfer, not N async ones.)
    """
    shapes = [l.shape for l in leaves]
    sizes = [int(l.size) for l in leaves]
    bounds = list(np.cumsum(sizes)[:-1])
    dtypes = {np.asarray(l).dtype for l in leaves}
    if len(dtypes) != 1:
        raise ValueError(f"leaves must share one dtype, got {dtypes}")
    dtype = dtypes.pop()

    @jax.jit
    def flat_grad_step(flat, X, y):
        parts = jnp.split(flat, bounds)
        lv = [p.reshape(s) for p, s in zip(parts, shapes)]
        loss, grads = grad_step(lv, X, y)
        return loss, jnp.concatenate([g.reshape(-1) for g in grads])

    def pack(lv: List[np.ndarray]) -> np.ndarray:
        # host-side on purpose: one np.concatenate feeds ONE device_put
        # (jnp/ravel_pytree here would eagerly create per-leaf device
        # arrays, re-paying the per-transfer latency this fn removes)
        return np.concatenate([np.asarray(l, dtype).ravel() for l in lv])

    def unpack(flat: np.ndarray) -> List[np.ndarray]:
        return [p.reshape(s)
                for p, s in zip(np.split(np.asarray(flat), bounds), shapes)]

    return flat_grad_step, pack, unpack


def eval_acc(test_iter, leaves: List[np.ndarray], eval_step) -> float:
    accs = []
    jleaves = [jnp.asarray(l) for l in leaves]
    for X, y in test_iter:
        accs.append(float(eval_step(jleaves, jnp.asarray(X), jnp.asarray(y))))
    return float(np.mean(accs)) if accs else 0.0


class Measure:
    """Per-iteration JSON metrics reporter (reference: utils.py:120)."""

    def __init__(self, log_dir: str = "/tmp/geomx_logs", sub_dir: str = "run"):
        self.begin = time.time()
        self.records = []
        self.log_path = os.path.join(log_dir, sub_dir)
        os.makedirs(self.log_path, exist_ok=True)

    def add(self, iteration: int, epoch: int, accuracy: float,
            num_samples: int, loss: float = 0.0):
        rec = {
            "iteration": iteration,
            "epoch": epoch,
            "time": round(time.time() - self.begin, 4),
            "accuracy": round(accuracy, 4),
            "num_samples": num_samples,
            "loss": round(float(loss), 5),
        }
        self.records.append(rec)
        return rec

    def dump(self, name: str = "measure.json"):
        path = os.path.join(self.log_path, name)
        with open(path, "w") as f:
            json.dump(self.records, f)
        return path
