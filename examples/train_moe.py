#!/usr/bin/env python
"""Expert-parallel MoE transformer training over a dp x ep mesh.

Beyond the reference (SURVEY.md §2.3 — its op set predates MoE): a
decoder-only transformer whose FFNs are top-1 Switch-style MoE blocks
(geomx_tpu.models.moe), expert weights sharded over the "ep" mesh axis,
batch over "dp"; GSPMD inserts the expert-parallel collectives from the
shardings. Includes the load-balancing auxiliary loss.

    python examples/train_moe.py --cpu --ep 2 --experts 4

On CPU set XLA_FLAGS=--xla_force_host_platform_device_count=8.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def parse_args():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--experts", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--ep", type=int, default=1)
    ap.add_argument("--aux-weight", type=float, default=0.01)
    ap.add_argument("-lr", "--learning-rate", type=float, default=3e-4)
    ap.add_argument("--max-iters", type=int, default=20)
    ap.add_argument("-c", "--cpu", action="store_true")
    return ap.parse_args()


def main():
    args = parse_args()
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from geomx_tpu.models.transformer import (
        Transformer, transformer_param_sharding)
    from geomx_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(jax.devices(), ep=args.ep)
    dp = mesh.devices.shape[0]
    print(f"mesh: dp={dp} ep={args.ep} ({len(jax.devices())} x "
          f"{jax.devices()[0].device_kind}), {args.experts} experts")

    model = Transformer(vocab=args.vocab, dim=args.dim, depth=args.depth,
                        heads=args.heads, max_len=args.seq_len,
                        moe_experts=args.experts,
                        compute_dtype=jnp.bfloat16)

    rng = np.random.RandomState(0)
    base = rng.randint(0, args.vocab, (args.batch_size, 1))
    tokens_np = (base + np.arange(args.seq_len)[None, :]) % args.vocab
    tokens = jnp.asarray(tokens_np, jnp.int32)

    with mesh:
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        params = transformer_param_sharding(mesh)(params)
        tokens = jax.device_put(
            tokens, NamedSharding(mesh, P("dp", None)))
        opt = optax.adamw(args.learning_rate)
        opt_state = opt.init(params)

        def loss_fn(p, toks):
            logits, state = model.apply(
                {"params": p}, toks[:, :-1], mutable=["losses"])
            tgt = toks[:, 1:]
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt).mean()
            aux = sum(jnp.asarray(v).sum()
                      for v in jax.tree_util.tree_leaves(
                          state.get("losses", {})))
            return ce + args.aux_weight * aux, (ce, aux)

        @jax.jit
        def step(p, s, toks):
            (loss, (ce, aux)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(p, toks)
            updates, s = opt.update(grads, s, p)
            return optax.apply_updates(p, updates), s, ce, aux

        t0 = time.time()
        for it in range(1, args.max_iters + 1):
            params, opt_state, ce, aux = step(params, opt_state, tokens)
            print(f"[Time {time.time() - t0:.3f}][Iteration {it}] "
                  f"Loss {float(ce):.4f} Aux {float(aux):.4f}",
                  flush=True)


if __name__ == "__main__":
    main()
