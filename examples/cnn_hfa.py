#!/usr/bin/env python
"""Hierarchical Frequency Aggregation (reference: examples/cnn_hfa.py).

Each worker runs a LOCAL optimizer every step; every K1 steps it pushes
its weights divided by the local worker count (so the party server's sum
is the party average) and pulls the synchronized weights back. The party
server syncs with the global tier only every K2 rounds, exchanging
milestone deltas (server-side logic; enable with MXNET_KVSTORE_USE_HFA=1,
MXNET_KVSTORE_HFA_K1, MXNET_KVSTORE_HFA_K2 — reference:
kvstore_dist_server.h:184-187, 1327-1346).
"""

import argparse
import logging
import os
import sys
import time

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import geomx_tpu as gx
from geomx_tpu import optimizer as gx_opt
from examples.utils import Measure, build_model_and_step, eval_acc, load_data


def main():
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser()
    parser.add_argument("-lr", "--learning-rate", type=float, default=0.001)
    parser.add_argument("-bs", "--batch-size", type=int, default=32)
    parser.add_argument("-ds", "--data-slice-idx", type=int, default=0)
    parser.add_argument("-ep", "--epoch", type=int, default=5)
    parser.add_argument("-sc", "--split-by-class", action="store_true")
    parser.add_argument("-c", "--cpu", action="store_true")
    parser.add_argument("--max-iters", type=int, default=0)
    args = parser.parse_args()
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")

    period_k1 = int(os.getenv("MXNET_KVSTORE_HFA_K1", 2))

    kv = gx.kv.create("dist_sync")
    num_all_workers = kv.num_all_workers
    num_local_workers = kv.num_workers
    my_rank = kv.rank
    time.sleep(1)

    leaves, _treedef, grad_step, eval_step = build_model_and_step(
        args.batch_size)
    local_opt = gx_opt.Adam(learning_rate=args.learning_rate)

    for idx, leaf in enumerate(leaves):
        kv.init(idx, leaf)
        if kv.is_master_worker:
            continue
        kv.pull(idx, out=leaves[idx])
    kv.wait()
    if kv.is_master_worker:
        return

    train_iter, test_iter, _, _ = load_data(
        args.batch_size, num_all_workers, args.data_slice_idx,
        split_by_class=args.split_by_class)

    begin_time = time.time()
    global_iters = 1
    measure = Measure(sub_dir=f"cnn_hfa_rank{my_rank}")
    print(f"Start training on {num_all_workers} workers, my rank is {my_rank}.")
    for epoch in range(args.epoch):
        for X, y in train_iter:
            loss, grads = grad_step([jnp.asarray(l) for l in leaves],
                                    jnp.asarray(X), jnp.asarray(y))
            # local step every iteration (reference: trainer.step)
            for idx, g in enumerate(grads):
                leaves[idx] = np.asarray(
                    local_opt.update(idx, leaves[idx], np.asarray(g))
                ).reshape(leaves[idx].shape)

            if global_iters % period_k1 == 0:
                # HFA sync: push weights/num_local_workers, pull party avg
                # (reference: cnn_hfa.py:120-123)
                for idx in range(len(leaves)):
                    kv.push(idx, leaves[idx] / num_local_workers,
                            priority=-idx)
                    kv.pull(idx, out=leaves[idx], priority=-idx)
                kv.wait()

                test_acc = eval_acc(test_iter, leaves, eval_step)
                print("[Time %.3f][Epoch %d][Iteration %d] Test Acc %.4f"
                      % (time.time() - begin_time, epoch, global_iters,
                         test_acc))
                measure.add(global_iters, epoch, test_acc, len(X), loss)
            if args.max_iters and global_iters >= args.max_iters:
                measure.dump()
                return
            global_iters += 1
    measure.dump()


if __name__ == "__main__":
    main()
