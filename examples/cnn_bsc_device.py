#!/usr/bin/env python
"""Bi-Sparse HiPS with the DEVICE-RESIDENT trainer (TPU-first flagship).

Same PS semantics as cnn_bsc.py (aggregator tiers, worker-side
optimizer, BSC both directions) but the worker keeps parameters on the
accelerator: per round the host<->device link carries one packed top-k
selection down and the aggregated nonzeros up
(geomx_tpu.trainer_device.DeviceResidentTrainer). On a host whose chip
sits across a network link this is the difference between
transfer-bound and protocol-bound training (see PERF.md).

Run exactly like cnn_bsc.py (scripts/hips_env.sh topology), or
single-process smoke: ``python examples/cnn_bsc_device.py --local``.
"""

import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser()
    parser.add_argument("-lr", "--learning-rate", type=float, default=0.05)
    parser.add_argument("-mom", "--momentum", type=float, default=0.0)
    parser.add_argument("-bs", "--batch-size", type=int, default=32)
    parser.add_argument("-ds", "--data-slice-idx", type=int, default=0)
    parser.add_argument("-ep", "--epoch", type=int, default=5)
    parser.add_argument("-cr", "--compression-ratio", type=float,
                        default=0.02)
    parser.add_argument("-c", "--cpu", action="store_true")
    parser.add_argument("--local", action="store_true",
                        help="single-process smoke (kv.create('local'))")
    parser.add_argument("--eval-every", type=int, default=5,
                        help="accuracy-eval cadence (tr.leaves pays one "
                             "full-weight device->host transfer)")
    parser.add_argument("--max-iters", type=int, default=0)
    args = parser.parse_args()
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    import geomx_tpu as gx
    from examples.utils import Measure, build_model_and_step, eval_acc, \
        load_data
    from geomx_tpu.trainer_device import DeviceResidentTrainer

    kv = gx.kv.create("local" if args.local else "dist_sync")
    if getattr(kv, "is_master_worker", False) or args.local:
        # WAN hop sparsified both directions, like cnn_bsc.py:50
        kv.set_gradient_compression(
            {"type": "bsc", "threshold": args.compression_ratio})
    num_all_workers = getattr(kv, "num_all_workers", 1) or 1
    my_rank = getattr(kv, "rank", 0)
    time.sleep(0 if args.local else 1)

    leaves, _treedef, grad_step, eval_step = build_model_and_step(
        args.batch_size)
    if getattr(kv, "is_master_worker", False):
        for idx, leaf in enumerate(leaves):
            kv.init(idx, leaf)
        kv.wait()
        return

    tr = DeviceResidentTrainer(
        leaves, kv, grad_step, threshold=args.compression_ratio,
        learning_rate=args.learning_rate, momentum=args.momentum)

    train_iter, test_iter, _, _ = load_data(
        args.batch_size, num_all_workers, args.data_slice_idx)

    begin_time = time.time()
    global_iters = 1
    measure = Measure(sub_dir=f"cnn_bsc_device_rank{my_rank}")
    print(f"Start training on {num_all_workers} workers, "
          f"my rank is {my_rank}.")
    test_acc = 0.0
    for epoch in range(args.epoch):
        for X, y in train_iter:
            loss = tr.step(jnp.asarray(X), jnp.asarray(y))
            # tr.leaves materializes the full params device->host; keep
            # it OFF the per-round path (the whole point of the
            # device-resident trainer) and eval on a cadence
            if global_iters % args.eval_every == 0:
                test_acc = eval_acc(test_iter, tr.leaves, eval_step)
            print("[Time %.3f][Epoch %d][Iteration %d] Test Acc %.4f"
                  % (time.time() - begin_time, epoch, global_iters,
                     test_acc))
            measure.add(global_iters, epoch, test_acc, len(X), loss)
            if args.max_iters and global_iters >= args.max_iters:
                measure.dump()
                return
            global_iters += 1
    measure.dump()


if __name__ == "__main__":
    main()
