#!/bin/bash
# Shared topology wiring for the single-host HiPS demo: 12 processes,
# 3 parties (reference: scripts/cpu/run_vanilla_hips.sh — central party with
# global scheduler + global server + master worker + scheduler; two data
# parties with scheduler + server + 2 workers each).
# Usage: source hips_env.sh; launch_hips <worker_script> [extra args...]
#
# Multi-host simulation (reference: docs/source/multi-host-deployment.rst):
# set HOST_CENTRAL / HOST_A / HOST_B to distinct addresses and each
# party's nodes bind 0.0.0.0 and ADVERTISE that address via
# DMLC_NODE_HOST — the same wiring a real deployment uses with one
# address per machine. Defaults keep everything on plain loopback.

REPO_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="$REPO_DIR${PYTHONPATH:+:$PYTHONPATH}"
GPORT=${GPORT:-9092}; CPORT=${CPORT:-9093}; APORT=${APORT:-9094}; BPORT=${BPORT:-9095}
PYTHON=${PYTHON:-python}
INFRA="-c \"import geomx_tpu\""
# NGS>1 = MultiGPS: several global servers share the central party
# (reference: scripts/cpu/run_multi_gps.sh, DMLC_NUM_GLOBAL_SERVER=2)
NGS=${NGS:-1}
HOST_CENTRAL=${HOST_CENTRAL:-127.0.0.1}
HOST_A=${HOST_A:-127.0.0.1}
HOST_B=${HOST_B:-127.0.0.1}
# advertise only when off plain loopback, so the default single-host
# demo keeps listening on 127.0.0.1 alone
NH_CENTRAL=$([ "$HOST_CENTRAL" = "127.0.0.1" ] || echo "DMLC_NODE_HOST=$HOST_CENTRAL")
NH_A=$([ "$HOST_A" = "127.0.0.1" ] || echo "DMLC_NODE_HOST=$HOST_A")
NH_B=$([ "$HOST_B" = "127.0.0.1" ] || echo "DMLC_NODE_HOST=$HOST_B")

GLOBALS="DMLC_PS_GLOBAL_ROOT_URI=$HOST_CENTRAL DMLC_PS_GLOBAL_ROOT_PORT=$GPORT \
DMLC_NUM_GLOBAL_SERVER=$NGS DMLC_NUM_GLOBAL_WORKER=2"

# one data-party server. If CHAOS_PLAN_SERVER_A is set, party A's
# server (and ONLY it) runs under its own fault plan — a node/tier
# match alone cannot single it out (every party's server is local id 8)
launch_hips_party_server() {
  local PPORT="$1" PHOST="$2" NH_P="$3" NWORK="$4"
  if [ "$PPORT" = "$APORT" ] && [ -n "${CHAOS_PLAN_SERVER_A:-}" ]; then
    env $(echo $GLOBALS) $NH_P DMLC_ROLE=server \
      DMLC_PS_ROOT_URI=$PHOST DMLC_PS_ROOT_PORT=$PPORT \
      DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=$NWORK \
      PS_FAULT_PLAN="$CHAOS_PLAN_SERVER_A" \
      $PYTHON -c "import geomx_tpu" > /tmp/hips_server_$PPORT.log 2>&1 &
  else
    env $(echo $GLOBALS) $NH_P DMLC_ROLE=server \
      DMLC_PS_ROOT_URI=$PHOST DMLC_PS_ROOT_PORT=$PPORT \
      DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=$NWORK \
      $PYTHON -c "import geomx_tpu" > /tmp/hips_server_$PPORT.log 2>&1 &
  fi
}

launch_hips() {
  local script="$1"; shift
  local extra="$@"

  # central party -----------------------------------------------------
  env $(echo $GLOBALS) $NH_CENTRAL DMLC_ROLE_GLOBAL=global_scheduler \
    $PYTHON -c "import geomx_tpu" > /tmp/hips_gsched.log 2>&1 &
  env $NH_CENTRAL DMLC_ROLE=scheduler DMLC_PS_ROOT_URI=$HOST_CENTRAL DMLC_PS_ROOT_PORT=$CPORT \
    DMLC_NUM_SERVER=$NGS DMLC_NUM_WORKER=1 \
    $PYTHON -c "import geomx_tpu" > /tmp/hips_csched.log 2>&1 &
  for g in $(seq 1 $NGS); do
    env $(echo $GLOBALS) $NH_CENTRAL DMLC_ROLE_GLOBAL=global_server DMLC_ROLE=server \
      DMLC_PS_ROOT_URI=$HOST_CENTRAL DMLC_PS_ROOT_PORT=$CPORT \
      DMLC_NUM_SERVER=$NGS DMLC_NUM_WORKER=1 DMLC_ENABLE_CENTRAL_WORKER=0 \
      DMLC_NUM_ALL_WORKER=4 \
      $PYTHON -c "import geomx_tpu" > /tmp/hips_gserver$g.log 2>&1 &
  done
  env $NH_CENTRAL DMLC_ROLE=worker DMLC_ROLE_MASTER_WORKER=1 \
    DMLC_PS_ROOT_URI=$HOST_CENTRAL DMLC_PS_ROOT_PORT=$CPORT \
    DMLC_NUM_SERVER=$NGS DMLC_NUM_WORKER=1 DMLC_NUM_ALL_WORKER=4 \
    $PYTHON $script $extra > /tmp/hips_master.log 2>&1 &

  # data parties ------------------------------------------------------
  local slice=0
  local PHOST NH_P
  for PPORT in $APORT $BPORT; do
    if [ "$PPORT" = "$APORT" ]; then PHOST=$HOST_A; NH_P=$NH_A; else PHOST=$HOST_B; NH_P=$NH_B; fi
    env $NH_P DMLC_ROLE=scheduler DMLC_PS_ROOT_URI=$PHOST DMLC_PS_ROOT_PORT=$PPORT \
      DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=2 \
      $PYTHON -c "import geomx_tpu" > /tmp/hips_sched_$PPORT.log 2>&1 &
    launch_hips_party_server "$PPORT" "$PHOST" "$NH_P" 2
    # PS_SORT_KEY pins each worker's local rank (worker $w -> local id
    # 9/11 deterministically) — registration otherwise sorts by
    # ephemeral bind port, a per-run coin flip, and the chaos matrix
    # worker-kill case targets local id 9 by plan
    for w in 0 1; do
      if [ "$PPORT" = "$BPORT" ] && [ "$w" = "1" ]; then
        # last worker runs in the foreground (reference pattern)
        env $NH_P DMLC_ROLE=worker DMLC_PS_ROOT_URI=$PHOST DMLC_PS_ROOT_PORT=$PPORT \
          DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=2 DMLC_NUM_ALL_WORKER=4 \
          PS_SORT_KEY=$w \
          $PYTHON -u $script --data-slice-idx $slice $extra
      else
        env $NH_P DMLC_ROLE=worker DMLC_PS_ROOT_URI=$PHOST DMLC_PS_ROOT_PORT=$PPORT \
          DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=2 DMLC_NUM_ALL_WORKER=4 \
          PS_SORT_KEY=$w \
          $PYTHON $script --data-slice-idx $slice $extra > /tmp/hips_w$slice.log 2>&1 &
      fi
      slice=$((slice+1))
    done
  done
}

# mesh-party topology (docs/mesh-party.md, scripts/run_mesh_hips.sh):
# 9 processes, 2 data parties, each a MESH_SIZE-device GSPMD mesh with
# ONE van worker — intra-party aggregation is a device collective, so
# DMLC_NUM_ALL_WORKER=2 (= parties): the global tier sums one
# party-aggregate per party, not one gradient per member.
# Honors CHAOS_PLAN_SERVER_A like launch_hips (chaos matrix
# dist_sync_mesh case: kill party A's server, NOT party B's or the
# global server's local role — all are local id 8).
launch_mesh_hips() {
  local script="$1"; shift
  local extra="$@"
  export GEOMX_PARTY_MESH=1
  export GEOMX_PARTY_MESH_SIZE=${MESH_SIZE:-2}
  # CPU demo stand-in for per-DC chips: give each worker process enough
  # virtual devices for its party mesh (a real deployment drops this
  # and uses the chips jax.devices() reports)
  export XLA_FLAGS="${XLA_FLAGS:+$XLA_FLAGS }--xla_force_host_platform_device_count=$GEOMX_PARTY_MESH_SIZE"

  # central party -----------------------------------------------------
  env $(echo $GLOBALS) $NH_CENTRAL DMLC_ROLE_GLOBAL=global_scheduler \
    $PYTHON -c "import geomx_tpu" > /tmp/hips_mesh_gsched.log 2>&1 &
  env $NH_CENTRAL DMLC_ROLE=scheduler DMLC_PS_ROOT_URI=$HOST_CENTRAL DMLC_PS_ROOT_PORT=$CPORT \
    DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=1 \
    $PYTHON -c "import geomx_tpu" > /tmp/hips_mesh_csched.log 2>&1 &
  env $(echo $GLOBALS) $NH_CENTRAL DMLC_ROLE_GLOBAL=global_server DMLC_ROLE=server \
    DMLC_PS_ROOT_URI=$HOST_CENTRAL DMLC_PS_ROOT_PORT=$CPORT \
    DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=1 DMLC_ENABLE_CENTRAL_WORKER=0 \
    DMLC_NUM_ALL_WORKER=2 \
    $PYTHON -c "import geomx_tpu" > /tmp/hips_mesh_gserver.log 2>&1 &
  env $NH_CENTRAL DMLC_ROLE=worker DMLC_ROLE_MASTER_WORKER=1 \
    DMLC_PS_ROOT_URI=$HOST_CENTRAL DMLC_PS_ROOT_PORT=$CPORT \
    DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=1 DMLC_NUM_ALL_WORKER=2 \
    $PYTHON $script $extra > /tmp/hips_mesh_master.log 2>&1 &

  # data parties (one mesh worker each) -------------------------------
  local slice=0
  local PHOST NH_P
  for PPORT in $APORT $BPORT; do
    if [ "$PPORT" = "$APORT" ]; then PHOST=$HOST_A; NH_P=$NH_A; else PHOST=$HOST_B; NH_P=$NH_B; fi
    env $NH_P DMLC_ROLE=scheduler DMLC_PS_ROOT_URI=$PHOST DMLC_PS_ROOT_PORT=$PPORT \
      DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=1 \
      $PYTHON -c "import geomx_tpu" > /tmp/hips_mesh_sched_$PPORT.log 2>&1 &
    launch_hips_party_server "$PPORT" "$PHOST" "$NH_P" 1
    if [ "$PPORT" = "$BPORT" ]; then
      # last worker runs in the foreground (reference pattern)
      env $NH_P DMLC_ROLE=worker DMLC_PS_ROOT_URI=$PHOST DMLC_PS_ROOT_PORT=$PPORT \
        DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=1 DMLC_NUM_ALL_WORKER=2 \
        $PYTHON -u $script --data-slice-idx $slice $extra
    else
      env $NH_P DMLC_ROLE=worker DMLC_PS_ROOT_URI=$PHOST DMLC_PS_ROOT_PORT=$PPORT \
        DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=1 DMLC_NUM_ALL_WORKER=2 \
        $PYTHON $script --data-slice-idx $slice $extra > /tmp/hips_mesh_w$slice.log 2>&1 &
    fi
    slice=$((slice+1))
  done
}
