#!/bin/bash
# Shared topology wiring for the single-host HiPS demo: 12 processes,
# 3 parties (reference: scripts/cpu/run_vanilla_hips.sh — central party with
# global scheduler + global server + master worker + scheduler; two data
# parties with scheduler + server + 2 workers each).
# Usage: source hips_env.sh; launch_hips <worker_script> [extra args...]

REPO_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="$REPO_DIR${PYTHONPATH:+:$PYTHONPATH}"
GPORT=${GPORT:-9092}; CPORT=${CPORT:-9093}; APORT=${APORT:-9094}; BPORT=${BPORT:-9095}
PYTHON=${PYTHON:-python}
INFRA="-c \"import geomx_tpu\""
# NGS>1 = MultiGPS: several global servers share the central party
# (reference: scripts/cpu/run_multi_gps.sh, DMLC_NUM_GLOBAL_SERVER=2)
NGS=${NGS:-1}

GLOBALS="DMLC_PS_GLOBAL_ROOT_URI=127.0.0.1 DMLC_PS_GLOBAL_ROOT_PORT=$GPORT \
DMLC_NUM_GLOBAL_SERVER=$NGS DMLC_NUM_GLOBAL_WORKER=2"

launch_hips() {
  local script="$1"; shift
  local extra="$@"

  # central party -----------------------------------------------------
  env $(echo $GLOBALS) DMLC_ROLE_GLOBAL=global_scheduler \
    $PYTHON -c "import geomx_tpu" > /tmp/hips_gsched.log 2>&1 &
  env DMLC_ROLE=scheduler DMLC_PS_ROOT_URI=127.0.0.1 DMLC_PS_ROOT_PORT=$CPORT \
    DMLC_NUM_SERVER=$NGS DMLC_NUM_WORKER=1 \
    $PYTHON -c "import geomx_tpu" > /tmp/hips_csched.log 2>&1 &
  for g in $(seq 1 $NGS); do
    env $(echo $GLOBALS) DMLC_ROLE_GLOBAL=global_server DMLC_ROLE=server \
      DMLC_PS_ROOT_URI=127.0.0.1 DMLC_PS_ROOT_PORT=$CPORT \
      DMLC_NUM_SERVER=$NGS DMLC_NUM_WORKER=1 DMLC_ENABLE_CENTRAL_WORKER=0 \
      DMLC_NUM_ALL_WORKER=4 \
      $PYTHON -c "import geomx_tpu" > /tmp/hips_gserver$g.log 2>&1 &
  done
  env DMLC_ROLE=worker DMLC_ROLE_MASTER_WORKER=1 \
    DMLC_PS_ROOT_URI=127.0.0.1 DMLC_PS_ROOT_PORT=$CPORT \
    DMLC_NUM_SERVER=$NGS DMLC_NUM_WORKER=1 DMLC_NUM_ALL_WORKER=4 \
    $PYTHON $script $extra > /tmp/hips_master.log 2>&1 &

  # data parties ------------------------------------------------------
  local slice=0
  for PPORT in $APORT $BPORT; do
    env DMLC_ROLE=scheduler DMLC_PS_ROOT_URI=127.0.0.1 DMLC_PS_ROOT_PORT=$PPORT \
      DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=2 \
      $PYTHON -c "import geomx_tpu" > /tmp/hips_sched_$PPORT.log 2>&1 &
    env $(echo $GLOBALS) DMLC_ROLE=server \
      DMLC_PS_ROOT_URI=127.0.0.1 DMLC_PS_ROOT_PORT=$PPORT \
      DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=2 \
      $PYTHON -c "import geomx_tpu" > /tmp/hips_server_$PPORT.log 2>&1 &
    for w in 0 1; do
      if [ "$PPORT" = "$BPORT" ] && [ "$w" = "1" ]; then
        # last worker runs in the foreground (reference pattern)
        env DMLC_ROLE=worker DMLC_PS_ROOT_URI=127.0.0.1 DMLC_PS_ROOT_PORT=$PPORT \
          DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=2 DMLC_NUM_ALL_WORKER=4 \
          $PYTHON -u $script --data-slice-idx $slice $extra
      else
        env DMLC_ROLE=worker DMLC_PS_ROOT_URI=127.0.0.1 DMLC_PS_ROOT_PORT=$PPORT \
          DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=2 DMLC_NUM_ALL_WORKER=4 \
          $PYTHON $script --data-slice-idx $slice $extra > /tmp/hips_w$slice.log 2>&1 &
      fi
      slice=$((slice+1))
    done
  done
}
