#!/bin/bash
# ESync: state-server-balanced local steps + synchronous model averaging
# (beyond parity — reference README.md:45 documents ESync, ships no code)
cd "$(dirname "$0")"
source ./hips_env.sh
launch_hips "$REPO_DIR/examples/cnn_esync.py" --cpu "$@"
