#!/bin/bash
# geomx-lint from any cwd: lock, traced-code and config-drift analysis.
# Flags pass through, e.g.:  scripts/run_analyze.sh --passes traced --json
# See docs/static-analysis.md for the rule catalogue + baseline workflow.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m tools.analyze "$@"
