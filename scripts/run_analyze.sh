#!/bin/bash
# geomx-lint from any cwd, all six analysis families: lock/lock-model
# (GX-L, concurrency + lockmodel passes), traced-code (GX-J),
# config-drift (GX-C), wire-protocol (GX-P3xx), membership state-model
# (GX-S5xx, frozen to state.lock.json; explorer in tools/modelcheck.py,
# runtime dual GEOMX_STATE_SANITIZER=1) and metrics-funnel (GX-M4xx)
# analysis.
# Flags pass through, e.g.:  scripts/run_analyze.sh --passes traced --json
# See docs/static-analysis.md for the rule catalogue + baseline workflow.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m tools.analyze "$@"
