#!/bin/bash
# geomx-lint from any cwd, all four passes: lock, traced-code,
# config-drift and wire-protocol (GX-P3xx) analysis.
# Flags pass through, e.g.:  scripts/run_analyze.sh --passes traced --json
# See docs/static-analysis.md for the rule catalogue + baseline workflow.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m tools.analyze "$@"
