#!/bin/bash
# Single-host HiPS demo: 12 processes, 3 parties (reference: scripts/cpu/run_fp16.sh)
cd "$(dirname "$0")"
source ./hips_env.sh
launch_hips "$REPO_DIR/examples/cnn_fp16.py" --cpu  "$@"
