#!/bin/bash
# Single-host mesh-party HiPS demo (docs/mesh-party.md): 2 data parties,
# each a 2-device GSPMD mesh with ONE van worker, so the wiring differs
# from hips_env.sh's launch_hips (which bakes 2 van workers per party):
#   central party: global scheduler + global server + master worker +
#                  central scheduler (unchanged vs run_vanilla_hips.sh)
#   data party:    scheduler + server + ONE mesh worker process that
#                  drives GEOMX_PARTY_MESH_SIZE virtual devices and is
#                  the party's only van speaker
# DMLC_NUM_ALL_WORKER=2 (= number of parties): the global tier sums one
# party-aggregate per party, not one gradient per member.
cd "$(dirname "$0")"

REPO_DIR="$(cd .. && pwd)"
export PYTHONPATH="$REPO_DIR${PYTHONPATH:+:$PYTHONPATH}"
GPORT=${GPORT:-9092}; CPORT=${CPORT:-9093}; APORT=${APORT:-9094}; BPORT=${BPORT:-9095}
PYTHON=${PYTHON:-python}
MESH_SIZE=${MESH_SIZE:-2}

# the mesh tier (see docs/env-var-summary.md "Mesh-party tier"):
export GEOMX_PARTY_MESH=1
export GEOMX_PARTY_MESH_SIZE=$MESH_SIZE
# CPU demo stand-in for per-DC chips: give each worker process enough
# virtual devices for its party mesh (a real deployment drops this and
# uses the chips jax.devices() reports)
export XLA_FLAGS="${XLA_FLAGS:+$XLA_FLAGS }--xla_force_host_platform_device_count=$MESH_SIZE"

GLOBALS="DMLC_PS_GLOBAL_ROOT_URI=127.0.0.1 DMLC_PS_GLOBAL_ROOT_PORT=$GPORT \
DMLC_NUM_GLOBAL_SERVER=1 DMLC_NUM_GLOBAL_WORKER=2"

# central party ------------------------------------------------------
env $(echo $GLOBALS) DMLC_ROLE_GLOBAL=global_scheduler \
  $PYTHON -c "import geomx_tpu" > /tmp/hips_mesh_gsched.log 2>&1 &
env DMLC_ROLE=scheduler DMLC_PS_ROOT_URI=127.0.0.1 DMLC_PS_ROOT_PORT=$CPORT \
  DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=1 \
  $PYTHON -c "import geomx_tpu" > /tmp/hips_mesh_csched.log 2>&1 &
env $(echo $GLOBALS) DMLC_ROLE_GLOBAL=global_server DMLC_ROLE=server \
  DMLC_PS_ROOT_URI=127.0.0.1 DMLC_PS_ROOT_PORT=$CPORT \
  DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=1 DMLC_ENABLE_CENTRAL_WORKER=0 \
  DMLC_NUM_ALL_WORKER=2 \
  $PYTHON -c "import geomx_tpu" > /tmp/hips_mesh_gserver.log 2>&1 &
env DMLC_ROLE=worker DMLC_ROLE_MASTER_WORKER=1 \
  DMLC_PS_ROOT_URI=127.0.0.1 DMLC_PS_ROOT_PORT=$CPORT \
  DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=1 DMLC_NUM_ALL_WORKER=2 \
  $PYTHON "$REPO_DIR/examples/cnn.py" --cpu "$@" > /tmp/hips_mesh_master.log 2>&1 &

# data parties (one mesh worker each) --------------------------------
slice=0
for PPORT in $APORT $BPORT; do
  env DMLC_ROLE=scheduler DMLC_PS_ROOT_URI=127.0.0.1 DMLC_PS_ROOT_PORT=$PPORT \
    DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=1 \
    $PYTHON -c "import geomx_tpu" > /tmp/hips_mesh_sched_$PPORT.log 2>&1 &
  env $(echo $GLOBALS) DMLC_ROLE=server \
    DMLC_PS_ROOT_URI=127.0.0.1 DMLC_PS_ROOT_PORT=$PPORT \
    DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=1 \
    $PYTHON -c "import geomx_tpu" > /tmp/hips_mesh_server_$PPORT.log 2>&1 &
  if [ "$PPORT" = "$BPORT" ]; then
    # last worker runs in the foreground (reference pattern)
    env DMLC_ROLE=worker DMLC_PS_ROOT_URI=127.0.0.1 DMLC_PS_ROOT_PORT=$PPORT \
      DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=1 DMLC_NUM_ALL_WORKER=2 \
      $PYTHON -u "$REPO_DIR/examples/cnn.py" --cpu --data-slice-idx $slice "$@"
  else
    env DMLC_ROLE=worker DMLC_PS_ROOT_URI=127.0.0.1 DMLC_PS_ROOT_PORT=$PPORT \
      DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=1 DMLC_NUM_ALL_WORKER=2 \
      $PYTHON "$REPO_DIR/examples/cnn.py" --cpu --data-slice-idx $slice "$@" > /tmp/hips_mesh_w$slice.log 2>&1 &
  fi
  slice=$((slice+1))
done
