#!/bin/bash
# Single-host mesh-party HiPS demo (docs/mesh-party.md): 2 data parties,
# each a 2-device GSPMD mesh with ONE van worker, so the wiring differs
# from hips_env.sh's launch_hips (which bakes 2 van workers per party):
#   central party: global scheduler + global server + master worker +
#                  central scheduler (unchanged vs run_vanilla_hips.sh)
#   data party:    scheduler + server + ONE mesh worker process that
#                  drives GEOMX_PARTY_MESH_SIZE virtual devices and is
#                  the party's only van speaker
# DMLC_NUM_ALL_WORKER=2 (= number of parties): the global tier sums one
# party-aggregate per party, not one gradient per member.
#
# GEOMX_MESH_CODEC=int8|2bit|fp16 additionally routes the intra-party
# gradient all-reduce through the quantized ppermute ring (EQuARX;
# docs/mesh-party.md "Quantized mesh collectives"). Default "none"
# keeps the fused psum byte-for-byte.
#
# The topology itself lives in hips_env.sh (launch_mesh_hips) so the
# chaos matrix can run the same wiring under fault plans.
cd "$(dirname "$0")"

GPORT=${GPORT:-9092}; CPORT=${CPORT:-9093}; APORT=${APORT:-9094}; BPORT=${BPORT:-9095}
MESH_SIZE=${MESH_SIZE:-2}
source ./hips_env.sh
launch_mesh_hips "$REPO_DIR/examples/cnn.py" --cpu "$@"
