#!/bin/bash
# Multi-host mesh-party bring-up (docs/mesh-party.md "Multi-host
# parties"): NPROC host processes join ONE party's device mesh via
# ``jax.distributed.initialize`` (GEOMX_MESH_COORDINATOR /
# GEOMX_MESH_NUM_PROCS / GEOMX_MESH_PROC_ID — the knobs
# kvstore.mesh_party.maybe_init_multihost reads), after which
# ``jax.process_index() == 0`` picks the party's ONE van-speaking
# global worker and the quantized ring (GEOMX_MESH_CODEC) runs across
# processes over real ICI/DCN.
#
# On a CPU-only host this script verifies everything it CAN verify —
# the process group forms, every process agrees on device count, and
# the global-worker selection picks exactly process 0 — then gates on
# the backend: jaxlib's CPU client cannot run multi-process
# computations ("Multiprocess computations aren't implemented on the
# CPU backend"), so the cross-process ring reduce itself is reported
# as QUEUED for a real TPU slice (e.g. one process per v4-32 host)
# rather than faked. On a TPU slice the same invocation runs the
# quantized ring end-to-end and prints per-codec link bytes.
#
# Usage: ./run_mesh_multihost.sh [nproc]
#   GEOMX_MESH_CODEC=int8|2bit|fp16|none picks the ring codec
#   COORD=host:port overrides the coordinator address
cd "$(dirname "$0")"
REPO_DIR="$(cd .. && pwd)"
export PYTHONPATH="$REPO_DIR${PYTHONPATH:+:$PYTHONPATH}"
PYTHON=${PYTHON:-python}
NPROC=${1:-${NPROC:-2}}
COORD=${COORD:-127.0.0.1:12357}
CODEC=${GEOMX_MESH_CODEC:-int8}

PIDS=()
for pid in $(seq 0 $((NPROC - 1))); do
  env GEOMX_MESH_COORDINATOR=$COORD \
      GEOMX_MESH_NUM_PROCS=$NPROC \
      GEOMX_MESH_PROC_ID=$pid \
      GEOMX_MESH_CODEC=$CODEC \
      $PYTHON - <<'PY' &
import os

import numpy as np

from geomx_tpu import config as cfg_mod
from geomx_tpu.kvstore.mesh_party import maybe_init_multihost

cfg = cfg_mod.load()
assert maybe_init_multihost(cfg), "GEOMX_MESH_* knobs did not form a group"
import jax

me = int(cfg.mesh_process_id)
pi = jax.process_index()
is_global = pi == 0
print(f"proc {me}: process_index={pi} global_worker={is_global} "
      f"devices={jax.device_count()} local={jax.local_device_count()}",
      flush=True)
# the PR-8 invariant: exactly the coordinator-designated process 0 is
# the party's van speaker, everywhere, with no extra config
assert (pi == 0) == (me == 0), \
    f"global-worker selection mismatch: proc {me} got process_index {pi}"

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()), ("dp",))
try:
    x = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")),
        np.ones((jax.local_device_count(), 8), np.float32) * (me + 1))
    float(np.asarray(jax.jit(lambda a: a.sum())(x))[()])
except Exception as e:  # noqa: BLE001 — gate on the known backend hole
    if "Multiprocess computations aren't implemented" in str(e):
        print(f"proc {me}: GATED — jaxlib CPU cannot run multi-process "
              f"computations; process group + global-worker selection "
              f"verified, quantized-ring capture QUEUED for a TPU slice",
              flush=True)
        raise SystemExit(0)
    raise

# collectives work (TPU slice / multi-process-capable backend): run the
# quantized ring across the whole party and report the link bytes
from geomx_tpu.parallel.quant_collectives import QuantRingReducer

n = 1 << 16
red = QuantRingReducer(mesh, cfg.mesh_codec, n, block=cfg.mesh_block,
                       mean=True)
rng = np.random.RandomState(me)
g = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp")),
    rng.randn(jax.local_device_count(), n).astype(np.float32))
out = np.asarray(red.reduce(g))
print(f"proc {me}: ring all-reduce OK codec={cfg.mesh_codec} n={n} "
      f"bytes/round={red.wire_bytes_per_round()} |out|={np.abs(out).max():.4f}",
      flush=True)
PY
  PIDS+=($!)
done

FAIL=0
for p in "${PIDS[@]}"; do
  wait "$p" || FAIL=1
done
if [ $FAIL -ne 0 ]; then
  echo "=== mesh multihost: FAILED ==="
  exit 1
fi
echo "=== mesh multihost: OK (nproc=$NPROC codec=$CODEC) ==="
