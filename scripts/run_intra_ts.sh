#!/bin/bash
# HiPS demo with intra-DC TSEngine: worker-to-worker gradient merge and
# model relay overlays built by the scheduler
# (reference: scripts/cpu/run_intra_tsengine.sh — ENABLE_INTRA_TS=1).
cd "$(dirname "$0")"
export ENABLE_INTRA_TS=1
export MAX_GREED_RATE_TS=${MAX_GREED_RATE_TS:-0.9}
source ./hips_env.sh
launch_hips "$REPO_DIR/examples/cnn.py" --cpu "$@"
