#!/bin/bash
# HFA: K1 local steps per sync, global sync every K2 rounds
# (reference: scripts/cpu/run_hfa.sh)
cd "$(dirname "$0")"
export MXNET_KVSTORE_USE_HFA=1
export MXNET_KVSTORE_HFA_K1=${MXNET_KVSTORE_HFA_K1:-2}
export MXNET_KVSTORE_HFA_K2=${MXNET_KVSTORE_HFA_K2:-2}
source ./hips_env.sh
launch_hips "$REPO_DIR/examples/cnn_hfa.py" --cpu "$@"
