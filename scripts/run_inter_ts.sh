#!/bin/bash
# HiPS demo with inter-DC TSEngine: party-to-party aggregate merge and
# global model relay on the WAN tier
# (reference: scripts/cpu/run_inter_tsengine.sh — ENABLE_INTER_TS=1).
cd "$(dirname "$0")"
export ENABLE_INTER_TS=1
export MAX_GREED_RATE_TS=${MAX_GREED_RATE_TS:-0.9}
source ./hips_env.sh
launch_hips "$REPO_DIR/examples/cnn.py" --cpu "$@"
