#!/bin/bash
# Single-host HiPS demo: 12 processes, 3 parties (reference: scripts/cpu/run_bsc.sh)
cd "$(dirname "$0")"
source ./hips_env.sh
launch_hips "$REPO_DIR/examples/cnn_bsc.py" --cpu  "$@"
