#!/bin/bash
# MultiGPS demo: two global servers load-balance the global tier
# (reference: scripts/cpu/run_multi_gps.sh — DMLC_NUM_GLOBAL_SERVER=2).
# 13 processes: the central party runs 2 global servers; keys shard
# across them by the canonical heuristic (small keys hash, big keys
# split — kvstore_dist.h:725-762 equivalent).
cd "$(dirname "$0")"
NGS=2
source ./hips_env.sh
launch_hips "$REPO_DIR/examples/cnn.py" --cpu "$@"
