#!/bin/bash
# HiPS demo with DGT (Differential Gradient Transmission) on the inter-DC
# tier (reference: scripts/cpu/run_dgt.sh — ENABLE_DGT + DMLC_UDP_CHANNEL_NUM
# + DGT_BLOCK_SIZE + DMLC_K on every node).
# ENABLE_DGT=1: unimportant blocks over lossy UDP channels
#            2: unimportant blocks over TCP (QoS queues only)
#            3: unimportant blocks 4-bit quantized over TCP
cd "$(dirname "$0")"
export ENABLE_DGT=${ENABLE_DGT:-1}
export DMLC_UDP_CHANNEL_NUM=${DMLC_UDP_CHANNEL_NUM:-3}
export DGT_BLOCK_SIZE=${DGT_BLOCK_SIZE:-4096}
export DMLC_K=${DMLC_K:-0.8}
export DGT_CONTRI_ALPHA=${DGT_CONTRI_ALPHA:-0.3}
source ./hips_env.sh
launch_hips "$REPO_DIR/examples/cnn.py" --cpu "$@"
