#!/bin/bash
# Single-host HiPS demo: 12 processes, 3 parties — the 59M transformer
# through the device-resident Bi-Sparse trainer (params never leave the
# accelerator; element-sparse LAN wire). Beyond the reference's script
# set: GeoMX's model layer predates transformers, so this config pairs
# its HiPS+BSC recipe (scripts/cpu/run_bsc.sh) with the TPU-era model.
# Small-model smoke on CPU:
#   bash scripts/run_transformer_bsc.sh --cpu --dim 64 --depth 2 \
#        --heads 4 --vocab 256 --seq-len 64 --max-iters 10
cd "$(dirname "$0")"
source ./hips_env.sh
launch_hips "$REPO_DIR/examples/transformer_bsc_device.py" "$@"
