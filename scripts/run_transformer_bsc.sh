#!/bin/bash
# Single-host HiPS demo: 12 processes, 3 parties — the 59M transformer
# through the device-resident Bi-Sparse trainer (params never leave the
# accelerator; element-sparse LAN wire). Beyond the reference's script
# set: GeoMX's model layer predates transformers, so this config pairs
# its HiPS+BSC recipe (scripts/cpu/run_bsc.sh) with the TPU-era model.
# Small-model smoke on CPU:
#   bash scripts/run_transformer_bsc.sh --cpu --dim 64 --depth 2 \
#        --heads 4 --vocab 256 --seq-len 64 --max-iters 10
cd "$(dirname "$0")"
# a 59M bootstrap costs minutes per worker on a slow accelerator link
# (236 MB device transfer + cold jit compiles) — the finished parties
# must out-wait it at the barriers (env-tunable; config.py)
export PS_BARRIER_TIMEOUT=${PS_BARRIER_TIMEOUT:-1800}
export PS_OP_TIMEOUT=${PS_OP_TIMEOUT:-900}
source ./hips_env.sh
launch_hips "$REPO_DIR/examples/transformer_bsc_device.py" "$@"
