#!/bin/bash
# HiPS demo with P3 priority-based parameter propagation enabled
# (reference: scripts/cpu/run_p3.sh — ENABLE_P3=1 on every node).
cd "$(dirname "$0")"
export ENABLE_P3=1
source ./hips_env.sh
launch_hips "$REPO_DIR/examples/cnn.py" --cpu "$@"
