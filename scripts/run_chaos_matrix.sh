#!/bin/bash
# Chaos matrix: the vanilla-HiPS demo (12 processes, 3 parties) run
# under ten representative seeded fault plans. Every random decision
# is drawn from PS_SEED-derived streams (geomx_tpu/ps/faults.py), so a
# failing case reproduces exactly by re-running with the same seed.
# The resender is always on: the point of each case is that training
# still completes despite the injected faults.
#
# Cases:
#   loss        20% data-frame drop on every link
#   wan-jitter  added latency + jitter on half the frames, 5% duplicates
#   partition   server id 8 cut off from everyone for 3s mid-run
#   overlap     pipelined round under drops + reordering + duplicates
#   quant-wire  2-bit quantized combined wire (error-feedback residuals
#               on every leg) under drops + duplicates; sanitizer on
#   dist-sync-mesh  mesh-party tier: int8 quantized ring intra-party +
#               2-bit quantized van; party A's server killed mid-run,
#               ring residuals must reset and the sanitizer stay silent
#   shaped-16p  16 in-process parties on the heterogeneous WAN plan
#               (scripts/shapes/hetero16.json): thin-party stragglers,
#               one flapping party server, asymmetric per-link 2-bit
#               codecs on the thin legs; the wire sanitizer audits
#               every van and any violation marker fails the case
#   shaped-16p-health  same 16-party topology with the health plane on
#               (docs/observability.md): a faulted run must raise
#               straggler + link-degradation anomalies naming the
#               planned culprits, then a clean run must raise ZERO
#               anomaly events — detectors that cry wolf fail the case
#   worker-kill both data parties' worker 0 crashes at round 3; elastic
#               membership resizes the round to the survivors
#   server-kill party A's server crashes mid-round; survivors keep
#               training and a respawned server restores the snapshot
#
# Usage: ./run_chaos_matrix.sh [extra worker args...]
#   PS_SEED=<n> picks the schedule (default 7).
cd "$(dirname "$0")"
SEED=${PS_SEED:-7}
FAILED=0
ARTIFACTS=""
CASE_DIRS=()

# On a failed case, gather everything a post-mortem needs into one
# directory: the flight-recorder dumps (last wire events per van), the
# per-round telemetry snapshots, and the process logs — /tmp/hips_*.log
# is overwritten by the NEXT case, so they must be copied now.
collect_artifacts() {
  local name="$1" fdir="$2" tdir="$3"
  [ -z "$ARTIFACTS" ] && ARTIFACTS=$(mktemp -d /tmp/chaos_artifacts.XXXXXX)
  local dest="$ARTIFACTS/$name"
  mkdir -p "$dest"
  cp "$fdir"/flightrec_*.json "$dest"/ 2>/dev/null
  cp "$tdir"/metrics_round*.json "$dest"/ 2>/dev/null
  cp /tmp/hips_*.log "$dest"/ 2>/dev/null
  echo "=== chaos[$name] artifacts: $dest ==="
}

run_case() {
  local name="$1" plan="$2" port_base="$3"; shift 3
  echo "=== chaos[$name] seed=$SEED ==="
  # per-case flight-recorder/telemetry dirs (collected on failure;
  # removed at the end of a fully green matrix)
  LAST_FDIR=$(mktemp -d) LAST_TDIR=$(mktemp -d)
  CASE_DIRS+=("$LAST_FDIR" "$LAST_TDIR")
  (
    export PS_SEED=$SEED
    export PS_FAULT_PLAN="$plan"
    # retransmit layer: short timeout so drops heal fast, an overall
    # delivery deadline so a wedged run fails loudly instead of hanging
    export PS_RESEND=1 PS_RESEND_TIMEOUT=500 PS_RESEND_DEADLINE=120
    export GEOMX_FLIGHTREC_DIR=$LAST_FDIR
    export GEOMX_TELEMETRY=1 GEOMX_TELEMETRY_DIR=$LAST_TDIR
    # distinct ports per case: no TIME_WAIT clashes between cases
    export GPORT=$port_base CPORT=$((port_base + 1)) \
           APORT=$((port_base + 2)) BPORT=$((port_base + 3))
    source ./hips_env.sh
    # || exit 1: a bare `wait` always returns 0, so the subshell's
    # status must come from the foreground worker itself
    launch_hips "$REPO_DIR/examples/cnn.py" --cpu "$@" || exit 1
    wait
  )
  if [ $? -eq 0 ]; then
    echo "=== chaos[$name] OK ==="
  else
    echo "=== chaos[$name] FAILED (re-run with PS_SEED=$SEED to reproduce) ==="
    collect_artifacts "$name" "$LAST_FDIR" "$LAST_TDIR"
    FAILED=1
  fi
}

run_case loss \
  '[{"type": "drop", "p": 0.2}]' \
  9490 "$@"

run_case wan-jitter \
  '[{"type": "delay", "delay_s": 0.02, "jitter_s": 0.03, "p": 0.5},
    {"type": "dup", "p": 0.05}]' \
  9590 "$@"

run_case partition \
  '[{"type": "partition", "between": [8, "*"], "start_s": 5.0, "duration_s": 3.0}]' \
  9690 "$@"

# pipelined round (async chunked push_pull, P3 slicing) under drops,
# reordering and duplicates: chunk responses land out of order and some
# retransmit; training must still complete with the same convergence.
# The wire AND lock sanitizers ride along on this case (no kills, so
# membership never churns): every van checks requests ack exactly once,
# countdowns drain, epochs stay monotone, and every traced lock feeds
# the witness (order inversions, blocking under a lock, @guarded_by
# locksets) — any violation of either fails the case below.
export GEOMX_OVERLAP=1 P3_SLICE_BYTES=131072 GEOMX_WIRE_SANITIZER=1 \
       GEOMX_LOCK_SANITIZER=1
run_case overlap \
  '[{"type": "drop", "p": 0.1},
    {"type": "reorder", "window": 4},
    {"type": "dup", "p": 0.05}]' \
  9790 "$@"
unset GEOMX_OVERLAP P3_SLICE_BYTES GEOMX_WIRE_SANITIZER \
      GEOMX_LOCK_SANITIZER
# launch_hips overwrites /tmp/hips_*.log per case, so these are the
# overlap run's logs
if grep -l "WIRE-SANITIZER VIOLATION" /tmp/hips_*.log 2>/dev/null; then
  echo "=== chaos[overlap] FAILED: wire-sanitizer violations (see logs above) ==="
  # the sanitizer also triggered flight-recorder dumps — collect them
  collect_artifacts overlap-sanitizer "$LAST_FDIR" "$LAST_TDIR"
  FAILED=1
fi
if grep -l "LOCK-SANITIZER VIOLATION" /tmp/hips_*.log 2>/dev/null; then
  echo "=== chaos[overlap] FAILED: lock-sanitizer violations (see logs above) ==="
  collect_artifacts overlap-locksan "$LAST_FDIR" "$LAST_TDIR"
  FAILED=1
fi

# quantized combined wire under loss: every push leg carries 2-bit
# error-feedback codes (the codec rides the async chunked rounds, so
# the pipelined-round knobs come along). Retransmits must replay the
# packed bytes as-sent — a retry that re-drained the residual stream
# would corrupt the error feedback — so the bar is the same as overlap:
# training completes AND the wire sanitizer stays silent.
export GEOMX_WIRE_CODEC=2bit
export GEOMX_OVERLAP=1 P3_SLICE_BYTES=131072 GEOMX_WIRE_SANITIZER=1
run_case quant-wire \
  '[{"type": "drop", "p": 0.1},
    {"type": "dup", "p": 0.05}]' \
  10090 "$@"
unset GEOMX_WIRE_CODEC GEOMX_OVERLAP P3_SLICE_BYTES GEOMX_WIRE_SANITIZER
if grep -l "WIRE-SANITIZER VIOLATION" /tmp/hips_*.log 2>/dev/null; then
  echo "=== chaos[quant-wire] FAILED: wire-sanitizer violations (see logs above) ==="
  collect_artifacts quant-wire-sanitizer "$LAST_FDIR" "$LAST_TDIR"
  FAILED=1
fi

# shaped 16-party chaos (in-process): the link-shaping layer
# (ps/shaping.py) composed with stragglers, a flapping party server
# and asymmetric per-link codecs, sanitizer on. tools/chaos_sim.py
# scales the matrix past the 12-process ceiling — 16-64 parties run as
# threads in ONE process — and exits non-zero on any sanitizer marker
# or incomplete worker, so run_case's plumbing isn't needed here.
echo "=== chaos[shaped-16p] seed=$SEED ==="
if PS_SEED=$SEED JAX_PLATFORMS=cpu \
     ${PYTHON:-python} "$(pwd)/../tools/chaos_sim.py" \
     --parties 16 --seed "$SEED" \
     --shape "$(pwd)/shapes/hetero16.json"; then
  echo "=== chaos[shaped-16p] OK ==="
else
  echo "=== chaos[shaped-16p] FAILED (re-run with PS_SEED=$SEED to reproduce) ==="
  FAILED=1
fi

# health-plane closed loop on the same shaped 16-party topology:
# chaos_sim --health runs the matrix twice — once with planned thin-
# downlink delays and a control-cut flapping server (the scheduler
# board must raise straggler and link-degradation anomalies naming
# those culprits), then once clean (ZERO anomaly events allowed).
# chaos_sim exits non-zero on a missed detection or a false positive.
echo "=== chaos[shaped-16p-health] seed=$SEED ==="
if PS_SEED=$SEED JAX_PLATFORMS=cpu \
     ${PYTHON:-python} "$(pwd)/../tools/chaos_sim.py" \
     --parties 16 --seed "$SEED" --health \
     --shape "$(pwd)/shapes/hetero16.json"; then
  echo "=== chaos[shaped-16p-health] OK ==="
else
  echo "=== chaos[shaped-16p-health] FAILED (re-run with PS_SEED=$SEED to reproduce) ==="
  FAILED=1
fi

# adaptive transport on the same shaped 16-party topology: the
# self-tuning controller (docs/adaptive-transport.md) drives per-link
# codec + slice decisions from live health estimates while both
# sanitizers audit every van and one shaped uplink is squeezed to
# 5 Mbps mid-run. chaos_sim exits non-zero on any sanitizer marker, an
# aborted round (incomplete worker), or a controller that made no live
# decision.
echo "=== chaos[shaped-16p-adaptive] seed=$SEED ==="
if PS_SEED=$SEED JAX_PLATFORMS=cpu \
     ${PYTHON:-python} "$(pwd)/../tools/chaos_sim.py" \
     --parties 16 --seed "$SEED" --controller \
     --shape "$(pwd)/shapes/hetero16.json"; then
  echo "=== chaos[shaped-16p-adaptive] OK ==="
else
  echo "=== chaos[shaped-16p-adaptive] FAILED (re-run with PS_SEED=$SEED to reproduce) ==="
  FAILED=1
fi

# quantized mesh + quantized van under a remote-server kill
# (dist_sync_mesh): 2 parties x 2-virtual-device meshes, intra-party
# gradients ride the int8 block-scaled ppermute ring
# (GEOMX_MESH_CODEC), the van carries the 2-bit combined wire, and
# party A's server crashes mid-run; a respawned server restores the
# snapshot. The abort path must zero every ring error-feedback
# residual stream (reset_mesh_residuals) before the retried round —
# stale error replaying into the ring would corrupt the feedback
# loop — and the wire sanitizer must stay silent through kill +
# recovery on every node of the mesh topology.
echo "=== chaos[dist-sync-mesh] seed=$SEED ==="
LAST_FDIR=$(mktemp -d) LAST_TDIR=$(mktemp -d)
CASE_DIRS+=("$LAST_FDIR" "$LAST_TDIR")
rm -f /tmp/hips_mesh_*.log /tmp/hips_server_1019[23].log
(
  export PS_SEED=$SEED
  export PS_RESEND=1 PS_RESEND_TIMEOUT=500 PS_RESEND_DEADLINE=120
  export PS_HEARTBEAT_INTERVAL=1 PS_HEARTBEAT_TIMEOUT=3
  export GEOMX_FLIGHTREC_DIR=$LAST_FDIR
  export GEOMX_TELEMETRY=1 GEOMX_TELEMETRY_DIR=$LAST_TDIR
  export PS_SNAPSHOT_DIR=$(mktemp -d) PS_SNAPSHOT_INTERVAL=1
  export GEOMX_MESH_CODEC=int8 GEOMX_WIRE_CODEC=2bit
  export GEOMX_OVERLAP=1 P3_SLICE_BYTES=131072 GEOMX_WIRE_SANITIZER=1
  # scoped via hips_env.sh so ONLY party A's server runs this plan
  # (see the server-kill case below); at=60 recv frames lands a few
  # training rounds in — past init, while the ring residuals are warm
  export CHAOS_PLAN_SERVER_A='[{"type": "crash", "node": 8, "at": 60, "on": "recv", "tier": "local"}]'
  export GPORT=10190 CPORT=10191 APORT=10192 BPORT=10193
  source ./hips_env.sh
  # replacement party-A server: registers after the crash has been
  # declared (mesh workers boot jax, so rounds — and the crash frame —
  # land later than in the host-only topologies)
  ( sleep 30
    env $(echo $GLOBALS) DMLC_ROLE=server \
      DMLC_PS_ROOT_URI=$HOST_A DMLC_PS_ROOT_PORT=$APORT \
      DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=1 \
      $PYTHON -c "import geomx_tpu" > /tmp/hips_mesh_server_A_respawn.log 2>&1
  ) &
  launch_mesh_hips "$REPO_DIR/examples/cnn.py" --cpu "$@" || exit 1
  wait
)
if [ $? -eq 0 ]; then
  echo "=== chaos[dist-sync-mesh] OK ==="
else
  echo "=== chaos[dist-sync-mesh] FAILED (re-run with PS_SEED=$SEED to reproduce) ==="
  collect_artifacts dist-sync-mesh "$LAST_FDIR" "$LAST_TDIR"
  FAILED=1
fi
if grep -l "WIRE-SANITIZER VIOLATION" /tmp/hips_mesh_*.log \
     /tmp/hips_server_1019[23].log 2>/dev/null; then
  echo "=== chaos[dist-sync-mesh] FAILED: wire-sanitizer violations (see logs above) ==="
  collect_artifacts dist-sync-mesh-sanitizer "$LAST_FDIR" "$LAST_TDIR"
  FAILED=1
fi

# elastic membership: both data parties' worker 0 (local id 9) dies at
# the start of training round 3 (cnn.py's kv.notify_round drives the
# at_round trigger; the master worker is also local id 9 but exits
# after init, before any round). Heartbeats declare the corpses dead,
# each party's server re-sizes the round countdown to the survivors,
# and the remaining worker per party completes the full run.
export PS_HEARTBEAT_INTERVAL=1 PS_HEARTBEAT_TIMEOUT=3
# the crashed workers' own kv.wait should give up with the resend
# deadline, not the default 300s op timeout — their exit path is serial
# in this single-host run
export PS_OP_TIMEOUT=120
# the full sanitizer complement rides along — wire (ack exactly once),
# lock (order witness) and state (every declare/adopt/fence must agree
# with the executable membership model in tools/analyze/statemodel.py).
# Membership churn is exactly what the state sanitizer mirrors, so a
# kill case with a silent sanitizer is the strongest conformance run.
export GEOMX_WIRE_SANITIZER=1 GEOMX_LOCK_SANITIZER=1 GEOMX_STATE_SANITIZER=1
run_case worker-kill \
  '[{"type": "crash", "node": 9, "at_round": 3, "tier": "local"}]' \
  9890 "$@"
unset PS_HEARTBEAT_INTERVAL PS_HEARTBEAT_TIMEOUT PS_OP_TIMEOUT
unset GEOMX_WIRE_SANITIZER GEOMX_LOCK_SANITIZER GEOMX_STATE_SANITIZER
for marker in WIRE LOCK STATE; do
  if grep -l "$marker-SANITIZER VIOLATION" /tmp/hips_*.log 2>/dev/null; then
    echo "=== chaos[worker-kill] FAILED: $marker sanitizer violations (see logs above) ==="
    collect_artifacts worker-kill-sanitizer "$LAST_FDIR" "$LAST_TDIR"
    FAILED=1
  fi
done

# elastic membership + durable recovery: party A's server crashes on
# its 50th local data frame (mid-round). Its workers' in-flight rounds
# fail fast once the declaration lands; party B and the global tier
# keep training (the FSA countdown re-sizes to the live parties); a
# replacement server then takes the dead slot (is_recovery) and
# restores party A's state from the snapshot.
echo "=== chaos[server-kill] seed=$SEED ==="
LAST_FDIR=$(mktemp -d) LAST_TDIR=$(mktemp -d)
CASE_DIRS+=("$LAST_FDIR" "$LAST_TDIR")
(
  export PS_SEED=$SEED
  export PS_RESEND=1 PS_RESEND_TIMEOUT=500 PS_RESEND_DEADLINE=120
  export PS_HEARTBEAT_INTERVAL=1 PS_HEARTBEAT_TIMEOUT=3
  export GEOMX_FLIGHTREC_DIR=$LAST_FDIR
  export GEOMX_TELEMETRY=1 GEOMX_TELEMETRY_DIR=$LAST_TDIR
  export PS_SNAPSHOT_DIR=$(mktemp -d) PS_SNAPSHOT_INTERVAL=1
  # all three sanitizers ride the crash + recovery: the state sanitizer
  # mirrors the dead-declaration, the replacement's revival and the
  # survivors' fences through the executable membership model
  export GEOMX_WIRE_SANITIZER=1 GEOMX_LOCK_SANITIZER=1 GEOMX_STATE_SANITIZER=1
  # scoped via hips_env.sh so ONLY party A's server runs this plan — a
  # node/tier match alone also hits party B's server and the global
  # servers' local role (all are local id 8)
  export CHAOS_PLAN_SERVER_A='[{"type": "crash", "node": 8, "at": 50, "on": "recv", "tier": "local"}]'
  export GPORT=9990 CPORT=9991 APORT=9992 BPORT=9993
  source ./hips_env.sh
  # replacement party-A server: registers after the crash has been
  # declared, is handed the dead slot and restores the snapshot
  ( sleep 20
    env $(echo $GLOBALS) DMLC_ROLE=server \
      DMLC_PS_ROOT_URI=$HOST_A DMLC_PS_ROOT_PORT=$APORT \
      DMLC_NUM_SERVER=1 DMLC_NUM_WORKER=2 \
      $PYTHON -c "import geomx_tpu" > /tmp/hips_server_A_respawn.log 2>&1
  ) &
  launch_hips "$REPO_DIR/examples/cnn.py" --cpu "$@" || exit 1
  wait
)
if [ $? -eq 0 ]; then
  echo "=== chaos[server-kill] OK ==="
else
  echo "=== chaos[server-kill] FAILED (re-run with PS_SEED=$SEED to reproduce) ==="
  collect_artifacts server-kill "$LAST_FDIR" "$LAST_TDIR"
  FAILED=1
fi
for marker in WIRE LOCK STATE; do
  if grep -l "$marker-SANITIZER VIOLATION" /tmp/hips_*.log 2>/dev/null; then
    echo "=== chaos[server-kill] FAILED: $marker sanitizer violations (see logs above) ==="
    collect_artifacts server-kill-sanitizer "$LAST_FDIR" "$LAST_TDIR"
    FAILED=1
  fi
done

# a green matrix leaves nothing behind; a red one leaves $ARTIFACTS
[ $FAILED -eq 0 ] && rm -rf "${CASE_DIRS[@]}"

exit $FAILED
