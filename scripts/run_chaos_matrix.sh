#!/bin/bash
# Chaos matrix: the vanilla-HiPS demo (12 processes, 3 parties) run
# under three representative seeded fault plans. Every random decision
# is drawn from PS_SEED-derived streams (geomx_tpu/ps/faults.py), so a
# failing case reproduces exactly by re-running with the same seed.
# The resender is always on: the point of each case is that training
# still completes despite the injected faults.
#
# Cases:
#   loss       20% data-frame drop on every link
#   wan-jitter added latency + jitter on half the frames, 5% duplicates
#   partition  server id 8 cut off from everyone for 3s mid-run
#
# Usage: ./run_chaos_matrix.sh [extra worker args...]
#   PS_SEED=<n> picks the schedule (default 7).
cd "$(dirname "$0")"
SEED=${PS_SEED:-7}
FAILED=0

run_case() {
  local name="$1" plan="$2" port_base="$3"; shift 3
  echo "=== chaos[$name] seed=$SEED ==="
  (
    export PS_SEED=$SEED
    export PS_FAULT_PLAN="$plan"
    # retransmit layer: short timeout so drops heal fast, an overall
    # delivery deadline so a wedged run fails loudly instead of hanging
    export PS_RESEND=1 PS_RESEND_TIMEOUT=500 PS_RESEND_DEADLINE=120
    # distinct ports per case: no TIME_WAIT clashes between cases
    export GPORT=$port_base CPORT=$((port_base + 1)) \
           APORT=$((port_base + 2)) BPORT=$((port_base + 3))
    source ./hips_env.sh
    launch_hips "$REPO_DIR/examples/cnn.py" --cpu "$@"
    wait
  )
  if [ $? -eq 0 ]; then
    echo "=== chaos[$name] OK ==="
  else
    echo "=== chaos[$name] FAILED (re-run with PS_SEED=$SEED to reproduce) ==="
    FAILED=1
  fi
}

run_case loss \
  '[{"type": "drop", "p": 0.2}]' \
  9490 "$@"

run_case wan-jitter \
  '[{"type": "delay", "delay_s": 0.02, "jitter_s": 0.03, "p": 0.5},
    {"type": "dup", "p": 0.05}]' \
  9590 "$@"

run_case partition \
  '[{"type": "partition", "between": [8, "*"], "start_s": 5.0, "duration_s": 3.0}]' \
  9690 "$@"

# pipelined round (async chunked push_pull, P3 slicing) under drops,
# reordering and duplicates: chunk responses land out of order and some
# retransmit; training must still complete with the same convergence
export GEOMX_OVERLAP=1 P3_SLICE_BYTES=131072
run_case overlap \
  '[{"type": "drop", "p": 0.1},
    {"type": "reorder", "window": 4},
    {"type": "dup", "p": 0.05}]' \
  9790 "$@"
unset GEOMX_OVERLAP P3_SLICE_BYTES

exit $FAILED
