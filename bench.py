#!/usr/bin/env python
"""Benchmark: flagship CNN training throughput, images/sec/chip.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "images/sec/chip", "vs_baseline": N}

Metric parity with BASELINE.md: the reference's observable signal is
examples/cnn.py per-iteration wall time on its demo CNN (2 conv + 3
dense); the driver's target is >= 0.9x per-chip V100 throughput at
accuracy parity. The reference publishes no V100 number (BASELINE.md), so
``V100_BASELINE_IMG_S`` is our documented estimate for this model at this
batch size on a V100 CUDA build; vs_baseline = value / (0.9 * estimate).

The measured step is the full training step — forward + backward + Adam
update — jitted on one chip, steady-state (compile excluded), on the
28x28x1 input the reference uses.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from geomx_tpu.models import create_cnn

# Documented estimate: the reference demo CNN (178k params) fwd+bwd+Adam
# at batch 256 on a V100 (CUDA build). No published table exists
# (BASELINE.md); 50k img/s is a generous estimate for this small model.
V100_BASELINE_IMG_S = 50_000.0

BATCH = 256
WARMUP = 5
ITERS = 30


def main():
    model = create_cnn(compute_dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    X = jax.random.uniform(rng, (BATCH, 28, 28, 1), jnp.float32)
    y = jax.random.randint(rng, (BATCH,), 0, 10)
    params = model.init(rng, X[:1])
    optimizer = optax.adam(1e-3)
    opt_state = optimizer.init(params)

    def loss_fn(p, X, y):
        logits = model.apply(p, X)
        oh = jax.nn.one_hot(y, 10)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * oh, axis=-1))

    @jax.jit
    def step(p, s, X, y):
        loss, grads = jax.value_and_grad(loss_fn)(p, X, y)
        updates, s = optimizer.update(grads, s, p)
        p = optax.apply_updates(p, updates)
        return p, s, loss

    for _ in range(WARMUP):
        params, opt_state, loss = step(params, opt_state, X, y)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        params, opt_state, loss = step(params, opt_state, X, y)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    img_s = BATCH * ITERS / dt
    print(json.dumps({
        "metric": "cnn_train_images_per_sec_per_chip",
        "value": round(img_s, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s / (0.9 * V100_BASELINE_IMG_S), 3),
    }))


if __name__ == "__main__":
    main()
